"""Fleet soak: run concurrent experiments over one shared fleet and check
the scheduling invariants from the journal artifacts.

The standard scenario (``run_fleet_soak``): a low-priority "bulk" sweep
takes the whole 2-runner fleet, then a high-priority "urgent" experiment
with ``min_runners=1`` is submitted mid-flight — the scheduler must
preempt one bulk trial (gracefully, checkpoint-assisted) to make room,
both experiments must complete, and the fleet journal must show shares
within the configured weights and no experiment starving past the bound.
bench.py's ``--fleet`` mode wraps this and records the replayed numbers
as its ``detail.fleet`` block (queue wait p50/p95, preemption count,
share error).
"""

from __future__ import annotations

import glob
import os
import tempfile
import time
from typing import Any, Dict, List, Optional

from maggy_tpu.fleet.scheduler import (FLEET_JOURNAL_NAME, Fleet,
                                       replay_fleet_journal)


def demo_train_fn(lr, units, reporter=None, ctx=None):
    """Closed-form fleet trial: checkpoints every step (TrialCheckpointer
    ``checkpoints/<step>/`` layout) and resumes from ``ctx.resume_step``
    after a preemption, broadcasting as it goes — module-level so CLI
    spec files can name it (``maggy_tpu.fleet.soak:demo_train_fn``)."""
    from maggy_tpu.chaos.harness import ckpt_train_fn

    return ckpt_train_fn(lr, units, reporter=reporter, ctx=ctx)


def run_fleet_soak(runners: int = 2, bulk_trials: int = 6,
                   urgent_trials: int = 2, seed: int = 7,
                   base_dir: Optional[str] = None,
                   saturation_timeout_s: float = 30.0,
                   preempt_grace_s: float = 0.25,
                   starvation_bound_s: float = 10.0,
                   hb_interval: float = 0.05) -> Dict[str, Any]:
    """Execute the standard two-experiment preemption soak; returns a
    report with ``ok``/``violations``, the fleet-journal replay, and the
    ``detail`` block bench.py records. Pure artifact-checking: shares and
    preemptions are derived from fleet.jsonl, the per-experiment
    invariants (single FINAL, preempted-then-resumed) from each
    experiment's own telemetry journal."""
    from maggy_tpu import OptimizationConfig, Searchspace, experiment
    from maggy_tpu.chaos.harness import check_invariants
    from maggy_tpu.telemetry import JOURNAL_NAME, read_events

    base_dir = base_dir or tempfile.mkdtemp(prefix="maggy_fleet_")
    space = Searchspace(lr=("DOUBLE", [0.0, 0.2]),
                        units=("INTEGER", [8, 64]))

    def cfg(name: str, trials: int) -> OptimizationConfig:
        return OptimizationConfig(
            name=name, num_trials=trials, optimizer="randomsearch",
            searchspace=space, direction="max", hb_interval=hb_interval,
            hb_loss_timeout=5.0, seed=seed, es_policy="none",
            experiment_dir=base_dir)

    t0 = time.time()
    fleet = Fleet(runners=runners, home_dir=os.path.join(base_dir, "fleet"),
                  preempt_grace_s=preempt_grace_s)
    with fleet:
        bulk = experiment.lagom_submit(
            demo_train_fn, cfg("bulk", bulk_trials), fleet=fleet,
            priority="low", weight=1.0, block=False)
        # The urgent arrival must hit a SATURATED fleet or there is
        # nothing to preempt: wait until bulk actually holds every
        # runner (driver startup latency varies), not a fixed delay.
        deadline = time.monotonic() + saturation_timeout_s
        while time.monotonic() < deadline:
            if bulk.entry.allocated() >= runners:
                break
            time.sleep(0.02)
        urgent = experiment.lagom_submit(
            demo_train_fn, cfg("urgent", urgent_trials), fleet=fleet,
            priority="high", weight=1.0, min_runners=1, max_runners=1,
            block=False)
        results = {"bulk": bulk.result(timeout=120),
                   "urgent": urgent.result(timeout=120)}
    wall_s = time.time() - t0

    journal = os.path.join(fleet.home_dir, FLEET_JOURNAL_NAME)
    replay = replay_fleet_journal(journal)
    violations: List[str] = []

    # Both experiments completed with their full schedules.
    for name, trials in (("bulk", bulk_trials), ("urgent", urgent_trials)):
        if results[name].get("num_trials") != trials:
            violations.append(
                "experiment {!r} finished {} of {} trials".format(
                    name, results[name].get("num_trials"), trials))

    # Per-experiment journal invariants: no lost trial, exactly one FINAL
    # per trial, experiment finalized — plus the preempted-then-resumed
    # chain for whatever the scheduler preempted.
    preempted_total = 0
    resumed_from: List[int] = []
    for exp_dir in sorted(d for d in glob.glob(os.path.join(base_dir, "*"))
                          if os.path.isdir(d) and d != fleet.home_dir):
        jp = os.path.join(exp_dir, JOURNAL_NAME)
        if not os.path.exists(jp):
            continue
        events = read_events(jp)
        rep = check_invariants(events, stall_flag_bound_s=None)
        violations.extend("{}: {}".format(os.path.basename(exp_dir), v)
                          for v in rep["violations"])
        for ev in events:
            if ev.get("ev") != "trial":
                continue
            if ev.get("phase") == "preempted":
                preempted_total += 1
                if ev.get("checkpointed") and not any(
                        e.get("phase") == "resumed"
                        and e.get("trial") == ev.get("trial")
                        and e.get("t", 0) >= ev.get("t", 0)
                        for e in events):
                    violations.append(
                        "{}: trial {} preempted at checkpoint step {} but "
                        "never resumed".format(os.path.basename(exp_dir),
                                               ev.get("trial"),
                                               ev.get("step")))
            elif ev.get("phase") == "resumed" and \
                    ev.get("from_step") is not None:
                resumed_from.append(int(ev["from_step"]))

    # The scheduler must actually have preempted (fleet journal) and the
    # driver must have executed it (experiment journals agree).
    if replay["preemptions"] < 1:
        violations.append("no preemption: the urgent experiment joined a "
                          "full fleet but the scheduler never preempted")
    # Starvation bound — the fleet half of chaos invariant 7: every
    # admitted experiment starts leasing within the bound.
    mqw = replay.get("max_queue_wait_s")
    if mqw is not None and mqw > starvation_bound_s:
        violations.append(
            "starvation: an experiment waited {:.2f}s for its first "
            "runner (bound {:.1f}s)".format(mqw, starvation_bound_s))
    for name in ("bulk", "urgent"):
        if name not in replay["experiments"]:
            violations.append(
                "fleet journal has no lease record for {!r}".format(name))

    detail = {
        "queue_wait_ms": replay["queue_wait_ms"],
        "preemptions": replay["preemptions"],
        "share": replay["share"],
        "expected_share": replay["expected_share"],
        "share_error": replay["share_error"],
        "max_queue_wait_s": replay["max_queue_wait_s"],
        "resumed_from_steps": sorted(resumed_from),
        "experiments": replay["experiments"],
        "wall_s": round(wall_s, 2),
    }
    return {"ok": not violations, "violations": violations,
            "results": {k: {"num_trials": v.get("num_trials"),
                            "best_val": v.get("best_val"),
                            "preemptions": v.get("preemptions", 0)}
                        for k, v in results.items()},
            "preempted": preempted_total,
            "replay": replay, "journal": journal, "detail": detail,
            "base_dir": base_dir}
