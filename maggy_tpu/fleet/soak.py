"""Fleet soak: run concurrent experiments over one shared fleet and check
the scheduling invariants from the journal artifacts.

The standard scenario (``run_fleet_soak``): a low-priority "bulk" sweep
takes the whole 2-runner fleet, then a high-priority "urgent" experiment
with ``min_runners=1`` is submitted mid-flight — the scheduler must
preempt one bulk trial (gracefully, checkpoint-assisted) to make room,
both experiments must complete, and the fleet journal must show shares
within the configured weights and no experiment starving past the bound.
bench.py's ``--fleet`` mode wraps this and records the replayed numbers
as its ``detail.fleet`` block (queue wait p50/p95, preemption count,
share error).
"""

from __future__ import annotations

import glob
import json
import os
import tempfile
import time
from typing import Any, Dict, List, Optional

from maggy_tpu.fleet.scheduler import (FLEET_JOURNAL_NAME, Fleet,
                                       replay_fleet_journal)


def demo_train_fn(lr, units, reporter=None, ctx=None):
    """Closed-form fleet trial: checkpoints every step (TrialCheckpointer
    ``checkpoints/<step>/`` layout) and resumes from ``ctx.resume_step``
    after a preemption, broadcasting as it goes — module-level so CLI
    spec files can name it (``maggy_tpu.fleet.soak:demo_train_fn``)."""
    from maggy_tpu.chaos.harness import ckpt_train_fn

    return ckpt_train_fn(lr, units, reporter=reporter, ctx=ctx)


def run_fleet_soak(runners: int = 2, bulk_trials: int = 6,
                   urgent_trials: int = 2, seed: int = 7,
                   base_dir: Optional[str] = None,
                   saturation_timeout_s: float = 30.0,
                   preempt_grace_s: float = 0.25,
                   starvation_bound_s: float = 10.0,
                   hb_interval: float = 0.05) -> Dict[str, Any]:
    """Execute the standard two-experiment preemption soak; returns a
    report with ``ok``/``violations``, the fleet-journal replay, and the
    ``detail`` block bench.py records. Pure artifact-checking: shares and
    preemptions are derived from fleet.jsonl, the per-experiment
    invariants (single FINAL, preempted-then-resumed) from each
    experiment's own telemetry journal."""
    from maggy_tpu import OptimizationConfig, Searchspace, experiment
    from maggy_tpu.chaos.harness import check_invariants
    from maggy_tpu.telemetry import JOURNAL_NAME, read_events

    base_dir = base_dir or tempfile.mkdtemp(prefix="maggy_fleet_")
    space = Searchspace(lr=("DOUBLE", [0.0, 0.2]),
                        units=("INTEGER", [8, 64]))

    def cfg(name: str, trials: int) -> OptimizationConfig:
        return OptimizationConfig(
            name=name, num_trials=trials, optimizer="randomsearch",
            searchspace=space, direction="max", hb_interval=hb_interval,
            hb_loss_timeout=5.0, seed=seed, es_policy="none",
            experiment_dir=base_dir)

    t0 = time.time()
    fleet = Fleet(runners=runners, home_dir=os.path.join(base_dir, "fleet"),
                  preempt_grace_s=preempt_grace_s)
    with fleet:
        bulk = experiment.lagom_submit(
            demo_train_fn, cfg("bulk", bulk_trials), fleet=fleet,
            priority="low", weight=1.0, block=False)
        # The urgent arrival must hit a SATURATED fleet or there is
        # nothing to preempt: wait until bulk actually holds every
        # runner (driver startup latency varies), not a fixed delay.
        deadline = time.monotonic() + saturation_timeout_s
        while time.monotonic() < deadline:
            if bulk.entry.allocated() >= runners:
                break
            time.sleep(0.02)
        urgent = experiment.lagom_submit(
            demo_train_fn, cfg("urgent", urgent_trials), fleet=fleet,
            priority="high", weight=1.0, min_runners=1, max_runners=1,
            block=False)
        results = {"bulk": bulk.result(timeout=120),
                   "urgent": urgent.result(timeout=120)}
    wall_s = time.time() - t0

    journal = os.path.join(fleet.home_dir, FLEET_JOURNAL_NAME)
    replay = replay_fleet_journal(journal)
    violations: List[str] = []

    # Both experiments completed with their full schedules.
    for name, trials in (("bulk", bulk_trials), ("urgent", urgent_trials)):
        if results[name].get("num_trials") != trials:
            violations.append(
                "experiment {!r} finished {} of {} trials".format(
                    name, results[name].get("num_trials"), trials))

    # Per-experiment journal invariants: no lost trial, exactly one FINAL
    # per trial, experiment finalized — plus the preempted-then-resumed
    # chain for whatever the scheduler preempted.
    preempted_total = 0
    resumed_from: List[int] = []
    for exp_dir in sorted(d for d in glob.glob(os.path.join(base_dir, "*"))
                          if os.path.isdir(d) and d != fleet.home_dir):
        jp = os.path.join(exp_dir, JOURNAL_NAME)
        if not os.path.exists(jp):
            continue
        events = read_events(jp)
        rep = check_invariants(events, stall_flag_bound_s=None)
        violations.extend("{}: {}".format(os.path.basename(exp_dir), v)
                          for v in rep["violations"])
        for ev in events:
            if ev.get("ev") != "trial":
                continue
            if ev.get("phase") == "preempted":
                preempted_total += 1
                if ev.get("checkpointed") and not any(
                        e.get("phase") == "resumed"
                        and e.get("trial") == ev.get("trial")
                        and e.get("t", 0) >= ev.get("t", 0)
                        for e in events):
                    violations.append(
                        "{}: trial {} preempted at checkpoint step {} but "
                        "never resumed".format(os.path.basename(exp_dir),
                                               ev.get("trial"),
                                               ev.get("step")))
            elif ev.get("phase") == "resumed" and \
                    ev.get("from_step") is not None:
                resumed_from.append(int(ev["from_step"]))

    # The scheduler must actually have preempted (fleet journal) and the
    # driver must have executed it (experiment journals agree).
    if replay["preemptions"] < 1:
        violations.append("no preemption: the urgent experiment joined a "
                          "full fleet but the scheduler never preempted")
    # Starvation bound — the fleet half of chaos invariant 7: every
    # admitted experiment starts leasing within the bound.
    mqw = replay.get("max_queue_wait_s")
    if mqw is not None and mqw > starvation_bound_s:
        violations.append(
            "starvation: an experiment waited {:.2f}s for its first "
            "runner (bound {:.1f}s)".format(mqw, starvation_bound_s))
    for name in ("bulk", "urgent"):
        if name not in replay["experiments"]:
            violations.append(
                "fleet journal has no lease record for {!r}".format(name))

    detail = {
        "queue_wait_ms": replay["queue_wait_ms"],
        "preemptions": replay["preemptions"],
        "share": replay["share"],
        "expected_share": replay["expected_share"],
        "share_error": replay["share_error"],
        "max_queue_wait_s": replay["max_queue_wait_s"],
        "resumed_from_steps": sorted(resumed_from),
        "experiments": replay["experiments"],
        # Per-tenant chip-time ledger roll-up (lease-derived
        # chip-seconds + each tenant's own journal fold).
        "goodput": replay.get("goodput"),
        "wall_s": round(wall_s, 2),
    }
    return {"ok": not violations, "violations": violations,
            "results": {k: {"num_trials": v.get("num_trials"),
                            "best_val": v.get("best_val"),
                            "preemptions": v.get("preemptions", 0)}
                        for k, v in results.items()},
            "preempted": preempted_total,
            "replay": replay, "journal": journal, "detail": detail,
            "base_dir": base_dir}


# --------------------------------------------------------------- scale soaks


def scale_train_fn(lr, units, reporter=None, ctx=None):
    """Cheapest possible tenant trial: pure python, one broadcast — the
    measurement is the control plane (admission, leasing, RPC, journal),
    never compute. Module-level so spool spec files can name it
    (``maggy_tpu.fleet.soak:scale_train_fn``)."""
    value = 1.0 / (1.0 + abs(lr - 0.1) + units / 1e4)
    if reporter is not None:
        reporter.broadcast(value, step=0)
    return {"metric": value}


def resident_train_fn(lr, units, reporter=None, ctx=None):
    """Fair-share resident trial: ~0.1 s of wall per trial so resident
    tenants hold leases long enough for share accounting to mean
    something while cheap tenants churn around them."""
    import time as _time

    value = 1.0 / (1.0 + abs(lr - 0.1) + units / 1e4)
    for step in range(2):
        if reporter is not None:
            reporter.broadcast(value * (step + 1), step=step)
        _time.sleep(0.05)
    return {"metric": value}


def _scale_config(name: str, trials: int, base_dir: str, seed: int,
                  hb_interval: float = 0.25, telemetry: bool = False,
                  sink: bool = False):
    """Config for a cheap churn tenant: the health engine OFF and —
    without the sink — per-experiment telemetry off too (500 concurrent
    journals/flushers would measure journal fan-out, not the scheduler).
    ``sink=True`` re-enables telemetry THROUGH the fleet's journal sink:
    one process-wide shipper thread and per-source files under the fleet
    home, no per-tenant flusher — telemetry at churn scale for free."""
    from maggy_tpu import OptimizationConfig, Searchspace

    return OptimizationConfig(
        name=name, num_trials=trials, optimizer="randomsearch",
        searchspace=Searchspace(lr=("DOUBLE", [0.0, 0.2]),
                                units=("INTEGER", [8, 64])),
        direction="max", hb_interval=hb_interval, hb_loss_timeout=10.0,
        seed=seed, es_policy="none", experiment_dir=base_dir,
        telemetry=telemetry or sink, sink=sink, health=False,
        verbose=False)


def run_scale_churn(experiments: int = 520, runners: int = 8,
                    max_active: int = 12, spool_specs: int = 24,
                    trials_per_exp: int = 1, seed: int = 7,
                    base_dir: Optional[str] = None,
                    max_queued: Optional[int] = None,
                    result_timeout_s: float = 900.0,
                    min_decisions_per_s: float = 10.0,
                    admission_p99_bound_s: Optional[float] = None,
                    sink: bool = False) -> Dict[str, Any]:
    """Churn soak: hammer ONE fleet with ``experiments`` concurrent cheap
    tenants — most via ``lagom_submit``, a slice via the spool path the
    CLI host uses — and gate the control plane's replayed numbers:

    - every admitted tenant completes its full schedule (no lost trials,
      no stuck admissions);
    - scheduler decision throughput (admits + leases + preempts + sheds
      per second) stays above ``min_decisions_per_s``;
    - admission latency p99 stays under ``admission_p99_bound_s``
      (default: the soak's own wall — i.e. the queue drains steadily
      instead of parking a cohort until the end).

    Deferred activation bounds live drivers to ``max_active`` no matter
    how many hundreds queue, which is what makes this shape feasible in
    one process at all."""
    from maggy_tpu import experiment
    from maggy_tpu.core.environment import EnvSing
    from maggy_tpu.fleet.__main__ import _drain_spool

    base_dir = base_dir or tempfile.mkdtemp(prefix="maggy_scale_")
    env = EnvSing.get_instance()
    t0 = time.time()
    fleet = Fleet(runners=runners, home_dir=os.path.join(base_dir, "fleet"),
                  max_active=max_active, max_queued=max_queued,
                  preempt_grace_s=5.0)
    direct = max(0, experiments - spool_specs)
    handles = {}
    spool_handles: Dict[str, Any] = {}
    failures: Dict[str, str] = {}
    shed = 0
    with fleet:
        spool = fleet.home_dir + "/queue"
        env.mkdir(spool)
        for i in range(spool_specs):
            spec = {"name": "spool{:04d}".format(i),
                    "train_fn": "maggy_tpu.fleet.soak:scale_train_fn",
                    "config": {"num_trials": trials_per_exp,
                               "optimizer": "randomsearch",
                               "direction": "max", "seed": seed + i,
                               "es_policy": "none", "telemetry": sink,
                               "sink": sink,
                               "health": False, "hb_interval": 0.25,
                               "searchspace": {
                                   "lr": ["DOUBLE", [0.0, 0.2]],
                                   "units": ["INTEGER", [8, 64]]}}}
            env.dump(json.dumps(spec),
                     "{}/spool{:04d}.json".format(spool, i))
        from maggy_tpu.fleet.scheduler import FleetSaturated

        for i in range(direct):
            name = "churn{:04d}".format(i)
            try:
                handles[name] = experiment.lagom_submit(
                    scale_train_fn,
                    _scale_config(name, trials_per_exp, base_dir, seed + i,
                                  sink=sink),
                    fleet=fleet, block=False, name=name)
            except FleetSaturated:
                shed += 1  # expected under a max_queued bound
            except Exception as e:  # noqa: BLE001 - anything else is a real failure
                failures[name] = repr(e)
        # Spool drain with the bounded (seen-set) scan, like the CLI host.
        seen: set = set()
        deadline = time.monotonic() + result_timeout_s
        while len(spool_handles) < spool_specs \
                and time.monotonic() < deadline:
            _drain_spool(fleet, env, spool, spool_handles,
                         base_dir=base_dir, seen=seen)
            if len(spool_handles) < spool_specs:
                time.sleep(0.2)
        handles.update(spool_handles)
        for name, handle in sorted(handles.items()):
            try:
                left = max(1.0, deadline - time.monotonic())
                result = handle.result(timeout=left)
                if result.get("num_trials") != trials_per_exp:
                    failures[name] = "finished {} of {} trials".format(
                        result.get("num_trials"), trials_per_exp)
            except BaseException as e:  # noqa: BLE001 - one tenant's failure is a finding
                failures[name] = repr(e)
    wall_s = time.time() - t0

    journal = os.path.join(fleet.home_dir, FLEET_JOURNAL_NAME)
    replay = replay_fleet_journal(journal)
    violations: List[str] = []
    if failures:
        sample = dict(list(sorted(failures.items()))[:5])
        violations.append(
            "{} of {} tenants failed/incomplete (sample: {})".format(
                len(failures), len(handles) + len(failures), sample))
    rate = replay.get("decisions_per_s")
    if rate is not None and rate < min_decisions_per_s:
        violations.append(
            "decision throughput {:.1f}/s under the {:.0f}/s "
            "floor".format(rate, min_decisions_per_s))
    p99_bound = admission_p99_bound_s \
        if admission_p99_bound_s is not None else wall_s
    p99 = replay.get("admission_p99_ms")
    if p99 is not None and p99 > p99_bound * 1e3:
        violations.append(
            "admission latency p99 {:.0f} ms over the {:.0f} ms bound "
            "(queue not draining steadily)".format(p99, p99_bound * 1e3))
    detail = {
        "experiments": len(handles), "spooled": len(spool_handles),
        # failures may include submit-time names that never got a handle
        # — only subtract the ones that did.
        "completed": len(handles) - sum(1 for n in failures
                                        if n in handles),
        "failed": len(failures),
        # The journal is the source of truth for sheds — the scheduler
        # journals each refusal before raising, so counting the local
        # FleetSaturated tally on top would double-count them.
        "shed": replay.get("sheds", 0),
        "wall_s": round(wall_s, 1),
        "experiments_per_s": round(len(handles) / wall_s, 2)
        if wall_s > 0 else None,
        "admission_ms": replay["admission_ms"],
        "admission_p99_ms": replay["admission_p99_ms"],
        "decisions": replay["decisions"],
        "decisions_per_s": replay["decisions_per_s"],
        "queue_wait_ms": replay["queue_wait_ms"],
        "preemptions": replay["preemptions"],
        # Journal-sink ingest (zero when the churn ran telemetry-off).
        "telemetry_sink": sink,
        "sink": replay.get("sink"),
    }
    return {"ok": not violations, "violations": violations,
            "detail": detail, "journal": journal, "base_dir": base_dir}


def run_weighted_share_soak(runners: int = 4, trials: int = 12,
                            seed: int = 7,
                            base_dir: Optional[str] = None,
                            share_error_bound: float = 0.35
                            ) -> Dict[str, Any]:
    """Fair-share phase: three resident tenants with weights 1/1/2 run
    concurrently; the journal-replayed share split over their overlap
    window must sit within ``share_error_bound`` of the weight-expected
    split."""
    from maggy_tpu import experiment

    base_dir = base_dir or tempfile.mkdtemp(prefix="maggy_share_")
    weights = {"res_a": 1.0, "res_b": 1.0, "res_c": 2.0}
    t0 = time.time()
    fleet = Fleet(runners=runners,
                  home_dir=os.path.join(base_dir, "fleet"))
    with fleet:
        handles = {
            name: experiment.lagom_submit(
                resident_train_fn,
                _scale_config(name, trials, base_dir, seed + i,
                              hb_interval=0.05),
                fleet=fleet, weight=weights[name], block=False, name=name)
            for i, name in enumerate(sorted(weights))}
        results = {n: h.result(timeout=300) for n, h in handles.items()}
    wall_s = time.time() - t0
    journal = os.path.join(fleet.home_dir, FLEET_JOURNAL_NAME)
    replay = replay_fleet_journal(journal, share_names=set(weights))
    violations: List[str] = []
    for name in sorted(weights):
        if results[name].get("num_trials") != trials:
            violations.append("{} finished {} of {} trials".format(
                name, results[name].get("num_trials"), trials))
    if replay["share_error"] is None:
        violations.append("no overlap window: share error not computable")
    elif replay["share_error"] > share_error_bound:
        violations.append(
            "fair-share error {} over the {} bound (shares {}, expected "
            "{})".format(replay["share_error"], share_error_bound,
                         replay["share"], replay["expected_share"]))
    detail = {"share": replay["share"],
              "expected_share": replay["expected_share"],
              "share_error": replay["share_error"],
              "wall_s": round(wall_s, 1)}
    return {"ok": not violations, "violations": violations,
            "detail": detail, "journal": journal, "base_dir": base_dir}


def slow_victim_train_fn(lr, units, reporter=None, ctx=None):
    """Victim-tenant trial for the slow-tenant soak: a few broadcasts
    with a short wall so hand-off gaps dominate the measurement."""
    import time as _time

    value = 1.0 / (1.0 + abs(lr - 0.1) + units / 1e4)
    for step in range(3):
        if reporter is not None:
            reporter.broadcast(value * (step + 1), step=step)
        _time.sleep(0.02)
    return {"metric": value}


def slow_tenant_train_fn(lr, units, reporter=None, ctx=None):
    """Slow-tenant trial: ~4 s of broadcasting wall per trial, so the
    tenant keeps heartbeating (each beat's handler artificially delayed
    by the soak's injection) for the whole window the victims sweep in —
    the overlap is what makes the head-of-line measurement mean
    anything."""
    import time as _time

    value = 1.0 / (1.0 + abs(lr - 0.1) + units / 1e4)
    for step in range(80):
        if reporter is not None:
            reporter.broadcast(value * (step + 1), step=step)
        _time.sleep(0.05)
    return {"metric": value}


def run_slow_tenant_soak(runners: int = 3, victims: int = 2,
                         victim_trials: int = 6, slow_trials: int = 2,
                         delay_ms: float = 150.0,
                         dispatch_pool: Optional[bool] = True,
                         seed: int = 7,
                         base_dir: Optional[str] = None,
                         handoff_p95_bound_ms: float = 150.0,
                         victim_rtt_bound_ms: float = 50.0,
                         lock_witness: Optional[bool] = None
                         ) -> Dict[str, Any]:
    """Head-of-line-isolation soak (the chaos side of the dispatch-pool
    refactor): one tenant's handlers are artificially delayed by
    ``delay_ms`` per heartbeat/FINAL (journaled as a ``chaos`` event,
    kind ``slow_tenant``), while ``victims`` ordinary tenants run their
    sweeps on the same shared listener. Invariants:

    - every victim completes with clean journal invariants (no lost
      trials, single FINALs);
    - every victim's journal-replayed hand-off p95 stays under
      ``handoff_p95_bound_ms`` (driver-side dispatch health);
    - every victim's journaled heartbeat RTT stays under
      ``victim_rtt_bound_ms`` — THE head-of-line signal: the RTT is
      measured client-side around the whole request, so shared-loop
      queueing behind the slow tenant's delayed handlers shows up here
      (the span-derived hand-off gap cannot see it: with the FINAL
      piggyback, ``finalized`` and ``running`` are journaled inside one
      dispatch).

    With ``dispatch_pool=False`` (the pre-fix shared-loop dispatch) the
    RTT invariant is EXPECTED to fail — bench.py --scale runs exactly
    that A/B and reports both sides. ``lock_witness`` arms the runtime
    lock-order witness like the chaos soaks do; any forbidden edge is a
    violation."""
    import threading

    from maggy_tpu import experiment
    from maggy_tpu.analysis import witness as _witness
    from maggy_tpu.chaos.harness import check_invariants
    from maggy_tpu.telemetry import JOURNAL_NAME, read_events
    from maggy_tpu.telemetry.spans import derive

    wit = None
    wit_installed_here = False
    wit_pre_violations = 0
    if lock_witness or (lock_witness is None and _witness.enabled_by_env()):
        wit_installed_here = _witness.active_witness() is None
        wit = _witness.install()
        wit_pre_violations = len(wit.violations)

    base_dir = base_dir or tempfile.mkdtemp(prefix="maggy_slowten_")
    delay_s = delay_ms / 1e3
    t0 = time.time()
    injected = {"n": 0}
    fleet = Fleet(runners=runners,
                  home_dir=os.path.join(base_dir, "fleet"),
                  dispatch_pool=dispatch_pool)
    try:
        with fleet:
            slow = experiment.lagom_submit(
                slow_tenant_train_fn,
                _scale_config("slow", slow_trials, base_dir, seed,
                              hb_interval=0.02, telemetry=True),
                fleet=fleet, max_runners=1, block=False, name="slow")

            def inject():
                # Wrap the slow tenant's handler path the moment its
                # driver/server exist: every subsequent METRIC/BATCH/
                # FINAL it handles sleeps ``delay_s`` first — on the
                # shared LOOP without pools, in its OWN dispatcher with
                # them. That asymmetry is the whole experiment.
                deadline = time.monotonic() + 30.0
                while time.monotonic() < deadline:
                    drv = slow.entry.driver
                    server = getattr(drv, "server", None) \
                        if drv is not None else None
                    if server is not None:
                        orig = server.handle_message

                        def delayed(msg, _orig=orig):
                            if msg.get("type") in ("METRIC", "BATCH",
                                                   "FINAL"):
                                time.sleep(delay_s)
                                injected["n"] += 1
                            return _orig(msg)

                        server.handle_message = delayed
                        telem = getattr(drv, "telemetry", None)
                        if telem is not None:
                            telem.event("chaos", kind="slow_tenant",
                                        delay_ms=delay_ms)
                        return
                    time.sleep(0.005)

            injector = threading.Thread(target=inject, daemon=True,
                                        name="slow-tenant-injector")
            injector.start()
            injector.join(timeout=35.0)
            victim_handles = {
                "victim{}".format(i): experiment.lagom_submit(
                    slow_victim_train_fn,
                    _scale_config("victim{}".format(i), victim_trials,
                                  base_dir, seed + 1 + i,
                                  hb_interval=0.05, telemetry=True),
                    fleet=fleet, max_runners=1, block=False,
                    name="victim{}".format(i))
                for i in range(victims)}
            results = {n: h.result(timeout=180)
                       for n, h in victim_handles.items()}
            results["slow"] = slow.result(timeout=180)
    finally:
        if wit is not None and wit_installed_here \
                and not _witness.enabled_by_env():
            _witness.uninstall()
    wall_s = time.time() - t0

    violations: List[str] = []
    victim_p95: Dict[str, Any] = {}
    victim_rtt: Dict[str, Any] = {}
    journals: Dict[str, str] = {}
    for exp_dir in sorted(d for d in glob.glob(os.path.join(base_dir, "*"))
                          if os.path.isdir(d) and d != fleet.home_dir):
        jp = os.path.join(exp_dir, JOURNAL_NAME)
        if not os.path.exists(jp):
            continue
        events = read_events(jp)
        name = None
        for ev in events:
            if ev.get("ev") == "experiment" and ev.get("name"):
                name = ev["name"]
                break
        name = name or os.path.basename(exp_dir)
        journals[name] = jp
        rep = check_invariants(events, stall_flag_bound_s=None)
        violations.extend("{}: {}".format(name, v)
                          for v in rep["violations"])
        if "victim" in name:
            handoff = derive(events).get("handoff") or {}
            victim_p95[name] = handoff.get("p95_ms")
            if handoff.get("p95_ms") is not None \
                    and handoff["p95_ms"] > handoff_p95_bound_ms:
                violations.append(
                    "{}: hand-off p95 {} ms over the {} ms isolation "
                    "bound (slow tenant leaked into this tenant's "
                    "dispatch path)".format(name, handoff["p95_ms"],
                                            handoff_p95_bound_ms))
            rtts = sorted(ev["hb_rtt_ms"] for ev in events
                          if ev.get("ev") == "runner_stats"
                          and ev.get("hb_rtt_ms") is not None)
            victim_rtt[name] = rtts[-1] if rtts else None
            if rtts and rtts[-1] > victim_rtt_bound_ms:
                violations.append(
                    "{}: heartbeat RTT reached {} ms, over the {} ms "
                    "isolation bound (slow tenant leaked into this "
                    "tenant's reply path)".format(
                        name, rtts[-1], victim_rtt_bound_ms))
    for name, result in sorted(results.items()):
        want = slow_trials if name == "slow" else victim_trials
        if result.get("num_trials") != want:
            violations.append("{} finished {} of {} trials".format(
                name, result.get("num_trials"), want))
    if injected["n"] == 0:
        violations.append("slow_tenant fault never injected: the soak "
                          "exercised nothing")
    witness_block = None
    if wit is not None:
        new_violations = wit.violations[wit_pre_violations:]
        witness_block = {"edges": len(wit.edges),
                         "violations": len(new_violations)}
        for v in new_violations:
            violations.append("lock-order witness: {}".format(v))
    detail = {
        "dispatch_pool": dispatch_pool,
        "delay_ms": delay_ms,
        "injections": injected["n"],
        "victim_handoff_p95_ms": victim_p95,
        "handoff_p95_bound_ms": handoff_p95_bound_ms,
        "victim_reply_rtt_ms": victim_rtt,
        "victim_rtt_bound_ms": victim_rtt_bound_ms,
        "wall_s": round(wall_s, 1),
        "witness": witness_block,
    }
    return {"ok": not violations, "violations": violations,
            "detail": detail, "journals": journals,
            "witness": witness_block, "base_dir": base_dir}


# --------------------------------------------------------------- agent soaks


def agent_train_fn(lr, units, reporter=None, ctx=None):
    """Remote-agent soak trial: ~1.5 s of broadcasting wall so a
    SIGKILL reliably lands MID-lease (the invariant-11 window), cheap
    enough that a soak of a dozen trials stays fast. Module-level so an
    ABIND lease can name it (``maggy_tpu.fleet.soak:agent_train_fn``)."""
    import time as _time

    value = 1.0 / (1.0 + abs(lr - 0.1) + units / 1e4)
    for step in range(30):
        if reporter is not None:
            reporter.broadcast(value * (step + 1), step=step)
        _time.sleep(0.05)
    return {"metric": value}


def spawn_agent_process(ticket_path: str, obs_port: Optional[int] = None,
                        log_path: Optional[str] = None,
                        idle_exit_s: Optional[float] = None):
    """Start one REAL agent daemon (``python -m maggy_tpu.fleet agent``)
    as a separate OS process, CPU-pinned — the substrate the agent soaks
    and ``bench.py --scale --remote`` measure. Returns the Popen."""
    import subprocess
    import sys

    cmd = [sys.executable, "-m", "maggy_tpu.fleet", "agent",
           "--ticket", ticket_path, "--wait-ticket", "60"]
    if obs_port is not None:
        cmd += ["--obs-port", str(obs_port)]
    if idle_exit_s is not None:
        cmd += ["--idle-exit", str(idle_exit_s)]
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    out = open(log_path, "ab") if log_path else subprocess.DEVNULL
    return subprocess.Popen(cmd, stdout=out, stderr=subprocess.STDOUT
                            if log_path else subprocess.DEVNULL, env=env)


def run_agent_soak(agents: int = 2, trials: int = 6, seed: int = 7,
                   base_dir: Optional[str] = None,
                   result_timeout_s: float = 240.0,
                   lease_timeout_s: float = 120.0,
                   lock_witness: Optional[bool] = None) -> Dict[str, Any]:
    """Chaos invariant 11: REAL agent processes serve leases over
    sockets; one is SIGKILLed mid-lease. The experiment's slot-reclaim
    liveness must requeue the killed trial EXACTLY once (the invariant-
    6/7/8 machinery extended to agent scope via the ``kill_agent`` chaos
    kind), the fleet must revoke the lease (``lease`` end
    ``reason=agent_lost`` + ``agent`` phase ``lost`` in fleet.jsonl),
    and the experiment must still complete its full schedule on the
    survivors (the thread runner + the remaining agent). Runs under the
    lock-order witness like every chaos soak."""
    import signal

    from maggy_tpu import experiment
    from maggy_tpu.analysis import witness as _witness
    from maggy_tpu.chaos.harness import check_invariants
    from maggy_tpu.telemetry import JOURNAL_NAME, read_events

    wit = None
    wit_installed_here = False
    wit_pre_violations = 0
    if lock_witness or (lock_witness is None and _witness.enabled_by_env()):
        wit_installed_here = _witness.active_witness() is None
        wit = _witness.install()
        wit_pre_violations = len(wit.violations)

    base_dir = base_dir or tempfile.mkdtemp(prefix="maggy_agent_soak_")
    t0 = time.time()
    fleet = Fleet(runners=1, max_agents=agents,
                  home_dir=os.path.join(base_dir, "fleet"),
                  agent_liveness_s=3.0, preempt_grace_s=5.0)
    procs = []
    killed = {"agent": None, "trial": None, "partition": None}
    violations: List[str] = []
    try:
        with fleet:
            ticket = os.path.join(fleet.home_dir, "agent_ticket.json")
            for i in range(agents):
                procs.append(spawn_agent_process(
                    ticket, log_path=os.path.join(
                        base_dir, "agent{}.log".format(i))))
            sub = experiment.lagom_submit(
                agent_train_fn,
                _scale_config("agentexp", trials, base_dir, seed,
                              hb_interval=0.05, telemetry=True),
                fleet=fleet, block=False, name="agentexp")
            # Wait for a LEASED agent whose partition holds a running
            # trial — the mid-lease window the kill must land in.
            deadline = time.monotonic() + lease_timeout_s
            plane = fleet.agent_plane
            while time.monotonic() < deadline and killed["agent"] is None:
                drv = sub.entry.driver
                if drv is None:
                    time.sleep(0.05)
                    continue
                for rec in plane.snapshot():
                    if rec["state"] != "leased" or rec["pid"] is None:
                        continue
                    tid = drv.server.reservations.get_assigned_trial(
                        rec["pid"])
                    if tid is None:
                        continue
                    drv.telemetry.event(
                        "chaos", kind="kill_agent", trial=tid,
                        partition=rec["pid"], agent=rec["agent"])
                    if not plane.kill_agent_by_runner(rec["runner"]):
                        violations.append(
                            "kill_agent could not signal agent {} "
                            "(runner {})".format(rec["agent"],
                                                 rec["runner"]))
                    killed.update(agent=rec["agent"], trial=tid,
                                  partition=rec["pid"])
                    break
                time.sleep(0.05)
            if killed["agent"] is None:
                violations.append(
                    "no agent lease with a running trial within {:.0f}s "
                    "— the kill was never injected".format(
                        lease_timeout_s))
            result = {}
            try:
                result = sub.result(timeout=result_timeout_s)
            except BaseException as e:  # noqa: BLE001 - a hung experiment IS the invariant-11 failure mode
                violations.append(
                    "experiment did not complete after the kill: {!r} — "
                    "the requeue machinery under test likely lost the "
                    "trial".format(e))
    finally:
        for p in procs:
            if p.poll() is None:
                try:
                    p.send_signal(signal.SIGTERM)
                except OSError:
                    pass
        if wit is not None and wit_installed_here \
                and not _witness.enabled_by_env():
            _witness.uninstall()
    wall_s = time.time() - t0

    if result and result.get("num_trials") != trials:
        violations.append("experiment finished {} of {} trials".format(
            result.get("num_trials"), trials))
    # Experiment journal: lifecycle + exactly-once requeue for the kill.
    exp_journal = None
    report = None
    for exp_dir in sorted(d for d in glob.glob(os.path.join(base_dir, "*"))
                          if os.path.isdir(d) and d != fleet.home_dir):
        jp = os.path.join(exp_dir, JOURNAL_NAME)
        if os.path.exists(jp):
            exp_journal = jp
            report = check_invariants(read_events(jp),
                                      stall_flag_bound_s=None)
            violations.extend(report["violations"])
    if exp_journal is None:
        violations.append("no experiment journal found under "
                          "{}".format(base_dir))
    # Fleet journal: the lease-revocation half of invariant 11.
    fleet_journal = os.path.join(fleet.home_dir, FLEET_JOURNAL_NAME)
    replay = replay_fleet_journal(fleet_journal)
    agents_replay = replay.get("agents") or {}
    if agents_replay.get("joins", 0) < agents:
        violations.append(
            "only {} of {} agents ever joined the fleet".format(
                agents_replay.get("joins", 0), agents))
    if killed["agent"] is not None:
        if agents_replay.get("losses", 0) < 1:
            violations.append(
                "agent {} was killed but the fleet journal carries no "
                "agent 'lost' event".format(killed["agent"]))
        if agents_replay.get("lost_leases", 0) < 1:
            violations.append(
                "agent {} was killed mid-lease but no lease ended with "
                "reason=agent_lost".format(killed["agent"]))
        elif agents_replay.get("lost_leases", 0) > 1:
            violations.append(
                "one kill produced {} agent_lost lease revocations "
                "(expected exactly 1)".format(
                    agents_replay["lost_leases"]))
    witness_block = None
    if wit is not None:
        new_violations = wit.violations[wit_pre_violations:]
        witness_block = {"edges": len(wit.edges),
                         "violations": len(new_violations)}
        for v in new_violations:
            violations.append("lock-order witness: {}".format(v))
    detail = {
        "agents": agents,
        "killed": killed,
        "agents_replay": agents_replay,
        "wall_s": round(wall_s, 1),
        "witness": witness_block,
    }
    return {"ok": not violations, "violations": violations,
            "detail": detail, "report": report,
            "journal": exp_journal, "fleet_journal": fleet_journal,
            "witness": witness_block, "base_dir": base_dir}


def sink_train_fn(lr, units, reporter=None):
    """Churn-shaped trial, stretched: enough broadcast steps that the
    sink soak's kill/recover window reliably lands while trials (and
    their journal events) are still flowing."""
    import time as _time

    value = 1.0 / (1.0 + abs(lr - 0.1) + units / 1e4)
    for step in range(4):
        if reporter is not None:
            reporter.broadcast(value * (step + 1), step=step)
        _time.sleep(0.08)
    return {"metric": value}


def run_sink_soak(tenants: int = 3, trials: int = 6, seed: int = 7,
                  base_dir: Optional[str] = None,
                  result_timeout_s: float = 240.0,
                  phase_timeout_s: float = 30.0,
                  lock_witness: Optional[bool] = None) -> Dict[str, Any]:
    """Chaos invariant 12 — the journal sink degrades, never dominates:
    tenants run with sink-routed telemetry (``config.sink``) while the
    soak KILLS the sink mid-run (``kill_sink``: the sink tenant detaches
    from the shared listener, exactly what a crashed/partitioned sink
    looks like to shippers) and restarts it once a shipper has provably
    degraded. Checked offline over the artifacts:

    - zero experiment failures: every tenant completes its schedule and
      its merged journal passes the standard trial invariants;
    - zero lost events: per source, the union of the sink's per-source
      segments and the surviving local journal covers every event id
      ``1..max`` (the degraded window re-shipped / fell back locally);
    - zero duplicates: the merged (sid-deduped) stream holds each event
      id exactly once across the fallback seam;
    - the seam is real: ``sink_degraded`` AND ``sink_recovered`` events
      exist, and the degraded source's local fallback journal exists.

    A soak-owned PROBE journal records on a steady cadence through the
    whole window, so the degrade/recover/re-ship path is exercised
    deterministically even when the tenants' own schedules drain early.
    Runs under the lock-order witness like every chaos soak."""
    from maggy_tpu import experiment
    from maggy_tpu.analysis import witness as _witness
    from maggy_tpu.chaos.harness import check_invariants
    from maggy_tpu.core.environment import EnvSing
    from maggy_tpu.telemetry import JOURNAL_NAME, Telemetry, read_events
    from maggy_tpu.telemetry.sink import (check_exactly_once,
                                          merge_source_events,
                                          sanitize_source)

    wit = None
    wit_installed_here = False
    wit_pre_violations = 0
    if lock_witness or (lock_witness is None and _witness.enabled_by_env()):
        wit_installed_here = _witness.active_witness() is None
        wit = _witness.install()
        wit_pre_violations = len(wit.violations)

    base_dir = base_dir or tempfile.mkdtemp(prefix="maggy_sink_soak_")
    env = EnvSing.get_instance()
    t0 = time.time()
    fleet = Fleet(runners=2, home_dir=os.path.join(base_dir, "fleet"),
                  preempt_grace_s=5.0)
    violations: List[str] = []
    handles: Dict[str, Any] = {}
    probe: Optional[Telemetry] = None
    killed_t = None
    recovered_seen = False
    expected: Dict[str, int] = {}
    exp_dirs: Dict[str, str] = {}
    try:
        with fleet:
            probe = Telemetry(
                env=env, journal_path=os.path.join(base_dir,
                                                   "probe_local.jsonl"),
                enabled=True, sink=fleet.sink_binding(),
                sink_source="probe")
            for i in range(tenants):
                name = "sink{:02d}".format(i)
                handles[name] = experiment.lagom_submit(
                    sink_train_fn,
                    _scale_config(name, trials, base_dir, seed + i,
                                  hb_interval=0.05, sink=True),
                    fleet=fleet, block=False, name=name)

            def _tick(n: int) -> None:
                probe.event("runner_stats", partition=0, probe=n)

            # Phase 1: the sink must be provably ingesting.
            deadline = time.monotonic() + phase_timeout_s
            n = 0
            while time.monotonic() < deadline:
                _tick(n)
                n += 1
                snap = fleet.sink.snapshot()
                if any(s["ingested"] > 0 for s in snap.values()):
                    break
                time.sleep(0.1)
            else:
                violations.append(
                    "sink never ingested a batch within {:.0f}s — the "
                    "kill had nothing to degrade".format(phase_timeout_s))
            # Phase 2: kill the sink; a shipper must degrade.
            fleet.telemetry.event("chaos", kind="kill_sink")
            fleet.kill_sink()
            killed_t = time.time()
            deadline = time.monotonic() + phase_timeout_s
            while time.monotonic() < deadline:
                _tick(n)
                n += 1
                if probe.journal is not None and probe.journal.degraded:
                    break
                time.sleep(0.1)
            else:
                violations.append(
                    "no shipper degraded within {:.0f}s of the sink "
                    "kill".format(phase_timeout_s))
            # Phase 3: restart; the degraded shipper must recover and
            # re-ship its spool.
            fleet.restart_sink()
            deadline = time.monotonic() + phase_timeout_s
            while time.monotonic() < deadline:
                _tick(n)
                n += 1
                if probe.journal is not None \
                        and not probe.journal.degraded:
                    recovered_seen = True
                    break
                time.sleep(0.1)
            if not recovered_seen:
                violations.append(
                    "shipper did not recover within {:.0f}s of the sink "
                    "restart".format(phase_timeout_s))
            for name, handle in sorted(handles.items()):
                try:
                    result = handle.result(timeout=result_timeout_s)
                    if result.get("num_trials") != trials:
                        violations.append(
                            "{} finished {} of {} trials".format(
                                name, result.get("num_trials"), trials))
                except BaseException as e:  # noqa: BLE001 - a failed tenant IS the invariant failure
                    violations.append(
                        "experiment {} failed after the sink kill: "
                        "{!r}".format(name, e))
            probe.close()
            expected["probe"] = probe.journal.max_sid() \
                if probe.journal is not None else 0
            for name, handle in handles.items():
                drv = handle.entry.driver
                if drv is None:
                    continue
                exp_dirs[name] = drv.exp_dir
                max_sid = getattr(drv.telemetry.journal, "max_sid", None)
                if max_sid is not None:
                    expected[name] = max_sid()
    finally:
        if wit is not None and wit_installed_here \
                and not _witness.enabled_by_env():
            _witness.uninstall()
    wall_s = time.time() - t0

    # Offline exactly-once check per source over sink segments + the
    # surviving local journals.
    sink_dir = os.path.join(fleet.home_dir, "journal")
    degraded_events = 0
    recovered_events = 0
    per_source: Dict[str, Dict[str, Any]] = {}
    local_paths = {"probe": os.path.join(base_dir, "probe_local.jsonl")}
    for name, exp_dir in exp_dirs.items():
        local_paths[name] = os.path.join(exp_dir, JOURNAL_NAME)
    for source, want in sorted(expected.items()):
        spath = os.path.join(sink_dir,
                             sanitize_source(source) + ".jsonl")
        shipped = read_events(spath) if os.path.exists(spath) else None
        lpath = local_paths.get(source)
        local = read_events(lpath) \
            if lpath and os.path.exists(lpath) else None
        merged = merge_source_events(shipped, local)
        source_violations = check_exactly_once(merged,
                                               expected_max_sid=want)
        degraded_events += sum(1 for e in merged
                               if e.get("ev") == "sink_degraded")
        recovered_events += sum(1 for e in merged
                                if e.get("ev") == "sink_recovered")
        if source != "probe":
            report = check_invariants(merged, stall_flag_bound_s=None)
            source_violations.extend(report["violations"])
        per_source[source] = {
            "expected": want,
            "sink_events": len(shipped) if shipped is not None else 0,
            "local_events": len(local) if local is not None else 0,
            "merged": len(merged),
            "violations": source_violations,
        }
        violations.extend("{}: {}".format(source, v)
                          for v in source_violations)
    if killed_t is not None and degraded_events < 1:
        violations.append("sink killed but no sink_degraded event "
                          "survives in any merged journal")
    if recovered_seen and recovered_events < 1:
        violations.append("shipper recovered but no sink_recovered "
                          "event survives in any merged journal")
    witness_block = None
    if wit is not None:
        new_violations = wit.violations[wit_pre_violations:]
        witness_block = {"edges": len(wit.edges),
                         "violations": len(new_violations)}
        for v in new_violations:
            violations.append("lock-order witness: {}".format(v))
    detail = {
        "tenants": tenants,
        "killed_t": killed_t,
        "degraded_events": degraded_events,
        "recovered_events": recovered_events,
        "per_source": per_source,
        "wall_s": round(wall_s, 1),
        "witness": witness_block,
    }
    return {"ok": not violations, "violations": violations,
            "detail": detail, "sink_dir": sink_dir,
            "fleet_journal": os.path.join(fleet.home_dir,
                                          FLEET_JOURNAL_NAME),
            "witness": witness_block, "base_dir": base_dir}


def run_remote_scale_soak(experiments: int = 40, agents: int = 4,
                          runners: int = 2, max_active: int = 8,
                          trials_per_exp: int = 1, seed: int = 7,
                          base_dir: Optional[str] = None,
                          result_timeout_s: float = 600.0
                          ) -> Dict[str, Any]:
    """The remote half of ROADMAP item 4 ("nothing yet measures hundreds
    of sockets"): the PR-11 churn driven by REAL agent processes over
    sockets — every agent is a separate OS process dialing the shared
    listener, every lease a full AJOIN/ABIND/REG/.../ADONE round trip.
    Gates: every tenant completes, every agent joins, remote leases
    actually happened (the churn must not quietly drain through the
    thread runners alone), and — with the journal sink on — the run
    yields ONE ``--unified`` Perfetto trace: driver track, one process
    group per agent, ABIND->execution->FINAL flow arrows, event order
    consistent with the journaled clock offsets. Records
    ``detail.remote``: agent join latency p50/p95 (process spawn ->
    fleet journal join), ABIND lease round-trip p50/p95, churn
    completion, and the unified-trace block."""
    import signal

    from maggy_tpu import experiment
    from maggy_tpu.fleet.scheduler import FleetSaturated
    from maggy_tpu.telemetry import read_events
    from maggy_tpu.telemetry.spans import _dist_stats

    base_dir = base_dir or tempfile.mkdtemp(prefix="maggy_remote_scale_")
    t0 = time.time()
    fleet = Fleet(runners=runners, max_agents=agents,
                  home_dir=os.path.join(base_dir, "fleet"),
                  max_active=max_active, agent_liveness_s=10.0,
                  preempt_grace_s=5.0)
    procs = []
    spawn_wall: List[float] = []
    handles: Dict[str, Any] = {}
    failures: Dict[str, str] = {}
    try:
        with fleet:
            ticket = os.path.join(fleet.home_dir, "agent_ticket.json")
            for i in range(agents):
                spawn_wall.append(time.time())
                procs.append(spawn_agent_process(
                    ticket, log_path=os.path.join(
                        base_dir, "agent{}.log".format(i))))
            for i in range(experiments):
                name = "remote{:04d}".format(i)
                try:
                    handles[name] = experiment.lagom_submit(
                        scale_train_fn,
                        _scale_config(name, trials_per_exp, base_dir,
                                      seed + i, sink=True),
                        fleet=fleet, block=False, name=name)
                except FleetSaturated:
                    pass
                except Exception as e:  # noqa: BLE001 - a real submission failure
                    failures[name] = repr(e)
            deadline = time.monotonic() + result_timeout_s
            for name, handle in sorted(handles.items()):
                try:
                    left = max(1.0, deadline - time.monotonic())
                    result = handle.result(timeout=left)
                    if result.get("num_trials") != trials_per_exp:
                        failures[name] = "finished {} of {} trials".format(
                            result.get("num_trials"), trials_per_exp)
                except BaseException as e:  # noqa: BLE001 - one tenant's failure is a finding
                    failures[name] = repr(e)
    finally:
        for p in procs:
            if p.poll() is None:
                try:
                    p.send_signal(signal.SIGTERM)
                except OSError:
                    pass
    wall_s = time.time() - t0

    journal = os.path.join(fleet.home_dir, FLEET_JOURNAL_NAME)
    replay = replay_fleet_journal(journal)
    agents_replay = replay.get("agents") or {}
    # Join latency: process spawn wall time -> the fleet journal's agent
    # join stamp, matched in order (agents join in spawn order on an
    # idle fleet; ties are within measurement noise).
    join_ts = sorted(ev.get("t") for ev in read_events(journal)
                     if ev.get("ev") == "agent"
                     and ev.get("phase") == "join"
                     and ev.get("t") is not None)
    join_ms = [(t - s) * 1e3 for s, t in zip(spawn_wall, join_ts)
               if t >= s]
    # Remote leases: leases granted to agent-slot runners (runner index
    # >= the thread-fleet size).
    remote_leases = sum(1 for ev in read_events(journal)
                        if ev.get("ev") == "lease"
                        and ev.get("phase") == "start"
                        and isinstance(ev.get("runner"), int)
                        and ev["runner"] >= runners)
    violations: List[str] = []
    if failures:
        sample = dict(list(sorted(failures.items()))[:5])
        violations.append(
            "{} of {} tenants failed/incomplete (sample: {})".format(
                len(failures), len(handles), sample))
    if agents_replay.get("joins", 0) < agents:
        violations.append("only {} of {} agents joined".format(
            agents_replay.get("joins", 0), agents))
    if remote_leases < 1:
        violations.append(
            "no lease was ever granted to a remote agent — the churn "
            "drained entirely through thread runners")
    # The unified trace: fleet journal + sink segments merged with any
    # surviving local journals, clock-corrected, flow-arrowed — the
    # artifact the acceptance gate inspects.
    unified: Dict[str, Any] = {}
    try:
        from maggy_tpu.telemetry.sink import (SINK_DIR_NAME,
                                              merge_source_events,
                                              read_sink_dir,
                                              sanitize_source)
        from maggy_tpu.telemetry.trace import (build_unified_trace,
                                               validate_trace)

        fleet_events = read_events(journal)
        sink_map = read_sink_dir(os.path.join(fleet.home_dir,
                                              SINK_DIR_NAME))
        agent_ids = {str(ev.get("agent")) for ev in fleet_events
                     if ev.get("ev") == "agent"
                     and ev.get("phase") == "join" and ev.get("agent")}
        exp_events: Dict[str, Any] = {}
        for name in handles:
            shipped = sink_map.pop(sanitize_source(name), None)
            if shipped:
                exp_events[name] = merge_source_events(shipped)
        agent_journals = {src: evs for src, evs in sink_map.items()
                          if src in agent_ids}
        trace = build_unified_trace(fleet_events, exp_events,
                                    agent_journals=agent_journals)
        validate_trace(trace)
        out_path = os.path.join(fleet.home_dir, "unified_trace.json")
        with open(out_path, "w") as f:
            json.dump(trace, f)
        other = trace.get("otherData") or {}
        unified = {"path": out_path,
                   "agents": len(other.get("agents") or []),
                   "flows": other.get("flows", 0),
                   "clock_offsets": len(other.get("clock_offsets")
                                        or {})}
        if unified["agents"] < min(2, agents):
            violations.append(
                "unified trace carries {} agent process group(s) "
                "(expected >= {})".format(unified["agents"],
                                          min(2, agents)))
        if unified["flows"] < 1:
            violations.append(
                "unified trace carries no ABIND->execution flow arrows")
    except Exception as e:  # noqa: BLE001 - a broken trace build is a gate failure, not a crash
        violations.append("unified trace build failed: {!r}".format(e))
    detail = {
        "experiments": len(handles),
        "completed": len(handles) - sum(1 for n in failures
                                        if n in handles),
        "failed": len(failures),
        "agents": agents,
        "agent_joins": agents_replay.get("joins", 0),
        "agent_join_ms": _dist_stats(join_ms),
        "abind_ms": agents_replay.get("abind_ms"),
        "remote_leases": remote_leases,
        "total_leases": agents_replay.get("leases", 0),
        "unified": unified,
        "sink": replay.get("sink"),
        "clock_offsets": replay.get("clock_offsets"),
        "wall_s": round(wall_s, 1),
        "experiments_per_s": round(len(handles) / wall_s, 2)
        if wall_s > 0 else None,
        "decisions_per_s": replay.get("decisions_per_s"),
        "admission_p99_ms": replay.get("admission_p99_ms"),
    }
    return {"ok": not violations, "violations": violations,
            "detail": detail, "journal": journal, "base_dir": base_dir}


def run_scale_soak(experiments: int = 520, runners: int = 8,
                   max_active: int = 12, seed: int = 7,
                   base_dir: Optional[str] = None,
                   churn_kwargs: Optional[Dict[str, Any]] = None,
                   sink_ab: bool = True,
                   sink_throughput_ratio: float = 0.9,
                   sink_lag_p95_bound_ms: float = 10_000.0
                   ) -> Dict[str, Any]:
    """The full ``bench.py --scale`` scenario, importable for tests:

    1. **churn** — ``experiments`` concurrent cheap tenants through one
       fleet (lagom_submit + spool), gating completion, scheduler
       decision throughput, and admission latency p99;
    2. **sink A/B** — the SAME churn with telemetry re-enabled through
       the fleet's journal sink (``config.sink``): decision throughput
       must stay within ``sink_throughput_ratio`` (default 10%) of the
       telemetry-off baseline, admission p99 within the mirrored 10%
       bound, and the sink's replayed ingest lag p95 under
       ``sink_lag_p95_bound_ms`` — telemetry at churn scale must be
       near-free, or the sink is dominating instead of observing;
    3. **fair share** — three weighted residents, gating journal-replayed
       share error;
    4. **slow-tenant A/B** — the head-of-line isolation proof: victims'
       hand-off p95 with the per-tenant dispatch pools ON must hold the
       isolation bound, and the pool-OFF (pre-fix shared-loop) arm must
       show the inflation the pools remove.
    """
    base_dir = base_dir or tempfile.mkdtemp(prefix="maggy_scale_soak_")
    churn = run_scale_churn(
        experiments=experiments, runners=runners, max_active=max_active,
        seed=seed, base_dir=os.path.join(base_dir, "churn"),
        **(churn_kwargs or {}))
    sink_detail = None
    sink_violations: List[str] = []
    if sink_ab:
        churn_sink = run_scale_churn(
            experiments=experiments, runners=runners,
            max_active=max_active, seed=seed,
            base_dir=os.path.join(base_dir, "churn_sink"), sink=True,
            **(churn_kwargs or {}))
        sink_violations.extend(churn_sink["violations"])
        off_rate = churn["detail"].get("decisions_per_s")
        on_rate = churn_sink["detail"].get("decisions_per_s")
        rate_ratio = None
        if off_rate and on_rate:
            rate_ratio = round(on_rate / off_rate, 3)
            if rate_ratio < sink_throughput_ratio:
                sink_violations.append(
                    "sink-on decision throughput {:.1f}/s is {:.0%} of "
                    "the telemetry-off baseline {:.1f}/s (floor "
                    "{:.0%})".format(on_rate, on_rate / off_rate,
                                     off_rate, sink_throughput_ratio))
        off_p99 = churn["detail"].get("admission_p99_ms")
        on_p99 = churn_sink["detail"].get("admission_p99_ms")
        p99_ratio = None
        if off_p99 and on_p99:
            p99_ratio = round(on_p99 / off_p99, 3)
            # Mirrored 10% bound, with an absolute floor so sub-second
            # p99s don't fail on scheduler jitter.
            if on_p99 > off_p99 * (2 - sink_throughput_ratio) + 500.0:
                sink_violations.append(
                    "sink-on admission p99 {:.0f} ms exceeds the "
                    "telemetry-off baseline {:.0f} ms by more than "
                    "{:.0%}".format(on_p99, off_p99,
                                    1 - sink_throughput_ratio))
        sink_replay = churn_sink["detail"].get("sink") or {}
        lag_p95 = (sink_replay.get("lag_ms") or {}).get("p95_ms")
        if not sink_replay.get("events"):
            sink_violations.append(
                "sink arm ran but the fleet journal carries no jsink "
                "ingest records — tenants did not ship")
        elif lag_p95 is not None and lag_p95 > sink_lag_p95_bound_ms:
            sink_violations.append(
                "sink ingest lag p95 {:.0f} ms over the {:.0f} ms "
                "bound".format(lag_p95, sink_lag_p95_bound_ms))
        sink_detail = {
            "baseline": {"decisions_per_s": off_rate,
                         "admission_p99_ms": off_p99},
            "sink_on": churn_sink["detail"],
            "decisions_ratio": rate_ratio,
            "admission_p99_ratio": p99_ratio,
            "ingest_lag_p95_ms": lag_p95,
            "ingest": sink_replay,
        }
    share = run_weighted_share_soak(
        seed=seed, base_dir=os.path.join(base_dir, "share"))
    pooled = run_slow_tenant_soak(
        seed=seed, dispatch_pool=True,
        base_dir=os.path.join(base_dir, "slow_pooled"))
    unpooled = run_slow_tenant_soak(
        seed=seed, dispatch_pool=False,
        base_dir=os.path.join(base_dir, "slow_unpooled"))

    def _max_rtt(report):
        vals = [v for v in report["detail"]
                ["victim_reply_rtt_ms"].values() if v is not None]
        return max(vals) if vals else None

    pooled_p95, unpooled_p95 = _max_rtt(pooled), _max_rtt(unpooled)
    violations: List[str] = []
    violations.extend("churn: {}".format(v) for v in churn["violations"])
    violations.extend("sink: {}".format(v) for v in sink_violations)
    violations.extend("share: {}".format(v) for v in share["violations"])
    violations.extend("slow_tenant(pool=on): {}".format(v)
                      for v in pooled["violations"])
    # The unpooled arm's isolation-bound violations are the EXPECTED
    # demonstration (the A/B's whole point); its lifecycle violations
    # (lost trials etc.) still count.
    violations.extend(
        "slow_tenant(pool=off): {}".format(v)
        for v in unpooled["violations"] if "isolation bound" not in v)
    ab_ok = None
    if pooled_p95 is not None and unpooled_p95 is not None:
        ab_ok = unpooled_p95 > pooled_p95
        if not ab_ok:
            violations.append(
                "A/B inversion: victim reply latency with pools "
                "({} ms) is not below the shared-loop arm ({} ms) — the "
                "isolation win did not materialize".format(
                    pooled_p95, unpooled_p95))
    detail = {
        "churn": churn["detail"],
        "sink": sink_detail,
        "share": share["detail"],
        "slow_tenant_ab": {
            "pooled_victim_reply_ms": pooled_p95,
            "unpooled_victim_reply_ms": unpooled_p95,
            "inflation_x": round(unpooled_p95 / pooled_p95, 2)
            if pooled_p95 and unpooled_p95 else None,
            "ab_ok": ab_ok,
            "pooled": pooled["detail"],
            "unpooled": unpooled["detail"],
        },
    }
    return {"ok": not violations, "violations": violations,
            "detail": detail, "base_dir": base_dir,
            "journal": churn["journal"]}
