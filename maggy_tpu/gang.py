"""Gang-scheduled multi-chip trials: declaration, placement, replay.

ROADMAP item 2 — the headline scenario. A trial may declare that it needs
N chips plus a sharding plan (mesh axes + strategy), and the driver
assembles a *gang* of N fleet runners (runner ≈ chip, the Podracer shape)
into one contiguous mesh slice: every member's chip is leased to the
trial, a designated leader runs the sharded train step through
``parallel/mesh.py`` + ``parallel/sharding.py``, and the members hold
their chips (idle-polling, heartbeating) until the gang releases. One
sweep can therefore mix 1-chip CNN ASHA trials with N-chip sharded-LLM
trials on the same fleet.

Three pieces live here:

- ``GangSpec`` — the declaration: chips, mesh axes ({"fsdp": 4} etc.,
  derived from the strategy when omitted), and the strategy string the
  model zoo's logical-axis rules understand (dp/fsdp/tp/sp/pp — see
  ``parallel.sharding.logical_axis_rules``). Declared per budget via
  ``config.chips_per_budget`` (int values stay 1-runner-per-trial
  elastic sizing; GangSpec values gang-schedule) or searched over via a
  ``Searchspace`` ``GANG`` entry.
- ``GangPlacer`` — topology-aware packing (the perf substance): chips
  form a line (consecutive ids = ICI-contiguous slice), gangs get
  best-fit *aligned contiguous* blocks — the smallest free gap that
  fits, at a start aligned to the gang size when the topology allows —
  so mixed-size churn cannot strand chips between gangs. When free
  chips >= need but no contiguous free window exists, the placer
  journals a fragmentation ``stall`` and reserves the window with the
  fewest busy chips so the block *drains* toward assembly instead of
  waiting for luck. Every decision is a journaled ``pack`` event, so
  packing efficiency is replayable offline.
- ``replay_pack`` — pure replay of pack + gang span events into the
  numbers the acceptance gate reads: chip-seconds utilization,
  fragmentation stalls, and gang assembly latency p50/p95.

``GangContext`` is what the leader's train function sees (``ctx.gang``):
the member chips, a mesh over exactly those devices, and the strategy to
hand to ``Trainer``/``shard_params``.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

#: Conventional trial-parameter name for a Searchspace ``GANG`` entry.
#: The driver resolves the entry by TYPE, so any name works; this is the
#: name examples and docs use.
GANG_PARAM = "gang"


def default_mesh_for(strategy: str, chips: int) -> Dict[str, int]:
    """Derive mesh axes from a strategy when the spec omits them: the
    strategy's primary sharded axis gets all the chips. Composite
    strategies ("fsdp_tp") must name axes explicitly — there is no one
    right split."""
    primary = {"dp": "data", "fsdp": "fsdp", "tp": "model", "sp": "seq",
               "pp": "pipe", "ep": "expert", "zero": "data",
               "dp_zero": "data"}
    axis = primary.get(strategy)
    if axis is None:
        raise ValueError(
            "GangSpec with strategy {!r} needs explicit mesh axes (only "
            "single-part strategies {} derive a default)".format(
                strategy, sorted(primary)))
    return {axis: chips}


class GangSpec:
    """A trial's multi-chip declaration: ``chips`` fleet runners gang up
    into a contiguous mesh slice shaped by ``mesh`` and sharded per
    ``strategy``. Serializes to a plain dict so it can ride in trial
    params / info over the fixed-schema msgpack wire."""

    __slots__ = ("chips", "mesh", "strategy")

    def __init__(self, chips: int, mesh: Optional[Dict[str, int]] = None,
                 strategy: str = "dp"):
        self.chips = int(chips)
        if self.chips < 1:
            raise ValueError("GangSpec.chips must be >= 1, got "
                             "{}".format(chips))
        from maggy_tpu.parallel.sharding import logical_axis_rules

        logical_axis_rules(strategy)  # validates the strategy parts
        self.strategy = strategy
        if mesh is None:
            mesh = default_mesh_for(strategy, self.chips) \
                if self.chips > 1 else {"data": 1}
        self.mesh = {str(k): int(v) for k, v in mesh.items()}
        prod = 1
        for v in self.mesh.values():
            prod *= v
        if prod != self.chips:
            raise ValueError(
                "GangSpec mesh {} multiplies to {} devices but chips={}"
                .format(self.mesh, prod, self.chips))

    def to_dict(self) -> Dict[str, Any]:
        return {"chips": self.chips, "mesh": dict(self.mesh),
                "strategy": self.strategy}

    @classmethod
    def from_value(cls, value) -> "GangSpec":
        """Normalize any declaration form — GangSpec, dict, or bare chip
        count — into a GangSpec."""
        if isinstance(value, GangSpec):
            return value
        if isinstance(value, dict):
            return cls(value["chips"], mesh=value.get("mesh"),
                       strategy=value.get("strategy", "dp"))
        return cls(int(value))

    def __eq__(self, other) -> bool:
        return isinstance(other, GangSpec) and \
            self.to_dict() == other.to_dict()

    def __hash__(self) -> int:
        return hash((self.chips, tuple(sorted(self.mesh.items())),
                     self.strategy))

    def __repr__(self) -> str:
        return "GangSpec(chips={}, mesh={}, strategy={!r})".format(
            self.chips, self.mesh, self.strategy)


def spec_chips(value) -> int:
    """Chip count of any chips_per_budget value (int or GangSpec/dict)."""
    if isinstance(value, GangSpec):
        return value.chips
    if isinstance(value, dict):
        return int(value.get("chips", 1))
    return int(value)


def config_max_gang_chips(config) -> int:
    """Largest gang any trial of this config can declare: over the
    chips_per_budget values and any Searchspace GANG entry. 1 = no
    gangs."""
    worst = 1
    cpb = getattr(config, "chips_per_budget", None) or {}
    for v in cpb.values():
        worst = max(worst, spec_chips(v))
    sp = getattr(config, "searchspace", None)
    if sp is not None:
        for name in sp.names():
            if sp.get_type(name) == "GANG":
                for v in sp.get(name):
                    worst = max(worst, spec_chips(v))
    return worst


def config_declares_gangs(config) -> bool:
    """Does this config declare any multi-runner gang (a GangSpec/dict
    chips_per_budget value or a Searchspace GANG entry)? On the elastic
    pool bare int chips_per_budget values size respawnable pinned
    runners, not gangs; on every other pool a bare int N is the
    documented shorthand for GangSpec(N) (config.py)."""
    cpb = getattr(config, "chips_per_budget", None) or {}
    if any(isinstance(v, (GangSpec, dict)) for v in cpb.values()):
        return True
    if getattr(config, "pool", "thread") != "elastic" \
            and any(spec_chips(v) > 1 for v in cpb.values()):
        return True
    sp = getattr(config, "searchspace", None)
    if sp is not None:
        return any(sp.get_type(n) == "GANG" for n in sp.names())
    return False


#: Process-wide jax.distributed rendezvous latch: ``initialize`` may run
#: at most once per process, however many remote-gang trials this agent
#: serves — later gangs in the same world reuse the first rendezvous.
_RENDEZVOUS_LOCK = threading.Lock()
_RENDEZVOUS_DONE = False


class GangContext:
    """What the gang leader's train function receives as ``ctx.gang``:
    the assembled slice (chips + mesh axes + strategy) and helpers that
    build the jax objects over exactly the gang's devices.

    Remote gangs (members living in DIFFERENT processes — fleet agents,
    TPU-VM workers) additionally carry ``rendezvous``: the
    driver-coordinated ``jax.distributed.initialize`` parameters
    (coordinator address = the leader agent's advertised coord port,
    process ids in chip order). ``ensure_rendezvous()`` joins that world
    exactly once per process; ``build_mesh``/``sharding_env`` call it
    implicitly, so the in-one-process assumption (runner ≈ chip by
    index) is gone the moment the assignment says otherwise."""

    def __init__(self, info: Dict[str, Any]):
        self.chips: List[int] = [int(c) for c in info.get("chips", [])]
        self.members: List[int] = [int(p) for p in info.get("members", [])]
        self.leader: Optional[int] = info.get("leader")
        self.mesh_shape: Dict[str, int] = dict(info.get("mesh", {}))
        self.strategy: str = info.get("strategy", "dp")
        # Remote-gang rendezvous block (None for in-process gangs) and
        # this member's own partition id (stamped into the assignment
        # info at serve time) — together they resolve our process_id.
        self.rendezvous: Optional[Dict[str, Any]] = \
            dict(info["rendezvous"]) if info.get("rendezvous") else None
        self.partition: Optional[int] = info.get("partition")

    @property
    def size(self) -> int:
        return len(self.chips)

    @property
    def process_id(self) -> Optional[int]:
        """This member's jax.distributed process id (0 = the leader),
        or None for in-process gangs."""
        if self.rendezvous is None or self.partition is None:
            return None
        pid = (self.rendezvous.get("process_ids") or {}).get(
            str(int(self.partition)))
        return None if pid is None else int(pid)

    def ensure_rendezvous(self) -> bool:
        """Join the gang's cross-process world via
        ``jax.distributed.initialize`` — once per process (jax allows
        exactly one distributed runtime; a later remote gang in the
        same agent process REUSES the first world, so keep an agent
        pool's world membership stable across gangs — re-shaping the
        world needs fresh agent processes). No-op (False) for
        in-process gangs; True when the world is up (joined now or
        earlier)."""
        global _RENDEZVOUS_DONE

        if self.rendezvous is None:
            return False
        with _RENDEZVOUS_LOCK:
            if _RENDEZVOUS_DONE:
                return True
            process_id = self.process_id
            if process_id is None:
                raise RuntimeError(
                    "gang rendezvous info names no process id for "
                    "partition {!r} (process_ids: {})".format(
                        self.partition,
                        self.rendezvous.get("process_ids")))
            import jax

            jax.distributed.initialize(
                coordinator_address=self.rendezvous["coordinator"],
                num_processes=int(self.rendezvous["num_processes"]),
                process_id=process_id)
            _RENDEZVOUS_DONE = True
        return True

    def devices(self):
        """The gang's jax devices, in chip order (runner ≈ chip: chip i
        is ``jax.devices()[i]`` on an in-process fleet / CPU proxy; in a
        rendezvous'd remote gang ``jax.devices()`` is the GLOBAL device
        list, same indexing contract across every member process)."""
        import jax

        self.ensure_rendezvous()
        devs = jax.devices()
        return [devs[c] for c in self.chips]

    def build_mesh(self):
        """Named mesh over the gang's contiguous device slice."""
        from maggy_tpu.parallel.mesh import slice_mesh

        self.ensure_rendezvous()
        return slice_mesh(self.chips, self.mesh_shape)

    def sharding_env(self):
        from maggy_tpu.parallel.mesh import ShardingEnv

        return ShardingEnv(self.build_mesh())

    def to_dict(self) -> Dict[str, Any]:
        out = {"chips": list(self.chips), "members": list(self.members),
               "leader": self.leader, "mesh": dict(self.mesh_shape),
               "strategy": self.strategy}
        if self.rendezvous is not None:
            out["rendezvous"] = dict(self.rendezvous)
        return out


# ------------------------------------------------------------------ placer


def contiguous_windows(total: int, size: int,
                       taken: Set[int]) -> List[List[int]]:
    """Every contiguous ``size``-chip window on a ``total``-chip line
    that avoids ``taken`` — the one shared piece of topology geometry."""
    return [list(range(s, s + size))
            for s in range(0, total - size + 1)
            if not any(c in taken for c in range(s, s + size))]


def aligned_windows(total: int, size: int,
                    taken: Set[int]) -> List[List[int]]:
    """``contiguous_windows`` preferring size-ALIGNED starts when any
    exist (aligned blocks tile: two 4-gangs on 8 chips can never strand
    2+2 chips between them). ``GangPlacer`` and
    ``FleetScheduler.request_gang`` both select from these windows, each
    with its own cost key."""
    windows = contiguous_windows(total, size, taken)
    return [w for w in windows if w[0] % size == 0] or windows


class GangPlacer:
    """Topology-aware packer: assigns gangs best-fit aligned contiguous
    chip blocks and journals every decision as a ``pack`` event.

    The chip line models the pod slice (consecutive ids = ICI
    neighbors). Placement policy, in order:

    1. among fully FREE windows, pick the best fit (the one inside the
       smallest maximal free run: big free runs are preserved for bigger
       gangs), size-aligned starts first (aligned blocks tile, so two
       4-gangs on 8 chips can never strand 2+2 chips between them) —
       but a free UNALIGNED window still beats waiting on a busy chip;
    2. if no free window exists but enough chips are free in total, that
       is a FRAGMENTATION STALL — journaled — and the gang reserves the
       (aligned-preferred) window with the fewest busy chips so the
       block drains toward assembly as those trials finish;
    3. if fewer than ``size`` chips are free at all, the same
       fewest-busy window is reserved (gang scheduling: members are
       conscripted as they free up).

    Reserved chips are excluded from other gangs' windows; the driver
    additionally stops handing 1-chip work to runners inside a reserved
    block (skipped-but-retained), which is what makes the reservation
    drain instead of churn.
    """

    def __init__(self, total_chips: int, telemetry=None):
        self.total_chips = int(total_chips)
        self.telemetry = telemetry
        self._lock = threading.Lock()
        # key (trial id) -> ordered chip block. A reservation persists
        # from reserve() until release(): reserved -> assembled is the
        # driver's business, the placer only owns the geometry.
        self._blocks: Dict[str, List[int]] = {}  # guarded-by: _lock
        self.stalls = 0  # guarded-by: _lock
        self._event("pack", op="init", chips=self.total_chips)

    def _event(self, kind: str, **fields: Any) -> None:
        telem = self.telemetry
        if telem is not None:
            telem.event(kind, **fields)

    def block_of(self, key: str) -> Optional[List[int]]:
        with self._lock:
            block = self._blocks.get(key)
            return list(block) if block is not None else None

    def reserved_chips(self) -> Set[int]:
        with self._lock:
            return {c for block in self._blocks.values() for c in block}

    def owner_of(self, chip: int) -> Optional[str]:
        """Which gang (trial id) reserved this chip, or None."""
        with self._lock:
            for key, block in self._blocks.items():
                if chip in block:
                    return key
        return None

    def reserve(self, key: str, size: int, free: Set[int],
                avoid: Optional[Set[int]] = None) -> Optional[List[int]]:
        """Reserve a contiguous ``size``-chip block for gang ``key``.
        ``free`` is the set of chips idle right now (registered, no
        trial, no hold); ``avoid`` chips are DEAD (silent/released
        runners) and excluded from every window — a block containing a
        chip that can never free would park the gang forever. Returns
        the block (existing reservations are sticky), or None when no
        admissible window exists."""
        with self._lock:
            existing = self._blocks.get(key)
            if existing is not None:
                return list(existing)
            taken = {c for k, b in self._blocks.items() for c in b}
            taken |= set(avoid or ())
            free = (set(free) - taken) & set(range(self.total_chips))
            block, stalled = self._choose_locked(size, free, taken)
            if block is None:
                return None
            self._blocks[key] = block
            if stalled:
                self.stalls += 1
                self._event("pack", op="stall", gang=key, need=size,
                            free=len(free))
            self._event("pack", op="reserve", gang=key, block=block,
                        free=sorted(free & set(block)),
                        busy=sorted(set(block) - free))
            return list(block)

    # locked-by: _lock
    def _choose_locked(self, size: int, free: Set[int],
                       taken: Set[int]) -> Tuple[Optional[List[int]], bool]:
        windows = contiguous_windows(self.total_chips, size, taken)
        if not windows:
            return None, False
        aligned = [w for w in windows if w[0] % size == 0] or windows

        # Best fit: the free window whose surrounding maximal free run
        # is smallest (preserve big runs for bigger gangs).
        def run_len(w):
            lo = w[0]
            while lo - 1 in free and lo - 1 not in taken:
                lo -= 1
            hi = w[-1]
            while hi + 1 in free and hi + 1 not in taken:
                hi += 1
            return hi - lo + 1

        # A fully free window assembles NOW: aligned windows tile best,
        # but a free UNALIGNED window still beats stalling behind a busy
        # chip inside an aligned one.
        for cands in (aligned, windows):
            free_runs = [w for w in cands if all(c in free for c in w)]
            if free_runs:
                return min(free_runs,
                           key=lambda w: (run_len(w), w[0])), False
        # No fully free window anywhere: reserve the aligned-preferred
        # one with fewest busy chips (it drains fastest). A fragmentation
        # stall is the specific case where enough chips are free overall
        # but scattered.
        stalled = len(free) >= size
        best = min(aligned,
                   key=lambda w: (sum(1 for c in w if c not in free), w[0]))
        return best, stalled

    def release(self, key: str, reason: str = "released") -> None:
        with self._lock:
            block = self._blocks.pop(key, None)
        if block is not None:
            self._event("pack", op="release", gang=key, block=block,
                        why=reason)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"chips": self.total_chips, "stalls": self.stalls,
                    "blocks": {k: list(b) for k, b in self._blocks.items()}}


# ------------------------------------------------------------------ replay


def replay_pack(events: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """Pure replay of one journal's packing record: chip-seconds
    utilization over the sweep window, fragmentation stalls, and gang
    assembly latency. Same journal, same numbers — bench.py's
    ``detail.pack`` is exactly this call.

    Busy accounting: a gang trial occupies ``len(chips)`` chips from its
    ``gang_assembled`` edge to ``gang_released``; a 1-chip trial
    occupies one from ``running`` to ``finalized``. The window is the
    experiment's first-busy to last-idle edge, so an empty tail doesn't
    dilute the number.
    """
    from maggy_tpu.telemetry.spans import _dist_stats

    chips_total = None
    stalls = 0
    reserves = 0
    gang_open: Dict[str, Tuple[float, int]] = {}
    busy_intervals: List[Tuple[float, float, int]] = []  # (t0, t1, width)
    run_open: Dict[str, float] = {}
    gang_trials: Set[str] = set()
    waiting_since: Dict[str, float] = {}
    assembly_ms: List[float] = []
    gangs_assembled = 0
    for ev in events:
        kind, t = ev.get("ev"), ev.get("t")
        if kind == "pack":
            op = ev.get("op")
            if op == "init" and ev.get("chips") is not None:
                chips_total = int(ev["chips"])
            elif op == "stall":
                stalls += 1
            elif op == "reserve":
                reserves += 1
                if ev.get("gang") is not None and t is not None:
                    waiting_since.setdefault(ev["gang"], t)
            continue
        if kind != "trial" or t is None:
            continue
        trial, phase = ev.get("trial"), ev.get("phase")
        if trial is None:
            continue
        if phase == "gang_assembled":
            gang_trials.add(trial)
            gangs_assembled += 1
            width = len(ev.get("chips") or ev.get("members") or []) or 1
            gang_open[trial] = (t, width)
            t0 = waiting_since.pop(trial, None)
            if t0 is not None:
                assembly_ms.append((t - t0) * 1e3)
        elif phase == "gang_released":
            opened = gang_open.pop(trial, None)
            if opened is not None:
                busy_intervals.append((opened[0], t, opened[1]))
        elif phase == "running":
            run_open.setdefault(trial, t)
        elif phase == "finalized":
            t0 = run_open.pop(trial, None)
            if t0 is not None and trial not in gang_trials:
                busy_intervals.append((t0, t, 1))
    # A journal ending mid-gang (crash) still counts the open interval.
    last_t = max([t1 for _, t1, _ in busy_intervals] or [0.0])
    for trial, (t0, width) in gang_open.items():
        busy_intervals.append((t0, max(t0, last_t), width))
    out: Dict[str, Any] = {
        "chips": chips_total,
        "gangs_assembled": gangs_assembled,
        "fragmentation_stalls": stalls,
        "reservations": reserves,
        "assembly_latency": _dist_stats(assembly_ms),
    }
    if busy_intervals and chips_total:
        w0 = min(t0 for t0, _, _ in busy_intervals)
        w1 = max(t1 for _, t1, _ in busy_intervals)
        busy = sum((t1 - t0) * width for t0, t1, width in busy_intervals)
        if w1 > w0:
            out["window_s"] = round(w1 - w0, 3)
            out["busy_chip_seconds"] = round(busy, 3)
            out["chip_seconds_utilization"] = round(
                busy / (chips_total * (w1 - w0)), 3)
    return out


# -------------------------------------------------------------- pack soak


def gang_train_fn(lr, budget=1, gang=None, reporter=None, ctx=None):
    """The mixed-sweep gang trial: a tiny sharded MLP trained through
    ``parallel/mesh.py`` + ``parallel/sharding.py`` over the gang's
    contiguous device slice (1-chip trials run the same program on one
    device). Deterministic in (lr, gang shape) and independent of WHICH
    chips the placer picked, so a gang trial's final loss is directly
    comparable to the single-process sharded reference — the MULTICHIP
    dryrun parity check. ``budget`` only selects the gang size (via
    chips_per_budget); it does not scale the work, so mixed-size trials
    have comparable durations and the utilization number reflects
    packing, not workload skew."""
    del budget, gang  # gang geometry arrives through ctx.gang
    g = ctx.gang.to_dict() if ctx is not None and ctx.gang is not None \
        else None
    return {"metric": reference_gang_loss(lr, g, reporter=reporter)}


def reference_gang_loss(lr, gang: Optional[Dict[str, Any]] = None,
                        reporter=None, steps: int = 4) -> float:
    """Single-process sharded reference: the exact computation a gang
    leader runs, callable standalone (same mesh axes over the leading
    jax devices) so tests can assert gang-vs-reference parity to
    numerical tolerance."""
    import time as _time

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from maggy_tpu.parallel.mesh import make_mesh
    from maggy_tpu.parallel.sharding import batch_sharding, shard_params

    if gang and isinstance(gang.get("chips"), list):
        # A GangContext dict: mesh over exactly those chips.
        devs = [jax.devices()[c] for c in gang["chips"]]
        mesh = make_mesh(dict(gang.get("mesh") or {}), devices=devs)
        strategy = gang.get("strategy", "dp")
    else:
        spec = GangSpec.from_value(gang) if gang else GangSpec(1)
        devs = jax.devices()[:spec.chips]
        mesh = make_mesh(spec.mesh, devices=devs)
        strategy = spec.strategy
    rng = np.random.default_rng(0)
    w1 = jnp.asarray(rng.normal(size=(16, 32)) * 0.1, jnp.float32)
    w2 = jnp.asarray(rng.normal(size=(32, 16)) * 0.1, jnp.float32)
    params = {"w1": w1, "w2": w2}
    x = jnp.asarray(rng.normal(size=(8 * 4, 16)), jnp.float32)
    y = jnp.asarray(rng.normal(size=(8 * 4, 16)), jnp.float32)
    tx = optax.sgd(float(lr))
    with mesh:
        shardings = shard_params(mesh, params, strategy)
        params = jax.tree_util.tree_map(
            lambda p, s: jax.device_put(p, s), params, shardings)
        batch_sh = batch_sharding(mesh, ndim=2)
        x = jax.device_put(x, batch_sh)
        y = jax.device_put(y, batch_sh)
        opt_state = tx.init(params)

        @jax.jit
        def step(params, opt_state, x, y):
            def loss_fn(p):
                h = jnp.tanh(x @ p["w1"])
                return jnp.mean((h @ p["w2"] - y) ** 2)

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state2 = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state2, loss

        loss = None
        for i in range(steps):
            params, opt_state, loss = step(params, opt_state, x, y)
            if reporter is not None:
                reporter.broadcast(-loss, step=i)
                # Pace the trial so heartbeats land mid-trial and mixed
                # sizes have comparable durations (packing soak realism).
                _time.sleep(0.04)
        return -float(loss)


def run_pack_soak(num_trials: int = 12, gang_chips: int = 4,
                  workers: int = 8, base_dir: Optional[str] = None,
                  seed: int = 7,
                  utilization_gate: float = 0.7) -> Dict[str, Any]:
    """The acceptance scenario: one mixed ASHA sweep — rung-0 trials on
    1 chip, promotions on ``gang_chips``-chip fsdp gangs — on a
    ``workers``-runner thread fleet over the 8-fake-device CPU proxy.
    The budget axis selects the gang size via ``chips_per_budget``
    (GangSpec values), exactly the headline "1-chip CNN ASHA trials +
    N-chip sharded trials on one fleet" shape. Returns the
    journal-replayed pack report plus the parity check (every gang
    trial's final loss vs the single-process sharded reference) and the
    invariant verdicts (no scheduling deadlock = experiment completed;
    chip-seconds utilization >= ``utilization_gate``)."""
    import glob
    import json as _json
    import os
    import tempfile

    import jax

    from maggy_tpu import OptimizationConfig, Searchspace, experiment
    from maggy_tpu.optimizers import Asha
    from maggy_tpu.telemetry import JOURNAL_NAME, read_events

    if jax.device_count() < workers:
        raise RuntimeError(
            "pack soak needs >= {} jax devices (runner ≈ chip by index) "
            "but the backend has {}; set XLA_FLAGS=--xla_force_host_"
            "platform_device_count={} before jax initializes".format(
                workers, jax.device_count(), workers))

    base_dir = base_dir or tempfile.mkdtemp(prefix="maggy_pack_")
    chips_map = {1: GangSpec(1),
                 gang_chips: GangSpec(gang_chips, strategy="fsdp")}
    config = OptimizationConfig(
        name="pack_soak", num_trials=num_trials,
        optimizer=Asha(reduction_factor=gang_chips, resource_min=1,
                       resource_max=gang_chips, seed=seed),
        searchspace=Searchspace(lr=("DOUBLE", [0.05, 0.2])),
        direction="max", num_workers=workers, pool="thread",
        hb_interval=0.05, seed=seed, es_policy="none",
        chips_per_budget=chips_map,
        experiment_dir=base_dir,
    )
    result = experiment.lagom(gang_train_fn, config)
    exp_dirs = sorted(d for d in glob.glob(os.path.join(base_dir, "*"))
                      if os.path.isdir(d))
    journal = os.path.join(exp_dirs[-1], JOURNAL_NAME)
    events = read_events(journal)
    pack = replay_pack(events)
    # Parity: each finalized gang trial's metric vs the sharded
    # single-process reference for its declared gang shape.
    parity = []
    for td in glob.glob(os.path.join(exp_dirs[-1], "*", "trial.json")):
        with open(td) as f:
            d = _json.load(f)
        budget = (d.get("params") or {}).get("budget")
        spec = chips_map.get(budget)
        if d.get("final_metric") is None or spec is None or spec.chips <= 1:
            continue
        ref = reference_gang_loss(d["params"]["lr"], spec.to_dict())
        parity.append({"trial": d.get("id"),
                       "metric": d["final_metric"], "reference": ref,
                       "abs_err": abs(d["final_metric"] - ref)})
    violations: List[str] = []
    if not result.get("num_trials"):
        violations.append("sweep finalized zero trials")
    util = pack.get("chip_seconds_utilization")
    if util is None or util < utilization_gate:
        violations.append(
            "chip-seconds utilization {} below the {} gate".format(
                util, utilization_gate))
    for p in parity:
        if p["abs_err"] > 1e-4:
            violations.append(
                "gang/reference divergence on {}: |{} - {}| = {}".format(
                    p["trial"], p["metric"], p["reference"], p["abs_err"]))
    if pack.get("gangs_assembled", 0) < 1:
        violations.append("no gang trial ever assembled")
    if not parity:
        violations.append("no finalized gang trial to parity-check")
    return {"ok": not violations, "violations": violations, "pack": pack,
            "parity": parity, "journal": journal,
            "result": {"num_trials": result.get("num_trials"),
                       "best_val": result.get("best_val")}}
