"""Model zoo: Flax models for the baseline configs (BASELINE.md).

- `mnist_cnn`: MNIST Keras-CNN analogue (README example / random-search HPO)
- `resnet`: ResNet for CIFAR-10 (ASHA sweep config)
- `bert`: BERT-base-style encoder (GLUE fine-tune HPO config)
- `llama`: Llama-style decoder + LoRA (the LoRA-sweep config; flagship)
- `surgery`: ablatable-module helpers for LOCO model surgery
"""

from maggy_tpu.models.mnist_cnn import MnistCNN, MnistMLP
from maggy_tpu.models.resnet import ResNet
from maggy_tpu.models.bert import BertEncoder, BertConfig
from maggy_tpu.models.llama import Llama, LlamaConfig
from maggy_tpu.models.moe import MoEMLP
from maggy_tpu.models.vit import ViT, ViTConfig

__all__ = ["MnistCNN", "MnistMLP", "ResNet", "BertEncoder", "BertConfig",
           "Llama", "LlamaConfig", "MoEMLP", "ViT", "ViTConfig"]
