"""BERT-style encoder (BASELINE.md config 4: "BERT-base GLUE fine-tune HPO").

Green-field Flax implementation: pre-LN transformer encoder with learned
positional embeddings and a pooled classification head, bfloat16 activations,
logically-partitioned weights (same rule table as the Llama model) so it
shards on a 4-chip "model" axis per the baseline config.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from maggy_tpu.models.llama import EMBED, HEADS, MLP, VOCAB
from maggy_tpu.ops.attention import multi_head_attention


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_dim: int = 768
    intermediate_dim: int = 3072
    num_layers: int = 12
    num_heads: int = 12
    max_seq_len: int = 512
    num_classes: int = 2
    dropout: float = 0.1
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @staticmethod
    def tiny(num_classes: int = 2) -> "BertConfig":
        return BertConfig(vocab_size=128, hidden_dim=32, intermediate_dim=64,
                          num_layers=2, num_heads=2, max_seq_len=64,
                          num_classes=num_classes, dropout=0.0)

    @staticmethod
    def base(num_classes: int = 2) -> "BertConfig":
        return BertConfig(num_classes=num_classes)


def _dense(features, axes, cfg, name):
    return nn.Dense(
        features, dtype=cfg.dtype, param_dtype=cfg.param_dtype, name=name,
        kernel_init=nn.with_logical_partitioning(
            nn.initializers.normal(0.02), axes),
        bias_init=nn.with_logical_partitioning(
            nn.initializers.zeros_init(), (axes[1],)),
    )


class EncoderLayer(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, x, pad_mask, train: bool = False):
        cfg = self.cfg
        B, S, _ = x.shape
        head_dim = cfg.hidden_dim // cfg.num_heads
        h = nn.LayerNorm(dtype=jnp.float32, name="ln_attn")(x).astype(cfg.dtype)
        q = _dense(cfg.hidden_dim, (EMBED, HEADS), cfg, "q_proj")(h)
        k = _dense(cfg.hidden_dim, (EMBED, HEADS), cfg, "k_proj")(h)
        v = _dense(cfg.hidden_dim, (EMBED, HEADS), cfg, "v_proj")(h)
        shape4 = (B, S, cfg.num_heads, head_dim)
        att = multi_head_attention(
            q.reshape(shape4), k.reshape(shape4), v.reshape(shape4),
            causal=False, mask=pad_mask[:, None, None, :])
        att = att.reshape(B, S, cfg.hidden_dim)
        att = _dense(cfg.hidden_dim, (HEADS, EMBED), cfg, "o_proj")(att)
        if cfg.dropout > 0:
            att = nn.Dropout(cfg.dropout, deterministic=not train)(att)
        x = x + att
        h = nn.LayerNorm(dtype=jnp.float32, name="ln_mlp")(x).astype(cfg.dtype)
        h = _dense(cfg.intermediate_dim, (EMBED, MLP), cfg, "fc_in")(h)
        h = nn.gelu(h)
        h = _dense(cfg.hidden_dim, (MLP, EMBED), cfg, "fc_out")(h)
        if cfg.dropout > 0:
            h = nn.Dropout(cfg.dropout, deterministic=not train)(h)
        return x + h


class BertEncoder(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, tokens, attention_mask=None, train: bool = False):
        cfg = self.cfg
        B, S = tokens.shape
        if attention_mask is None:
            attention_mask = jnp.ones((B, S), bool)
        tok_emb = self.param("tok_embedding", nn.with_logical_partitioning(
            nn.initializers.normal(0.02), (VOCAB, EMBED)),
            (cfg.vocab_size, cfg.hidden_dim), cfg.param_dtype)
        pos_emb = self.param("pos_embedding", nn.with_logical_partitioning(
            nn.initializers.normal(0.02), (None, EMBED)),
            (cfg.max_seq_len, cfg.hidden_dim), cfg.param_dtype)
        x = tok_emb.astype(cfg.dtype)[tokens] + pos_emb[None, :S].astype(cfg.dtype)
        for i in range(cfg.num_layers):
            x = EncoderLayer(cfg, name="layer_{}".format(i))(
                x, attention_mask.astype(bool), train=train)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_final")(x)
        # [CLS] pooling + classification head (GLUE fine-tune shape).
        # (EMBED, None), not (EMBED, EMBED): one PartitionSpec must not name
        # the same mesh axis twice under fsdp strategies.
        pooled = nn.tanh(_dense(cfg.hidden_dim, (EMBED, None), cfg, "pooler")(
            x[:, 0].astype(cfg.dtype)))
        return _dense(cfg.num_classes, (EMBED, None), cfg, "classifier")(
            pooled).astype(jnp.float32)
