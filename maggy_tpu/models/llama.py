"""Llama-style decoder-only transformer with optional LoRA adapters.

The flagship model (BASELINE.md config 5: "Llama-3-8B LoRA hyperparameter
sweep"). The reference contains no model code at all (SURVEY.md §5.7) — this
is green-field TPU-first design:

- bfloat16 activations; fp32 params + softmax accumulations (MXU-friendly)
- RMSNorm + RoPE + SwiGLU + grouped-query attention (Llama-3 architecture)
- every weight created with `nn.with_logical_partitioning`, so one
  `logical_axis_rules` table maps the model onto any dp/fsdp/tp mesh
- attention dispatches to the Pallas flash kernel on TPU (ops/attention.py),
  falling back to an XLA softmax path elsewhere
- LoRA: frozen base + low-rank adapters on q/k/v/o, the idiomatic target for
  hyperparameter sweeps over (rank, alpha, lr)
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from flax.linen import partitioning as nn_partitioning

from maggy_tpu.ops.attention import multi_head_attention

# Logical axis names -> mesh axes (see parallel/sharding.LOGICAL_RULES).
EMBED = "embed"
MLP = "mlp"
HEADS = "heads"
KV = "kv"
VOCAB = "vocab"


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_dim: int = 4096
    intermediate_dim: int = 14336
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    head_dim: int = 128
    max_seq_len: int = 8192
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    # LoRA: rank 0 disables adapters.
    lora_rank: int = 0
    lora_alpha: float = 16.0
    remat: bool = True
    # Sequence/context parallelism: attention_impl="ring" runs blockwise
    # ring attention over ``seq_mesh``'s ``seq_axis`` (Q/K/V sharded on the
    # sequence dim, K/V shards circulated via ppermute over ICI). "auto"
    # dispatches to the Pallas flash kernel / XLA reference path.
    attention_impl: str = "auto"
    seq_axis: str = "seq"
    seq_mesh: Any = None
    # Mixture-of-experts: num_experts > 0 replaces the dense MLP with a
    # top-k routed MoE MLP (experts sharded over the "expert" mesh axis).
    num_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 2.0

    @staticmethod
    def tiny(vocab_size: int = 256, lora_rank: int = 0) -> "LlamaConfig":
        """Test-size config: same code path, toy shapes."""
        return LlamaConfig(
            vocab_size=vocab_size, hidden_dim=64, intermediate_dim=128,
            num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
            max_seq_len=128, lora_rank=lora_rank, remat=False,
        )

    @staticmethod
    def llama3_8b(lora_rank: int = 16) -> "LlamaConfig":
        # 8.03B params: the Llama-3 128k vocabulary, not Llama-2's 32k.
        return LlamaConfig(vocab_size=128256, lora_rank=lora_rank)


def _rms_norm(x, weight, eps):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + eps)).astype(x.dtype) * weight


class RMSNorm(nn.Module):
    eps: float = 1e-5
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        w = self.param("scale", nn.with_logical_partitioning(
            nn.initializers.ones_init(), (EMBED,)), (x.shape[-1],), self.param_dtype)
        return _rms_norm(x, w.astype(x.dtype), self.eps)


def rope(x: jnp.ndarray, positions: jnp.ndarray, theta: float) -> jnp.ndarray:
    """Rotary position embedding over the last (head_dim) axis.

    x: [B, S, H, D]; positions: [B, S].
    """
    d = x.shape[-1]
    freqs = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B, S, D/2]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


class LoRADense(nn.Module):
    """Dense with an optional frozen-base + low-rank adapter.

    Adapter params are the `lora_a`/`lora_b` leaves of the params tree;
    `train.lora.only_lora(tx)` masks an optimizer so only they train (and
    only they carry optimizer state — the 8B-scale memory win).
    """

    features: int
    kernel_axes: Tuple[str, str]
    lora_rank: int = 0
    lora_alpha: float = 16.0
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    use_bias: bool = False

    @nn.compact
    def __call__(self, x):
        in_dim = x.shape[-1]
        kernel = self.param("kernel", nn.with_logical_partitioning(
            nn.initializers.lecun_normal(), self.kernel_axes),
            (in_dim, self.features), self.param_dtype)
        y = jnp.dot(x, kernel.astype(self.dtype))
        if self.lora_rank > 0:
            a = self.param("lora_a", nn.with_logical_partitioning(
                nn.initializers.normal(0.02), (self.kernel_axes[0], None)),
                (in_dim, self.lora_rank), self.param_dtype)
            b = self.param("lora_b", nn.with_logical_partitioning(
                nn.initializers.zeros_init(), (None, self.kernel_axes[1])),
                (self.lora_rank, self.features), self.param_dtype)
            scale = self.lora_alpha / self.lora_rank
            y = y + jnp.dot(jnp.dot(x, a.astype(self.dtype)),
                            b.astype(self.dtype)) * scale
        if self.use_bias:
            bias = self.param("bias", nn.with_logical_partitioning(
                nn.initializers.zeros_init(), (self.kernel_axes[1],)),
                (self.features,), self.param_dtype)
            y = y + bias.astype(self.dtype)
        return y


class Attention(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x, positions, mask=None):
        cfg = self.cfg
        dense = lambda feat, axes, name: LoRADense(  # noqa: E731
            feat, axes, lora_rank=cfg.lora_rank, lora_alpha=cfg.lora_alpha,
            dtype=cfg.dtype, param_dtype=cfg.param_dtype, name=name)
        B, S, _ = x.shape
        q = dense(cfg.num_heads * cfg.head_dim, (EMBED, HEADS), "q_proj")(x)
        k = dense(cfg.num_kv_heads * cfg.head_dim, (EMBED, KV), "k_proj")(x)
        v = dense(cfg.num_kv_heads * cfg.head_dim, (EMBED, KV), "v_proj")(x)
        q = q.reshape(B, S, cfg.num_heads, cfg.head_dim)
        k = k.reshape(B, S, cfg.num_kv_heads, cfg.head_dim)
        v = v.reshape(B, S, cfg.num_kv_heads, cfg.head_dim)
        q = rope(q, positions, cfg.rope_theta)
        k = rope(k, positions, cfg.rope_theta)
        if cfg.attention_impl == "ring":
            if cfg.seq_mesh is None:
                raise ValueError(
                    "attention_impl='ring' requires cfg.seq_mesh (a Mesh "
                    "with a '{}' axis)".format(cfg.seq_axis))
            if mask is not None:
                raise ValueError(
                    "attention_impl='ring' supports only causal masking; "
                    "got an explicit mask")
            from maggy_tpu.parallel.ring_attention import ring_attention

            # GQA rides the ring natively: k/v rotate with Hkv heads and
            # the flash path indexes the shared kv head per group.
            out = ring_attention(q, k, v, cfg.seq_mesh,
                                 axis_name=cfg.seq_axis, causal=True)
        else:
            out = multi_head_attention(q, k, v, causal=True, mask=mask)
        out = out.reshape(B, S, cfg.num_heads * cfg.head_dim)
        return dense(cfg.hidden_dim, (HEADS, EMBED), "o_proj")(out)


class MLP(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        dense = lambda feat, axes, name: LoRADense(  # noqa: E731
            feat, axes, lora_rank=0, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype, name=name)
        gate = dense(cfg.intermediate_dim, (EMBED, MLP), "gate_proj")(x)
        up = dense(cfg.intermediate_dim, (EMBED, MLP), "up_proj")(x)
        return dense(cfg.hidden_dim, (MLP, EMBED), "down_proj")(
            nn.silu(gate) * up)


class DecoderLayer(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x, positions, mask=None):
        cfg = self.cfg
        h = x + Attention(cfg, name="attn")(
            RMSNorm(cfg.norm_eps, cfg.param_dtype, name="attn_norm")(x),
            positions, mask)
        if cfg.num_experts > 0:
            from maggy_tpu.models.moe import MoEMLP

            mlp = MoEMLP(
                hidden_dim=cfg.hidden_dim,
                intermediate_dim=cfg.intermediate_dim,
                num_experts=cfg.num_experts, top_k=cfg.moe_top_k,
                capacity_factor=cfg.moe_capacity_factor, dtype=cfg.dtype,
                param_dtype=cfg.param_dtype, name="moe_mlp")
        else:
            mlp = MLP(cfg, name="mlp")
        return h + mlp(
            RMSNorm(cfg.norm_eps, cfg.param_dtype, name="mlp_norm")(h))


class Llama(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, tokens, positions=None, return_hidden=False):
        cfg = self.cfg
        if positions is None:
            positions = jnp.broadcast_to(
                jnp.arange(tokens.shape[1]), tokens.shape)
        emb = self.param("embedding", nn.with_logical_partitioning(
            nn.initializers.normal(0.02), (VOCAB, EMBED)),
            (cfg.vocab_size, cfg.hidden_dim), cfg.param_dtype)
        x = emb.astype(cfg.dtype)[tokens]
        layer_cls = DecoderLayer
        if cfg.remat:
            # Rematerialize each layer: trade FLOPs for HBM (activation
            # memory is the binding constraint at 8B scale).
            layer_cls = nn.remat(DecoderLayer, static_argnums=())
        for i in range(cfg.num_layers):
            x = layer_cls(cfg, name="layer_{}".format(i))(x, positions)
        x = RMSNorm(cfg.norm_eps, cfg.param_dtype, name="final_norm")(x)
        # Tied-untied choice: untied lm head (Llama-3 style).
        head = self.param("lm_head", nn.with_logical_partitioning(
            nn.initializers.normal(0.02), (EMBED, VOCAB)),
            (cfg.hidden_dim, cfg.vocab_size), cfg.param_dtype)
        if return_hidden:
            # Pre-head output for the vocab-chunked loss
            # (ops.losses.chunked_next_token_loss): at 128k vocab the full
            # [B, S, V] fp32 logits are the largest activation in the
            # model — the chunked loss never materializes them.
            return x, head
        return jnp.dot(x, head.astype(cfg.dtype)).astype(jnp.float32)
