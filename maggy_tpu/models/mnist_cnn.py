"""MNIST CNN — the README random-search example model.

Parity target: the reference's README example trains a Keras CNN whose
kernel size / pooling size / dropout are the searched hyperparameters
(`README.rst:56-84`). Flax version, hparam-parameterized the same way; NHWC
with feature counts kept MXU-friendly.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class MnistCNN(nn.Module):
    kernel_size: int = 3
    pool_size: int = 2
    dropout: float = 0.0
    features: int = 32
    num_classes: int = 10
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        k, p = self.kernel_size, self.pool_size
        x = x.astype(self.dtype)
        x = nn.Conv(self.features, (k, k), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (p, p), strides=(p, p))
        x = nn.Conv(self.features * 2, (k, k), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (p, p), strides=(p, p))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(128, dtype=self.dtype)(x)
        x = nn.relu(x)
        if self.dropout > 0:
            x = nn.Dropout(self.dropout, deterministic=not train)(x)
        return nn.Dense(self.num_classes, dtype=self.dtype)(x)


class MnistMLP(nn.Module):
    """Dense-only MNIST classifier for vectorized (K-lane vmapped)
    sweeps. Matmul/elementwise ops produce bitwise-identical per-lane
    results under ``jax.vmap`` on every backend we gate on, which the
    batched-kernel convolutions of ``MnistCNN`` do not — the vectorized
    bench and the lane-parity tests pin that property on this model."""

    features: int = 8
    num_classes: int = 10
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(self.features, dtype=self.dtype)(x)
        x = nn.tanh(x)
        return nn.Dense(self.num_classes, dtype=self.dtype)(x)
