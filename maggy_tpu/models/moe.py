"""Mixture-of-experts MLP with expert parallelism over an "expert" mesh axis.

Green-field TPU-first design (the reference has no model code, SURVEY.md
§5.7; expert parallelism is listed absent in §2.8). GShard-style top-k
routing with a static expert capacity so every shape is fixed under jit:

- router logits -> top-k experts per token, position-in-expert via cumsum
- dispatch/combine are ONE-HOT EINSUMS (dense [B,S,E,C] tensors), which XLA
  maps onto the MXU and — when the stacked expert dim of the weights is
  sharded over the "expert" mesh axis while tokens are sharded over "data" —
  lowers the dispatch into an all-to-all over ICI. No gather/scatter, no
  dynamic shapes, no sorting.
- load-balancing auxiliary loss (Shazeer et al. 2017 / GShard eq. 4) is
  sowed into the "losses" collection; train/trainer.py adds every sowed
  "losses" leaf to the objective when aux collections are enabled.

Weights are annotated with logical axis ("expert", embed, mlp) so
parallel/sharding.logical_axis_rules("..._ep") maps them onto the mesh.
"""

from __future__ import annotations

from typing import Any, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

EXPERT = "expert"


def _top_k_mask(probs: jnp.ndarray, k: int) -> jnp.ndarray:
    """[*, E] -> 0/1 mask of the k largest entries per row."""
    top_vals = jax.lax.top_k(probs, k)[0]
    thresh = top_vals[..., -1:]
    return (probs >= thresh).astype(probs.dtype)


def routing_tensors(
    router_logits: jnp.ndarray, num_experts: int, capacity: int, top_k: int
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Compute (dispatch [B,S,E,C] 0/1, combine [B,S,E,C], aux_loss).

    Tokens beyond an expert's capacity are dropped (their combine weight is
    zero — the residual connection carries them through unchanged).
    """
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    mask = _top_k_mask(probs, top_k)  # [B,S,E]
    # Position of each token within each expert's buffer (tokens ordered by
    # sequence position), counted over the flattened (B,S) token stream per
    # batch row: capacity is per (batch row, expert).
    pos = jnp.cumsum(mask, axis=1) * mask - 1.0  # [B,S,E], -1 where unrouted
    keep = (pos >= 0) & (pos < capacity)
    pos = jnp.clip(pos, 0, capacity - 1).astype(jnp.int32)
    onehot_pos = jax.nn.one_hot(pos, capacity, dtype=probs.dtype)  # [B,S,E,C]
    dispatch = onehot_pos * keep.astype(probs.dtype)[..., None]
    gates = probs * mask
    # Renormalize kept gates so the combine weights of each token sum to ~1.
    denom = jnp.sum(gates, axis=-1, keepdims=True)
    gates = gates / jnp.maximum(denom, 1e-9)
    combine = dispatch * gates[..., None]
    # Load-balancing aux loss: E * sum_e f_e * p_e  (f = fraction of tokens
    # routed to e, p = mean router prob of e). Minimized when uniform.
    f = jnp.mean(mask, axis=(0, 1))
    p = jnp.mean(probs, axis=(0, 1))
    aux_loss = num_experts * jnp.sum(f * p)
    return dispatch, combine, aux_loss


class MoEMLP(nn.Module):
    """Top-k routed SwiGLU MLP with E stacked experts.

    x: [B, S, D] -> [B, S, D]. Expert weights are stacked on a leading
    expert dim with logical axis EXPERT, so under an "..._ep" strategy each
    device holds |E|/|expert axis| experts and XLA inserts the token
    all-to-all.
    """

    hidden_dim: int
    intermediate_dim: int
    num_experts: int
    top_k: int = 2
    capacity_factor: float = 2.0
    aux_loss_weight: float = 0.01
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    embed_axis: str = "embed"
    mlp_axis: str = "mlp"

    @nn.compact
    def __call__(self, x):
        B, S, D = x.shape
        E = self.num_experts
        # A single-expert config degenerates to top-1 routing (top_k can't
        # exceed the number of experts).
        top_k = min(self.top_k, E)
        capacity = max(1, int(self.capacity_factor * S * top_k / E))

        router = self.param(
            "router", nn.with_logical_partitioning(
                nn.initializers.lecun_normal(), (self.embed_axis, EXPERT)),
            (D, E), self.param_dtype)
        logits = jnp.dot(x.astype(jnp.float32), router)  # [B,S,E]
        dispatch, combine, aux = routing_tensors(logits, E, capacity, top_k)
        self.sow("losses", "moe_aux_loss", self.aux_loss_weight * aux)

        def expert_param(name, shape, axes):
            return self.param(name, nn.with_logical_partitioning(
                nn.initializers.lecun_normal(), (EXPERT,) + axes), shape,
                self.param_dtype)

        F = self.intermediate_dim
        w_gate = expert_param("gate_proj", (E, D, F), (self.embed_axis, self.mlp_axis))
        w_up = expert_param("up_proj", (E, D, F), (self.embed_axis, self.mlp_axis))
        w_down = expert_param("down_proj", (E, F, D), (self.mlp_axis, self.embed_axis))

        dispatch = dispatch.astype(self.dtype)
        combine = combine.astype(self.dtype)
        xd = x.astype(self.dtype)
        # Dispatch: [B,S,E,C] x [B,S,D] -> [E,B,C,D] expert inputs.
        expert_in = jnp.einsum("bsec,bsd->ebcd", dispatch, xd)
        gate = jnp.einsum("ebcd,edf->ebcf", expert_in, w_gate.astype(self.dtype))
        up = jnp.einsum("ebcd,edf->ebcf", expert_in, w_up.astype(self.dtype))
        act = nn.silu(gate) * up
        expert_out = jnp.einsum("ebcf,efd->ebcd", act, w_down.astype(self.dtype))
        # Combine back to token order, weighted by the (renormalized) gates.
        return jnp.einsum("bsec,ebcd->bsd", combine, expert_out)
