"""ResNet for CIFAR-10 (BASELINE.md config 3: "ResNet-50/CIFAR-10 ASHA sweep").

Green-field Flax implementation (the reference has no model code): classic
pre-activation basic/bottleneck blocks, NHWC, bfloat16-friendly, batch-norm
statistics in fp32.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp


class BasicBlock(nn.Module):
    features: int
    strides: Tuple[int, int] = (1, 1)
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, dtype=jnp.float32)
        residual = x
        y = nn.Conv(self.features, (3, 3), self.strides, use_bias=False,
                    dtype=self.dtype)(x)
        y = norm()(y)
        y = nn.relu(y)
        y = nn.Conv(self.features, (3, 3), use_bias=False, dtype=self.dtype)(y)
        y = norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.features, (1, 1), self.strides,
                               use_bias=False, dtype=self.dtype)(residual)
            residual = norm()(residual)
        return nn.relu(residual + y)


class BottleneckBlock(nn.Module):
    features: int
    strides: Tuple[int, int] = (1, 1)
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, dtype=jnp.float32)
        residual = x
        y = nn.Conv(self.features, (1, 1), use_bias=False, dtype=self.dtype)(x)
        y = nn.relu(norm()(y))
        y = nn.Conv(self.features, (3, 3), self.strides, use_bias=False,
                    dtype=self.dtype)(y)
        y = nn.relu(norm()(y))
        y = nn.Conv(self.features * 4, (1, 1), use_bias=False, dtype=self.dtype)(y)
        y = norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.features * 4, (1, 1), self.strides,
                               use_bias=False, dtype=self.dtype)(residual)
            residual = norm()(residual)
        return nn.relu(residual + y)


STAGE_SIZES = {
    18: ([2, 2, 2, 2], BasicBlock),
    34: ([3, 4, 6, 3], BasicBlock),
    50: ([3, 4, 6, 3], BottleneckBlock),
    101: ([3, 4, 23, 3], BottleneckBlock),
}


class ResNet(nn.Module):
    depth: int = 50
    num_classes: int = 10
    width: int = 64
    dtype: Any = jnp.float32
    cifar_stem: bool = True  # 3x3 stem, no max-pool (32x32 inputs)

    @nn.compact
    def __call__(self, x, train: bool = False):
        stages, block_cls = STAGE_SIZES[self.depth]
        x = x.astype(self.dtype)
        if self.cifar_stem:
            x = nn.Conv(self.width, (3, 3), use_bias=False, dtype=self.dtype)(x)
        else:
            x = nn.Conv(self.width, (7, 7), (2, 2), use_bias=False,
                        dtype=self.dtype)(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         dtype=jnp.float32)(x)
        x = nn.relu(x)
        for i, n_blocks in enumerate(stages):
            for j in range(n_blocks):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = block_cls(self.width * 2 ** i, strides,
                              dtype=self.dtype)(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)
