"""Flax model surgery for LOCO ablation.

The reference rebuilds Keras models from json with a layer removed
(`loco.py:82-136`), never touching the first (input) or last (output) layer.
Flax modules are code, not json — so ablation works on a declarative layer
list: `AblatableSequential` skips layers whose names match the ablated set
(exact names, or prefix for prefix groups), preserving first/last.
"""

from __future__ import annotations

from typing import Any, Callable, FrozenSet, List, Sequence, Tuple

import flax.linen as nn


def filter_layers(
    names: Sequence[str], ablated: FrozenSet[str]
) -> List[str]:
    """Names surviving ablation. A spec entry matches a layer by exact name
    or as a prefix; first and last layers are always kept (reference
    `loco.py:99-134`)."""
    if not ablated:
        return list(names)
    kept = []
    for i, name in enumerate(names):
        protected = i == 0 or i == len(names) - 1
        hit = any(name == a or name.startswith(a) for a in ablated)
        if protected or not hit:
            kept.append(name)
    return kept


class AblatableSequential(nn.Module):
    """Sequential module over (name, make_layer) pairs with layer dropout by
    name/prefix. ``layers`` must be a tuple of (str, callable-returning-module)
    so the module stays hashable/comparable for Flax."""

    layers: Tuple[Tuple[str, Callable[[], nn.Module]], ...]
    ablated_layers: FrozenSet[str] = frozenset()

    @nn.compact
    def __call__(self, x, *args, **kwargs):
        names = [n for n, _ in self.layers]
        kept = set(filter_layers(names, self.ablated_layers))
        for name, make in self.layers:
            if name in kept:
                x = make()(x)
        return x


def ablatable_model_generator(layers: Sequence[Tuple[str, Callable]],
                              ablated_layers: FrozenSet[str] = frozenset()):
    """Convenience base_model_generator for AblationStudy: returns an
    AblatableSequential minus the ablated components."""
    return AblatableSequential(tuple(layers), frozenset(ablated_layers))
