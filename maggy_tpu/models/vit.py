"""Vision Transformer (ViT) classifier.

Extends the model zoo beyond the reference's CNN/torch examples with the
TPU-sweet architecture (patch embedding is one big conv that lowers to an
MXU matmul; everything else is the shared transformer encoder). Reuses
`maggy_tpu.models.bert.EncoderLayer` — pre-LN, logical partitioning — so
ViT shards under the same dp/fsdp/tp rule table as the language models.

Attention dispatch caveat: the Pallas flash kernel needs the sequence to
tile by 128, and a standard ViT's patch sequence doesn't (base/16 at 224px
is 196 patches + CLS = 197), so attention runs on the XLA reference path.
That is the right trade at these lengths — a 197x197 score matrix is tiny —
and XLA fuses it fine; pick image/patch sizes with num_patches+1 divisible
by 128 if you want the kernel.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from maggy_tpu.models.bert import BertConfig, EncoderLayer, _dense
from maggy_tpu.models.llama import EMBED


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    channels: int = 3
    hidden_dim: int = 768
    intermediate_dim: int = 3072
    num_layers: int = 12
    num_heads: int = 12
    num_classes: int = 1000
    dropout: float = 0.0
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    def encoder_cfg(self) -> BertConfig:
        """The shared EncoderLayer consumes a BertConfig; only the fields
        the layer reads matter (vocab/seq fields are unused there)."""
        return BertConfig(
            hidden_dim=self.hidden_dim,
            intermediate_dim=self.intermediate_dim,
            num_heads=self.num_heads, dropout=self.dropout,
            dtype=self.dtype, param_dtype=self.param_dtype)

    @staticmethod
    def tiny(num_classes: int = 10) -> "ViTConfig":
        return ViTConfig(image_size=32, patch_size=8, channels=3,
                         hidden_dim=32, intermediate_dim=64, num_layers=2,
                         num_heads=2, num_classes=num_classes)

    @staticmethod
    def base(num_classes: int = 1000) -> "ViTConfig":
        return ViTConfig(num_classes=num_classes)


class ViT(nn.Module):
    cfg: ViTConfig

    @nn.compact
    def __call__(self, images, train: bool = False):
        """images: [B, H, W, C] -> logits [B, num_classes]."""
        cfg = self.cfg
        B = images.shape[0]
        p = cfg.patch_size
        if images.shape[1] != cfg.image_size or images.shape[2] != cfg.image_size:
            raise ValueError(
                "Expected {0}x{0} images, got {1}x{2}".format(
                    cfg.image_size, images.shape[1], images.shape[2]))
        # Patch embedding: a stride-p conv == one [p*p*C, D] matmul per
        # patch; XLA lowers it straight onto the MXU.
        x = nn.Conv(
            cfg.hidden_dim, kernel_size=(p, p), strides=(p, p),
            dtype=cfg.dtype, param_dtype=cfg.param_dtype, name="patch_embed",
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.normal(0.02), (None, None, None, EMBED)),
            bias_init=nn.with_logical_partitioning(
                nn.initializers.zeros_init(), (EMBED,)),
        )(images.astype(cfg.dtype))
        x = x.reshape(B, cfg.num_patches, cfg.hidden_dim)
        cls = self.param(
            "cls_token", nn.with_logical_partitioning(
                nn.initializers.zeros_init(), (None, None, EMBED)),
            (1, 1, cfg.hidden_dim), cfg.param_dtype)
        x = jnp.concatenate(
            [jnp.broadcast_to(cls.astype(cfg.dtype),
                              (B, 1, cfg.hidden_dim)), x], axis=1)
        pos = self.param(
            "pos_embedding", nn.with_logical_partitioning(
                nn.initializers.normal(0.02), (None, EMBED)),
            (cfg.num_patches + 1, cfg.hidden_dim), cfg.param_dtype)
        x = x + pos[None].astype(cfg.dtype)
        enc = self.cfg.encoder_cfg()
        mask = jnp.ones((B, cfg.num_patches + 1), bool)
        for i in range(cfg.num_layers):
            x = EncoderLayer(enc, name="layer_{}".format(i))(
                x, mask, train=train)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_final")(x)
        return _dense(cfg.num_classes, (EMBED, None), enc, "head")(
            x[:, 0].astype(cfg.dtype)).astype(jnp.float32)
