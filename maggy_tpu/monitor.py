"""Experiment monitor: ``python -m maggy_tpu.monitor``.

The reference streams progress to Jupyter by having sparkmagic poll the
driver's LOG message (`rpc.py:369-377`, `driver.py:167-175`). The TPU-native
equivalent is this standalone watcher: it polls the same LOG RPC over the
control plane — from any machine that can reach the driver — and renders a
progress snapshot, so long sweeps can be observed without attaching to the
driver process.

    python -m maggy_tpu.monitor --ticket /shared/exp_dir/runner_ticket.json
    python -m maggy_tpu.monitor --driver 10.0.0.2:41234 --secret-file s.txt --once
    python -m maggy_tpu.monitor --ticket .../runner_ticket.json --telem
    python -m maggy_tpu.monitor --ticket .../runner_ticket.json --health
    python -m maggy_tpu.monitor --fleet ~/maggy_tpu_experiments/fleets/fleet

``--fleet`` watches a shared fleet (maggy_tpu.fleet) from its home dir:
per-experiment share vs configured weight, queue depth, and preemption
counts, replayed from status.json + fleet.jsonl.

``--telem`` polls the TELEM verb instead: the driver's live telemetry
snapshot (trial-span scheduling numbers + RPC service-time histograms).
``--goodput`` renders the chip-time goodput ledger over the same verb:
the experiment's goodput fraction, top badput buckets, and per-partition
held-time split (telemetry/goodput.py; docs/telemetry.md).
``--health`` renders the live health view over the same verb: the health
engine's straggler/hang/RTT flags plus per-partition runner stats (step
cadence, time-to-first-metric, heartbeat RTT, RSS) — see
docs/telemetry.md.
"""

from __future__ import annotations

import argparse
import socket
import sys
import time
from typing import Any, Dict, Tuple

from maggy_tpu import util
from maggy_tpu.core.rpc import MessageSocket


def _poll(addr: Tuple[str, int], secret: str, msg_type: str,
          timeout: float = 10.0) -> Dict[str, Any]:
    key = secret.encode() if isinstance(secret, str) else secret
    sock = socket.create_connection(addr, timeout=timeout)
    try:
        MessageSocket.send_msg(sock, {"type": msg_type}, key)
        return MessageSocket.recv_msg(sock, key)
    finally:
        sock.close()


def poll_progress(addr: Tuple[str, int], secret: str,
                  timeout: float = 10.0) -> Dict[str, Any]:
    """One LOG round trip: the driver's live progress snapshot."""
    return _poll(addr, secret, "LOG", timeout=timeout)


def poll_telemetry(addr: Tuple[str, int], secret: str,
                   timeout: float = 10.0) -> Dict[str, Any]:
    """One TELEM round trip: metrics registry + span-derived scheduling
    numbers (hand-off gap, early-stop reaction, RPC service times)."""
    return _poll(addr, secret, "TELEM", timeout=timeout)


def poll_live(base_url: str,
              timeout: float = 10.0) -> Tuple[Dict[str, Any], int,
                                              Dict[str, Any]]:
    """One scrape of the observability plane (telemetry.obs): ``(status
    document, healthz HTTP code, healthz body)``. ``base_url`` is
    ``host:port`` or a full ``http://`` URL — no secret needed, the obs
    endpoints are plain HTTP (loopback-bound by default)."""
    import json as _json
    import urllib.error
    import urllib.request

    if "//" not in base_url:
        base_url = "http://" + base_url
    base_url = base_url.rstrip("/")
    with urllib.request.urlopen(base_url + "/status",
                                timeout=timeout) as resp:
        status = _json.loads(resp.read().decode())
    try:
        with urllib.request.urlopen(base_url + "/healthz",
                                    timeout=timeout) as resp:
            return status, resp.status, _json.loads(resp.read().decode())
    except urllib.error.HTTPError as e:
        # 503 = unhealthy, still a valid, body-carrying reply.
        return status, e.code, _json.loads(e.read().decode())


def render(snap: Dict[str, Any]) -> str:
    if "num_trials" in snap:  # HPO / ablation experiment
        done = snap.get("finalized", 0)
        total = snap.get("num_trials", 0)
        parts = [util.progress_bar(done, total)]
        if snap.get("best_val") is not None:
            parts.append("best={:.6g}".format(snap["best_val"]))
        if snap.get("early_stopped"):
            parts.append("early_stopped={}".format(snap["early_stopped"]))
        return " ".join(parts)
    if "num_workers" in snap:  # distributed training
        return util.progress_bar(snap.get("workers_done", 0),
                                 snap.get("num_workers", 0)) + " workers done"
    return str({k: v for k, v in snap.items() if k != "type"})


def _fmt_dist(stats: Dict[str, Any]) -> str:
    if not stats:
        return "n/a"
    return "median {} ms / p95 {} ms (n={})".format(
        stats.get("median_ms"), stats.get("p95_ms"), stats.get("n"))


def render_telem(snap: Dict[str, Any]) -> str:
    """Multi-line view of a TELEM snapshot: the scheduling numbers the
    paper's efficiency claim rests on, plus the busiest RPC verbs."""
    if snap.get("type") == "ERR":
        return "telemetry: {}".format(snap.get("error"))
    if not snap.get("enabled", True):
        return "telemetry: disabled for this experiment"
    spans = snap.get("spans") or {}
    trials = spans.get("trials") or {}
    lines = [
        "trials: {} queued / {} finalized / {} early-stopped / {} errors"
        " / {} lost".format(trials.get("created", 0),
                            trials.get("finalized", 0),
                            trials.get("early_stopped", 0),
                            trials.get("errors", 0), trials.get("lost", 0)),
        "hand-off gap: {}".format(_fmt_dist(spans.get("handoff") or {})),
        "early-stop reaction: {}".format(
            _fmt_dist(spans.get("early_stop_reaction") or {})),
    ]
    if (spans.get("requeue_recovery") or {}).get("n"):
        # Only shown when recovery actually happened: a healthy run has
        # no requeues and the line would be noise.
        lines.append("requeue recovery: {}".format(
            _fmt_dist(spans["requeue_recovery"])))
    suggest = spans.get("suggest") or {}
    if suggest:
        # Pipelined hand-off health: how many hand-offs rode the FINAL
        # reply vs fell back to GET, and what a suggest() costs.
        lines.append(
            "hand-off pipeline: {} hits / {} misses (hit rate {}), "
            "suggest {}".format(
                suggest.get("prefetch_hits", 0),
                suggest.get("prefetch_misses", 0),
                suggest.get("hit_rate"),
                _fmt_dist(suggest.get("latency") or {})))
    comp = spans.get("compile") or {}
    if comp:
        # Compile-once hot path: how many trials rode a warm program vs
        # paid a fresh trace+compile, and what each cost.
        lines.append(
            "compile-once: {} warm / {} cold (hit rate {}), ttfm warm "
            "{} vs cold {}".format(
                comp.get("warm_hits", 0), comp.get("warm_misses", 0),
                comp.get("warm_hit_rate"),
                _fmt_dist(comp.get("ttfm_warm") or {}),
                _fmt_dist(comp.get("ttfm_cold") or {})))
        cache = comp.get("cache") or {}
        if cache:
            lines.append(
                "  xla persistent cache: {} hits / {} misses (hit rate "
                "{})".format(cache.get("hits", 0), cache.get("misses", 0),
                             cache.get("hit_rate")))
    fork = spans.get("fork") or {}
    if fork:
        # Checkpoint-forking search: promotions/exploits that RESUMED a
        # parent's checkpoint vs re-trained from scratch, and what each
        # fork saved / cost.
        lines.append(
            "forking: {} forked / {} from-scratch, {} steps saved, "
            "load {}{}".format(
                fork.get("forked", 0), fork.get("from_scratch", 0),
                fork.get("steps_saved", 0),
                _fmt_dist(fork.get("fork_load_ms") or {}),
                ", {} ckpt GC'd".format(fork["ckpt_gc"])
                if fork.get("ckpt_gc") else ""))
    hists = (snap.get("metrics") or {}).get("histograms") or {}
    rpc = sorted(((name, h) for name, h in hists.items()
                  if name.startswith("rpc.handle_ms.")),
                 key=lambda kv: -kv[1].get("count", 0))
    for name, h in rpc[:5]:
        lines.append("rpc {}: n={} p50 {} ms p95 {} ms".format(
            name[len("rpc.handle_ms."):], h.get("count"),
            h.get("p50"), h.get("p95")))
    health = snap.get("health") or {}
    if health.get("flags"):
        # One summary line; the full view lives under --health.
        lines.append("health: {} active flag(s) — run with --health for "
                     "detail".format(len(health["flags"])))
    torn = (snap.get("journal") or {}).get("torn_lines") or 0
    if torn:
        lines.append("WARNING: journal has {} torn/corrupt line(s) "
                     "(events were lost)".format(torn))
    return "\n".join(lines)


def _fmt_flag(flag: Dict[str, Any]) -> str:
    check = flag.get("check")
    pid = flag.get("partition")
    if check == "hang":
        return ("  [hang] partition {}: trial {} silent {}s "
                "({} bound {}s; thread dump journaled)".format(
                    pid, flag.get("trial"), flag.get("silent_s"),
                    flag.get("window", "steady"), flag.get("bound_s")))
    if check == "straggler":
        return ("  [straggler] partition {}: {} {} ms vs fleet median {} ms"
                " (score {})".format(
                    pid, flag.get("metric"), flag.get("value_ms"),
                    flag.get("fleet_median_ms"), flag.get("score")))
    if check == "hb_rtt":
        return ("  [hb_rtt] partition {}: heartbeat RTT {} ms vs fleet "
                "median {} ms".format(pid, flag.get("value_ms"),
                                      flag.get("fleet_median_ms")))
    return "  [{}] partition {}: {}".format(
        check, pid, {k: v for k, v in flag.items()
                     if k not in ("check", "partition")})


def render_health(snap: Dict[str, Any]) -> str:
    """Multi-line view of the TELEM snapshot's health section: active
    straggler/hang/RTT flags plus a per-partition runner-stats table."""
    if snap.get("type") == "ERR":
        return "telemetry: {}".format(snap.get("error"))
    if not snap.get("enabled", True):
        return "telemetry: disabled for this experiment"
    health = snap.get("health")
    if health is None:
        return "health: engine not running (health=False or pre-health " \
               "driver)"
    flags = health.get("flags") or []
    lines = ["health: {} active flag(s), {} raised total, {} checks "
             "run".format(len(flags), health.get("raised_total", 0),
                          health.get("checks_run", 0))]
    for flag in flags:
        lines.append(_fmt_flag(flag))
    runners = snap.get("runners") or {}
    for pid in sorted(runners, key=int):
        s = runners[pid]
        lines.append(
            "  runner {}: trial={} steps={} cadence={} ms ttfm={} ms "
            "hb_rtt={} ms rss={} MB".format(
                pid, s.get("trial"), s.get("steps"), s.get("cadence_ms"),
                s.get("ttfm_ms"), s.get("hb_rtt_ms"), s.get("rss_mb")))
    torn = (snap.get("journal") or {}).get("torn_lines") or 0
    if torn:
        lines.append("WARNING: journal has {} torn/corrupt line(s) "
                     "(events were lost)".format(torn))
    return "\n".join(lines)


def render_goodput_view(snap: Dict[str, Any]) -> str:
    """Multi-line view of the TELEM snapshot's goodput ledger: the
    fleet's goodput fraction, the top badput buckets, and each
    partition's held-time split (telemetry/goodput.py)."""
    if snap.get("type") == "ERR":
        return "telemetry: {}".format(snap.get("error"))
    if not snap.get("enabled", True):
        return "telemetry: disabled for this experiment"
    from maggy_tpu.telemetry.goodput import render_goodput

    block = (snap.get("spans") or {}).get("goodput") or {}
    return "\n".join(render_goodput(block))


def render_live(status: Dict[str, Any], healthz_code: int,
                healthz: Dict[str, Any]) -> str:
    """Multi-line view of one obs /status + /healthz scrape: a header
    per registered experiment (progress, backlog, reservations, gangs,
    fleet share) above the familiar telemetry block."""
    lines = ["healthz: {} ({})".format(
        healthz_code, healthz.get("status", "?"))]
    for flags in (e.get("flags") or []
                  for e in (healthz.get("experiments") or {}).values()):
        for flag in flags:
            lines.append(_fmt_flag(flag))
    experiments = status.get("experiments") or {}
    if not experiments:
        lines.append("no experiments registered")
    for key in sorted(experiments):
        doc = experiments[key]
        st = doc.get("status") or {}
        progress = st.get("progress") or {}
        lines.append("== {} ({}) ==".format(
            (doc.get("labels") or {}).get("experiment", key), key))
        if "num_trials" in progress or "finalized" in progress:
            lines.append("progress: {}/{} finalized, best={}".format(
                progress.get("finalized", "?"),
                progress.get("num_trials", "?"),
                progress.get("best_val")))
        store = st.get("store") or {}
        if store:
            lines.append(
                "store: {} trials / {} finalized / {} requeued / {} "
                "parked / {} gang-waiting".format(
                    store.get("trials", 0), store.get("finalized", 0),
                    store.get("requeue", 0), store.get("parked", 0),
                    store.get("gang_wait", 0)))
        reservations = st.get("reservations") or {}
        if reservations:
            busy = sum(1 for r in reservations.values() if r.get("trial"))
            lines.append("runners: {} registered, {} busy".format(
                len(reservations), busy))
        gangs = st.get("gangs") or {}
        for tid, g in sorted(gangs.items()):
            lines.append("gang {}: {} chips, members {}, leader {}{}".format(
                tid, g.get("chips"), g.get("members"), g.get("leader"),
                " [revoking]" if g.get("revoking") else ""))
        fleet = st.get("fleet") or {}
        if fleet:
            lines.append("fleet: {} runner(s), {} active, queue depth "
                         "{}".format(fleet.get("fleet_size"),
                                     fleet.get("active"),
                                     fleet.get("queue_depth")))
        telem = doc.get("telem") or {}
        if telem.get("enabled"):
            lines.extend("  " + ln for ln in render_telem(telem).split("\n"))
    return "\n".join(lines)


def render_fleet(status: Dict[str, Any],
                 replay: Dict[str, Any]) -> str:
    """Multi-line view of a fleet: scheduler status (from status.json)
    plus journal-replayed shares/queue-waits/preemptions — who holds the
    runners, who is waiting, and whether the split tracks the weights."""
    if not status and not replay:
        return "fleet: no status.json or fleet.jsonl yet"
    lines = ["fleet {}: {} runner(s), {} active, queue depth {}{}".format(
        status.get("name", "?"), status.get("runners", "?"),
        status.get("active", 0), status.get("queue_depth", 0),
        " [stopped]" if status.get("stopped") else "")]
    shares = replay.get("share") or {}
    expected = replay.get("expected_share") or {}
    rexps = replay.get("experiments") or {}
    for exp in status.get("experiments", []):
        name = exp.get("name")
        extra = ""
        if name in shares:
            extra = ", share {} (want {})".format(shares[name],
                                                  expected.get(name))
        qw = (rexps.get(name) or {}).get("queue_wait_s",
                                         exp.get("queue_wait_s"))
        lines.append(
            "  {} [{}, prio {}, w {}]: {} runner(s), {} lease(s), "
            "{} preemption(s), queue wait {}s{}".format(
                name, exp.get("state"), exp.get("priority"),
                exp.get("weight"), exp.get("allocated"), exp.get("leases"),
                exp.get("preemptions"), qw, extra))
    agents = status.get("agents") or []
    if agents or status.get("max_agents"):
        lines.append("agents: {} joined / {} slot(s)".format(
            len(agents), status.get("max_agents", "?")))
        for a in agents:
            lines.append(
                "  {} [runner {}, {}@{}, {} chip(s)]: {}{}, {} lease(s), "
                "last beat {}s ago".format(
                    a.get("agent"), a.get("runner"),
                    a.get("process_index"), a.get("host"), a.get("chips"),
                    a.get("state"),
                    " -> {}".format(a.get("lease")) if a.get("lease")
                    else "",
                    a.get("leases"), a.get("last_beat_age_s")))
    sink = status.get("sink") or {}
    if sink:
        # Per-source telemetry fan-in lag: how far behind the unified
        # journal dir is for each tenant/agent — backlog still buffered
        # fleet-side plus the age of the newest ingested event. A
        # DEGRADED source's shipper lost the sink and is journaling
        # locally (it re-ships on reconnect).
        lines.append("journal sink: {} source(s)".format(len(sink)))
        for src, s in sorted(sink.items()):
            lines.append(
                "  {}: backlog {}, last event {}s ago, "
                "{} event(s) in {} batch(es){}".format(
                    src, s.get("backlog", 0),
                    s.get("last_event_age_s"),
                    s.get("ingested"), s.get("batches"),
                    " DEGRADED" if s.get("degraded") else ""))
    sreplay = replay.get("sink") or {}
    if sreplay.get("batches"):
        lag = sreplay.get("lag_ms") or {}
        lines.append(
            "sink ingest: {} event(s) / {} batch(es) from {} source(s), "
            "lag p50 {} ms / p95 {} ms, {} dup dropped".format(
                sreplay.get("events"), sreplay.get("batches"),
                sreplay.get("sources"), lag.get("median_ms"),
                lag.get("p95_ms"), sreplay.get("dup", 0)))
    areplay = replay.get("agents") or {}
    if areplay.get("joins"):
        abind = areplay.get("abind_ms") or {}
        lines.append(
            "agent plane: {} join(s), {} lease(s) delivered (abind p50 "
            "{} ms / p95 {} ms), {} lost ({} lease(s) revoked)".format(
                areplay.get("joins"), areplay.get("leases"),
                abind.get("median_ms"), abind.get("p95_ms"),
                areplay.get("losses", 0), areplay.get("lost_leases", 0)))
    if replay.get("share_error") is not None:
        lines.append("share error vs weights: {} (overlap window)".format(
            replay["share_error"]))
    if replay.get("preemptions"):
        lines.append("preemptions: {}".format(replay["preemptions"]))
    if status.get("shed") or replay.get("sheds"):
        # Load shedding happened: the fleet refused submissions at its
        # admission bound — say so next to the queue numbers.
        lines.append("shed submissions: {} (admission bound {})".format(
            status.get("shed", replay.get("sheds")),
            status.get("max_queued")))
    qwd = replay.get("queue_wait_ms") or {}
    if qwd:
        lines.append("queue wait: p50 {} ms / p95 {} ms (n={})".format(
            qwd.get("median_ms"), qwd.get("p95_ms"), qwd.get("n")))
    if replay.get("decisions_per_s"):
        lines.append("scheduler decisions: {} ({}/s); admission p99 {} "
                     "ms".format(replay.get("decisions"),
                                 replay.get("decisions_per_s"),
                                 replay.get("admission_p99_ms")))
    return "\n".join(lines)


def _poll_fleet(home: str) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    import json as _json
    import os as _os

    from maggy_tpu.fleet import FLEET_JOURNAL_NAME, replay_fleet_journal

    if home.endswith("status.json"):
        home = _os.path.dirname(home)
    status: Dict[str, Any] = {}
    status_path = _os.path.join(home, "status.json")
    if _os.path.exists(status_path):
        with open(status_path) as f:
            status = _json.load(f)
    journal = _os.path.join(home, FLEET_JOURNAL_NAME)
    replay = replay_fleet_journal(journal) if _os.path.exists(journal) \
        else {}
    return status, replay


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="maggy_tpu.monitor", description="Watch a running experiment.")
    p.add_argument("--ticket", help="path to the driver's runner_ticket.json")
    p.add_argument("--driver", help="driver control-plane address HOST:PORT")
    p.add_argument("--secret", help="shared experiment secret (hex)")
    p.add_argument("--secret-file", help="file containing the shared secret")
    p.add_argument("--interval", type=float, default=2.0,
                   help="seconds between polls (default 2)")
    p.add_argument("--once", action="store_true",
                   help="print one snapshot and exit")
    p.add_argument("--logs", action="store_true",
                   help="also stream executor log lines (reporter.log and, "
                        "with ship_prints=True, user print() output)")
    p.add_argument("--telem", action="store_true",
                   help="poll the TELEM verb instead of LOG: span-derived "
                        "scheduling numbers (hand-off gap, early-stop "
                        "reaction) and RPC service-time histograms "
                        "(mutually exclusive with --logs, which streams "
                        "over the LOG verb)")
    p.add_argument("--health", action="store_true",
                   help="poll the TELEM verb and render the live health "
                        "view: straggler/hang/RTT flags from the driver's "
                        "health engine plus per-partition runner stats "
                        "(step cadence, time-to-first-metric, heartbeat "
                        "RTT, RSS)")
    p.add_argument("--goodput", action="store_true",
                   help="poll the TELEM verb and render the chip-time "
                        "goodput ledger: the experiment's goodput "
                        "fraction, top badput buckets (compile, rework, "
                        "idle, ...), and per-partition held-time split")
    p.add_argument("--live", metavar="HOST:PORT",
                   help="watch via the observability plane instead of the "
                        "RPC verbs: scrape GET /status + /healthz from a "
                        "driver/fleet started with config.obs_port (or "
                        "MAGGY_TPU_OBS_PORT) — no secret needed; the "
                        "bound address is journaled as obs_started")
    p.add_argument("--fleet", metavar="HOME",
                   help="watch a shared fleet instead of one experiment: "
                        "renders per-experiment share, queue depth, and "
                        "preemption counts from the fleet home dir's "
                        "status.json + fleet.jsonl (no RPC — works after "
                        "the fleet exits too)")
    args = p.parse_args(argv)
    if (args.telem or args.health or args.goodput) and args.logs:
        p.error("--logs streams over the LOG verb; run it without "
                "--telem/--health/--goodput (or use two monitor "
                "processes)")
    if args.live:
        if args.telem or args.health or args.logs or args.fleet \
                or args.goodput:
            p.error("--live scrapes the obs HTTP endpoints; drop "
                    "--telem/--health/--logs/--fleet/--goodput")
        polled_ok = False
        failures = 0
        last = None
        while True:
            try:
                status, code, healthz = poll_live(args.live)
            except OSError as e:
                if not polled_ok:
                    print("cannot reach obs server at {}: {}".format(
                        args.live, e), file=sys.stderr)
                    return 1
                failures += 1
                if failures >= 3:
                    print("experiment finished (obs server gone)")
                    return 0
                time.sleep(args.interval)
                continue
            failures = 0
            polled_ok = True
            line = render_live(status, code, healthz)
            if line != last:
                print(line, flush=True)
                last = line
            if args.once:
                return 0
            time.sleep(args.interval)
    if args.fleet:
        if args.telem or args.health or args.logs or args.goodput:
            p.error("--fleet is file-based; drop "
                    "--telem/--health/--logs/--goodput")
        last = None
        while True:
            status, replay = _poll_fleet(args.fleet)
            line = render_fleet(status, replay)
            if line != last:
                print(line, flush=True)
                last = line
            if args.once:
                return 0
            time.sleep(args.interval)

    if args.ticket:
        from maggy_tpu.runner import read_ticket

        ticket = read_ticket(args.ticket, wait_s=0)
        addr = (ticket["host"], int(ticket["port"]))
        secret = ticket["secret"]
    elif args.driver:
        host, _, port = args.driver.rpartition(":")
        addr = (host, int(port))
        if args.secret_file:
            with open(args.secret_file) as f:
                secret = f.read().strip()
        elif args.secret:
            secret = args.secret
        else:
            p.error("--driver requires --secret or --secret-file")
    else:
        p.error("one of --ticket or --driver is required")

    polled_ok = False
    consecutive_failures = 0
    logs_seen = 0
    while True:
        try:
            snap = (poll_telemetry
                    if (args.telem or args.health or args.goodput)
                    else poll_progress)(addr, secret)
        except (ConnectionError, socket.timeout, OSError) as e:
            if not polled_ok:
                print("cannot reach driver at {}:{}: {}".format(
                    addr[0], addr[1], e), file=sys.stderr)
                return 1
            # Distinguish a transient blip (driver briefly saturated) from a
            # finished experiment: require a few consecutive failures.
            consecutive_failures += 1
            if consecutive_failures >= 3:
                print("experiment finished (driver gone)")
                return 0
            time.sleep(args.interval)
            continue
        consecutive_failures = 0
        polled_ok = True
        if args.health:
            print(render_health(snap), flush=True)
        elif args.goodput:
            print(render_goodput_view(snap), flush=True)
        else:
            print(render_telem(snap) if args.telem else render(snap),
                  flush=True)
        if args.logs:
            total = snap.get("log_total", 0)
            tail = snap.get("log_tail", [])
            missed = total - logs_seen - len(tail)
            if logs_seen and missed > 0:
                print("  | ... {} line(s) skipped (poll faster or read the "
                      "executor logs)".format(missed), flush=True)
            new = min(total - logs_seen, len(tail))
            for line in (tail[-new:] if new > 0 else []):
                print("  | {}".format(line), flush=True)
            logs_seen = max(logs_seen, total)
        if args.once:
            return 0
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
