"""Native (C++) control-plane codec, loaded via ctypes.

Builds `framing.cpp` into `_maggy_native.so` with g++ on first import (cached
next to the source); every entry point has a pure-Python fallback so the
framework works without a toolchain. See framing.cpp for what/why.
"""

from __future__ import annotations

import ctypes
import hashlib
import hmac as _py_hmac
import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "framing.cpp")
_SO = os.path.join(_HERE, "_maggy_native.so")

_lib = None
_lock = threading.Lock()
_build_attempted = False


def _build() -> bool:
    # Compile to a per-pid temp path then rename: os.rename is atomic, so
    # concurrent runner processes never dlopen a partially written .so.
    tmp = "{}.tmp.{}".format(_SO, os.getpid())
    try:
        subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", tmp],
            check=True, capture_output=True, timeout=120,
        )
        os.replace(tmp, _SO)
        return True
    except Exception:  # noqa: BLE001 - no toolchain -> python fallback
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def get_lib():
    """The loaded native library, or None (fallback mode)."""
    global _lib, _build_attempted
    with _lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_SO) or os.path.getmtime(_SO) < os.path.getmtime(_SRC):
            if _build_attempted:
                return None
            _build_attempted = True
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        lib.maggy_hmac_sha256.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p,
            ctypes.c_size_t, ctypes.c_char_p]
        lib.maggy_hmac_sha256.restype = None
        lib.maggy_digest_eq.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t]
        lib.maggy_digest_eq.restype = ctypes.c_int
        lib.maggy_frame_scan.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p,
            ctypes.c_size_t, ctypes.c_size_t]
        lib.maggy_frame_scan.restype = ctypes.c_long
        lib.maggy_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
        lib.maggy_crc32c.restype = ctypes.c_uint32
        lib.maggy_tfrecord_scan.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
            ctypes.c_long, ctypes.c_int]
        lib.maggy_tfrecord_scan.restype = ctypes.c_long
        _lib = lib
        return _lib


def hmac_sha256(key: bytes, msg: bytes) -> bytes:
    lib = get_lib()
    if lib is None:
        return _py_hmac.new(key, msg, hashlib.sha256).digest()
    out = ctypes.create_string_buffer(32)
    lib.maggy_hmac_sha256(key, len(key), msg, len(msg), out)
    return out.raw


def frame_scan(buf, key: bytes, max_frame: int) -> int:
    """Scan one frame: >0 total size consumed (valid), 0 incomplete,
    -1 oversized, -2 bad HMAC. Pure-Python fallback mirrors framing.cpp."""
    lib = get_lib()
    if lib is not None:
        if isinstance(buf, bytearray):
            # Zero-copy view into the connection's reassembly buffer — this
            # runs once per frame on the server's single event-loop thread.
            cbuf = (ctypes.c_char * len(buf)).from_buffer(buf)
            return int(lib.maggy_frame_scan(cbuf, len(buf), key, len(key),
                                            max_frame))
        return int(lib.maggy_frame_scan(bytes(buf), len(buf), key, len(key),
                                        max_frame))
    header = 4 + 32
    if len(buf) < header:
        return 0
    length = int.from_bytes(buf[:4], "big")
    if length > max_frame:
        return -1
    if len(buf) < header + length:
        return 0
    mac = _py_hmac.new(key, bytes(buf[header:header + length]),
                       hashlib.sha256).digest()
    if not _py_hmac.compare_digest(mac, bytes(buf[4:header])):
        return -2
    return header + length


def crc32c(data: bytes):
    """Native crc32c (Castagnoli), or None when in fallback mode — the
    caller (maggy_tpu.train.tfrecord) owns the pure-Python table."""
    lib = get_lib()
    if lib is None:
        return None
    return int(lib.maggy_crc32c(data, len(data)))


def tfrecord_scan(data: bytes, verify: bool = True):
    """Offsets/lengths of every record payload in a TFRecord buffer, crc
    verified natively. Returns a list of (offset, length), or None in
    fallback mode. Raises ValueError on truncation/corruption."""
    lib = get_lib()
    if lib is None:
        return None
    # One entry per 16 bytes is a safe upper bound (min record = 16 bytes).
    cap = max(1, len(data) // 16)
    offs = (ctypes.c_int64 * cap)()
    lens = (ctypes.c_int64 * cap)()
    n = int(lib.maggy_tfrecord_scan(data, len(data), offs, lens, cap,
                                    1 if verify else 0))
    if n == -1:
        raise ValueError("Truncated TFRecord buffer")
    if n == -2:
        raise ValueError("Corrupt TFRecord crc")
    if n < 0:
        raise ValueError("TFRecord scan failed ({})".format(n))
    return [(offs[i], lens[i]) for i in range(n)]


def is_native() -> bool:
    return get_lib() is not None
