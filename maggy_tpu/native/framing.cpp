// Control-plane wire codec: SHA-256, HMAC-SHA256, and frame scanning.
//
// The hot path of the DCN control plane: with >=64 concurrent runners
// heartbeating every second, the driver-side server authenticates and
// reassembles thousands of frames per minute. This native codec verifies
// HMACs and scans length-prefixed frames out of connection buffers in one
// pass, exported with a plain C ABI for ctypes (no pybind11 in the image).
//
// The reference delegates all native work to external libs (SURVEY.md §2.9);
// its wire format was pickle-over-TCP with a plaintext secret
// (reference rpc.py:116-162). This is the from-scratch TPU-framework
// equivalent: fixed header || HMAC || msgpack payload.
//
// SHA-256 per FIPS 180-4; implementation written from the spec.

#include <cstdint>
#include <cstring>
#include <mutex>

namespace {

constexpr uint32_t K[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

inline uint32_t rotr(uint32_t x, int n) { return (x >> n) | (x << (32 - n)); }

struct Sha256 {
  uint32_t h[8];
  uint64_t total = 0;
  uint8_t buf[64];
  size_t buflen = 0;

  Sha256() {
    static const uint32_t init[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372,
                                     0xa54ff53a, 0x510e527f, 0x9b05688c,
                                     0x1f83d9ab, 0x5be0cd19};
    memcpy(h, init, sizeof(h));
  }

  void block(const uint8_t* p) {
    uint32_t w[64];
    for (int i = 0; i < 16; i++)
      w[i] = (uint32_t(p[4 * i]) << 24) | (uint32_t(p[4 * i + 1]) << 16) |
             (uint32_t(p[4 * i + 2]) << 8) | uint32_t(p[4 * i + 3]);
    for (int i = 16; i < 64; i++) {
      uint32_t s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
      uint32_t s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
      w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint32_t a = h[0], b = h[1], c = h[2], d = h[3], e = h[4], f = h[5],
             g = h[6], hh = h[7];
    for (int i = 0; i < 64; i++) {
      uint32_t S1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
      uint32_t ch = (e & f) ^ (~e & g);
      uint32_t t1 = hh + S1 + ch + K[i] + w[i];
      uint32_t S0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
      uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
      uint32_t t2 = S0 + maj;
      hh = g; g = f; f = e; e = d + t1;
      d = c; c = b; b = a; a = t1 + t2;
    }
    h[0] += a; h[1] += b; h[2] += c; h[3] += d;
    h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
  }

  void update(const uint8_t* data, size_t len) {
    total += len;
    if (buflen) {
      size_t need = 64 - buflen;
      size_t take = len < need ? len : need;
      memcpy(buf + buflen, data, take);
      buflen += take;
      data += take;
      len -= take;
      if (buflen == 64) { block(buf); buflen = 0; }
    }
    while (len >= 64) { block(data); data += 64; len -= 64; }
    if (len) { memcpy(buf, data, len); buflen = len; }
  }

  void final(uint8_t out[32]) {
    uint64_t bits = total * 8;
    uint8_t pad = 0x80;
    update(&pad, 1);
    uint8_t zero = 0;
    while (buflen != 56) update(&zero, 1);
    uint8_t lenb[8];
    for (int i = 0; i < 8; i++) lenb[i] = uint8_t(bits >> (56 - 8 * i));
    update(lenb, 8);
    for (int i = 0; i < 8; i++) {
      out[4 * i] = uint8_t(h[i] >> 24);
      out[4 * i + 1] = uint8_t(h[i] >> 16);
      out[4 * i + 2] = uint8_t(h[i] >> 8);
      out[4 * i + 3] = uint8_t(h[i]);
    }
  }
};

void sha256(const uint8_t* data, size_t len, uint8_t out[32]) {
  Sha256 s;
  s.update(data, len);
  s.final(out);
}

void hmac_sha256_impl(const uint8_t* key, size_t keylen, const uint8_t* msg,
                      size_t msglen, uint8_t out[32]) {
  uint8_t k[64] = {0};
  if (keylen > 64) {
    sha256(key, keylen, k);  // hash long keys down
  } else {
    memcpy(k, key, keylen);
  }
  uint8_t ipad[64], opad[64];
  for (int i = 0; i < 64; i++) {
    ipad[i] = k[i] ^ 0x36;
    opad[i] = k[i] ^ 0x5c;
  }
  uint8_t inner[32];
  Sha256 si;
  si.update(ipad, 64);
  si.update(msg, msglen);
  si.final(inner);
  Sha256 so;
  so.update(opad, 64);
  so.update(inner, 32);
  so.final(out);
}

}  // namespace

extern "C" {

// HMAC-SHA256 of msg under key; writes 32 bytes to out.
void maggy_hmac_sha256(const uint8_t* key, size_t keylen, const uint8_t* msg,
                       size_t msglen, uint8_t* out) {
  hmac_sha256_impl(key, keylen, msg, msglen, out);
}

// Constant-time digest comparison (timing-safe like hmac.compare_digest).
int maggy_digest_eq(const uint8_t* a, const uint8_t* b, size_t len) {
  uint8_t acc = 0;
  for (size_t i = 0; i < len; i++) acc |= a[i] ^ b[i];
  return acc == 0;
}

// Scan one frame out of a reassembly buffer.
//   buffer layout: [4-byte BE length][32-byte HMAC][payload]
// Returns:  >0  = total frame size consumed (payload verified; payload
//                 starts at offset 36, length = return - 36)
//            0  = incomplete (need more bytes)
//           -1  = oversized frame (protocol violation; drop connection)
//           -2  = HMAC mismatch (drop connection)
long maggy_frame_scan(const uint8_t* buf, size_t buflen, const uint8_t* key,
                      size_t keylen, size_t max_frame) {
  const size_t header = 4 + 32;
  if (buflen < header) return 0;
  size_t len = (size_t(buf[0]) << 24) | (size_t(buf[1]) << 16) |
               (size_t(buf[2]) << 8) | size_t(buf[3]);
  if (len > max_frame) return -1;
  if (buflen < header + len) return 0;
  uint8_t mac[32];
  hmac_sha256_impl(key, keylen, buf + header, len, mac);
  if (!maggy_digest_eq(mac, buf + 4, 32)) return -2;
  return long(header + len);
}

// ---------------------------------------------------------------- crc32c
// Castagnoli CRC (iSCSI/TFRecord polynomial), slice-by-8 tables: the data
// plane's hot loop for .tfrecord ingestion — pure-Python crc32c runs at
// ~1 MB/s, this at ~GB/s.

namespace {
uint32_t crc_tab[8][256];
// ctypes releases the GIL, so concurrent first calls from runner threads
// race a hand-rolled init flag (UB on weakly-ordered CPUs); call_once
// publishes the table stores with the required fence.
std::once_flag crc_once;

void crc_init() {
  for (int n = 0; n < 256; n++) {
    uint32_t c = uint32_t(n);
    for (int k = 0; k < 8; k++) c = (c & 1) ? 0x82F63B78u ^ (c >> 1) : c >> 1;
    crc_tab[0][n] = c;
  }
  for (int n = 0; n < 256; n++) {
    uint32_t c = crc_tab[0][n];
    for (int t = 1; t < 8; t++) {
      c = crc_tab[0][c & 0xFF] ^ (c >> 8);
      crc_tab[t][n] = c;
    }
  }
}

inline uint32_t crc32c_impl(const uint8_t* p, size_t len, uint32_t crc0) {
  std::call_once(crc_once, crc_init);
  uint32_t crc = crc0 ^ 0xFFFFFFFFu;
  while (len >= 8) {
    crc ^= uint32_t(p[0]) | (uint32_t(p[1]) << 8) | (uint32_t(p[2]) << 16) |
           (uint32_t(p[3]) << 24);
    uint32_t hi = uint32_t(p[4]) | (uint32_t(p[5]) << 8) |
                  (uint32_t(p[6]) << 16) | (uint32_t(p[7]) << 24);
    crc = crc_tab[7][crc & 0xFF] ^ crc_tab[6][(crc >> 8) & 0xFF] ^
          crc_tab[5][(crc >> 16) & 0xFF] ^ crc_tab[4][crc >> 24] ^
          crc_tab[3][hi & 0xFF] ^ crc_tab[2][(hi >> 8) & 0xFF] ^
          crc_tab[1][(hi >> 16) & 0xFF] ^ crc_tab[0][hi >> 24];
    p += 8;
    len -= 8;
  }
  while (len--) crc = crc_tab[0][(crc ^ *p++) & 0xFF] ^ (crc >> 8);
  return crc ^ 0xFFFFFFFFu;
}

inline uint32_t masked_crc(const uint8_t* p, size_t len) {
  uint32_t crc = crc32c_impl(p, len, 0);
  return uint32_t(((crc >> 15) | (crc << 17)) + 0xA282EAD8u);
}

inline uint32_t load_le32(const uint8_t* p) {
  return uint32_t(p[0]) | (uint32_t(p[1]) << 8) | (uint32_t(p[2]) << 16) |
         (uint32_t(p[3]) << 24);
}
}  // namespace

uint32_t maggy_crc32c(const uint8_t* data, size_t len) {
  return crc32c_impl(data, len, 0);
}

// Scan a whole TFRecord buffer:
//   record layout: [8-byte LE length][4-byte masked crc32c(length bytes)]
//                  [payload][4-byte masked crc32c(payload)]
// Fills offs[i]/lens[i] with each payload's offset and length.
// Returns record count (>= 0), or:
//   -1 = truncated record, -2 = crc mismatch, -3 = more than max_records.
long maggy_tfrecord_scan(const uint8_t* buf, size_t buflen, int64_t* offs,
                         int64_t* lens, long max_records, int verify) {
  size_t pos = 0;
  long count = 0;
  while (pos < buflen) {
    if (buflen - pos < 12) return -1;
    uint64_t len = 0;
    for (int i = 7; i >= 0; i--) len = (len << 8) | buf[pos + i];
    if (verify && load_le32(buf + pos + 8) != masked_crc(buf + pos, 8))
      return -2;
    // Untrusted length: compare without forming 12+len+4 (which can wrap
    // for a corrupt length near UINT64_MAX and defeat the bounds check).
    if (len > buflen - pos - 12 || buflen - pos - 12 - len < 4) return -1;
    const uint8_t* payload = buf + pos + 12;
    if (verify && load_le32(payload + len) != masked_crc(payload, len))
      return -2;
    if (count >= max_records) return -3;
    offs[count] = int64_t(pos + 12);
    lens[count] = int64_t(len);
    count++;
    pos += 12 + len + 4;
  }
  return count;
}

}  // extern "C"
