"""TPU compute kernels: Pallas where it pays, XLA elsewhere."""

from maggy_tpu.ops.attention import multi_head_attention, flash_attention, attention_reference

__all__ = ["multi_head_attention", "flash_attention", "attention_reference"]
