"""TPU compute kernels: Pallas where it pays, XLA elsewhere."""

from maggy_tpu.ops.attention import multi_head_attention, flash_attention, attention_reference
from maggy_tpu.ops.losses import chunked_next_token_loss, chunked_softmax_xent

__all__ = ["multi_head_attention", "flash_attention", "attention_reference",
           "chunked_next_token_loss", "chunked_softmax_xent"]
