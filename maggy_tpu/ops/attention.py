"""Attention: Pallas flash kernel (TPU) with an XLA reference path.

The reference framework has no attention code (SURVEY.md §5.7); this is the
TPU-first hot-op design the BERT/Llama baseline configs need:

- `flash_attention`: Pallas TPU kernels — tiled online-softmax forward and
  a two-kernel backward (dK/dV streaming Q tiles, dQ streaming K/V tiles),
  fp32 accumulators in VMEM scratch, causal block skipping, O(tile) VMEM
  and no S x S materialization in either direction.
- `attention_reference`: straightforward XLA softmax attention (CPU tests,
  odd shapes).
- `multi_head_attention`: public entry — handles GQA (kv-head repeat),
  dispatches to the kernel when shapes tile cleanly on a TPU backend.

Kernel layout follows the pallas guide (/opt/skills/guides/pallas_guide.md):
grid = (B*H, Sq/BLK_Q, Sk/BLK_K) with the k-block dimension sequential
("arbitrary") and the online-softmax state in persistent VMEM scratch, so
VMEM holds one K/V tile at a time (long-context capable); (8,128)-aligned
tiles, `preferred_element_type=float32` on every MXU dot.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


# ----------------------------------------------------------------- reference


def attention_reference(q, k, v, causal: bool = True, mask=None):
    """[B,S,H,D]x[B,S,Hkv,D] softmax attention in plain XLA (fp32 softmax)."""
    B, Sq, H, D = q.shape
    Hkv = k.shape[2]
    if Hkv != H:
        rep = H // Hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) / jnp.sqrt(D).astype(jnp.float32)
    if causal:
        Sk = k.shape[1]
        cm = jnp.tril(jnp.ones((Sq, Sk), bool), k=Sk - Sq)
        logits = jnp.where(cm[None, None], logits, NEG_INF)
    if mask is not None:
        logits = jnp.where(mask, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32)).astype(q.dtype)


# -------------------------------------------------------------- pallas kernel


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                      acc_ref, m_ref, l_ref, *, causal, sm_scale):
    """One (batch*head, q-block, k-block) program: K/V stream through the
    grid's innermost (sequential) dimension, so VMEM holds only one
    [blk_k, D] tile of K and V at a time — sequence length is bounded by
    HBM, not VMEM. Online-softmax state (acc, running max, running sum)
    lives in VMEM scratch that persists across the k-block iterations of
    each (bh, qi) program group.

    Refs: q [BLK_Q, D]; k/v [BLK_K, D]; o [BLK_Q, D]; lse [BLK_Q, 128]
    (lane-padded); scratch acc [BLK_Q, D], m/l [BLK_Q, 128] fp32.
    """
    from jax.experimental import pallas as pl

    blk_q = q_ref.shape[0]
    blk_k = k_ref.shape[0]
    qi = pl.program_id(1)
    kb = pl.program_id(2)
    num_kb = pl.num_programs(2)

    @pl.when(kb == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    def contribute():
        q = q_ref[:].astype(jnp.float32) * sm_scale
        k_blk = k_ref[:].astype(jnp.float32)
        v_blk = v_ref[:].astype(jnp.float32)
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            q_pos = qi * blk_q + jax.lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 0)
            k_pos = kb * blk_k + jax.lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_prev = m_ref[:, 0]
        l_prev = l_ref[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=1)
        acc_ref[:] = acc_ref[:] * alpha[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_new[:, None], m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new[:, None], l_ref.shape)

    if causal:
        # Blocks entirely above the diagonal contribute nothing — skip the
        # compute (the tile fetch still happens; cheap next to the MXU work).
        @pl.when(kb * blk_k < (qi + 1) * blk_q)
        def _():
            contribute()
    else:
        contribute()

    @pl.when(kb == num_kb - 1)
    def _finalize():
        l_safe = jnp.maximum(l_ref[:, 0], 1e-30)
        o_ref[:] = (acc_ref[:] / l_safe[:, None]).astype(o_ref.dtype)
        lse = m_ref[:, 0] + jnp.log(l_safe)
        lse_ref[:] = jnp.broadcast_to(lse[:, None], lse_ref.shape)


def _flash_fwd(q, k, v, causal: bool, blk_q: int, blk_k: int, interpret: bool):
    """q,k,v: [BH, S, D] (kv already GQA-expanded). Returns (out, lse)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    BH, Sq, D = q.shape
    Sk = k.shape[1]
    sm_scale = 1.0 / (D ** 0.5)
    grid = (BH, Sq // blk_q, Sk // blk_k)
    kernel = functools.partial(_flash_fwd_kernel, causal=causal,
                               sm_scale=sm_scale)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, blk_q, D), lambda bh, qi, kb: (bh, qi, 0)),
            pl.BlockSpec((None, blk_k, D), lambda bh, qi, kb: (bh, kb, 0)),
            pl.BlockSpec((None, blk_k, D), lambda bh, qi, kb: (bh, kb, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, blk_q, D), lambda bh, qi, kb: (bh, qi, 0)),
            pl.BlockSpec((None, blk_q, 128), lambda bh, qi, kb: (bh, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Sq, D), q.dtype),
            jax.ShapeDtypeStruct((BH, Sq, 128), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((blk_q, D), jnp.float32),
            pltpu.VMEM((blk_q, 128), jnp.float32),
            pltpu.VMEM((blk_q, 128), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            # bh/qi programs are independent (megacore-splittable); the
            # k-block dimension carries the online-softmax accumulation and
            # must run sequentially.
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v)
    return out, lse[:, :, 0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal: bool = True, blk_q: int = 128,
                    blk_k: int = 128, interpret: bool = False):
    """Flash attention on [B,S,H,D] with H == Hkv (pre-expanded)."""
    out, _ = _flash_fwd_4d(q, k, v, causal, blk_q, blk_k, interpret)
    return out


def _to_bh3(x):
    """[B,S,H,D] -> heads-major [B*H, S, D] (the kernels' layout)."""
    B, S, H, D = x.shape
    return x.transpose(0, 2, 1, 3).reshape(B * H, S, D)


def _from_bh3(x, B, H):
    """[B*H, S, D] -> [B,S,H,D]."""
    _, S, D = x.shape
    return x.reshape(B, H, S, D).transpose(0, 2, 1, 3)


def _flash_fwd_4d(q, k, v, causal, blk_q, blk_k, interpret):
    B, Sq, H, D = q.shape
    out3, lse = _flash_fwd(_to_bh3(q), _to_bh3(k), _to_bh3(v), causal,
                           blk_q, blk_k, interpret)
    return _from_bh3(out3, B, H), lse


def _flash_fwd_rule(q, k, v, causal, blk_q, blk_k, interpret):
    out, lse = _flash_fwd_4d(q, k, v, causal, blk_q, blk_k, interpret)
    return out, (q, k, v, out, lse)


def _recompute_p_ds(q, k_blk, v_blk, do, lse, delta, q_pos0, k_pos0,
                    causal, sm_scale):
    """Shared bwd block math: probabilities from the saved LSE, then the
    softmax-transpose ds = p * (dO·Vᵀ - delta) * scale. All [blk_q, blk_k]."""
    blk_q, blk_k = q.shape[0], k_blk.shape[0]
    s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * sm_scale
    if causal:
        q_pos = q_pos0 + jax.lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 0)
        k_pos = k_pos0 + jax.lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 1)
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)
    p = jnp.exp(s - lse[:, None])
    dp = jax.lax.dot_general(do, v_blk, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta[:, None]) * sm_scale
    return p, ds


def _flash_bwd_dkdv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                           dk_ref, dv_ref, dk_acc, dv_acc, *, causal, sm_scale):
    """grid (BH, kb, qi): one K/V tile per program group; stream Q/dO tiles
    through the sequential qi dimension, accumulating dK/dV in VMEM scratch."""
    from jax.experimental import pallas as pl

    blk_q = q_ref.shape[0]
    blk_k = k_ref.shape[0]
    kb = pl.program_id(1)
    qi = pl.program_id(2)
    num_qb = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def contribute():
        q = q_ref[:].astype(jnp.float32)
        do = do_ref[:].astype(jnp.float32)
        p, ds = _recompute_p_ds(
            q, k_ref[:].astype(jnp.float32), v_ref[:].astype(jnp.float32),
            do, lse_ref[:, 0], delta_ref[:, 0],
            qi * blk_q, kb * blk_k, causal, sm_scale)
        dv_acc[:] += jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)
        dk_acc[:] += jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)

    if causal:
        # Q blocks strictly above this K tile's diagonal see none of it.
        @pl.when((qi + 1) * blk_q > kb * blk_k)
        def _():
            contribute()
    else:
        contribute()

    @pl.when(qi == num_qb - 1)
    def _finalize():
        dk_ref[:] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[:] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, dq_acc, *, causal, sm_scale):
    """grid (BH, qi, kb): one Q tile per program group; stream K/V tiles
    through the sequential kb dimension, accumulating dQ in VMEM scratch."""
    from jax.experimental import pallas as pl

    blk_q = q_ref.shape[0]
    blk_k = k_ref.shape[0]
    qi = pl.program_id(1)
    kb = pl.program_id(2)
    num_kb = pl.num_programs(2)

    @pl.when(kb == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    def contribute():
        _, ds = _recompute_p_ds(
            q_ref[:].astype(jnp.float32), k_ref[:].astype(jnp.float32),
            v_ref[:].astype(jnp.float32), do_ref[:].astype(jnp.float32),
            lse_ref[:, 0], delta_ref[:, 0],
            qi * blk_q, kb * blk_k, causal, sm_scale)
        dq_acc[:] += jax.lax.dot_general(ds, k_ref[:].astype(jnp.float32),
                                         (((1,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)

    if causal:
        @pl.when(kb * blk_k < (qi + 1) * blk_q)
        def _():
            contribute()
    else:
        contribute()

    @pl.when(kb == num_kb - 1)
    def _finalize():
        dq_ref[:] = dq_acc[:].astype(dq_ref.dtype)


def _flash_bwd(q3, k3, v3, do3, lse, delta, causal, blk_q, blk_k, interpret):
    """Pallas flash backward. q3/k3/v3/do3: [BH, S, D]; lse/delta: [BH, Sq]
    fp32. Returns (dq, dk, dv) in [BH, S, D]."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    BH, Sq, D = q3.shape
    Sk = k3.shape[1]
    sm_scale = 1.0 / (D ** 0.5)
    # Lane-pad the per-row statistics so their tiles are (blk, 128).
    lse_p = jnp.broadcast_to(lse[:, :, None], (BH, Sq, 128))
    delta_p = jnp.broadcast_to(delta[:, :, None], (BH, Sq, 128))

    q_spec_qi = pl.BlockSpec((None, blk_q, D), lambda bh, qi, kb: (bh, qi, 0))
    k_spec_kb = pl.BlockSpec((None, blk_k, D), lambda bh, qi, kb: (bh, kb, 0))
    stat_spec_qi = pl.BlockSpec((None, blk_q, 128), lambda bh, qi, kb: (bh, qi, 0))
    # dK/dV grid is (BH, kb, qi): swap the roles of the two inner dims.
    q_spec_by_inner = pl.BlockSpec((None, blk_q, D), lambda bh, kb, qi: (bh, qi, 0))
    k_spec_by_outer = pl.BlockSpec((None, blk_k, D), lambda bh, kb, qi: (bh, kb, 0))
    stat_spec_by_inner = pl.BlockSpec((None, blk_q, 128),
                                      lambda bh, kb, qi: (bh, qi, 0))

    seq_params = pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel", "arbitrary"))

    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkdv_kernel, causal=causal,
                          sm_scale=sm_scale),
        grid=(BH, Sk // blk_k, Sq // blk_q),
        in_specs=[q_spec_by_inner, k_spec_by_outer, k_spec_by_outer,
                  q_spec_by_inner, stat_spec_by_inner, stat_spec_by_inner],
        out_specs=[
            pl.BlockSpec((None, blk_k, D), lambda bh, kb, qi: (bh, kb, 0)),
            pl.BlockSpec((None, blk_k, D), lambda bh, kb, qi: (bh, kb, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Sk, D), k3.dtype),
            jax.ShapeDtypeStruct((BH, Sk, D), v3.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((blk_k, D), jnp.float32),
            pltpu.VMEM((blk_k, D), jnp.float32),
        ],
        compiler_params=seq_params,
        interpret=interpret,
    )(q3, k3, v3, do3, lse_p, delta_p)

    (dq,) = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, causal=causal,
                          sm_scale=sm_scale),
        grid=(BH, Sq // blk_q, Sk // blk_k),
        in_specs=[q_spec_qi, k_spec_kb, k_spec_kb, q_spec_qi,
                  stat_spec_qi, stat_spec_qi],
        out_specs=[
            pl.BlockSpec((None, blk_q, D), lambda bh, qi, kb: (bh, qi, 0)),
        ],
        out_shape=[jax.ShapeDtypeStruct((BH, Sq, D), q3.dtype)],
        scratch_shapes=[pltpu.VMEM((blk_q, D), jnp.float32)],
        compiler_params=seq_params,
        interpret=interpret,
    )(q3, k3, v3, do3, lse_p, delta_p)
    return dq, dk, dv


def _flash_bwd_rule(causal, blk_q, blk_k, interpret, res, g):
    """Flash backward as two Pallas kernels (dK/dV then dQ), recomputing
    probabilities from the saved log-sum-exp — the S x S matrix never
    materializes and VMEM holds one tile pair at a time."""
    q, k, v, out, lse = res
    B, Sq, H, D = q.shape
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)  # [B,Sq,H]
    delta3 = delta.transpose(0, 2, 1).reshape(B * H, Sq)
    dq3, dk3, dv3 = _flash_bwd(_to_bh3(q), _to_bh3(k), _to_bh3(v), _to_bh3(g),
                               lse, delta3, causal, blk_q, blk_k, interpret)
    return (_from_bh3(dq3, B, H).astype(q.dtype),
            _from_bh3(dk3, B, H).astype(k.dtype),
            _from_bh3(dv3, B, H).astype(v.dtype))


flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)


# ----------------------------------------------------------------- dispatch


def _tpu_backend() -> bool:
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:  # noqa: BLE001
        return False


def multi_head_attention(q, k, v, causal: bool = True, mask=None,
                         force: Optional[str] = None):
    """Public attention entry: GQA expand + kernel dispatch.

    q: [B,S,H,D], k/v: [B,S,Hkv,D]. ``force`` in {"flash", "reference"}
    overrides dispatch (tests).
    """
    B, Sq, H, D = q.shape
    Hkv = k.shape[2]
    # The kernel's causal mask assumes Sq == Sk (absolute positions); the
    # blk_k loop assumes Sk tiles exactly. Violations fall back (or raise
    # under force=) instead of silently mis-masking/truncating.
    tiles_ok = (
        mask is None and D % 128 == 0 and Sq == k.shape[1] and Sq % 128 == 0
    )
    if force == "flash":
        if not tiles_ok:
            raise ValueError(
                "force='flash' requires mask=None, D%128==0, and Sq==Sk with "
                "Sq%128==0; got D={}, Sq={}, Sk={}, mask={}".format(
                    D, Sq, k.shape[1], mask is not None))
        use_flash = True
    else:
        use_flash = force is None and _tpu_backend() and tiles_ok
    if not use_flash:
        return attention_reference(q, k, v, causal=causal, mask=mask)
    if Hkv != H:
        rep = H // Hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    blk = 128 if Sq % 128 == 0 else Sq
    interpret = not _tpu_backend()
    return flash_attention(q, k, v, causal, min(blk, Sq), min(128, k.shape[1]),
                           interpret)
