"""Attention: Pallas flash kernel (TPU) with an XLA reference path.

The reference framework has no attention code (SURVEY.md §5.7); this is the
TPU-first hot-op design the BERT/Llama baseline configs need:

- `flash_attention`: Pallas TPU kernel — tiled online-softmax forward, fp32
  accumulators in VMEM scratch, causal block skipping via the grid, O(S)
  memory. Backward is a flash-style recompute VJP (no S x S materialization
  thanks to blockwise lax.map) — good enough until a Pallas bwd kernel lands.
- `attention_reference`: straightforward XLA softmax attention (CPU tests,
  odd shapes).
- `multi_head_attention`: public entry — handles GQA (kv-head repeat),
  dispatches to the kernel when shapes tile cleanly on a TPU backend.

Kernel layout follows the pallas guide (/opt/skills/guides/pallas_guide.md):
grid = (B*H, Sq/BLK_Q), K/V streamed block-by-block with `fori_loop`,
(8,128)-aligned tiles, `preferred_element_type=float32` on every MXU dot.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


# ----------------------------------------------------------------- reference


def attention_reference(q, k, v, causal: bool = True, mask=None):
    """[B,S,H,D]x[B,S,Hkv,D] softmax attention in plain XLA (fp32 softmax)."""
    B, Sq, H, D = q.shape
    Hkv = k.shape[2]
    if Hkv != H:
        rep = H // Hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) / jnp.sqrt(D).astype(jnp.float32)
    if causal:
        Sk = k.shape[1]
        cm = jnp.tril(jnp.ones((Sq, Sk), bool), k=Sk - Sq)
        logits = jnp.where(cm[None, None], logits, NEG_INF)
    if mask is not None:
        logits = jnp.where(mask, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32)).astype(q.dtype)


# -------------------------------------------------------------- pallas kernel


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, blk_k, seq_k,
                      causal, sm_scale):
    """One (batch*head, q-block) program: stream K/V blocks, online softmax.

    Refs: q [BLK_Q, D]; k/v [Sk, D] (full K/V for this head in VMEM);
    o [BLK_Q, D]; lse [BLK_Q, 128] (lane-padded).
    """
    from jax.experimental import pallas as pl

    blk_q = q_ref.shape[0]
    d = q_ref.shape[1]
    qi = pl.program_id(1)
    q = q_ref[:].astype(jnp.float32) * sm_scale

    num_kb = seq_k // blk_k

    def body(kb, carry):
        acc, m_i, l_i = carry
        k_blk = k_ref[pl.ds(kb * blk_k, blk_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.ds(kb * blk_k, blk_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            q_pos = qi * blk_q + jax.lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 0)
            k_pos = kb * blk_k + jax.lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m_i, jnp.max(s, axis=1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_i - m_new)
        l_new = alpha * l_i + jnp.sum(p, axis=1)
        acc = acc * alpha[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc, m_new, l_new

    acc0 = jnp.zeros((blk_q, d), jnp.float32)
    m0 = jnp.full((blk_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((blk_q,), jnp.float32)
    if causal:
        # Only K blocks at or before this Q block's diagonal contribute.
        last_kb = jnp.minimum(((qi + 1) * blk_q + blk_k - 1) // blk_k, num_kb)
        acc, m_i, l_i = jax.lax.fori_loop(0, last_kb, body, (acc0, m0, l0))
    else:
        acc, m_i, l_i = jax.lax.fori_loop(0, num_kb, body, (acc0, m0, l0))

    l_safe = jnp.maximum(l_i, 1e-30)
    o_ref[:] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    lse = (m_i + jnp.log(l_safe))
    lse_ref[:] = jnp.broadcast_to(lse[:, None], lse_ref.shape)


def _flash_fwd(q, k, v, causal: bool, blk_q: int, blk_k: int, interpret: bool):
    """q,k,v: [BH, S, D] (kv already GQA-expanded). Returns (out, lse)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    BH, Sq, D = q.shape
    Sk = k.shape[1]
    sm_scale = 1.0 / (D ** 0.5)
    grid = (BH, Sq // blk_q)
    kernel = functools.partial(_flash_fwd_kernel, blk_k=blk_k, seq_k=Sk,
                               causal=causal, sm_scale=sm_scale)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, blk_q, D), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((None, Sk, D), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((None, Sk, D), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, blk_q, D), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((None, blk_q, 128), lambda bh, qi: (bh, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Sq, D), q.dtype),
            jax.ShapeDtypeStruct((BH, Sq, 128), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return out, lse[:, :, 0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal: bool = True, blk_q: int = 128,
                    blk_k: int = 128, interpret: bool = False):
    """Flash attention on [B,S,H,D] with H == Hkv (pre-expanded)."""
    out, _ = _flash_fwd_4d(q, k, v, causal, blk_q, blk_k, interpret)
    return out


def _flash_fwd_4d(q, k, v, causal, blk_q, blk_k, interpret):
    B, Sq, H, D = q.shape
    to3 = lambda x: x.transpose(0, 2, 1, 3).reshape(B * H, x.shape[1], D)  # noqa: E731
    out3, lse = _flash_fwd(to3(q), to3(k), to3(v), causal, blk_q, blk_k, interpret)
    out = out3.reshape(B, H, Sq, D).transpose(0, 2, 1, 3)
    return out, lse


def _flash_fwd_rule(q, k, v, causal, blk_q, blk_k, interpret):
    out, lse = _flash_fwd_4d(q, k, v, causal, blk_q, blk_k, interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd_rule(causal, blk_q, blk_k, interpret, res, g):
    """Flash-style backward: recompute probabilities blockwise from the saved
    log-sum-exp; never materializes the full S x S matrix."""
    q, k, v, out, lse = res
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    scale = 1.0 / (D ** 0.5)
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    delta = jnp.sum(gf * out.astype(jnp.float32), axis=-1)  # [B,S,H]
    lse4 = lse.reshape(B, H, Sq).transpose(0, 2, 1)  # [B,S,H]

    n_blocks = max(1, Sq // blk_q)

    def block_grads(qb_idx):
        sl = lambda x: jax.lax.dynamic_slice_in_dim(x, qb_idx * blk_q, blk_q, 1)  # noqa: E731
        qb, gb = sl(qf), sl(gf)
        lseb, deltab = sl(lse4), sl(delta)
        s = jnp.einsum("bqhd,bkhd->bhqk", qb, kf) * scale
        if causal:
            q_pos = qb_idx * blk_q + jnp.arange(blk_q)
            cm = q_pos[:, None] >= jnp.arange(Sk)[None, :]
            s = jnp.where(cm[None, None], s, NEG_INF)
        p = jnp.exp(s - lseb.transpose(0, 2, 1)[:, :, :, None])
        dv_b = jnp.einsum("bhqk,bqhd->bkhd", p, gb)
        dp = jnp.einsum("bqhd,bkhd->bhqk", gb, vf)
        ds = p * (dp - deltab.transpose(0, 2, 1)[:, :, :, None]) * scale
        dq_b = jnp.einsum("bhqk,bkhd->bqhd", ds, kf)
        dk_b = jnp.einsum("bhqk,bqhd->bkhd", ds, qb)
        return dq_b, dk_b, dv_b

    dq_blocks, dk_blocks, dv_blocks = jax.lax.map(
        block_grads, jnp.arange(n_blocks))
    # dq_blocks: [n_blocks, B, blk_q, H, D] -> [B, Sq, H, D]
    dq = dq_blocks.transpose(1, 0, 2, 3, 4).reshape(B, Sq, H, D)
    dk = jnp.sum(dk_blocks, axis=0)
    dv = jnp.sum(dv_blocks, axis=0)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)


# ----------------------------------------------------------------- dispatch


def _tpu_backend() -> bool:
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:  # noqa: BLE001
        return False


def multi_head_attention(q, k, v, causal: bool = True, mask=None,
                         force: Optional[str] = None):
    """Public attention entry: GQA expand + kernel dispatch.

    q: [B,S,H,D], k/v: [B,S,Hkv,D]. ``force`` in {"flash", "reference"}
    overrides dispatch (tests).
    """
    B, Sq, H, D = q.shape
    Hkv = k.shape[2]
    # The kernel's causal mask assumes Sq == Sk (absolute positions); the
    # blk_k loop assumes Sk tiles exactly. Violations fall back (or raise
    # under force=) instead of silently mis-masking/truncating.
    tiles_ok = (
        mask is None and D % 128 == 0 and Sq == k.shape[1] and Sq % 128 == 0
    )
    if force == "flash":
        if not tiles_ok:
            raise ValueError(
                "force='flash' requires mask=None, D%128==0, and Sq==Sk with "
                "Sq%128==0; got D={}, Sq={}, Sk={}, mask={}".format(
                    D, Sq, k.shape[1], mask is not None))
        use_flash = True
    else:
        use_flash = force is None and _tpu_backend() and tiles_ok
    if not use_flash:
        return attention_reference(q, k, v, causal=causal, mask=mask)
    if Hkv != H:
        rep = H // Hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    blk = 128 if Sq % 128 == 0 else Sq
    interpret = not _tpu_backend()
    return flash_attention(q, k, v, causal, min(blk, Sq), min(128, k.shape[1]),
                           interpret)
