"""Attention: Pallas flash kernel (TPU) with an XLA reference path.

The reference framework has no attention code (SURVEY.md §5.7); this is the
TPU-first hot-op design the BERT/Llama baseline configs need:

- `flash_attention`: Pallas TPU kernel — tiled online-softmax forward, fp32
  accumulators in VMEM scratch, causal block skipping via the grid, O(S)
  memory. Backward is a flash-style recompute VJP (no S x S materialization
  thanks to blockwise lax.map) — good enough until a Pallas bwd kernel lands.
- `attention_reference`: straightforward XLA softmax attention (CPU tests,
  odd shapes).
- `multi_head_attention`: public entry — handles GQA (kv-head repeat),
  dispatches to the kernel when shapes tile cleanly on a TPU backend.

Kernel layout follows the pallas guide (/opt/skills/guides/pallas_guide.md):
grid = (B*H, Sq/BLK_Q, Sk/BLK_K) with the k-block dimension sequential
("arbitrary") and the online-softmax state in persistent VMEM scratch, so
VMEM holds one K/V tile at a time (long-context capable); (8,128)-aligned
tiles, `preferred_element_type=float32` on every MXU dot.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


# ----------------------------------------------------------------- reference


def attention_reference(q, k, v, causal: bool = True, mask=None):
    """[B,S,H,D]x[B,S,Hkv,D] softmax attention in plain XLA (fp32 softmax)."""
    B, Sq, H, D = q.shape
    Hkv = k.shape[2]
    if Hkv != H:
        rep = H // Hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) / jnp.sqrt(D).astype(jnp.float32)
    if causal:
        Sk = k.shape[1]
        cm = jnp.tril(jnp.ones((Sq, Sk), bool), k=Sk - Sq)
        logits = jnp.where(cm[None, None], logits, NEG_INF)
    if mask is not None:
        logits = jnp.where(mask, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32)).astype(q.dtype)


# -------------------------------------------------------------- pallas kernel


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                      acc_ref, m_ref, l_ref, *, causal, sm_scale):
    """One (batch*head, q-block, k-block) program: K/V stream through the
    grid's innermost (sequential) dimension, so VMEM holds only one
    [blk_k, D] tile of K and V at a time — sequence length is bounded by
    HBM, not VMEM. Online-softmax state (acc, running max, running sum)
    lives in VMEM scratch that persists across the k-block iterations of
    each (bh, qi) program group.

    Refs: q [BLK_Q, D]; k/v [BLK_K, D]; o [BLK_Q, D]; lse [BLK_Q, 128]
    (lane-padded); scratch acc [BLK_Q, D], m/l [BLK_Q, 128] fp32.
    """
    from jax.experimental import pallas as pl

    blk_q = q_ref.shape[0]
    blk_k = k_ref.shape[0]
    qi = pl.program_id(1)
    kb = pl.program_id(2)
    num_kb = pl.num_programs(2)

    @pl.when(kb == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    def contribute():
        q = q_ref[:].astype(jnp.float32) * sm_scale
        k_blk = k_ref[:].astype(jnp.float32)
        v_blk = v_ref[:].astype(jnp.float32)
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            q_pos = qi * blk_q + jax.lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 0)
            k_pos = kb * blk_k + jax.lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_prev = m_ref[:, 0]
        l_prev = l_ref[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=1)
        acc_ref[:] = acc_ref[:] * alpha[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_new[:, None], m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new[:, None], l_ref.shape)

    if causal:
        # Blocks entirely above the diagonal contribute nothing — skip the
        # compute (the tile fetch still happens; cheap next to the MXU work).
        @pl.when(kb * blk_k < (qi + 1) * blk_q)
        def _():
            contribute()
    else:
        contribute()

    @pl.when(kb == num_kb - 1)
    def _finalize():
        l_safe = jnp.maximum(l_ref[:, 0], 1e-30)
        o_ref[:] = (acc_ref[:] / l_safe[:, None]).astype(o_ref.dtype)
        lse = m_ref[:, 0] + jnp.log(l_safe)
        lse_ref[:] = jnp.broadcast_to(lse[:, None], lse_ref.shape)


def _flash_fwd(q, k, v, causal: bool, blk_q: int, blk_k: int, interpret: bool):
    """q,k,v: [BH, S, D] (kv already GQA-expanded). Returns (out, lse)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    BH, Sq, D = q.shape
    Sk = k.shape[1]
    sm_scale = 1.0 / (D ** 0.5)
    grid = (BH, Sq // blk_q, Sk // blk_k)
    kernel = functools.partial(_flash_fwd_kernel, causal=causal,
                               sm_scale=sm_scale)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, blk_q, D), lambda bh, qi, kb: (bh, qi, 0)),
            pl.BlockSpec((None, blk_k, D), lambda bh, qi, kb: (bh, kb, 0)),
            pl.BlockSpec((None, blk_k, D), lambda bh, qi, kb: (bh, kb, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, blk_q, D), lambda bh, qi, kb: (bh, qi, 0)),
            pl.BlockSpec((None, blk_q, 128), lambda bh, qi, kb: (bh, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Sq, D), q.dtype),
            jax.ShapeDtypeStruct((BH, Sq, 128), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((blk_q, D), jnp.float32),
            pltpu.VMEM((blk_q, 128), jnp.float32),
            pltpu.VMEM((blk_q, 128), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            # bh/qi programs are independent (megacore-splittable); the
            # k-block dimension carries the online-softmax accumulation and
            # must run sequentially.
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v)
    return out, lse[:, :, 0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal: bool = True, blk_q: int = 128,
                    blk_k: int = 128, interpret: bool = False):
    """Flash attention on [B,S,H,D] with H == Hkv (pre-expanded)."""
    out, _ = _flash_fwd_4d(q, k, v, causal, blk_q, blk_k, interpret)
    return out


def _flash_fwd_4d(q, k, v, causal, blk_q, blk_k, interpret):
    B, Sq, H, D = q.shape
    to3 = lambda x: x.transpose(0, 2, 1, 3).reshape(B * H, x.shape[1], D)  # noqa: E731
    out3, lse = _flash_fwd(to3(q), to3(k), to3(v), causal, blk_q, blk_k, interpret)
    out = out3.reshape(B, H, Sq, D).transpose(0, 2, 1, 3)
    return out, lse


def _flash_fwd_rule(q, k, v, causal, blk_q, blk_k, interpret):
    out, lse = _flash_fwd_4d(q, k, v, causal, blk_q, blk_k, interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd_rule(causal, blk_q, blk_k, interpret, res, g):
    """Flash-style backward: recompute probabilities blockwise from the saved
    log-sum-exp; never materializes the full S x S matrix."""
    q, k, v, out, lse = res
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    scale = 1.0 / (D ** 0.5)
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    delta = jnp.sum(gf * out.astype(jnp.float32), axis=-1)  # [B,S,H]
    lse4 = lse.reshape(B, H, Sq).transpose(0, 2, 1)  # [B,S,H]

    n_blocks = max(1, Sq // blk_q)

    def block_grads(qb_idx):
        sl = lambda x: jax.lax.dynamic_slice_in_dim(x, qb_idx * blk_q, blk_q, 1)  # noqa: E731
        qb, gb = sl(qf), sl(gf)
        lseb, deltab = sl(lse4), sl(delta)
        s = jnp.einsum("bqhd,bkhd->bhqk", qb, kf) * scale
        if causal:
            q_pos = qb_idx * blk_q + jnp.arange(blk_q)
            cm = q_pos[:, None] >= jnp.arange(Sk)[None, :]
            s = jnp.where(cm[None, None], s, NEG_INF)
        p = jnp.exp(s - lseb.transpose(0, 2, 1)[:, :, :, None])
        dv_b = jnp.einsum("bhqk,bqhd->bkhd", p, gb)
        dp = jnp.einsum("bqhd,bkhd->bhqk", gb, vf)
        ds = p * (dp - deltab.transpose(0, 2, 1)[:, :, :, None]) * scale
        dq_b = jnp.einsum("bhqk,bkhd->bqhd", ds, kf)
        dk_b = jnp.einsum("bhqk,bqhd->bkhd", ds, qb)
        return dq_b, dk_b, dv_b

    dq_blocks, dk_blocks, dv_blocks = jax.lax.map(
        block_grads, jnp.arange(n_blocks))
    # dq_blocks: [n_blocks, B, blk_q, H, D] -> [B, Sq, H, D]
    dq = dq_blocks.transpose(1, 0, 2, 3, 4).reshape(B, Sq, H, D)
    dk = jnp.sum(dk_blocks, axis=0)
    dv = jnp.sum(dv_blocks, axis=0)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)


# ----------------------------------------------------------------- dispatch


def _tpu_backend() -> bool:
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:  # noqa: BLE001
        return False


def multi_head_attention(q, k, v, causal: bool = True, mask=None,
                         force: Optional[str] = None):
    """Public attention entry: GQA expand + kernel dispatch.

    q: [B,S,H,D], k/v: [B,S,Hkv,D]. ``force`` in {"flash", "reference"}
    overrides dispatch (tests).
    """
    B, Sq, H, D = q.shape
    Hkv = k.shape[2]
    # The kernel's causal mask assumes Sq == Sk (absolute positions); the
    # blk_k loop assumes Sk tiles exactly. Violations fall back (or raise
    # under force=) instead of silently mis-masking/truncating.
    tiles_ok = (
        mask is None and D % 128 == 0 and Sq == k.shape[1] and Sq % 128 == 0
    )
    if force == "flash":
        if not tiles_ok:
            raise ValueError(
                "force='flash' requires mask=None, D%128==0, and Sq==Sk with "
                "Sq%128==0; got D={}, Sq={}, Sk={}, mask={}".format(
                    D, Sq, k.shape[1], mask is not None))
        use_flash = True
    else:
        use_flash = force is None and _tpu_backend() and tiles_ok
    if not use_flash:
        return attention_reference(q, k, v, causal=causal, mask=mask)
    if Hkv != H:
        rep = H // Hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    blk = 128 if Sq % 128 == 0 else Sq
    interpret = not _tpu_backend()
    return flash_attention(q, k, v, causal, min(blk, Sq), min(128, k.shape[1]),
                           interpret)
