"""Attention: Pallas flash kernel (TPU) with an XLA reference path.

The reference framework has no attention code (SURVEY.md §5.7); this is the
TPU-first hot-op design the BERT/Llama baseline configs need:

- `flash_attention`: Pallas TPU kernels — tiled online-softmax forward and
  a two-kernel backward (dK/dV streaming Q tiles, dQ streaming K/V tiles),
  fp32 accumulators in VMEM scratch, causal block skipping, O(tile) VMEM
  and no S x S materialization in either direction. Natively supports:
    * GQA — K/V carry Hkv < H heads and are NEVER repeat-expanded: the
      query heads are viewed as [B, Hkv, rep, S, D] and the kv BlockSpec
      index maps simply ignore the rep axis, so each kv tile is fetched
      once per group and dK/dV accumulate across the group's rep
      (sequential) grid dimension.
    * key-padding masks ([B, Sk] keep-mask) — the BERT fine-tune config's
      mask shape, streamed as one [1, blk_k] tile per k-block.
    * Sq != Sk, with bottom-right-aligned causal masking (offset = Sk-Sq),
      e.g. decode windows / ring-attention shards.
    * head_dim >= 64 (64 for BERT-base; Mosaic lane-pads D < 128 tiles).
  Per-row statistics (log-sum-exp, and delta in the backward) are stored
  COMPACTLY as [B, G, rep, 1, Sq] fp32 with q-rows on the lane dimension
  (one [1, blk_q] tile per q-block) — not broadcast to 128 lanes in HBM.
- `attention_reference`: straightforward XLA softmax attention (CPU tests,
  odd shapes).
- `multi_head_attention`: public entry — dispatches to the kernel when
  shapes tile cleanly on a TPU backend, XLA reference otherwise.

Kernel layout follows the pallas guide (/opt/skills/guides/pallas_guide.md):
the k-block grid dimension is sequential ("arbitrary") and carries the
online-softmax state in persistent VMEM scratch, so VMEM holds one K/V tile
at a time (long-context capable); (8,128)-aligned tiles,
`preferred_element_type=float32` on every MXU dot.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


# ----------------------------------------------------------------- reference


def attention_reference(q, k, v, causal: bool = True, mask=None):
    """[B,Sq,H,D]x[B,Sk,Hkv,D] softmax attention in plain XLA (fp32 softmax).

    ``mask`` broadcasts against [B,H,Sq,Sk] logits (True = attend). When
    ``causal`` and Sq != Sk the mask is bottom-right aligned (the last query
    row sees every key), matching the flash kernel.
    """
    B, Sq, H, D = q.shape
    Hkv = k.shape[2]
    if Hkv != H:
        rep = H // Hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) / jnp.sqrt(D).astype(jnp.float32)
    if causal:
        Sk = k.shape[1]
        cm = jnp.tril(jnp.ones((Sq, Sk), bool), k=Sk - Sq)
        logits = jnp.where(cm[None, None], logits, NEG_INF)
    if mask is not None:
        logits = jnp.where(mask, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32)).astype(q.dtype)


# --------------------------------------------------------------- head views


def _grouped_q(x, Hkv):
    """[B,S,H,D] -> [B, Hkv, rep, S, D]: query heads grouped by the kv head
    they share, so kv index maps can drop the rep axis (GQA without repeat)."""
    B, S, H, D = x.shape
    rep = H // Hkv
    return x.transpose(0, 2, 1, 3).reshape(B, Hkv, rep, S, D)


def _grouped_kv(x):
    """[B,S,Hkv,D] -> [B, Hkv, S, D]."""
    return x.transpose(0, 2, 1, 3)


def _ungroup_q(x):
    """[B, Hkv, rep, S, D] -> [B,S,H,D]."""
    B, G, R, S, D = x.shape
    return x.reshape(B, G * R, S, D).transpose(0, 2, 1, 3)


def _ungroup_kv(x):
    """[B, Hkv, S, D] -> [B,S,Hkv,D]."""
    return x.transpose(0, 2, 1, 3)


def _tpu_compiler_params(**kwargs):
    """Version-guarded Pallas TPU CompilerParams: the class was renamed
    ``TPUCompilerParams`` -> ``CompilerParams`` across JAX releases, and
    kernel construction must not assume either spelling (the lone tier-1
    failure this guard fixes was exactly that assumption)."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None) \
        or getattr(pltpu, "TPUCompilerParams")
    return cls(**kwargs)


def _causal_tile_mask(s, qi, kb, blk_q, blk_k, offset):
    """Bottom-right-aligned causal mask for one [blk_q, blk_k] tile:
    query row p attends key col c iff c <= p + offset (offset = Sk - Sq)."""
    q_pos = qi * blk_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    k_pos = kb * blk_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(q_pos + offset >= k_pos, s, NEG_INF)


def _apply_pad_mask(s, mask_ref):
    """mask_ref: [1, blk_k] int32 keep-mask tile, broadcast over q rows."""
    return jnp.where(mask_ref[0][None, :] != 0, s, NEG_INF)


# -------------------------------------------------------------- pallas kernel


def _flash_fwd_kernel(*refs, causal, sm_scale, has_mask, offset):
    """One (b, g, r, q-block, k-block) program: K/V stream through the
    grid's innermost (sequential) dimension, so VMEM holds only one
    [blk_k, D] tile of K and V at a time — sequence length is bounded by
    HBM, not VMEM. Online-softmax state (acc, running max, running sum)
    lives in VMEM scratch that persists across the k-block iterations of
    each program group.

    Refs: q [BLK_Q, D]; k/v [BLK_K, D]; (mask [1, BLK_K] int32);
    o [BLK_Q, D]; lse [1, BLK_Q] (q-rows on lanes — compact, no 128x pad);
    scratch acc [BLK_Q, D], m/l [BLK_Q, 128] fp32.
    """
    from jax.experimental import pallas as pl

    if has_mask:
        q_ref, k_ref, v_ref, mask_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref = refs
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref = refs
        mask_ref = None

    blk_q = q_ref.shape[0]
    blk_k = k_ref.shape[0]
    qi = pl.program_id(3)
    kb = pl.program_id(4)
    num_kb = pl.num_programs(4)

    @pl.when(kb == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    def contribute():
        q = q_ref[:].astype(jnp.float32) * sm_scale
        k_blk = k_ref[:].astype(jnp.float32)
        v_blk = v_ref[:].astype(jnp.float32)
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            s = _causal_tile_mask(s, qi, kb, blk_q, blk_k, offset)
        if mask_ref is not None:
            s = _apply_pad_mask(s, mask_ref)
        m_prev = m_ref[:, 0]
        l_prev = l_ref[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=1)
        acc_ref[:] = acc_ref[:] * alpha[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_new[:, None], m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new[:, None], l_ref.shape)

    if causal:
        # Blocks entirely above the diagonal contribute nothing — skip the
        # compute (the tile fetch still happens; cheap next to the MXU work).
        @pl.when(kb * blk_k < (qi + 1) * blk_q + offset)
        def _():
            contribute()
    else:
        contribute()

    @pl.when(kb == num_kb - 1)
    def _finalize():
        l_safe = jnp.maximum(l_ref[:, 0], 1e-30)
        o_ref[:] = (acc_ref[:] / l_safe[:, None]).astype(o_ref.dtype)
        lse = m_ref[:, 0] + jnp.log(l_safe)
        lse_ref[:] = lse[None, :]


def _flash_fwd(qg, kg, vg, mask, causal, blk_q, blk_k, interpret):
    """qg: [B,G,R,Sq,D]; kg/vg: [B,G,Sk,D]; mask: [B,1,Sk] int32 or None.
    Returns (out [B,G,R,Sq,D], lse [B,G,R,1,Sq] fp32)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, G, R, Sq, D = qg.shape
    Sk = kg.shape[2]
    offset = Sk - Sq
    sm_scale = 1.0 / (D ** 0.5)
    grid = (B, G, R, Sq // blk_q, Sk // blk_k)

    q_spec = pl.BlockSpec((None, None, None, blk_q, D),
                          lambda b, g, r, qi, kb: (b, g, r, qi, 0))
    kv_spec = pl.BlockSpec((None, None, blk_k, D),
                           lambda b, g, r, qi, kb: (b, g, kb, 0))
    in_specs = [q_spec, kv_spec, kv_spec]
    operands = [qg, kg, vg]
    if mask is not None:
        in_specs.append(pl.BlockSpec((None, 1, blk_k),
                                     lambda b, g, r, qi, kb: (b, 0, kb)))
        operands.append(mask)

    kernel = functools.partial(_flash_fwd_kernel, causal=causal,
                               sm_scale=sm_scale, has_mask=mask is not None,
                               offset=offset)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            q_spec,
            pl.BlockSpec((None, None, None, 1, blk_q),
                         lambda b, g, r, qi, kb: (b, g, r, 0, qi)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, G, R, Sq, D), qg.dtype),
            jax.ShapeDtypeStruct((B, G, R, 1, Sq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((blk_q, D), jnp.float32),
            pltpu.VMEM((blk_q, 128), jnp.float32),
            pltpu.VMEM((blk_q, 128), jnp.float32),
        ],
        compiler_params=_tpu_compiler_params(
            # b/g/r/qi programs are independent (megacore-splittable); the
            # k-block dimension carries the online-softmax accumulation and
            # must run sequentially.
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*operands)
    return out, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def flash_attention(q, k, v, mask=None, causal: bool = True, blk_q: int = 128,
                    blk_k: int = 128, interpret: bool = False):
    """Flash attention on q [B,Sq,H,D], k/v [B,Sk,Hkv,D] (Hkv divides H —
    GQA handled without materializing repeated K/V). ``mask``: optional
    [B, Sk] (or [B,1,Sk]) keep-mask over keys. A query row whose keys are
    ALL masked outputs the uniform average of V (p = exp(NEG_INF-NEG_INF)
    per key — the same value the reference's softmax-of-all-masked
    produces); such rows are padding and must be excluded from the loss."""
    out, _ = _flash_fwd_4d(q, k, v, mask, causal, blk_q, blk_k, interpret)
    return out


def _canon_mask(mask, B, Sk):
    if mask is None:
        return None
    m = jnp.asarray(mask)
    if m.ndim == 1:
        m = m[None, :]
    if m.ndim == 2:
        m = m[:, None, :]
    if m.shape != (B, 1, Sk):
        m = jnp.broadcast_to(m, (B, 1, Sk))
    return m.astype(jnp.int32)


def _flash_fwd_4d(q, k, v, mask, causal, blk_q, blk_k, interpret):
    B, Sq, H, D = q.shape
    Hkv = k.shape[2]
    mask3 = _canon_mask(mask, B, k.shape[1])
    out_g, lse = _flash_fwd(_grouped_q(q, Hkv), _grouped_kv(k), _grouped_kv(v),
                            mask3, causal, blk_q, blk_k, interpret)
    return _ungroup_q(out_g), lse


def _flash_fwd_rule(q, k, v, mask, causal, blk_q, blk_k, interpret):
    out, lse = _flash_fwd_4d(q, k, v, mask, causal, blk_q, blk_k, interpret)
    return out, (q, k, v, mask, out, lse)


def _recompute_p_ds(q, k_blk, v_blk, do, lse, delta, qi, kb, blk_q, blk_k,
                    causal, sm_scale, offset, mask_ref):
    """Shared bwd block math: probabilities from the saved LSE, then the
    softmax-transpose ds = p * (dO·Vᵀ - delta) * scale. All [blk_q, blk_k].
    ``lse``/``delta`` arrive as [blk_q, 1] (lane->sublane relayout done by
    the caller from the compact [1, blk_q] tiles)."""
    s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * sm_scale
    if causal:
        s = _causal_tile_mask(s, qi, kb, blk_q, blk_k, offset)
    if mask_ref is not None:
        s = _apply_pad_mask(s, mask_ref)
    p = jnp.exp(s - lse)
    dp = jax.lax.dot_general(do, v_blk, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta) * sm_scale
    return p, ds


def _flash_bwd_dkdv_kernel(*refs, causal, sm_scale, has_mask, offset):
    """grid (B, G, kb, r, qi): one K/V tile per program group; the two
    sequential inner dims stream every (rep, q-block) pair of the group
    through it, accumulating dK/dV in VMEM scratch — GQA gradients sum over
    the group's query heads without any repeated K/V in HBM."""
    from jax.experimental import pallas as pl

    if has_mask:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, mask_ref,
         dk_ref, dv_ref, dk_acc, dv_acc) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dk_ref, dv_ref, dk_acc, dv_acc) = refs
        mask_ref = None

    blk_q = q_ref.shape[0]
    blk_k = k_ref.shape[0]
    kb = pl.program_id(2)
    r = pl.program_id(3)
    qi = pl.program_id(4)
    num_r = pl.num_programs(3)
    num_qb = pl.num_programs(4)

    @pl.when((r == 0) & (qi == 0))
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def contribute():
        q = q_ref[:].astype(jnp.float32)
        do = do_ref[:].astype(jnp.float32)
        p, ds = _recompute_p_ds(
            q, k_ref[:].astype(jnp.float32), v_ref[:].astype(jnp.float32),
            do, lse_ref[0][:, None], delta_ref[0][:, None],
            qi, kb, blk_q, blk_k, causal, sm_scale, offset, mask_ref)
        dv_acc[:] += jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)
        dk_acc[:] += jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)

    if causal:
        # Q blocks strictly above this K tile's diagonal see none of it.
        @pl.when(kb * blk_k < (qi + 1) * blk_q + offset)
        def _():
            contribute()
    else:
        contribute()

    @pl.when((r == num_r - 1) & (qi == num_qb - 1))
    def _finalize():
        dk_ref[:] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[:] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd_dq_kernel(*refs, causal, sm_scale, has_mask, offset):
    """grid (B, G, r, qi, kb): one Q tile per program group; stream K/V
    tiles through the sequential kb dimension, accumulating dQ in VMEM."""
    from jax.experimental import pallas as pl

    if has_mask:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, mask_ref,
         dq_ref, dq_acc) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dq_ref, dq_acc) = refs
        mask_ref = None

    blk_q = q_ref.shape[0]
    blk_k = k_ref.shape[0]
    qi = pl.program_id(3)
    kb = pl.program_id(4)
    num_kb = pl.num_programs(4)

    @pl.when(kb == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    def contribute():
        _, ds = _recompute_p_ds(
            q_ref[:].astype(jnp.float32), k_ref[:].astype(jnp.float32),
            v_ref[:].astype(jnp.float32), do_ref[:].astype(jnp.float32),
            lse_ref[0][:, None], delta_ref[0][:, None],
            qi, kb, blk_q, blk_k, causal, sm_scale, offset, mask_ref)
        dq_acc[:] += jax.lax.dot_general(ds, k_ref[:].astype(jnp.float32),
                                         (((1,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)

    if causal:
        @pl.when(kb * blk_k < (qi + 1) * blk_q + offset)
        def _():
            contribute()
    else:
        contribute()

    @pl.when(kb == num_kb - 1)
    def _finalize():
        dq_ref[:] = dq_acc[:].astype(dq_ref.dtype)


def _flash_bwd(qg, kg, vg, dog, lse, delta, mask, causal, blk_q, blk_k,
               interpret):
    """Pallas flash backward. qg/dog: [B,G,R,Sq,D]; kg/vg: [B,G,Sk,D];
    lse/delta: [B,G,R,1,Sq] fp32 (compact); mask: [B,1,Sk] int32 or None.
    Returns (dq [B,G,R,Sq,D], dk/dv [B,G,Sk,D])."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, G, R, Sq, D = qg.shape
    Sk = kg.shape[2]
    offset = Sk - Sq
    sm_scale = 1.0 / (D ** 0.5)
    has_mask = mask is not None

    # --- dK/dV: grid (B, G, kb, r, qi); r+qi sequential, accumulating.
    q_by_inner = pl.BlockSpec((None, None, None, blk_q, D),
                              lambda b, g, kb, r, qi: (b, g, r, qi, 0))
    kv_by_outer = pl.BlockSpec((None, None, blk_k, D),
                               lambda b, g, kb, r, qi: (b, g, kb, 0))
    stat_by_inner = pl.BlockSpec((None, None, None, 1, blk_q),
                                 lambda b, g, kb, r, qi: (b, g, r, 0, qi))
    in_specs = [q_by_inner, kv_by_outer, kv_by_outer, q_by_inner,
                stat_by_inner, stat_by_inner]
    operands = [qg, kg, vg, dog, lse, delta]
    if has_mask:
        in_specs.append(pl.BlockSpec((None, 1, blk_k),
                                     lambda b, g, kb, r, qi: (b, 0, kb)))
        operands.append(mask)

    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkdv_kernel, causal=causal,
                          sm_scale=sm_scale, has_mask=has_mask, offset=offset),
        grid=(B, G, Sk // blk_k, R, Sq // blk_q),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((None, None, blk_k, D),
                         lambda b, g, kb, r, qi: (b, g, kb, 0)),
            pl.BlockSpec((None, None, blk_k, D),
                         lambda b, g, kb, r, qi: (b, g, kb, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, G, Sk, D), kg.dtype),
            jax.ShapeDtypeStruct((B, G, Sk, D), vg.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((blk_k, D), jnp.float32),
            pltpu.VMEM((blk_k, D), jnp.float32),
        ],
        compiler_params=_tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary", "arbitrary")),
        interpret=interpret,
    )(*operands)

    # --- dQ: grid (B, G, r, qi, kb); kb sequential, accumulating.
    q_spec = pl.BlockSpec((None, None, None, blk_q, D),
                          lambda b, g, r, qi, kb: (b, g, r, qi, 0))
    kv_spec = pl.BlockSpec((None, None, blk_k, D),
                           lambda b, g, r, qi, kb: (b, g, kb, 0))
    stat_spec = pl.BlockSpec((None, None, None, 1, blk_q),
                             lambda b, g, r, qi, kb: (b, g, r, 0, qi))
    in_specs = [q_spec, kv_spec, kv_spec, q_spec, stat_spec, stat_spec]
    operands = [qg, kg, vg, dog, lse, delta]
    if has_mask:
        in_specs.append(pl.BlockSpec((None, 1, blk_k),
                                     lambda b, g, r, qi, kb: (b, 0, kb)))
        operands.append(mask)

    (dq,) = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, causal=causal,
                          sm_scale=sm_scale, has_mask=has_mask, offset=offset),
        grid=(B, G, R, Sq // blk_q, Sk // blk_k),
        in_specs=in_specs,
        out_specs=[q_spec],
        out_shape=[jax.ShapeDtypeStruct((B, G, R, Sq, D), qg.dtype)],
        scratch_shapes=[pltpu.VMEM((blk_q, D), jnp.float32)],
        compiler_params=_tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "parallel", "arbitrary")),
        interpret=interpret,
    )(*operands)
    return dq, dk, dv


def _flash_bwd_rule(causal, blk_q, blk_k, interpret, res, g):
    """Flash backward as two Pallas kernels (dK/dV then dQ), recomputing
    probabilities from the saved log-sum-exp — the S x S matrix never
    materializes and VMEM holds one tile pair at a time."""
    q, k, v, mask, out, lse = res
    B, Sq, H, D = q.shape
    Hkv = k.shape[2]
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)  # [B,Sq,H]
    delta_g = delta.transpose(0, 2, 1).reshape(
        B, Hkv, H // Hkv, 1, Sq)
    mask3 = _canon_mask(mask, B, k.shape[1])
    dqg, dkg, dvg = _flash_bwd(
        _grouped_q(q, Hkv), _grouped_kv(k), _grouped_kv(v),
        _grouped_q(g, Hkv), lse, delta_g, mask3,
        causal, blk_q, blk_k, interpret)
    return (_ungroup_q(dqg).astype(q.dtype),
            _ungroup_kv(dkg).astype(k.dtype),
            _ungroup_kv(dvg).astype(v.dtype),
            None)


flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)


# ------------------------------------------------- ring-attention building blocks


def flash_block_fwd(q, k, v, causal: bool = True, blk_q: int = 128,
                    blk_k: int = 128, interpret: bool = False):
    """One (Q shard, K/V shard) flash forward returning BOTH the normalized
    block output and its log-sum-exp — the partial-softmax state ring
    attention merges across shards. q: [B,Sq,H,D], k/v: [B,Sk,Hkv,D];
    returns (out [B,Sq,H,D], lse [B,H,Sq] fp32). Not differentiable on its
    own: the ring owns the VJP (see parallel/ring_attention.py)."""
    out, lse = _flash_fwd_4d(q, k, v, None, causal, blk_q, blk_k, interpret)
    B, Sq, H, _ = q.shape
    return out, lse.reshape(B, H, Sq)


def flash_block_bwd(q, k, v, do, lse, delta, causal: bool = True,
                    blk_q: int = 128, blk_k: int = 128,
                    interpret: bool = False):
    """One block of the ring-attention backward: given the GLOBAL per-row
    log-sum-exp and delta = sum(dO*O), each (Q shard, K/V shard) pair's
    gradient contribution is independent and additive — p recomputed from
    the global lse is the true global probability for this block.
    lse/delta: [B,H,Sq] fp32. Returns (dq, dk, dv) fp32."""
    B, Sq, H, D = q.shape
    Hkv = k.shape[2]
    R = H // Hkv
    stats = lambda x: x.reshape(B, Hkv, R, 1, Sq).astype(jnp.float32)  # noqa: E731
    dqg, dkg, dvg = _flash_bwd(
        _grouped_q(q, Hkv), _grouped_kv(k), _grouped_kv(v),
        _grouped_q(do, Hkv), stats(lse), stats(delta), None,
        causal, blk_q, blk_k, interpret)
    return (_ungroup_q(dqg).astype(jnp.float32),
            _ungroup_kv(dkg).astype(jnp.float32),
            _ungroup_kv(dvg).astype(jnp.float32))


# ----------------------------------------------------------------- dispatch


def _tpu_backend() -> bool:
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:  # noqa: BLE001
        return False


def _flash_disabled() -> bool:
    """Operational kill switch: MAGGY_TPU_NO_FLASH=1 forces the XLA
    reference path everywhere (e.g. to isolate a Mosaic regression on a new
    libtpu without touching code)."""
    import os

    return os.environ.get("MAGGY_TPU_NO_FLASH") == "1"


def resolve_seq_parallel_impl(seq_len: int, head_dim: int, impl: str,
                              interpret: bool, what: str) -> str:
    """Shared flash/xla dispatch for the sequence-parallel wrappers (ring
    attention's inner blocks, Ulysses' full-sequence kernel): one policy so
    the two entry points cannot drift. ``seq_len`` is whatever length the
    kernel actually sees (the ring's shard, Ulysses' gathered S)."""
    flash_ok = seq_len % 128 == 0 and head_dim >= 64 and head_dim % 8 == 0
    if impl == "auto":
        impl = "flash" if flash_ok and not _flash_disabled() \
            and (interpret or (_tpu_backend() and _flash_compiles())) \
            else "xla"
    if impl == "flash" and not flash_ok:
        raise ValueError(
            "impl='flash' needs {} divisible by 128 and D>=64 with D%8==0; "
            "got {}, D={}".format(what, seq_len, head_dim))
    return impl


_FLASH_PROBE: Optional[bool] = None


def _flash_compiles() -> bool:
    """One-time compile probe of the Pallas kernels on the live backend.

    Auto-dispatch must not brick every attention model if a libtpu/Mosaic
    update rejects a kernel layout: probe a tiny flash call once per
    process; on failure warn LOUDLY and fall back to XLA attention
    (force="flash" still surfaces the real compile error). The probe
    lowers an independent jit, so it is safe to run while an outer model
    step is being traced."""
    global _FLASH_PROBE
    if _FLASH_PROBE is None:
        try:
            q = jnp.zeros((1, 128, 2, 128), jnp.bfloat16)
            kv = jnp.zeros((1, 128, 1, 128), jnp.bfloat16)
            mask = jnp.ones((1, 128), jnp.int32)

            def probe(q, k, v, m):
                # Cover every kernel auto-dispatch can reach: masked AND
                # mask-free forwards (distinct specializations), and — via
                # grad — both backward kernels in each variant.
                return (jnp.sum(flash_attention(q, k, v, m, True) ** 2)
                        + jnp.sum(flash_attention(q, k, v, None, True) ** 2))

            jax.jit(jax.grad(probe, (0, 1, 2))).lower(q, kv, kv, mask).compile()
            _FLASH_PROBE = True
        except Exception as e:  # noqa: BLE001
            import warnings

            warnings.warn(
                "Pallas flash attention failed to COMPILE on backend {!r}; "
                "falling back to XLA reference attention everywhere "
                "(error: {!r})".format(jax.default_backend(), e),
                stacklevel=2)
            _FLASH_PROBE = False
    return _FLASH_PROBE


def _key_padding_mask(mask, B, Sk):
    """Reduce an attention mask to a [B, Sk] keep-mask, or (None, False)
    when it cannot be PROVEN key-padding-only. Only the unambiguous forms
    are accepted: [B,1,1,Sk] (broadcast against [B,H,Sq,Sk] logits) and
    [Sk]. A 2-d mask is NOT accepted — [B, Sk] and a per-query [Sq, Sk]
    mask are indistinguishable by shape when B == Sq, and misreading the
    latter as key padding silently corrupts attention; ambiguous or unknown
    shapes fall back to the XLA reference, which broadcasts them exactly.
    Returns (mask2d, ok)."""
    if mask is None:
        return None, True
    try:
        m = jnp.asarray(mask)
        if m.ndim == 4 and m.shape[1] == 1 and m.shape[2] == 1 \
                and m.shape[3] == Sk and m.shape[0] in (1, B):
            return jnp.broadcast_to(m[:, 0, 0, :], (B, Sk)), True
        if m.ndim == 1 and m.shape[0] == Sk:
            return jnp.broadcast_to(m[None, :], (B, Sk)), True
    except Exception:  # noqa: BLE001 - unbroadcastable -> fall back
        pass
    return None, False


def multi_head_attention(q, k, v, causal: bool = True, mask=None,
                         force: Optional[str] = None):
    """Public attention entry: kernel dispatch with XLA fallback.

    q: [B,Sq,H,D], k/v: [B,Sk,Hkv,D]. ``force`` in {"flash", "reference"}
    overrides dispatch (tests). Flash handles GQA natively (no kv repeat),
    key-padding masks, Sq != Sk, and head_dim >= 64; masks with per-query
    structure or non-tiling shapes fall back to the XLA reference.
    """
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    Hkv = k.shape[2]
    if H % Hkv != 0:
        raise ValueError("H={} not divisible by Hkv={}".format(H, Hkv))
    pad_mask, mask_ok = _key_padding_mask(mask, B, Sk)
    tiles_ok = (
        mask_ok and D >= 64 and D % 8 == 0
        and Sq % 128 == 0 and Sk % 128 == 0
    )
    if force == "flash":
        if not tiles_ok:
            raise ValueError(
                "force='flash' requires a key-padding (or no) mask, "
                "D>=64 with D%8==0, and 128-tiling Sq/Sk; got D={}, Sq={}, "
                "Sk={}, mask shape={}".format(
                    D, Sq, Sk, None if mask is None else jnp.shape(mask)))
        use_flash = True
    else:
        use_flash = force is None and _tpu_backend() and tiles_ok \
            and not _flash_disabled() and _flash_compiles()
    if not use_flash:
        return attention_reference(q, k, v, causal=causal, mask=mask)
    interpret = not _tpu_backend()
    return flash_attention(q, k, v, pad_mask, causal, 128, 128, interpret)
