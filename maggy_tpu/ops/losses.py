"""Memory-efficient losses: vocab-chunked softmax cross-entropy.

At flagship scale the lm-head logits are the single largest activation:
Llama-3's 128256-vocab head at [B=4, S=8192] is ~16.8 GB of fp32 logits —
more than half a v4 chip's HBM, and the full tensor is live across the
softmax forward AND stashed for the backward. The reference has no model
code at all (SURVEY.md §5.7); this is TPU-first design for the 8B LoRA
sweep (BASELINE configs[4]).

``chunked_softmax_xent`` computes the exact same loss while only ever
materializing ``[N, vocab_chunk]`` logits: a `lax.scan` over vocab chunks
maintains online logsumexp statistics (the flash-attention trick applied to
the classifier head), and `jax.checkpoint` on the scan body re-derives each
chunk's logits in the backward instead of stashing them. Peak logits
memory drops from O(N·V) to O(N·chunk) in both passes; the matmuls stay
MXU-shaped ([N,H] x [H,chunk], fp32 accumulation).

Sharding: designed for dp/fsdp meshes (vocab replicated, embed sharded —
the flagship layout). Under tp the head's vocab dim is sharded over
"model"; prefer the dense path there (XLA's all-gather per chunk would
serialize the ring).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def chunked_softmax_xent(h, kernel, targets, vocab_chunk: int = 16384):
    """Mean softmax cross-entropy of ``h @ kernel`` against ``targets``,
    without materializing the full [N, V] logits.

    h: [N, H] activations (any float dtype; products accumulate fp32).
    kernel: [H, V] classifier weights.
    targets: [N] int class ids in [0, V).

    Numerically equivalent to
    ``-mean(log_softmax((h @ kernel).astype(f32))[i, targets[i]])``.
    """
    N, H = h.shape
    V = kernel.shape[1]
    vocab_chunk = int(min(vocab_chunk, V))
    num_chunks = -(-V // vocab_chunk)
    col = jnp.arange(vocab_chunk)
    tgt = targets.astype(jnp.int32)

    def body(carry, c0):
        m, s, t = carry
        # The final ragged chunk slides its START back (dynamic_slice-style
        # clamp) rather than padding the kernel — jnp.pad would materialize
        # a second full-size [H, V'] copy of the head, defeating the HBM
        # point. Masking below keeps each column counted exactly once: the
        # chunk OWNS global columns [c0, c0+chunk) ∩ [0, V).
        cs = jnp.minimum(c0, V - vocab_chunk)
        Wk = jax.lax.dynamic_slice_in_dim(kernel, cs, vocab_chunk, axis=1)
        # bf16 MXU matmul with fp32 accumulation — same numerics contract
        # as the dense head (llama.py casts the head to activation dtype).
        logits = jnp.dot(h, Wk.astype(h.dtype),
                         preferred_element_type=jnp.float32)
        gcol = cs + col  # global column index of each slice column
        owned = (gcol >= c0) & (gcol < V)
        logits = jnp.where(owned[None, :], logits, -jnp.inf)
        m_new = jnp.maximum(m, logits.max(axis=-1))
        s = s * jnp.exp(m - m_new) + \
            jnp.exp(logits - m_new[:, None]).sum(axis=-1)
        in_chunk = (tgt >= c0) & (tgt < c0 + vocab_chunk)
        picked = jnp.take_along_axis(
            logits, jnp.clip(tgt - cs, 0, vocab_chunk - 1)[:, None], axis=1
        )[:, 0]
        t = jnp.where(in_chunk, picked, t)
        return (m_new, s, t), None

    init = (jnp.full((N,), -jnp.inf, jnp.float32),
            jnp.zeros((N,), jnp.float32),
            jnp.zeros((N,), jnp.float32))
    starts = jnp.arange(num_chunks, dtype=jnp.int32) * vocab_chunk
    # checkpoint: the backward re-derives each chunk's logits instead of
    # keeping num_chunks * [N, chunk] residuals alive.
    (m, s, t), _ = jax.lax.scan(jax.checkpoint(body), init, starts)
    return jnp.mean(m + jnp.log(s) - t)


def chunked_next_token_loss(hidden, kernel, tokens, vocab_chunk: int = 16384):
    """Causal-LM next-token loss from PRE-head activations.

    hidden: [B, S, H] final-norm outputs (`Llama(..., return_hidden=True)`
    yields exactly this plus the head kernel); kernel: [H, V]; tokens:
    [B, S]. Matches ``next_token_loss(hidden @ kernel, tokens)`` with
    O(B·S·vocab_chunk) instead of O(B·S·V) peak logits memory::

        trainer = Trainer(model, tx,
            lambda out, batch: chunked_next_token_loss(
                out[0], out[1], batch["tokens"]),
            mesh, strategy="fsdp",
            train_kwargs={"return_hidden": True})
    """
    B, S, H = hidden.shape
    h = hidden[:, :-1, :].reshape(-1, H)
    targets = tokens[:, 1:].reshape(-1)
    return chunked_softmax_xent(h, kernel, targets, vocab_chunk)
