"""Driver-side search/scheduling algorithm plugins."""

from maggy_tpu.optimizers.abstractoptimizer import AbstractOptimizer
from maggy_tpu.optimizers.randomsearch import RandomSearch
from maggy_tpu.optimizers.gridsearch import GridSearch
from maggy_tpu.optimizers.singlerun import SingleRun
from maggy_tpu.optimizers.asha import Asha
from maggy_tpu.optimizers.pbt import PBT

__all__ = ["AbstractOptimizer", "RandomSearch", "GridSearch", "SingleRun", "Asha", "PBT"]
