"""Abstract optimizer: the driver-side search-algorithm plugin contract.

Parity: reference `maggy/optimizer/abstractoptimizer.py` — contract at
:54-79; driver-injected attributes at :36-40 (wired by
`optimization_driver.py:87-93`); observation getters with direction
normalization at :136-252; duplicate detection at :254-295; pruner init at
:297-315; trial factory with info_dict/budget injection at :317-376;
ybest/yworst/ymean at :378-443.

Design change vs reference: all optimizers take an optional ``seed`` and draw
from their own ``numpy.random.Generator`` — reproducible schedules.
"""

from __future__ import annotations

import time
from abc import ABC, abstractmethod
from typing import Any, Dict, List, Optional, Union

import numpy as np

from maggy_tpu.searchspace import Searchspace
from maggy_tpu.trial import Trial


class AbstractOptimizer(ABC):
    #: Cost class of one ``suggest()`` call: "cheap" (dict ops — the driver
    #: may run it inline on the RPC dispatch thread to piggyback a reply)
    #: or "expensive" (model fit — suggester thread only).
    SUGGEST_COST = "cheap"

    def __init__(self, seed: Optional[int] = None, pruner=None, pruner_kwargs=None):
        # Fail at construction, not mid-experiment: before the contract
        # split, get_suggestion was @abstractmethod and an incomplete
        # subclass could not even instantiate. Neither method can be
        # abstract now (each has a working default in terms of the other
        # side of the split), so enforce the same guarantee explicitly.
        cls = type(self)
        if cls.get_suggestion is AbstractOptimizer.get_suggestion and \
                cls.suggest is AbstractOptimizer.suggest:
            raise TypeError(
                "{} must implement suggest() (and optionally report()/"
                "recycle()), or override get_suggestion() wholesale".format(
                    cls.__name__))
        # Injected by the driver after construction (reference
        # `optimization_driver.py:87-93`).
        self.searchspace: Optional[Searchspace] = None
        self.num_trials: int = 0
        self.trial_store: Dict[str, Trial] = {}
        self.final_store: List[Trial] = []
        self.direction: str = "max"

        self.seed = seed
        self.rng = np.random.default_rng(seed)
        #: Bumped by ``report`` whenever a FINAL changes the upcoming
        #: schedule (promotion available, pruner stop, experiment done).
        #: The driver stamps prefetched suggestions with the version at
        #: suggest time and refuses to dispatch a stale one.
        self.schedule_version = 0
        self.pruner = None
        self._pruner_name = pruner
        self._pruner_kwargs = pruner_kwargs or {}
        self._log_lines: List[str] = []

    # ------------------------------------------------------------- contract
    #
    # The contract is SPLIT so the driver can pipeline trial hand-offs:
    #
    # - ``report(trial)`` ingests a just-finalized trial (rung/pruner/member
    #   bookkeeping). It MUST run on the FINAL path, before the freed runner
    #   is handed new work, and it is cheap by design (dict ops only).
    # - ``suggest()`` proposes the next Trial / "IDLE" / None and MAY run
    #   ahead of FINALs (a driver-side prefetcher materializes suggestions
    #   on a dedicated thread while runners train, so an expensive model
    #   fit never stalls a freed runner).
    # - ``recycle(trial)`` takes back a suggestion the driver prefetched
    #   but will not dispatch (the schedule changed underneath it — see
    #   ``schedule_version``); controllers with a finite pre-sampled
    #   schedule push the config back so no schedule entry is lost.
    #
    # ``get_suggestion(trial)`` is kept as the legacy single-call form
    # (report + suggest); subclasses that override it wholesale opt OUT of
    # prefetching (``supports_prefetch`` returns False) and get the
    # synchronous driver path.

    @abstractmethod
    def initialize(self) -> None:
        """Called once by the driver before any suggestions are requested."""

    def report(self, trial: Trial) -> None:
        """Ingest a finalized (or errored) trial: schedule bookkeeping that
        must happen before the reporting runner is handed new work.
        Controllers whose ``suggest`` reads only ``final_store`` (already
        updated by the driver) need nothing here. Implementations that
        change the upcoming schedule (an ASHA promotion becoming available,
        the experiment finishing) must bump ``schedule_version`` so the
        driver drops stale prefetched suggestions instead of dispatching
        them."""

    def suggest(self):
        """Return the next Trial, "IDLE" (ask again later), or None (no
        more work). May be called ahead of FINALs by the prefetcher; the
        driver serializes all calls, so no internal locking is needed."""
        raise NotImplementedError

    def recycle(self, trial: Trial) -> None:
        """Take back a suggestion the driver prefetched but invalidated
        before dispatch. Default: drop it (samplers re-draw to fill their
        schedule); buffer-backed controllers re-queue the config."""

    def get_suggestion(self, trial: Optional[Trial] = None):
        """Legacy single-call form: return the next Trial, "IDLE" (ask
        again later), or None (done). ``trial`` is the just-finalized
        trial, if any (reference `abstractoptimizer.py:62-75`)."""
        if trial is not None:
            self.report(trial)
        return self.suggest()

    def supports_prefetch(self) -> bool:
        """True when this controller implements the split report/suggest
        contract (the default ``get_suggestion`` is untouched) — the
        precondition for the driver's prefetch pipeline. Subclasses that
        override ``get_suggestion`` wholesale fall back to the synchronous
        path."""
        return type(self).get_suggestion is AbstractOptimizer.get_suggestion \
            and type(self).suggest is not AbstractOptimizer.suggest

    def fork_gc_eligible(self) -> List[str]:
        """Checkpoint-GC eligibility (checkpoint-forking search,
        config.fork): trial ids whose on-disk checkpoints NO live or
        schedulable child can still fork from — the driver retires
        their ``checkpoints/`` dir and journals ``ckpt_gc``, bounding a
        forking sweep's disk. Must be CONSERVATIVE: a parent that could
        still be promoted/exploited/continued from must never appear
        (the driver additionally refuses to touch live trials). Default:
        nothing is ever eligible (controllers that fork must say which
        parents are spent)."""
        return []

    def finalize_experiment(self, trials: List[Trial]) -> None:
        """Called once after the experiment completes."""

    def restore(self, finalized: List[Trial]) -> None:
        """Rebuild schedule state from a previous run's finalized trials
        (experiment resume — the reference cannot resume an interrupted
        schedule, SURVEY.md §5.4). The driver has already populated
        final_store; subclasses drop already-executed configs from their
        sampling buffers / rebuild bookkeeping. Default: rely on
        final_store alone."""

    def restore_from_finals(self, finalized: List[Trial],
                            inflight: List[Trial] = ()) -> None:
        """Crash-only driver recovery: rebuild this controller's state by
        re-playing the journal's FINAL stream through the SPLIT contract.
        Default, built on report()/recycle() semantics: ``restore`` runs
        over finalized PLUS in-flight trials — buffer-backed samplers
        must drop the in-flight configs too, since the driver already
        reconstructed those Trial objects and a re-suggested duplicate
        would collide in the store — then every finalized trial is
        ``report()``ed in completion order, exactly the bookkeeping the
        live FINAL path would have done. Prefetched-but-undispatched
        suggestions died with the crashed process: nothing recycles them
        here; they were never committed (no ``queued`` edge), so the
        fresh controller simply re-derives them. Controllers whose
        ``restore`` already rebuilds the same ledgers ``report`` writes
        (ASHA rungs, PBT chains) override this to avoid double entry."""
        self.restore(list(finalized) + list(inflight))
        for trial in finalized:
            self.report(trial)

    @staticmethod
    def _drop_executed(buffer: List[dict], finalized: List[Trial]) -> List[dict]:
        """Filter a config buffer down to configs the previous run did NOT
        execute (trial ids are content-addressed md5s of the params)."""
        done = {t.trial_id for t in finalized}
        return [c for c in buffer
                if Trial._compute_id(dict(c), "optimization") not in done]

    # ------------------------------------------------------------- plumbing

    def _initialize(self, exp_dir: Optional[str] = None) -> None:
        """Driver-side init hook: sets up pruner and calls initialize()."""
        self.init_pruner()
        self.initialize()

    def _finalize_experiment(self, trials: List[Trial]) -> None:
        self.finalize_experiment(trials)

    def _log(self, msg: str) -> None:
        self._log_lines.append("{:.3f} {}".format(time.time(), msg))

    def init_pruner(self):
        """Instantiate the pruner by name; only 'hyperband' exists (reference
        `abstractoptimizer.py:297-315`). Idempotent: the driver calls this
        early to size the schedule, `_initialize` may call it again."""
        if self.pruner is not None or self._pruner_name is None:
            return self.pruner
        if isinstance(self._pruner_name, str):
            if self._pruner_name.lower() != "hyperband":
                raise ValueError(
                    "Unknown pruner '{}'; supported: 'hyperband'.".format(self._pruner_name)
                )
            from maggy_tpu.pruner.hyperband import Hyperband

            self.pruner = Hyperband(
                trial_metric_getter=self.get_metrics_dict, **self._pruner_kwargs
            )
        else:
            self.pruner = self._pruner_name  # pre-built instance
            self.pruner.trial_metric_getter = self.get_metrics_dict
        return self.pruner

    # --------------------------------------------------------- observations
    #
    # Everything is normalized to a MINIMIZATION problem: metrics are negated
    # when direction == "max" (reference `abstractoptimizer.py:136-252`).

    def _sign(self) -> float:
        return -1.0 if self.direction == "max" else 1.0

    def get_hparams_dict(self, trial_ids: Union[str, List[str], None] = None) -> Dict[str, dict]:
        ids = self._select_ids(trial_ids)
        return {t.trial_id: t.params for t in self.final_store if t.trial_id in ids}

    def get_hparams_array(self, budget: Optional[float] = None) -> np.ndarray:
        trials = self._finalized(budget)
        return self.searchspace.transform_batch([self._strip_budget(t.params) for t in trials])

    def get_metrics_dict(self, trial_ids: Union[str, List[str], None] = None) -> Dict[str, float]:
        ids = self._select_ids(trial_ids)
        sign = self._sign()
        out = {}
        for t in self.final_store:
            if t.trial_id in ids and t.final_metric is not None:
                out[t.trial_id] = sign * t.final_metric
        return out

    def get_metrics_array(self, budget: Optional[float] = None) -> np.ndarray:
        trials = self._finalized(budget)
        sign = self._sign()
        return np.asarray([sign * t.final_metric for t in trials], dtype=np.float64)

    def _finalized(self, budget: Optional[float] = None) -> List[Trial]:
        out = [t for t in self.final_store if t.final_metric is not None]
        if budget is not None and budget != 0:
            out = [t for t in out if t.params.get("budget") == budget]
        return out

    def _select_ids(self, trial_ids) -> set:
        if trial_ids is None:
            return {t.trial_id for t in self.final_store}
        if isinstance(trial_ids, str):
            return {trial_ids}
        return set(trial_ids)

    # Scheduler-injected params that are NOT hyperparameters: stripped from
    # reported best_hp/worst_hp and from duplicate/encoding comparisons.
    # Subclasses that inject more (PBT: generation/member) extend this.
    SYNTHETIC_PARAMS = ("budget",)

    def _strip_budget(self, params: Dict[str, Any]) -> Dict[str, Any]:
        return {k: v for k, v in params.items()
                if k not in self.SYNTHETIC_PARAMS}

    def hparams_exist(self, trial: Trial) -> bool:
        """True if this trial's budget-stripped params match any finalized or
        in-flight trial (reference `abstractoptimizer.py:254-295`)."""
        target = self._strip_budget(trial.params)
        for t in self.final_store:
            if self._strip_budget(t.params) == target:
                return True
        for t in self.trial_store.values():
            if self._strip_budget(t.params) == target:
                return True
        return False

    # ----------------------------------------------------------- trial factory

    def create_trial(
        self,
        hparams: Dict[str, Any],
        sample_type: str = "random",
        run_budget: float = 0,
        model_budget: Optional[float] = None,
        parent: Optional[str] = None,
    ) -> Trial:
        """Build a Trial with provenance info (reference
        `abstractoptimizer.py:317-376`): info_dict carries run_budget,
        sample_type ∈ {random, random_forced, model, promoted, grid},
        sampling_time, model_budget; the budget is injected into hparams when
        multi-fidelity (pruner active)."""
        info: Dict[str, Any] = {
            "run_budget": run_budget,
            "sample_type": sample_type,
            "sampling_time": time.time(),
        }
        if model_budget is not None:
            info["model_budget"] = model_budget
        if parent is not None:
            # Promoted-trial lineage: lets the executor warm-start from the
            # parent's checkpoint (TrialContext.restore_parent).
            info["parent"] = parent
        params = dict(hparams)
        if self.pruner is not None and run_budget:
            params["budget"] = run_budget
        return Trial(params, trial_type="optimization", info_dict=info)

    def get_max_budget(self) -> float:
        if self.pruner is None:
            raise ValueError("get_max_budget requires a pruner.")
        return self.pruner.max_budget

    # ------------------------------------------------------------- aggregates

    def ybest(self, budget: Optional[float] = None) -> float:
        y = self.get_metrics_array(budget=budget)
        return float(np.min(y)) if y.size else float("inf")

    def yworst(self, budget: Optional[float] = None) -> float:
        y = self.get_metrics_array(budget=budget)
        return float(np.max(y)) if y.size else float("-inf")

    def ymean(self, budget: Optional[float] = None) -> float:
        y = self.get_metrics_array(budget=budget)
        return float(np.mean(y)) if y.size else float("nan")

    def name(self) -> str:
        return type(self).__name__
