"""ASHA — Asynchronous Successive Halving (arXiv:1810.05934).

Parity: reference `maggy/optimizer/asha.py` — params and validation (:39-69),
rung bookkeeping (:71-82), num_trials assertion (:84), stop at max rung
(:89-92), top-down promotion scan (:94-147), fresh rung-0 sampling (:149-156).

Deliberate fix (flagged in SURVEY.md §2.5): the reference's `_top_k` hardcodes
descending sort (`asha.py:161-170`), silently assuming direction="max". Here
promotion uses the direction-normalized metrics from
`AbstractOptimizer.get_metrics_dict` (everything is a min-problem), so ASHA is
correct for both directions.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional

from maggy_tpu.optimizers.abstractoptimizer import AbstractOptimizer
from maggy_tpu.trial import Trial


class Asha(AbstractOptimizer):
    def __init__(
        self,
        reduction_factor: int = 2,
        resource_min: float = 1,
        resource_max: float = 4,
        seed=None,
    ):
        super().__init__(seed=seed)
        if reduction_factor < 2:
            raise ValueError("reduction_factor must be >= 2, got {}".format(reduction_factor))
        if resource_min <= 0 or resource_max < resource_min:
            raise ValueError(
                "Require 0 < resource_min <= resource_max, got min={} max={}".format(
                    resource_min, resource_max
                )
            )
        self.reduction_factor = reduction_factor
        self.resource_min = resource_min
        self.resource_max = resource_max
        # rung index k -> list of trial ids finalized at that rung
        self.rungs: Dict[int, List[str]] = {0: []}
        # rung index k -> list of trial ids already promoted out of rung k
        self.promoted: Dict[int, List[str]] = {}
        # Exact integer loop, not floor(log()): float error would drop a rung
        # for exact eta-power ratios (log(243, 3) == 4.9999...).
        self.max_rung, b = 0, float(resource_min)
        while b * reduction_factor <= resource_max * (1 + 1e-9):
            b *= reduction_factor
            self.max_rung += 1
        # A survivor reached the top rung: the experiment is over. Set by
        # report(), consumed by suggest() — the split keeps the done
        # decision on the FINAL path while sampling may run ahead.
        self._exhausted = False
        # K-at-a-time rung drain (config.vmap_lanes > 1, advertised by
        # the driver as ``self.vmap_lanes``): once a drain starts, the
        # promotable backlog empties before rung-0 sampling resumes —
        # True between the first and last promotion of a burst.
        self._draining = False

    def initialize(self) -> None:
        # rf^max_rung rung-0 samples are the minimum that lets one trial
        # climb the full ladder (the reference demands rf^(max_rung+1),
        # `asha.py:84` — an extra factor of rf with no correctness purpose).
        needed = self.reduction_factor ** self.max_rung
        if self.num_trials < needed:
            raise ValueError(
                "ASHA with rf={} and {} rungs needs num_trials >= {}, got {}.".format(
                    self.reduction_factor, self.max_rung + 1, needed, self.num_trials
                )
            )

    def rung_budget(self, rung: int) -> float:
        return self.resource_min * (self.reduction_factor ** rung)

    def report(self, trial: Trial) -> None:
        """Bookkeep the just-finalized trial into its rung. Bumps
        ``schedule_version`` when the FINAL changes what suggest() would
        return next — a survivor reaching the top rung (experiment done) or
        a promotion becoming available — so the driver invalidates any
        prefetched rung-0 sample instead of dispatching it ahead of the
        promotion."""
        if trial.final_metric is None:
            return
        rung = trial.info_dict.get("rung", 0)
        self.rungs.setdefault(rung, []).append(trial.trial_id)
        if rung == self.max_rung:
            self._exhausted = True
            self.schedule_version += 1
        elif self._promotable() is not None:
            self.schedule_version += 1

    def _promotable_all(self) -> List[tuple]:
        """Every promotable (rung, parent_id), top rung first and
        best-metric first within a rung — the order both the single-step
        scan and the K-at-a-time drain consume. Pure — promotion is
        committed by suggest()."""
        metrics = self.get_metrics_dict()  # normalized: lower is better
        out: List[tuple] = []
        for rung in sorted(self.rungs.keys(), reverse=True):
            if rung >= self.max_rung:
                continue
            finalized = [tid for tid in self.rungs[rung] if tid in metrics]
            k = len(finalized) // self.reduction_factor
            if k == 0:
                continue
            top_k = sorted(finalized, key=lambda tid: metrics[tid])[:k]
            out.extend((rung, tid) for tid in top_k
                       if tid not in self.promoted.get(rung, []))
        return out

    def _promotable(self):
        """Top-down scan for a promotable (not-yet-promoted) trial:
        (rung, parent_id), or None (reference `asha.py:94-147`)."""
        candidates = self._promotable_all()
        return candidates[0] if candidates else None

    def _rung0_budget_left(self) -> bool:
        sampled = sum(1 for t in self.final_store
                      if t.info_dict.get("rung", 0) == 0)
        in_flight = sum(1 for t in self.trial_store.values()
                        if t.info_dict.get("rung", 0) == 0)
        return sampled + in_flight < self.num_trials

    def suggest(self):
        if self._exhausted:
            return None  # a survivor reached the top — experiment done

        promotable = self._promotable_all()
        if promotable:
            # K-at-a-time rung drain (vectorized dispatch): under
            # config.vmap_lanes = K > 1 a lone promotion (scalar — it
            # restores a checkpoint, so it can never ride a block) would
            # interleave with the rung-0 sample stream and break block
            # assembly one trial at a time. Hold promotions while rung-0
            # sampling can still fill chips, until K pile up — then
            # drain the whole backlog consecutively, so same-rung
            # (same-budget, same program family) promotions run
            # back-to-back on a warm slot and the sample stream stays
            # contiguous. Scalar mode (lanes == 1) takes promotions
            # immediately, bit-for-bit the old schedule.
            lanes = max(1, int(getattr(self, "vmap_lanes", 1) or 1))
            defer = (lanes > 1 and not self._draining
                     and len(promotable) < lanes
                     and self._rung0_budget_left())
            if not defer:
                self._draining = len(promotable) > 1
                rung, parent_id = promotable[0]
                self.promoted.setdefault(rung, []).append(parent_id)
                parent_params = self._lookup_params(parent_id)
                params = self._strip_budget(parent_params)
                params["budget"] = self.rung_budget(rung + 1)
                return Trial(
                    params,
                    info_dict={
                        "sample_type": "promoted",
                        "rung": rung + 1,
                        "parent": parent_id,
                    },
                )
        else:
            self._draining = False

        # No promotion possible (or deferred for the drain): fresh random
        # config at rung 0, unless the sampling budget is exhausted.
        if not self._rung0_budget_left():
            # Everything sampled; wait for in-flight trials to enable promotion.
            return "IDLE" if self.trial_store else None
        params = self.searchspace.get_random_parameter_values(1, rng=self.rng)[0]
        params["budget"] = self.rung_budget(0)
        return Trial(params, info_dict={"sample_type": "random", "rung": 0})

    def recycle(self, trial: Trial) -> None:
        """Take back an invalidated prefetched suggestion. A PROMOTED trial
        must un-commit its parent from the promoted ledger — suggest()
        marked it at materialization, and without this the parent's next
        rung would silently never run (the rung ladder loses an entry).
        Dropped rung-0 random samples need nothing: the sampling budget is
        count-based over final_store + trial_store, so a fresh sample
        replaces them."""
        parent = trial.info_dict.get("parent")
        rung = trial.info_dict.get("rung", 0)
        if parent is not None and rung > 0:
            promoted = self.promoted.get(rung - 1, [])
            if parent in promoted:
                promoted.remove(parent)

    def fork_gc_eligible(self):
        """Checkpoint GC (checkpoint-forking search): a rung parent's
        checkpoint is spent once its PROMOTION CHILD has finalized
        successfully — the child resumed (or chose not to), nothing can
        fork from the parent again (a trial is promoted out of a rung at
        most once, and _promotable never re-picks a promoted id). A
        not-yet-promoted trial stays: promotion eligibility GROWS as
        rungs fill (top-k widens with every FINAL). Once the experiment
        is exhausted every finalized trial's checkpoint is spent —
        EXCEPT the top-rung survivors': the sweep's whole point is the
        winner's trained state, and GC'ing it at the finish line would
        delete the model the user came for."""
        metrics = self.get_metrics_dict()
        if self._exhausted:
            keep = set(self.rungs.get(self.max_rung, []))
            return sorted(tid for tid in metrics if tid not in keep)
        eligible = []
        finalized_children: Dict[str, int] = {}
        for t in self.final_store:
            parent = t.info_dict.get("parent")
            if parent is not None and t.final_metric is not None:
                finalized_children[parent] = \
                    finalized_children.get(parent, 0) + 1
        for rung, parents in self.promoted.items():
            for parent in parents:
                if finalized_children.get(parent):
                    eligible.append(parent)
        return eligible

    def restore(self, finalized) -> None:
        """Rebuild the rung ladder from a previous run: each finalized trial
        re-enters its rung, and a promoted child marks its parent as already
        promoted out of the rung below (in-flight promotions at crash time
        are simply re-derived — same parent, same budget, same trial id)."""
        for t in finalized:
            rung = t.info_dict.get("rung", 0)
            self.rungs.setdefault(rung, []).append(t.trial_id)
            parent = t.info_dict.get("parent")
            if parent is not None and rung > 0:
                self.promoted.setdefault(rung - 1, []).append(parent)

    def restore_from_finals(self, finalized, inflight=()) -> None:
        """Crash-only recovery: ``restore`` already rebuilds exactly the
        ledgers ``report`` writes (rungs) plus the promoted ledger report
        never touches — re-reporting on top would double-enter every
        rung. What restore alone missed is report's DONE decision: a
        survivor that reached the top rung before the crash must leave
        the restored controller exhausted, or the resumed sweep would
        keep promoting past its own finish line. In-flight trials (a
        reconstructed promotion child counts its parent as promoted via
        its own info) need no buffer work — sampling is count-based over
        the stores the driver already repopulated."""
        self.restore(finalized)
        for t in inflight:
            # An in-flight PROMOTED child was committed by the dead
            # incarnation's suggest(): its parent must re-enter the
            # promoted ledger, or _promotable would re-promote the
            # parent into a duplicate child.
            parent = t.info_dict.get("parent")
            rung = t.info_dict.get("rung", 0)
            if parent is not None and rung > 0 \
                    and parent not in self.promoted.get(rung - 1, []):
                self.promoted.setdefault(rung - 1, []).append(parent)
        if any(t.info_dict.get("rung", 0) >= self.max_rung
               for t in finalized if t.final_metric is not None):
            self._exhausted = True
            self.schedule_version += 1

    def _lookup_params(self, trial_id: str) -> dict:
        for t in self.final_store:
            if t.trial_id == trial_id:
                return dict(t.params)
        raise KeyError("Unknown trial id {}".format(trial_id))
