from maggy_tpu.optimizers.bayes.base import BaseAsyncBO
from maggy_tpu.optimizers.bayes.gp import GP
from maggy_tpu.optimizers.bayes.tpe import TPE

__all__ = ["BaseAsyncBO", "GP", "TPE"]
