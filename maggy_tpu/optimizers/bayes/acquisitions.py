"""Acquisition functions for GP-based async Bayesian optimization.

Parity: reference `maggy/optimizer/bayes/acquisitions.py` — strategy objects
with `evaluate(X, model, y_opt)` and an lbfgs-compatible value+gradient form
(:25-62); EI/PI/LCB (:68-135) and async Thompson sampling (:158-179). The
reference wraps skopt's `_gaussian_acquisition`; these are direct closed-form
implementations (all convention: LOWER metric is better, acquisitions are
MINIMIZED).
"""

from __future__ import annotations

import numpy as np
from scipy.stats import norm


class AbstractAcquisition:
    def evaluate(self, X: np.ndarray, model, y_opt: float) -> np.ndarray:
        """Return acquisition values at X (lower = more desirable)."""
        raise NotImplementedError

    def name(self) -> str:
        return type(self).__name__


class GaussianProcess_EI(AbstractAcquisition):
    """Negative expected improvement below the incumbent y_opt."""

    def __init__(self, xi: float = 0.01):
        self.xi = xi

    def evaluate(self, X, model, y_opt):
        mu, std = model.predict(np.atleast_2d(X), return_std=True)
        std = np.maximum(std, 1e-12)
        imp = y_opt - self.xi - mu
        z = imp / std
        ei = imp * norm.cdf(z) + std * norm.pdf(z)
        return -ei


class GaussianProcess_PI(AbstractAcquisition):
    """Negative probability of improvement."""

    def __init__(self, xi: float = 0.01):
        self.xi = xi

    def evaluate(self, X, model, y_opt):
        mu, std = model.predict(np.atleast_2d(X), return_std=True)
        std = np.maximum(std, 1e-12)
        return -norm.cdf((y_opt - self.xi - mu) / std)


class GaussianProcess_LCB(AbstractAcquisition):
    """Lower confidence bound mu - kappa * sigma (already a minimization)."""

    def __init__(self, kappa: float = 1.96):
        self.kappa = kappa

    def evaluate(self, X, model, y_opt):
        mu, std = model.predict(np.atleast_2d(X), return_std=True)
        return mu - self.kappa * std


class AsyTS(AbstractAcquisition):
    """Async Thompson sampling: one joint posterior draw over the candidate
    set; the argmin of the sample is the proposal (reference
    `acquisitions.py:158-179`)."""

    def __init__(self, seed=None):
        self.rng = np.random.default_rng(seed)

    def evaluate(self, X, model, y_opt):
        sample = model.sample_y(np.atleast_2d(X),
                                random_state=int(self.rng.integers(0, 2 ** 31)))
        return sample.reshape(X.shape[0] if X.ndim > 1 else 1, -1)[:, 0]


ACQUISITIONS = {
    "ei": GaussianProcess_EI,
    "pi": GaussianProcess_PI,
    "lcb": GaussianProcess_LCB,
    "asy_ts": AsyTS,
}
