"""Asynchronous Bayesian optimization skeleton.

Parity: reference `maggy/optimizer/bayes/base.py` — warmup buffer (:358-373),
ε-random exploration with random_fraction=0.33 (:239-245), per-budget
surrogate `models` dict with key 0 = single-fidelity (:135-139), pruner
delegation identical to RandomSearch (:187-226), duplicate rejection ending
the experiment after 4 forced-random collisions (:285-298), finished check
(:375-395), async-diversity machinery: busy locations with imputed metrics
for in-flight trials (:397-454), `get_XY` training-matrix builder with
optional interim results where configs are augmented with a normalized
fidelity coordinate z=[x, n] (:456-638), busy-location gating (:667-677).

Subclasses implement ``update_model(budget)`` and
``sampling_routine(budget) -> params_dict``.
"""

from __future__ import annotations

from abc import abstractmethod
from typing import Dict, List, Optional

import numpy as np

from maggy_tpu.optimizers.abstractoptimizer import AbstractOptimizer
from maggy_tpu.trial import Trial


class BaseAsyncBO(AbstractOptimizer):
    #: A GP/TPE fit takes seconds: the driver must never run suggest()
    #: inline on the RPC dispatch thread — only on the suggester thread.
    SUGGEST_COST = "expensive"

    def __init__(
        self,
        num_warmup_trials: int = 15,
        random_fraction: float = 0.33,
        interim_results: bool = False,
        interim_results_interval: int = 10,
        fork_eps: Optional[float] = None,
        seed=None,
        pruner=None,
        pruner_kwargs=None,
    ):
        super().__init__(seed=seed, pruner=pruner, pruner_kwargs=pruner_kwargs)
        self.num_warmup_trials = num_warmup_trials
        self.random_fraction = random_fraction
        self.interim_results = interim_results
        self.interim_results_interval = interim_results_interval
        #: Checkpoint-forking near-duplicate warm start (config.fork):
        #: when a MODEL-proposed config lands within ``fork_eps`` (L2 in
        #: the searchspace's normalized transform) of an already
        #: finalized config, the suggestion carries that trial as its
        #: ``parent`` — the driver forks the neighbor's checkpoint and a
        #: ctx-aware train fn fine-tunes from it instead of re-training
        #: from scratch. None (default) = off: BO proposals are
        #: exploratory by design, so opting in is an explicit judgment
        #: that the space is smooth enough for neighbor warm starts.
        self.fork_eps = fork_eps
        self.warmup_buffer: List[dict] = []
        #: budget -> fitted surrogate (0 = single fidelity), set by update_model
        self.models: Dict[float, object] = {}
        #: trial_id -> imputed metric for busy locations (diagnostics)
        self.imputed_metrics: Dict[str, float] = {}
        self._forced_random_failures = 0

    # ------------------------------------------------------------- contract

    @abstractmethod
    def update_model(self, budget: float = 0) -> None:
        """(Re)fit the surrogate for ``budget`` from current observations."""

    @abstractmethod
    def sampling_routine(self, budget: float = 0) -> dict:
        """Propose the next hyperparameter dict by optimizing the surrogate."""

    # ----------------------------------------------------------- main logic

    def initialize(self) -> None:
        n = min(self.num_warmup_trials, self.num_trials) if self.pruner is None \
            else self.num_warmup_trials
        self.warmup_buffer = self.searchspace.get_random_parameter_values(n, rng=self.rng)

    def suggest(self):
        # report() is a no-op: the surrogate trains on final_store (already
        # updated by the driver before report runs) and in-flight configs
        # come from trial_store — which includes prefetched trials, so a
        # suggestion materialized ahead of time is imputed as a busy
        # location exactly like a dispatched one. The model fit below is
        # the expensive step the driver's suggester thread exists for.
        if self._experiment_finished():
            return None

        budget = 0
        parent_id = None
        if self.pruner is None:
            # Count in-flight trials against the budget, else N concurrent
            # runners overshoot num_trials by up to N-1.
            if len(self.final_store) + len(self.trial_store) >= self.num_trials:
                return "IDLE" if self.trial_store else None
        if self.pruner is not None:
            next_run = self.pruner.pruning_routine()
            if next_run == "IDLE":
                return "IDLE"
            if next_run is None:
                return None
            parent_id, budget = next_run["trial_id"], next_run["budget"]
            if parent_id is not None:
                # Promotion: re-run parent's config at the new budget.
                params = self._strip_budget(self._lookup_params(parent_id))
                new_trial = self.create_trial(params, sample_type="promoted",
                                              run_budget=budget, parent=parent_id)
                self.pruner.report_trial(parent_id, new_trial.trial_id)
                return new_trial

        new_trial = self._propose(budget)
        if new_trial is None:
            return None
        if self.pruner is not None:
            self.pruner.report_trial(None, new_trial.trial_id)
        return new_trial

    def restore(self, finalized) -> None:
        # final_store (already repopulated by the driver) is the surrogate's
        # training data; only the warmup buffer needs dedup against the
        # previous run (the driver enforces a fixed seed for resume, so the
        # rerun presamples the same warmup configs).
        self.warmup_buffer = self._drop_executed(self.warmup_buffer, finalized)

    def _propose(self, budget: float) -> Optional[Trial]:
        # 1. warmup buffer
        if self.warmup_buffer:
            params = self.warmup_buffer.pop(0)
            return self.create_trial(params, sample_type="random", run_budget=budget)
        # 2. ε-random exploration / not enough data for a model
        model_budget = self._model_budget(budget)
        have_data = len(self._finalized(model_budget if model_budget else None)) >= max(
            3, len(self.searchspace) + 1
        )
        trial = None
        if self.rng.random() >= self.random_fraction and have_data:
            self.update_model(model_budget)
            if self.models.get(model_budget) is not None:
                params = self.sampling_routine(model_budget)
                trial = self.create_trial(
                    params, sample_type="model", run_budget=budget, model_budget=model_budget
                )
        if trial is None:
            params = self.searchspace.get_random_parameter_values(1, rng=self.rng)[0]
            trial = self.create_trial(params, sample_type="random", run_budget=budget)
        # 3. duplicate rejection: up to 4 forced-random retries (reference
        #    `base.py:285-298`).
        retries = 0
        while self.hparams_exist(trial) and retries < 4:
            retries += 1
            params = self.searchspace.get_random_parameter_values(1, rng=self.rng)[0]
            trial = self.create_trial(params, sample_type="random_forced", run_budget=budget)
        if self.hparams_exist(trial):
            self._forced_random_failures += 1
            return None
        # Near-duplicate warm start (fork_eps): a model proposal next to
        # an executed config inherits its checkpoint as a fork parent.
        # Model proposals only — warmup/random samples are exploration
        # and must stay unbiased by a neighbor's training trajectory.
        if self.fork_eps is not None \
                and trial.info_dict.get("sample_type") == "model":
            donor = self._near_duplicate(trial)
            if donor is not None:
                trial.info_dict["parent"] = donor
                trial.info_dict["near_duplicate"] = True
        return trial

    #: Weight of the warm-started-neighbor acquisition discount: how
    #: strongly a candidate near an executed config is favored because it
    #: is CHEAPER to evaluate, not better — it forks the neighbor's
    #: checkpoint (config.fork), and under vectorized dispatch
    #: (config.vmap_lanes > 1) it rides the parent's family as a fork
    #: lane inside an already-compiled block, costing a lane instead of
    #: a chip. Scalar forks get half the weight (the checkpoint still
    #: skips the prefix, but the trial holds its own chip).
    FORK_DISCOUNT = 0.25

    def warm_neighbor_proximity(self, X_cand) -> Optional[np.ndarray]:
        """Per-candidate proximity in [0, 1] to the nearest FINALIZED
        config, linear within ``fork_eps`` of the normalized transform
        (1 = exact re-run, 0 = at/beyond the fork radius). None when the
        discount is inactive (``fork_eps`` unset or nothing finalized
        yet). Subclasses fold this into their acquisition as a
        cost-awareness tilt — see ``GP.sampling_routine`` /
        ``TPE.sampling_routine``."""
        if self.fork_eps is None or not np.isfinite(float(self.fork_eps)) \
                or float(self.fork_eps) <= 0:
            return None
        finalized = self._finalized()
        if not finalized:
            return None
        X = np.asarray(self.searchspace.transform_batch(
            [self._strip_budget(t.params) for t in finalized]),
            dtype=np.float64)
        Xc = np.asarray(X_cand, dtype=np.float64)
        if Xc.ndim == 1:
            Xc = Xc[np.newaxis, :]
        d = np.sqrt(((Xc[:, None, :] - X[None, :, :]) ** 2)
                    .sum(axis=2)).min(axis=1)
        return np.clip(1.0 - d / float(self.fork_eps), 0.0, 1.0)

    def fork_discount_weight(self) -> float:
        """The effective discount weight: full under vectorized lanes
        (the driver advertises ``vmap_lanes`` on the controller — a fork
        lane shares its parent's block), half for scalar checkpoint
        forks."""
        lanes = max(1, int(getattr(self, "vmap_lanes", 1) or 1))
        return self.FORK_DISCOUNT * (1.0 if lanes > 1 else 0.5)

    def _near_duplicate(self, trial: Trial) -> Optional[str]:
        """The nearest finalized trial within ``fork_eps`` (L2 over the
        searchspace's normalized transform), or None."""
        finalized = self._finalized()
        if not finalized:
            return None
        X = self.searchspace.transform_batch(
            [self._strip_budget(t.params) for t in finalized])
        x = self.searchspace.transform_batch(
            [self._strip_budget(trial.params)])[0]
        d = np.linalg.norm(np.asarray(X) - np.asarray(x), axis=1)
        i = int(np.argmin(d))
        if float(d[i]) <= float(self.fork_eps):
            return finalized[i].trial_id
        return None

    def _model_budget(self, run_budget: float) -> float:
        """Which surrogate to use for a given run budget: largest budget with
        enough observations, else the run budget itself (reference trains one
        model per budget, falling back down the ladder)."""
        if self.pruner is None:
            return 0
        candidates = sorted(
            {t.params.get("budget", 0) for t in self.final_store}, reverse=True
        )
        for b in candidates:
            if len(self._finalized(b)) >= max(3, len(self.searchspace) + 1):
                return b
        return run_budget

    def _experiment_finished(self) -> bool:
        if self.pruner is not None:
            return self.pruner.finished()
        return len(self.final_store) >= self.num_trials

    def _lookup_params(self, trial_id: str) -> dict:
        for t in self.final_store:
            if t.trial_id == trial_id:
                return dict(t.params)
        if trial_id in self.trial_store:
            return dict(self.trial_store[trial_id].params)
        raise KeyError("Unknown trial id {}".format(trial_id))

    # ------------------------------------------------- training-matrix build

    def busy_locations(self, budget: float = 0) -> List[tuple]:
        """(trial_id, config) of in-flight trials at this budget."""
        out = []
        for t in self.trial_store.values():
            if budget in (0, t.params.get("budget", 0)):
                out.append((t.trial_id, self._strip_budget(t.params)))
        return out

    def get_XY(
        self,
        budget: float = 0,
        include_busy_locations: bool = False,
        impute_strategy: str = "cl_min",
        interim: bool = False,
    ):
        """Build (X, y) for surrogate training (reference `base.py:456-638`).

        - metrics are direction-normalized (lower better)
        - ``include_busy_locations``: append in-flight configs with an imputed
          metric — constant liar cl_min/cl_max/cl_mean, or 'kb' (kriging
          believer: posterior mean of the current model)
        - ``interim``: one row per interim observation, config augmented with
          a normalized fidelity coordinate n ∈ (0, 1]
        """
        trials = self._finalized(budget if budget else None)
        sign = self._sign()
        if not interim:
            X = self.searchspace.transform_batch(
                [self._strip_budget(t.params) for t in trials]
            )
            y = np.asarray([sign * t.final_metric for t in trials], dtype=np.float64)
        else:
            rows, ys = [], []
            for t in trials:
                hist = t.metric_history
                if not hist:
                    continue
                x = self.searchspace.transform(self._strip_budget(t.params))
                steps = list(range(0, len(hist), self.interim_results_interval))
                if (len(hist) - 1) not in steps:
                    steps.append(len(hist) - 1)
                for s in steps:
                    rows.append(np.concatenate([x, [(s + 1) / len(hist)]]))
                    ys.append(sign * hist[s])
            X = np.asarray(rows) if rows else np.zeros((0, len(self.searchspace) + 1))
            y = np.asarray(ys, dtype=np.float64)

        if include_busy_locations and not interim:
            busy = self.busy_locations(budget)
            if busy:
                busy_ids = [tid for tid, _ in busy]
                Xb = self.searchspace.transform_batch([cfg for _, cfg in busy])
                yb = self._impute(Xb, y, impute_strategy, budget)
                for tid, m in zip(busy_ids, yb):
                    self.imputed_metrics[tid] = float(m)
                X = np.vstack([X, Xb]) if X.size else Xb
                y = np.concatenate([y, yb])
        return X, y

    def _impute(self, Xb: np.ndarray, y_obs: np.ndarray, strategy: str, budget: float):
        if y_obs.size == 0:
            return np.zeros(len(Xb))
        if strategy == "cl_min":
            return np.full(len(Xb), float(np.min(y_obs)))
        if strategy == "cl_max":
            return np.full(len(Xb), float(np.max(y_obs)))
        if strategy == "cl_mean":
            return np.full(len(Xb), float(np.mean(y_obs)))
        if strategy == "kb":
            model = self.models.get(budget)
            if model is None:
                return np.full(len(Xb), float(np.mean(y_obs)))
            return np.asarray(model.predict(Xb)).reshape(-1)
        raise ValueError("Unknown impute strategy {!r}".format(strategy))
