"""GP-surrogate async Bayesian optimization.

Parity: reference `maggy/optimizer/bayes/gp.py` — surrogate is a Gaussian
process with ConstantKernel x Matern(nu=2.5) + white noise, normalize_y
(:262-287); async strategies 'impute' (constant liar cl_min/cl_max/cl_mean or
kriging believer 'kb') and 'asy_ts' (async Thompson sampling) (:110-161,
:325-369); sampling routine: evaluate the acquisition on n_points random
candidates (10k default, 100 for asy_ts), refine the best starts with
L-BFGS-B over [0,1]^d, clip and inverse-transform (:183-260).

The reference wraps skopt; here sklearn's GaussianProcessRegressor is used
directly (skopt is dead upstream) with our own closed-form acquisitions.
"""

from __future__ import annotations

import warnings
from typing import Optional

import numpy as np
from scipy.optimize import fmin_l_bfgs_b
from sklearn.exceptions import ConvergenceWarning
from sklearn.gaussian_process import GaussianProcessRegressor
from sklearn.gaussian_process.kernels import ConstantKernel, Matern, WhiteKernel

from maggy_tpu.optimizers.bayes.acquisitions import ACQUISITIONS, AsyTS
from maggy_tpu.optimizers.bayes.base import BaseAsyncBO


class GP(BaseAsyncBO):
    def __init__(
        self,
        acquisition: str = "ei",
        async_strategy: str = "impute",
        impute_strategy: str = "cl_min",
        n_points: Optional[int] = None,
        n_restarts_optimizer: int = 5,
        **kwargs,
    ):
        super().__init__(**kwargs)
        if async_strategy not in ("impute", "asy_ts"):
            raise ValueError("async_strategy must be 'impute' or 'asy_ts'")
        if impute_strategy not in ("cl_min", "cl_max", "cl_mean", "kb"):
            raise ValueError("Unknown impute_strategy {!r}".format(impute_strategy))
        self.async_strategy = async_strategy
        self.impute_strategy = impute_strategy
        if async_strategy == "asy_ts":
            self.acquisition = AsyTS(seed=kwargs.get("seed"))
            self.n_points = n_points or 100
        else:
            if acquisition not in ACQUISITIONS or acquisition == "asy_ts":
                raise ValueError("Unknown acquisition {!r}".format(acquisition))
            self.acquisition = ACQUISITIONS[acquisition]()
            self.n_points = n_points or 10000
        self.n_restarts_optimizer = n_restarts_optimizer

    # ------------------------------------------------------------- surrogate

    def _make_gp(self) -> GaussianProcessRegressor:
        d = len(self.searchspace)
        kernel = ConstantKernel(1.0, (0.01, 100.0)) * Matern(
            length_scale=np.full(d if not self.interim_results else d + 1, 0.3),
            length_scale_bounds=(0.01, 10.0),
            nu=2.5,
        ) + WhiteKernel(1e-4, (1e-8, 1e-1))
        return GaussianProcessRegressor(
            kernel=kernel,
            normalize_y=True,
            n_restarts_optimizer=1,
            random_state=int(self.rng.integers(0, 2 ** 31)),
        )

    def update_model(self, budget: float = 0) -> None:
        include_busy = self.async_strategy == "impute" and len(self.trial_store) > 0
        X, y = self.get_XY(
            budget=budget,
            include_busy_locations=include_busy,
            impute_strategy=self.impute_strategy,
            interim=self.interim_results,
        )
        if len(X) < 2:
            return
        gp = self._make_gp()
        with warnings.catch_warnings():
            # Hyperparameter ML-II on tiny early datasets routinely stops at
            # maxiter; the fit is still usable.
            warnings.simplefilter("ignore", category=ConvergenceWarning)
            gp.fit(X, y)
        self.models[budget] = gp
        # Incumbent in original metric space for the acquisitions (avoids
        # reaching into sklearn's private normalize_y internals).
        self._y_opt = getattr(self, "_y_opt", {})
        self._y_opt[budget] = float(np.min(y))

    # -------------------------------------------------------------- sampling

    def sampling_routine(self, budget: float = 0) -> dict:
        model = self.models[budget]
        d = len(self.searchspace)
        y_opt = self._y_opt[budget]

        X_cand = self.rng.uniform(size=(self.n_points, d))
        if self.interim_results:
            # evaluate at full fidelity n = 1
            X_acq = np.hstack([X_cand, np.ones((len(X_cand), 1))])
        else:
            X_acq = X_cand
        values = self.acquisition.evaluate(X_acq, model, y_opt)

        # Warm-started-neighbor discount (fork_eps): candidates near an
        # executed config are cheaper — a checkpoint fork, or under
        # config.vmap_lanes a fork LANE in the parent's block — so tilt
        # the (lower-is-better) acquisition toward them, scaled by the
        # sweep's value spread so the tilt is a preference, never a
        # takeover of the raw acquisition ranking.
        prox = self.warm_neighbor_proximity(X_cand)
        tilt_scale = 0.0
        if prox is not None:
            v = np.asarray(values, dtype=np.float64).reshape(-1)
            spread = float(np.max(v) - np.min(v))
            if spread > 0.0:
                tilt_scale = self.fork_discount_weight() * spread
                values = (v - tilt_scale * prox).reshape(np.shape(values))

        if isinstance(self.acquisition, AsyTS):
            best = int(np.argmin(values))
            x_best = X_cand[best]
        else:
            # L-BFGS-B refinement from the top starts (reference `gp.py:183-246`).
            order = np.argsort(values.reshape(-1))[: self.n_restarts_optimizer]
            x_best, f_best = X_cand[order[0]], float(values.reshape(-1)[order[0]])

            def objective(x):
                xq = np.concatenate([x, [1.0]]) if self.interim_results else x
                val = float(self.acquisition.evaluate(
                    xq[np.newaxis, :], model, y_opt)[0])
                if tilt_scale > 0.0:
                    p = self.warm_neighbor_proximity(x[np.newaxis, :])
                    if p is not None:
                        val -= tilt_scale * float(p[0])
                return val

            for i in order:
                x0 = X_cand[i]
                xo, fo, _ = fmin_l_bfgs_b(
                    objective, x0, approx_grad=True, bounds=[(0.0, 1.0)] * d, maxfun=50
                )
                if fo < f_best:
                    x_best, f_best = xo, fo
        return self.searchspace.inverse_transform(np.clip(x_best, 0.0, 1.0))
