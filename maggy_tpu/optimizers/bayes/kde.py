"""Mixed-type multivariate kernel density estimation.

The reference delegates to ``statsmodels.nonparametric.KDEMultivariate``
(`tpe.py:223-251`) with var_type 'c' (continuous, Gaussian kernel) and 'u'
(unordered categorical, Aitchison-Aitken kernel). statsmodels is not in this
environment, so this is a from-scratch implementation of exactly the two
kernels TPE needs, with normal-reference-rule bandwidths.
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np


def normal_reference_bw(x: np.ndarray) -> float:
    """Silverman's normal-reference rule for a 1-d continuous sample."""
    n = len(x)
    if n < 2:
        return 1.0
    sigma = np.std(x, ddof=1)
    iqr = np.subtract(*np.percentile(x, [75, 25])) / 1.349
    spread = min(sigma, iqr) if iqr > 0 else sigma
    if spread <= 0:
        spread = max(np.abs(x).max(), 1.0) * 0.1
    return 1.06 * spread * n ** (-1.0 / 5.0)


class MixedKDE:
    """KDE over vectors with continuous ('c') and categorical ('u') dims.

    Continuous dims use Gaussian kernels; categorical dims (encoded as
    integer category indices) use the Aitchison-Aitken kernel
    ``K(x, xi) = 1 - lam + lam/c`` if x == xi else ``lam/c`` — matching
    statsmodels' behavior the reference relies on.
    """

    def __init__(self, data: np.ndarray, var_types: Sequence[str],
                 n_categories: Sequence[int] | None = None):
        self.data = np.atleast_2d(np.asarray(data, dtype=np.float64))
        self.var_types = list(var_types)
        assert self.data.shape[1] == len(self.var_types)
        self.n, self.d = self.data.shape
        self.n_categories = list(n_categories) if n_categories is not None else [
            int(self.data[:, j].max()) + 1 if t == "u" else 0
            for j, t in enumerate(self.var_types)
        ]
        self.bw = np.empty(self.d)
        for j, t in enumerate(self.var_types):
            if t == "c":
                self.bw[j] = max(normal_reference_bw(self.data[:, j]), 1e-3)
            else:
                # Aitchison-Aitken lambda in [0, (c-1)/c]; normal-reference-
                # style shrink with n.
                c = max(self.n_categories[j], 2)
                lam = min((c - 1) / c, 0.5 * self.n ** (-2.0 / (self.d + 4)) + 0.1)
                self.bw[j] = lam

    def pdf(self, X: np.ndarray) -> np.ndarray:
        """Density at each row of X, shape (m,)."""
        X = np.atleast_2d(np.asarray(X, dtype=np.float64))
        m = X.shape[0]
        # (m, n) product of per-dim kernels
        logk = np.zeros((m, self.n))
        for j, t in enumerate(self.var_types):
            diff = X[:, j:j + 1] - self.data[np.newaxis, :, j]
            if t == "c":
                h = self.bw[j]
                logk += -0.5 * (diff / h) ** 2 - np.log(h * np.sqrt(2 * np.pi))
            else:
                lam = self.bw[j]
                c = max(self.n_categories[j], 2)
                same = np.isclose(diff, 0.0)
                k = np.where(same, 1.0 - lam + lam / c, lam / c)
                logk += np.log(k)
        # logsumexp over data points
        mx = logk.max(axis=1, keepdims=True)
        return np.exp(mx.squeeze(1) + np.log(np.exp(logk - mx).sum(axis=1))) / self.n

    def sample_around(self, rng: np.random.Generator, idx: int,
                      bw_factor: float = 1.0) -> np.ndarray:
        """Draw one candidate around data point ``idx`` (TPE's proposal move,
        reference `tpe.py:75-119`): truncated-normal-like draw for continuous
        dims, bandwidth-probability resample for categorical dims."""
        x = np.empty(self.d)
        base = self.data[idx]
        for j, t in enumerate(self.var_types):
            if t == "c":
                h = self.bw[j] * bw_factor
                # rejection-free truncation to [0, 1] (codec range)
                for _ in range(16):
                    v = rng.normal(base[j], h)
                    if 0.0 <= v <= 1.0:
                        break
                x[j] = np.clip(v, 0.0, 1.0)
            else:
                lam = self.bw[j]
                c = max(self.n_categories[j], 2)
                if rng.random() < 1.0 - lam + lam / c:
                    x[j] = base[j]
                else:
                    x[j] = float(rng.integers(0, c))
        return x
