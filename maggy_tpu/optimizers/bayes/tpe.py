"""Tree-structured Parzen Estimator (BOHB/HpBandSter-style).

Parity: reference `maggy/optimizer/bayes/tpe.py` — γ=0.15 good/bad split with
n_good/n_bad floors of d+1 (:191-221), two mixed-type KDEs with var_type c/u
per hparam (:180-189, :223-251), candidate sampling: 24 draws around random
good-KDE datapoints via truncated normals (bandwidth clipped to 1e-3, scaled
by bw_factor=3) for continuous dims and bandwidth-probability resampling for
categorical dims (:75-119), EI = max(good.pdf, 1e-32) / max(bad.pdf, 1e-32)
maximized over candidates (:253-266), interim-results mode rejected (:62-66).

statsmodels is unavailable; the KDE is a from-scratch implementation of the
same two kernels in `kde.py`.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from maggy_tpu.optimizers.bayes.base import BaseAsyncBO
from maggy_tpu.optimizers.bayes.kde import MixedKDE
from maggy_tpu.searchspace import Searchspace


class TPE(BaseAsyncBO):
    def __init__(
        self,
        gamma: float = 0.15,
        num_samples: int = 24,
        bw_factor: float = 3.0,
        **kwargs,
    ):
        if kwargs.get("interim_results"):
            raise ValueError("TPE does not support interim_results.")
        super().__init__(**kwargs)
        self.gamma = gamma
        self.num_samples = num_samples
        self.bw_factor = bw_factor

    # --------------------------------------------------------------- helpers

    def _encode(self, params_list):
        """Encode params: continuous dims via the unit-cube codec, categorical
        dims as integer category indices (what the AA kernel expects)."""
        sp = self.searchspace
        rows = []
        for params in params_list:
            row = []
            for name, hp_type in sp._hparam_types.items():
                region = sp._hparams[name]
                v = params[name]
                if hp_type in Searchspace.CONTINUOUS_TYPES:
                    row.append(sp.encode_continuous(name, v))
                else:
                    row.append(float(region.index(v)))
            rows.append(row)
        return np.asarray(rows, dtype=np.float64)

    def _decode(self, x: np.ndarray) -> dict:
        sp = self.searchspace
        params = {}
        for j, (name, hp_type) in enumerate(sp._hparam_types.items()):
            region = sp._hparams[name]
            if hp_type in Searchspace.CONTINUOUS_TYPES:
                params[name] = sp.decode_continuous(name, x[j])
            else:
                params[name] = region[int(np.clip(x[j], 0, len(region) - 1))]
        return params

    def _n_categories(self):
        sp = self.searchspace
        return [
            len(sp._hparams[name])
            if t in (Searchspace.DISCRETE, Searchspace.CATEGORICAL,
                     Searchspace.GANG) else 0
            for name, t in sp._hparam_types.items()
        ]

    # -------------------------------------------------------------- contract

    def update_model(self, budget: float = 0) -> None:
        trials = self._finalized(budget if budget else None)
        d = len(self.searchspace)
        if len(trials) < 2 * (d + 1):
            self.models.pop(budget, None)
            return
        sign = self._sign()
        y = np.asarray([sign * t.final_metric for t in trials])
        order = np.argsort(y)  # ascending: best first
        n_good = max(d + 1, int(np.ceil(self.gamma * len(trials))))
        n_bad = max(d + 1, len(trials) - n_good)
        X = self._encode([self._strip_budget(t.params) for t in trials])
        var_types = self.searchspace.var_types()
        ncat = self._n_categories()
        good = MixedKDE(X[order[:n_good]], var_types, ncat)
        bad = MixedKDE(X[order[-n_bad:]], var_types, ncat)
        self.models[budget] = {"good": good, "bad": bad}

    def sampling_routine(self, budget: float = 0) -> dict:
        kdes = self.models[budget]
        good, bad = kdes["good"], kdes["bad"]
        best_x, best_ei = None, -np.inf
        weight = self.fork_discount_weight()
        for _ in range(self.num_samples):
            idx = int(self.rng.integers(0, good.n))
            x = good.sample_around(self.rng, idx, bw_factor=self.bw_factor)
            ei = max(good.pdf(x[np.newaxis, :])[0], 1e-32) / max(
                bad.pdf(x[np.newaxis, :])[0], 1e-32
            )
            # Warm-started-neighbor discount (fork_eps): the l/g ratio is
            # higher-is-better, so a candidate near an executed config —
            # a checkpoint fork, or a fork lane in the parent's vmap
            # block — gets a multiplicative boost (cost-aware EI). The
            # KDE's encoding is its own (category indices), so proximity
            # is measured in the searchspace's normalized transform.
            prox = None
            if weight > 0 and self.fork_eps is not None:
                prox = self.warm_neighbor_proximity(
                    self.searchspace.transform(self._decode(x)))
            if prox is not None and prox[0] > 0:
                ei *= 1.0 + weight * float(prox[0])
            if ei > best_ei:
                best_x, best_ei = x, ei
        return self._decode(best_x)
