"""Grid search over DISCRETE/CATEGORICAL spaces.

Parity: reference `maggy/optimizer/gridsearch.py` — cartesian product
(:72-79), continuous-param rejection (:81-90), `get_num_trials` classmethod
used by the driver (:33-43), no pruner support (:47-51).
"""

from __future__ import annotations

from maggy_tpu.optimizers.abstractoptimizer import AbstractOptimizer
from maggy_tpu.searchspace import Searchspace
from maggy_tpu.trial import Trial


class GridSearch(AbstractOptimizer):
    def __init__(self, seed=None, pruner=None, pruner_kwargs=None):
        if pruner is not None:
            raise ValueError("GridSearch does not support pruners.")
        super().__init__(seed=seed)
        self.config_buffer = []

    @classmethod
    def get_num_trials(cls, searchspace: Searchspace) -> int:
        return len(searchspace.grid())

    def initialize(self) -> None:
        self.config_buffer = self.searchspace.grid()

    def suggest(self):
        # report() is a no-op: the grid is fixed, so suggestions may be
        # prefetched arbitrarily far ahead.
        if not self.config_buffer:
            return None
        params = self.config_buffer.pop(0)
        return self.create_trial(params, sample_type="grid")

    def recycle(self, trial: Trial) -> None:
        # The schedule is exactly the grid: an invalidated prefetch goes
        # back so no cell is lost.
        self.config_buffer.insert(0, self._strip_budget(trial.params))

    def restore(self, finalized) -> None:
        # The grid is deterministic; drop cells the previous run covered.
        self.config_buffer = self._drop_executed(self.config_buffer, finalized)
