"""PBT — asynchronous Population Based Training (arXiv:1711.09846).

Beyond the reference's optimizer set (SURVEY.md §2.5 lists randomsearch /
gridsearch / asha / singlerun / GP / TPE): PBT trains a population jointly,
periodically replacing the weakest members with perturbed clones of the
strongest — weights included. It exists here because this framework already
has the two ingredients the reference lacks: an async driver that can hand a
member its next segment the moment the previous one finalizes (no
generation barrier), and per-trial orbax checkpoints with parent warm-start
(`TrialContext.restore_parent`) so "clone the winner's weights" is the same
mechanism ASHA promotions use.

Scheduling model: each population member runs ``generations`` consecutive
trials ("segments") of ``resource_per_generation`` budget each. When member
m's generation-g segment finalizes, its g+1 segment is decided IMMEDIATELY
against the generation-g results seen so far (async PBT, like the paper's
population-device variant):

- bottom ``exploit_quantile`` of finalized gen-g peers -> EXPLOIT: adopt a
  top-quantile peer's hparams (perturbed) and set ``parent`` to that peer's
  segment so the executor warm-starts from its checkpoint;
- otherwise -> CONTINUE: same hparams, ``parent`` = own previous segment.

The train function sees ``generation``, ``member``, and ``budget`` as
hparams and is expected to ``ctx.restore_parent(...)``
(examples/llama_lora_sweep.py shows the pattern for ASHA; PBT uses the
identical contract).
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional

from maggy_tpu.optimizers.abstractoptimizer import AbstractOptimizer
from maggy_tpu.searchspace import Searchspace
from maggy_tpu.trial import Trial


class PBT(AbstractOptimizer):
    SYNTHETIC_PARAMS = ("budget", "generation", "member")

    def __init__(
        self,
        population: int = 8,
        generations: int = 4,
        resource_per_generation: float = 1,
        exploit_quantile: float = 0.25,
        perturb_factors=(0.8, 1.2),
        resample_probability: float = 0.25,
        seed=None,
    ):
        super().__init__(seed=seed)
        if population < 2:
            raise ValueError("population must be >= 2, got {}".format(population))
        if generations < 2:
            raise ValueError("generations must be >= 2, got {}".format(generations))
        if not 0.0 < exploit_quantile <= 0.5:
            raise ValueError(
                "exploit_quantile must be in (0, 0.5], got {}".format(exploit_quantile))
        self.population = population
        self.generations = generations
        self.resource_per_generation = resource_per_generation
        self.exploit_quantile = exploit_quantile
        self.perturb_factors = tuple(perturb_factors)
        self.resample_probability = resample_probability
        self._pending: List[Trial] = []
        # member -> consecutive segment errors; a member that errors twice
        # in a row is retired (dead) so the experiment can still finish.
        self._errors: Dict[int, int] = {}
        self._dead: set = set()

    # ------------------------------------------------------------- lifecycle

    def schedule_size(self) -> int:
        """Total segments = population x generations (the driver-side
        num_trials; same role as GridSearch.get_num_trials)."""
        return self.population * self.generations

    def max_concurrency(self) -> int:
        """Members are sequential segment chains: at most ``population``
        trials are ever in flight, whatever num_workers says."""
        return self.population

    def initialize(self) -> None:
        # All-categorical spaces are fine (explore = resample; the member
        # key keeps same-hparam segments id-unique), so unlike RandomSearch
        # there is no continuous-parameter requirement.
        for member, params in enumerate(
                self.searchspace.get_random_parameter_values(
                    self.population, rng=self.rng)):
            self._pending.append(self._segment(member, 0, params, parent=None,
                                               sample_type="random"))

    # ------------------------------------------------------------ scheduling

    def report(self, trial: Trial) -> None:
        """Decide the member's next segment against the population seen so
        far (the async exploit/continue step) — on the FINAL path, so the
        decision uses this FINAL's metric. Pending segments are appended
        here, never invalidated: each is a committed link of a member's
        sequential chain, so a prefetched segment stays valid whatever
        later FINALs decide (schedule_version is never bumped)."""
        member = trial.info_dict.get("member")
        if member is None:
            return
        if trial.final_metric is not None:
            self._errors.pop(member, None)
            if trial.info_dict.get("generation", 0) + 1 < self.generations:
                self._pending.append(self._next_segment(trial))
        else:
            self._handle_segment_error(trial, member)

    def suggest(self):
        if self._pending:
            return self._pending.pop(0)
        if self._finished():
            return None
        return "IDLE" if self._in_flight() else None

    def recycle(self, trial: Trial) -> None:
        # A member's chain is sequential: a taken-back segment goes to the
        # FRONT so the chain cannot reorder.
        self._pending.insert(0, trial)

    def _handle_segment_error(self, trial: Trial, member: int) -> None:
        """A segment ERRORed (train_fn raised). Retry once from the member's
        last finalized state — or a fresh config if it has none — then
        retire the member so a deterministically-broken lineage cannot spin
        the experiment forever. Without this, one errored segment silently
        ends the whole member (SURVEY.md §5.3's requeue covers runner DEATH,
        not train-side errors)."""
        errors = self._errors.get(member, 0) + 1
        self._errors[member] = errors
        if errors > 1:
            self._dead.add(member)
            return
        prev = self._population_state().get(member)
        if prev is not None:
            self._pending.append(self._next_segment(prev))
        else:
            params = self.searchspace.get_random_parameter_values(
                1, rng=self.rng)[0]
            self._pending.append(self._segment(member, 0, params, parent=None,
                                               sample_type="random"))

    def _finished(self) -> bool:
        done = {t.info_dict.get("member") for t in self.final_store
                if t.info_dict.get("generation", 0) == self.generations - 1
                and t.final_metric is not None}
        return len(done | self._dead) >= self.population

    def _in_flight(self) -> bool:
        return bool(self.trial_store)

    # -------------------------------------------------------------- segments

    def _segment(self, member: int, generation: int, hparams: dict,
                 parent: Optional[str], sample_type: str) -> Trial:
        params = dict(hparams)
        params["generation"] = generation
        # member rides in params so segment ids stay unique: trial ids hash
        # params only, and two members exploiting the same donor with the
        # same perturb draw produce IDENTICAL hparams — without the member
        # key their segments collapse into one driver-store entry and a
        # lineage silently dies (observed: 7 of 9 segments run).
        params["member"] = member
        params["budget"] = self.resource_per_generation
        info = {"sample_type": sample_type, "member": member,
                "generation": generation}
        if parent is not None:
            info["parent"] = parent
        return Trial(params, info_dict=info)

    def _population_state(self) -> Dict[int, Trial]:
        """Each member's LATEST finalized segment — the population the
        paper's exploit step compares against. Comparing only
        same-generation peers would let the first finisher of every
        generation escape unchallenged (it has no peers yet) while later
        finishers compare against a bottom already held by that early
        weak member; the population view is also what makes the decision
        sound when members drift generations apart (async)."""
        latest: Dict[int, Trial] = {}
        for t in self.final_store:
            member = t.info_dict.get("member")
            # final_store also holds ERRORED segments (final_metric None):
            # they are not population state — using one as a member's
            # "latest" would skip a generation and point warm-starts at a
            # checkpoint that may not exist.
            if member is None or t.final_metric is None:
                continue
            if (member not in latest
                    or t.info_dict.get("generation", 0)
                    > latest[member].info_dict.get("generation", 0)):
                latest[member] = t
        return latest

    def _next_segment(self, finalized: Trial) -> Trial:
        member = finalized.info_dict["member"]
        generation = finalized.info_dict.get("generation", 0)
        metrics = self.get_metrics_dict()  # normalized: lower is better
        population = self._population_state()
        population[member] = finalized
        ranked = sorted((t for t in population.values()
                         if t.trial_id in metrics),
                        key=lambda t: metrics[t.trial_id])
        k = max(1, math.ceil(len(ranked) * self.exploit_quantile))
        bottom = {t.trial_id for t in ranked[-k:]} if len(ranked) > 1 else set()
        if finalized.trial_id in bottom:
            donor = ranked[int(self.rng.integers(0, k))]
            if donor.info_dict.get("member") != member:
                return self._segment(
                    member, generation + 1,
                    self._perturb(self._hparams_of(donor)),
                    parent=donor.trial_id, sample_type="exploit")
        return self._segment(member, generation + 1,
                             self._hparams_of(finalized),
                             parent=finalized.trial_id, sample_type="continue")

    def _hparams_of(self, trial: Trial) -> dict:
        return self._strip_budget(trial.params)

    def _perturb(self, hparams: dict) -> dict:
        """Explore step: scale continuous params by a perturb factor (clipped
        to the space), resample discrete/categorical with small probability."""
        out = {}
        for name in self.searchspace.names():
            hp_type = self.searchspace.get_type(name)
            value = hparams[name]
            spec = self.searchspace.get(name)
            if hp_type in Searchspace.CONTINUOUS_TYPES:
                factor = self.perturb_factors[
                    int(self.rng.integers(0, len(self.perturb_factors)))]
                lo, hi = min(spec), max(spec)
                scaled = min(max(value * factor, lo), hi)
                out[name] = int(round(scaled)) \
                    if hp_type == Searchspace.INTEGER else float(scaled)
            else:
                if self.rng.random() < self.resample_probability:
                    out[name] = spec[int(self.rng.integers(0, len(spec)))]
                else:
                    out[name] = value
        return out

    def fork_gc_eligible(self):
        """Checkpoint GC (checkpoint-forking search): a segment's
        checkpoint is spent once it is SUPERSEDED — it is no longer any
        member's latest finalized segment (exploit donors and continue
        parents are always drawn from ``_population_state``), and no
        pending or in-flight segment still names it as parent (a queued
        exploit must be able to stage its donor's checkpoint when it
        finally dispatches)."""
        keep = {t.trial_id for t in self._population_state().values()}
        for pending in self._pending:
            parent = pending.info_dict.get("parent")
            if parent is not None:
                keep.add(parent)
        for t in self.trial_store.values():
            parent = t.info_dict.get("parent")
            if parent is not None:
                keep.add(parent)
        return [t.trial_id for t in self.final_store
                if t.final_metric is not None and t.trial_id not in keep]

    # ---------------------------------------------------------------- resume

    def restore(self, finalized) -> None:
        """Rebuild the schedule from a previous run; in-flight segments at
        crash time are re-derived as their parents' successors below.

        Error state (``_errors``/``_dead``) is deliberately NOT restored:
        only FINALIZED trials survive a crash (ERRORED segments write no
        final_metric, so the driver's resume never hands them back), so the
        retry ledger is unrecoverable. A member retired by the
        two-consecutive-error rule therefore re-enters with a FRESH retry
        budget on resume — the lineage re-runs from its last finalized
        state and gets retired again after two further errors if it is
        deterministically broken. Bounded re-work, never a livelock within
        one run."""
        # Drop initial segments whose member already ran generation 0.
        done0 = {t.info_dict.get("member") for t in finalized
                 if t.info_dict.get("generation", 0) == 0}
        self._pending = [p for p in self._pending
                         if p.info_dict["member"] not in done0]
        # Queue next segments for members whose LAST finalized generation
        # has no successor yet.
        latest: Dict[int, Trial] = {}
        for t in finalized:
            member = t.info_dict.get("member")
            if member is None:
                continue
            generation = t.info_dict.get("generation", 0)
            if (member not in latest
                    or generation > latest[member].info_dict.get("generation", 0)):
                latest[member] = t
        for t in latest.values():
            if t.info_dict.get("generation", 0) + 1 < self.generations:
                self._pending.append(self._next_segment(t))

    def restore_from_finals(self, finalized, inflight=()) -> None:
        """Crash-only recovery: ``restore`` already re-derives each
        member's next segment from its last finalized generation — the
        exact segments ``report`` would have appended — so re-reporting
        on top would double-append every chain link. In-flight segments
        the driver reconstructed from the journal ARE those successors
        (same member, same generation, same content-addressed id):
        drop them from the pending queue, or the chain would run its
        next link twice."""
        self.restore(finalized)
        have = {t.trial_id for t in inflight}
        if have:
            self._pending = [p for p in self._pending
                             if p.trial_id not in have]
