"""Random search, with optional multi-fidelity pruning.

Parity: reference `maggy/optimizer/randomsearch.py` — pre-sampled buffer
(:28-40), continuous-param requirement (:30-36), pruner delegation handling
IDLE/None/promoted/fresh (:47-90), plain buffer pop otherwise (:93-106).
"""

from __future__ import annotations

from maggy_tpu.optimizers.abstractoptimizer import AbstractOptimizer
from maggy_tpu.searchspace import Searchspace
from maggy_tpu.trial import Trial


class RandomSearch(AbstractOptimizer):
    def __init__(self, seed=None, pruner=None, pruner_kwargs=None):
        super().__init__(seed=seed, pruner=pruner, pruner_kwargs=pruner_kwargs)
        self.config_buffer = []

    def initialize(self) -> None:
        types = set(self.searchspace._hparam_types.values())
        if not types & set(Searchspace.CONTINUOUS_TYPES):
            raise ValueError(
                "RandomSearch requires at least one continuous (DOUBLE/INTEGER) "
                "parameter; use GridSearch for purely discrete spaces."
            )
        if self.pruner is None:
            self.config_buffer = self.searchspace.get_random_parameter_values(
                self.num_trials, rng=self.rng
            )

    def suggest(self):
        # report() is a no-op: the schedule is a pre-sampled buffer (or
        # pruner-delegated), so nothing about a FINAL changes what comes
        # next — suggestions may be prefetched arbitrarily far ahead.
        if self.pruner is not None:
            return self._pruner_suggestion()
        if not self.config_buffer:
            return None
        params = self.config_buffer.pop(0)
        return self.create_trial(params, sample_type="random")

    def recycle(self, trial: Trial) -> None:
        # The non-pruner schedule is EXACTLY num_trials buffer entries;
        # dropping an invalidated prefetch would silently shrink it. The
        # pruner path never invalidates (report is a no-op), so its
        # bracket slots cannot come back here.
        if self.pruner is None:
            self.config_buffer.insert(0, self._strip_budget(trial.params))

    def restore(self, finalized) -> None:
        # Same seed => same presampled buffer; drop the configs the previous
        # run already executed. (The driver refuses resume when the seed is
        # None — an unseeded rerun would presample a disjoint buffer and
        # silently over-run the schedule.)
        self.config_buffer = self._drop_executed(self.config_buffer, finalized)

    def _pruner_suggestion(self):
        """Delegate budget/promotion decisions to the pruner (reference
        `randomsearch.py:47-90`)."""
        next_run = self.pruner.pruning_routine()
        if next_run == "IDLE":
            return "IDLE"
        if next_run is None:
            return None
        parent_id, budget = next_run["trial_id"], next_run["budget"]
        if parent_id is None:
            # fresh rung-0 config, with duplicate detection (reference
            # `abstractoptimizer.py:254-295`): after resume=True the seeded
            # rng REPLAYS the interrupted run's sample sequence — without
            # this the bracket would re-evaluate configs that already
            # finalized instead of exploring fresh ones.
            params = self.searchspace.get_random_parameter_values(1, rng=self.rng)[0]
            for _ in range(32):
                if not self.hparams_exist(Trial(dict(params))):
                    break
                params = self.searchspace.get_random_parameter_values(
                    1, rng=self.rng)[0]
            new_trial = self.create_trial(params, sample_type="random", run_budget=budget)
        else:
            # promoted config re-run at a bigger budget
            parent_params = self._lookup_params(parent_id)
            params = self._strip_budget(parent_params)
            new_trial = self.create_trial(params, sample_type="promoted",
                                          run_budget=budget, parent=parent_id)
        self.pruner.report_trial(original_trial_id=parent_id, new_trial_id=new_trial.trial_id)
        return new_trial

    def _lookup_params(self, trial_id: str) -> dict:
        for t in self.final_store:
            if t.trial_id == trial_id:
                return t.params
        if trial_id in self.trial_store:
            return self.trial_store[trial_id].params
        raise KeyError("Unknown parent trial id {}".format(trial_id))
