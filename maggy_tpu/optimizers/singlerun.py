"""SingleRun: N empty-parameter trials for plain parallel execution.

Parity: reference `maggy/optimizer/singlerun.py:21-37`; selected by
optimizer="none" in the driver registry (`optimization_driver.py:40`).
"""

from __future__ import annotations

from typing import Optional

from maggy_tpu.optimizers.abstractoptimizer import AbstractOptimizer
from maggy_tpu.trial import Trial


class SingleRun(AbstractOptimizer):
    def __init__(self, seed=None, pruner=None, pruner_kwargs=None):
        if pruner is not None:
            raise ValueError("SingleRun does not support pruners.")
        super().__init__(seed=seed)

    def initialize(self) -> None:
        self._remaining = self.num_trials

    def get_suggestion(self, trial: Optional[Trial] = None):
        if self._remaining <= 0:
            return None
        self._remaining -= 1
        # Distinguish otherwise-identical empty-param trials by an index so
        # their md5 ids differ.
        return self.create_trial({"run_index": self.num_trials - self._remaining - 1},
                                 sample_type="random")
