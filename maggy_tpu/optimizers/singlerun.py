"""SingleRun: N empty-parameter trials for plain parallel execution.

Parity: reference `maggy/optimizer/singlerun.py:21-37`; selected by
optimizer="none" in the driver registry (`optimization_driver.py:40`).
"""

from __future__ import annotations

from maggy_tpu.optimizers.abstractoptimizer import AbstractOptimizer
from maggy_tpu.trial import Trial


class SingleRun(AbstractOptimizer):
    def __init__(self, seed=None, pruner=None, pruner_kwargs=None):
        if pruner is not None:
            raise ValueError("SingleRun does not support pruners.")
        super().__init__(seed=seed)

    def initialize(self) -> None:
        # Distinguish otherwise-identical empty-param trials by an index so
        # their md5 ids differ.
        self._pending = list(range(self.num_trials))

    def suggest(self):
        # report() is a no-op: the schedule is a fixed index list, so
        # suggestions may be prefetched arbitrarily far ahead.
        if not self._pending:
            return None
        return self.create_trial({"run_index": self._pending.pop(0)},
                                 sample_type="random")

    def recycle(self, trial: Trial) -> None:
        self._pending.insert(0, trial.params.get("run_index"))

    def restore(self, finalized) -> None:
        # Parallel runners finish out of order: skip exactly the indices
        # that finalized, not a count (index 3 may finish before index 2).
        done = {t.params.get("run_index") for t in finalized}
        self._pending = [i for i in self._pending if i not in done]
