from maggy_tpu.parallel.mesh import ShardingEnv, make_mesh, slice_mesh
from maggy_tpu.parallel.sharding import shard_params, batch_sharding, param_sharding
from maggy_tpu.parallel.pipeline import (
    PipelinedLM, pipeline_1f1b_grads, pipeline_apply, stage_param_sharding)
from maggy_tpu.parallel.ulysses import ulysses_attention

__all__ = ["ShardingEnv", "make_mesh", "slice_mesh", "shard_params", "batch_sharding",
           "param_sharding", "PipelinedLM", "pipeline_1f1b_grads",
           "pipeline_apply", "stage_param_sharding", "ulysses_attention"]
