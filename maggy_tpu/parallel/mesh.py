"""Device meshes and the ShardingEnv handed to distributed train functions.

This is the ICI data plane the reference delegates to NCCL/DDP
(`dist_executor.py:89-102,197-223`): instead of wrapping a model in DDP, the
user's train function receives a `ShardingEnv` — a named `jax.sharding.Mesh`
plus helpers — and writes a jit-compiled step; GSPMD inserts the gradient
all-reduces over ICI.

Mesh axis conventions (scaling-book style):
- "data":   data parallelism (batch axis; gradients all-reduced)
- "fsdp":   fully-sharded data parallelism (params sharded over data axis)
- "model":  tensor parallelism (weights sharded within layers)
- "seq":    sequence/context parallelism (ring attention)
- "pipe":   pipeline parallelism (GPipe microbatching, parallel/pipeline.py)
- "expert": expert parallelism (MoE token all-to-all, models/moe.py)
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np


def make_mesh(mesh_shape: Dict[str, int], devices: Optional[Sequence] = None):
    """Build a named Mesh from {"axis": size}. Sizes must multiply to the
    device count; a single -1 axis is inferred."""
    import jax

    devices = list(devices if devices is not None else jax.devices())
    shape = dict(mesh_shape) if mesh_shape else {"data": len(devices)}
    sizes = list(shape.values())
    if sizes.count(-1) > 1:
        raise ValueError("At most one mesh axis may be -1.")
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        if len(devices) % known:
            raise ValueError(
                "Device count {} not divisible by fixed axes {}".format(len(devices), known)
            )
        sizes[sizes.index(-1)] = len(devices) // known
    if int(np.prod(sizes)) != len(devices):
        raise ValueError(
            "Mesh {} needs {} devices, have {}.".format(shape, int(np.prod(sizes)), len(devices))
        )
    arr = np.asarray(devices).reshape(sizes)
    return jax.sharding.Mesh(arr, tuple(shape.keys()))


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` across jax versions: the public spelling when
    present, else ``jax.experimental.shard_map`` with ``check_vma``
    mapped to its older ``check_rep`` name."""
    import jax

    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as sm

    return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
              check_rep=check_vma)


def slice_mesh(chip_ids: Sequence[int], mesh_shape: Dict[str, int]):
    """Named mesh over a slice of the global device inventory, by device
    index. This is the gang-scheduling mesh constructor: the driver
    assembles runners whose chips are CONSECUTIVE indices (the placer's
    contiguity invariant — consecutive ids model ICI neighbors), and the
    leader builds the trial's mesh over exactly that slice."""
    import jax

    devs = jax.devices()
    return make_mesh(dict(mesh_shape),
                     devices=[devs[int(c)] for c in chip_ids])


@dataclass
class ShardingEnv:
    """What a distributed train function gets instead of a DDP model wrapper.

    ``process_index``/``process_count`` mirror the reference's RANK/WORLD_SIZE
    (`dist_executor.py:89-100`); ``shard_count``/``current_shard`` express the
    per-rank input sharding contract of `patching.py:70-79`.
    """

    mesh: Any
    process_index: int = 0
    process_count: int = 1

    @property
    def axis_names(self) -> Tuple[str, ...]:
        return tuple(self.mesh.axis_names)

    @property
    def num_devices(self) -> int:
        return int(np.prod(list(self.mesh.shape.values())))

    @property
    def current_shard(self) -> int:
        return self.process_index

    @property
    def shard_count(self) -> int:
        return self.process_count

    def data_sharding(self, *rest_axes: Optional[str]):
        """NamedSharding for a batch: leading dim over every data-like mesh
        axis, remaining dims as given (None = replicated)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        data_axes = tuple(a for a in ("data", "fsdp") if a in self.axis_names)
        spec = P(data_axes if data_axes else None, *rest_axes)
        return NamedSharding(self.mesh, spec)

    def replicated(self):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        return NamedSharding(self.mesh, P())

    def shard_batch(self, batch):
        """Place a host batch onto the mesh, sharded on the leading axis."""
        import jax

        def place(x):
            sh = self.data_sharding(*([None] * (x.ndim - 1)))
            return jax.device_put(x, sh)

        return jax.tree_util.tree_map(place, batch)
