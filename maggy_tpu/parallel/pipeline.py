"""Pipeline parallelism: GPipe-style microbatching over a "pipe" mesh axis.

Parallelism the reference entirely lacks (SURVEY.md §2.8 lists PP absent).
TPU-first SPMD design — instead of per-stage processes with P2P sends (the
GPU/NCCL shape), every device runs the SAME program under `shard_map`:

- the stacked stage dim of the layer params is sharded over "pipe", so each
  device holds exactly its stage's weights (no weight broadcast);
- a single activation "slot" per device circulates via `lax.ppermute`
  (neighbor exchange over ICI) once per tick;
- `lax.scan` over M + n - 1 ticks: stage 0 ingests microbatch t, stage n-1
  emits microbatch t-(n-1); the scan is reverse-differentiable, so the
  backward pipeline falls out of autodiff (ppermute transposes to the
  reversed ring) — no hand-written 1F1B schedule needed;
- all shapes are static; the bubble is the usual (n-1)/(M+n-1) fraction.

`pipeline_apply` is the generic schedule; `PipelinedLM` is a small
functional decoder (embed -> pipelined residual blocks -> head) used by the
multi-chip dry run and tests.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from maggy_tpu.parallel.mesh import shard_map as version_shard_map


def pipeline_apply(
    stage_fn: Callable,
    stage_params,
    x: jnp.ndarray,
    mesh,
    axis: str = "pipe",
    num_microbatches: Optional[int] = None,
):
    """Run ``x`` through ``n = mesh.shape[axis]`` pipeline stages.

    stage_fn(params, act) -> act: one stage's compute; must preserve the
        activation's shape/dtype (residual-block style).
    stage_params: pytree whose leaves are stacked [n, ...] on dim 0 (stage i
        uses leaf[i]); shard them with `stage_param_sharding`.
    x: [B, ...] global batch; B must divide into ``num_microbatches``
        (default n) equal microbatches.
    """
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis]
    M = int(num_microbatches or n)
    B = x.shape[0]
    if B % M:
        raise ValueError(
            "Batch {} must divide into {} microbatches".format(B, M))
    x_mb = x.reshape((M, B // M) + x.shape[1:])

    def local_fn(params_local, x_mb):
        idx = jax.lax.axis_index(axis)
        # shard_map hands each device a [1, ...] slice of the stacked stage
        # dim; drop it to get this stage's params.
        params = jax.tree_util.tree_map(lambda p: p[0], params_local)
        state0 = jnp.zeros_like(x_mb[0])

        def tick(state, t):
            inp = jax.lax.dynamic_index_in_dim(
                x_mb, jnp.clip(t, 0, M - 1), axis=0, keepdims=False)
            state = jnp.where(idx == 0, inp, state)
            out = stage_fn(params, state)
            # Rotate forward one stage per tick (ICI neighbor exchange);
            # stage n-1 -> 0 wraps but is overwritten by fresh input.
            nxt = jax.lax.ppermute(
                out, axis, [(i, (i + 1) % n) for i in range(n)])
            return nxt, out

        _, emits = jax.lax.scan(tick, state0, jnp.arange(M + n - 1))
        # On the last stage, microbatch m leaves the pipe at tick m + n - 1.
        y_local = emits[n - 1:]
        # Broadcast the last stage's outputs to every device (replicated
        # result lets the unsharded head/loss follow under plain GSPMD).
        return jax.lax.psum(
            jnp.where(idx == n - 1, y_local, jnp.zeros_like(y_local)), axis)

    stage_spec = jax.tree_util.tree_map(
        lambda p: P(axis, *([None] * (np.ndim(p) - 1))), stage_params)
    # Shard the per-microbatch batch dim over any data axes so those axes do
    # real data parallelism instead of replicated identical stage compute
    # (the pipeline is batch-elementwise, so each data shard pipelines its
    # own slice independently).
    data_axes = tuple(a for a in ("data", "fsdp") if a in mesh.axis_names)
    dp = int(np.prod([mesh.shape[a] for a in data_axes])) if data_axes else 1
    if data_axes and (B // M) % dp == 0:
        x_spec = P(None, data_axes, *([None] * (x_mb.ndim - 2)))
    else:
        x_spec = P()
    out = version_shard_map(
        local_fn, mesh=mesh,
        in_specs=(stage_spec, x_spec), out_specs=x_spec,
        check_vma=False,
    )(stage_params, x_mb)
    return out.reshape((B,) + out.shape[2:])


def pipeline_1f1b_grads(
    stage_fn: Callable,
    loss_fn: Callable,
    stage_params,
    x: jnp.ndarray,
    targets,
    mesh,
    axis: str = "pipe",
    num_microbatches: Optional[int] = None,
):
    """One-forward-one-backward (PipeDream-flush) pipelined TRAINING step:
    returns ``(mean_loss, stage_param_grads)`` directly.

    Why a separate entry point: `pipeline_apply` + autodiff IS GPipe — the
    whole forward flushes before the backward starts, so every microbatch's
    scan residuals stay live and activation memory grows with M. True 1F1B
    starts each microbatch's backward as soon as the last stage finishes
    its forward, which means the loss must be computed INSIDE the pipeline
    (a custom_vjp around `pipeline_apply` could never reorder fwd/bwd
    across its own boundary). In-flight activations are bounded by n — the
    stash here is a static [n, ...] ring buffer — so at EQUAL activation
    memory 1F1B affords ~M/n× more microbatches, and the bubble fraction
    (n-1)/(M+n-1) shrinks accordingly. Inputs are re-staged through the
    stash and the stage forward is recomputed in the backward sub-step
    (remat-style), the standard 1F1B memory/FLOPs trade.

    Schedule (0-based stage i, microbatch m, n stages, M microbatches,
    one slot = one F and one B sub-step, T = 2(M+n-1) slots):

    - warmup forwards (m < n - i):  F_m(i) = i + m
    - steady forwards  (m >= n-i):  F_m(i) = 2m + i
    - backwards:                    B_m(i) = 2n - 1 - i + 2m

    Backward grads arrive exactly at their consumption slot
    (B_m(i) = B_m(i+1) + 1). Forward activations arrive just-in-time too
    EXCEPT each sender's last warmup microbatch (m = n-i-1), which lands
    n-i-1 slots early — so arrivals are stashed into the [n, ...] ring
    buffer keyed by microbatch (mod n) at arrival time, and the same
    buffer doubles as the backward-recompute stash (entry m is written at
    arrival <= F_m(i) and last read at B_m(i), strictly before microbatch
    m+n's arrival overwrites it).

    stage_fn(params, act) -> act          (shape-preserving, as GPipe)
    loss_fn(act, target) -> scalar        (applied per microbatch on the
                                           last stage's output)
    targets: [B, ...] aligned with x's batch dim (microbatched the same
        way); pass e.g. next-token labels.
    """
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis]
    M = int(num_microbatches or n)
    B = x.shape[0]
    if B % M:
        raise ValueError(
            "Batch {} must divide into {} microbatches".format(B, M))
    x_mb = x.reshape((M, B // M) + x.shape[1:])
    t_mb = targets.reshape((M, B // M) + targets.shape[1:])
    T = 2 * (M + n - 1)

    def local_fn(params_local, x_mb, t_mb):
        idx = jax.lax.axis_index(axis)
        params = jax.tree_util.tree_map(lambda p: p[0], params_local)
        mb_shape = x_mb.shape[1:]

        def fwd_mb(t, stage=None):
            """(active, m) for ``stage``'s F sub-step at slot t."""
            i = idx if stage is None else stage
            d = t - i
            warm = (i <= t) & (t < n) & (d < M)
            m_steady = d // 2
            steady = (d >= 0) & (d % 2 == 0) & (m_steady >= n - i) \
                & (m_steady < M)
            m = jnp.where(warm, d, m_steady)
            return warm | steady, jnp.clip(m, 0, M - 1)

        def bwd_mb(t):
            r = t - (2 * n - 1 - idx)
            m = r // 2
            active = (r >= 0) & (r % 2 == 0) & (m < M)
            return active, jnp.clip(m, 0, M - 1)

        def f_with_params(p, a):
            return stage_fn(p, a)

        def slot(carry, t):
            stash, act_in, grad_in, dy_pending, loss_sum, gacc = carry

            # ---- stash the activation that just arrived ---------------
            # act_in was sent by stage idx-1 at slot t-1; its microbatch
            # index comes from the SENDER's schedule.
            in_active, m_in = fwd_mb(t - 1, stage=idx - 1)
            stash = jnp.where(
                in_active & (idx > 0),
                jax.lax.dynamic_update_index_in_dim(
                    stash, act_in, m_in % n, axis=0),
                stash)

            # ---- forward sub-step -------------------------------------
            f_active, m_f = fwd_mb(t)
            inp = jnp.where(
                idx == 0,
                jax.lax.dynamic_index_in_dim(x_mb, m_f, 0, keepdims=False),
                jax.lax.dynamic_index_in_dim(
                    stash, m_f % n, axis=0, keepdims=False))
            # Stage 0 ring-buffers its OWN input for the backward recompute
            # (other stages already stashed it at arrival).
            stash = jnp.where(
                f_active & (idx == 0),
                jax.lax.dynamic_update_index_in_dim(
                    stash, inp, m_f % n, axis=0),
                stash)
            out = stage_fn(params, inp)
            # Last stage: per-microbatch loss + output cotangent, consumed
            # by this stage's OWN backward next slot (B_m = F_m + 1 there).
            tgt = jax.lax.dynamic_index_in_dim(t_mb, m_f, 0, keepdims=False)
            loss_val, dy_new = jax.value_and_grad(loss_fn)(out, tgt)
            is_last = idx == n - 1
            loss_sum = loss_sum + jnp.where(f_active & is_last, loss_val, 0.0)
            dy_pending_next = jnp.where(f_active & is_last, dy_new, dy_pending)

            # ---- backward sub-step ------------------------------------
            b_active, m_b = bwd_mb(t)
            inp_b = jax.lax.dynamic_index_in_dim(
                stash, m_b % n, axis=0, keepdims=False)
            g_out = jnp.where(is_last, dy_pending, grad_in)
            _, vjp_fn = jax.vjp(f_with_params, params, inp_b)
            dparams, dx = vjp_fn(g_out)
            gacc = jax.tree_util.tree_map(
                lambda acc, d: jnp.where(b_active, acc + d, acc), gacc, dparams)

            # ---- neighbor exchanges (one hop each way per slot) -------
            act_next = jax.lax.ppermute(
                out, axis, [(i, (i + 1) % n) for i in range(n)])
            grad_next = jax.lax.ppermute(
                jnp.where(b_active, dx, jnp.zeros_like(dx)), axis,
                [(i, (i - 1) % n) for i in range(n)])
            return (stash, act_next, grad_next, dy_pending_next,
                    loss_sum, gacc), None

        zeros = jnp.zeros(mb_shape, x_mb.dtype)
        carry0 = (
            jnp.zeros((n,) + mb_shape, x_mb.dtype),  # recompute stash
            zeros,                                   # incoming activation
            zeros,                                   # incoming out-grad
            zeros,                                   # last stage's pending dy
            jnp.zeros((), jnp.float32),
            jax.tree_util.tree_map(lambda p: jnp.zeros_like(p), params),
        )
        (_, _, _, _, loss_sum, gacc), _ = jax.lax.scan(
            slot, carry0, jnp.arange(T))
        # Only the last stage accumulated loss; share it around the ring.
        loss = jax.lax.psum(loss_sum, axis) / M
        data_axes = tuple(a for a in ("data", "fsdp") if a in mesh.axis_names)
        if data_axes:
            loss = jax.lax.pmean(loss, data_axes)
            gacc = jax.tree_util.tree_map(
                lambda g: jax.lax.pmean(g, data_axes), gacc)
        # Mean-of-microbatch-means, matching `mean_m loss_fn(y_m, t_m)`.
        gacc = jax.tree_util.tree_map(lambda g: (g / M)[None], gacc)
        return loss, gacc

    stage_spec = jax.tree_util.tree_map(
        lambda p: P(axis, *([None] * (np.ndim(p) - 1))), stage_params)
    data_axes = tuple(a for a in ("data", "fsdp") if a in mesh.axis_names)
    dp = int(np.prod([mesh.shape[a] for a in data_axes])) if data_axes else 1
    if data_axes and (B // M) % dp == 0:
        mb_spec = P(None, data_axes, *([None] * (x_mb.ndim - 2)))
        tgt_spec = P(None, data_axes, *([None] * (t_mb.ndim - 2)))
    else:
        mb_spec, tgt_spec = P(), P()
    return version_shard_map(
        local_fn, mesh=mesh,
        in_specs=(stage_spec, mb_spec, tgt_spec),
        out_specs=(P(), stage_spec),
        check_vma=False,
    )(stage_params, x_mb, t_mb)


def stage_param_sharding(mesh, stage_params, axis: str = "pipe"):
    """NamedShardings placing each leaf's stacked stage dim on ``axis``."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    return jax.tree_util.tree_map(
        lambda p: NamedSharding(mesh, P(axis, *([None] * (np.ndim(p) - 1)))),
        stage_params)


class PipelinedLM:
    """Minimal functional decoder for the pp path: embedding -> n_stages of
    residual SwiGLU blocks (stacked + pipelined) -> head.

    Pure functions over an explicit param pytree (no flax) so the stacked
    stage dim is first-class; init places params directly into their
    shardings when a mesh is given.
    """

    def __init__(self, vocab_size: int, hidden_dim: int, intermediate_dim: int,
                 num_stages: int, layers_per_stage: int = 1,
                 dtype: Any = jnp.bfloat16):
        self.vocab_size = vocab_size
        self.hidden_dim = hidden_dim
        self.intermediate_dim = intermediate_dim
        self.num_stages = num_stages
        self.layers_per_stage = layers_per_stage
        self.dtype = dtype

    def init(self, rng, mesh=None, axis: str = "pipe"):
        V, D, F = self.vocab_size, self.hidden_dim, self.intermediate_dim
        n, L = self.num_stages, self.layers_per_stage
        ks = jax.random.split(rng, 5)
        scale = lambda fan_in: 1.0 / np.sqrt(fan_in)  # noqa: E731
        params = {
            "embed": jax.random.normal(ks[0], (V, D), jnp.float32) * 0.02,
            "stages": {
                "w_gate": jax.random.normal(ks[1], (n, L, D, F)) * scale(D),
                "w_up": jax.random.normal(ks[2], (n, L, D, F)) * scale(D),
                "w_down": jax.random.normal(ks[3], (n, L, F, D)) * scale(F),
            },
            "head": jax.random.normal(ks[4], (D, V), jnp.float32) * 0.02,
        }
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            shardings = {
                "embed": NamedSharding(mesh, P()),
                "stages": stage_param_sharding(mesh, params["stages"], axis),
                "head": NamedSharding(mesh, P()),
            }
            params = jax.tree_util.tree_map(jax.device_put, params, shardings)
        return params

    def stage_fn(self, stage_params, x):
        """L residual SwiGLU blocks; [mb, S, D] -> [mb, S, D]."""

        def block(x, layer):
            w_gate, w_up, w_down = layer
            h = jnp.dot(x, w_gate.astype(self.dtype))
            u = jnp.dot(x, w_up.astype(self.dtype))
            y = jnp.dot(jax.nn.silu(h) * u, w_down.astype(self.dtype))
            return x + y, None

        layers = (stage_params["w_gate"], stage_params["w_up"],
                  stage_params["w_down"])
        x, _ = jax.lax.scan(block, x, layers)
        return x

    def apply(self, params, tokens, mesh, axis: str = "pipe",
              num_microbatches: Optional[int] = None):
        x = params["embed"].astype(self.dtype)[tokens]
        x = pipeline_apply(
            lambda p, a: self.stage_fn(p, a), params["stages"], x, mesh,
            axis=axis, num_microbatches=num_microbatches)
        return jnp.dot(x, params["head"].astype(self.dtype)).astype(jnp.float32)

    def apply_sequential(self, params, tokens):
        """Reference forward with NO pipelining (correctness oracle)."""
        x = params["embed"].astype(self.dtype)[tokens]
        for i in range(self.num_stages):
            stage = jax.tree_util.tree_map(lambda p: p[i], params["stages"])
            x = self.stage_fn(stage, x)
        return jnp.dot(x, params["head"].astype(self.dtype)).astype(jnp.float32)
