"""Ring attention: sequence/context parallelism over a "seq" mesh axis.

Long-context training support the reference entirely lacks (SURVEY.md §5.7).
Design (blockwise ring attention, Liu et al. 2023): Q/K/V are sharded along
the sequence axis across devices; each device holds its Q shard and, over
`seq`-axis ring steps, receives successive K/V shards via `jax.lax.ppermute`
(ICI neighbor exchange), accumulating attention with a numerically-stable
online softmax. Peak memory per device is O(S/n) and the K/V transfer
overlaps compute under XLA's async collectives.

Causal masking is block-aware: a device skips K/V shards strictly in its
future; the diagonal shard applies the intra-block triangular mask.
Implemented with `shard_map` so it runs identically on a CPU test mesh and a
TPU pod. The per-(shard x shard) inner attention is plain XLA (scores are
[S/n, S/n] per step — already n^2 smaller than full attention); swap in the
Pallas flash kernel from ops/attention.py per block if per-device shards
grow past VMEM-friendly sizes.
"""

from __future__ import annotations



import jax
import jax.numpy as jnp

from maggy_tpu.ops.attention import NEG_INF


def _block_attend(q, k, v, q_offset, k_offset, causal, sm_scale):
    """Online-softmax partial attention of one (Q shard, K/V shard) pair.

    q: [B,Sq,H,D], k/v: [B,Sk,H,D]; returns (acc [B,Sq,H,D] fp32,
    m [B,Sq,H] fp32, l [B,Sq,H] fp32) partial-softmax statistics.
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    if causal:
        q_pos = q_offset + jnp.arange(q.shape[1])
        k_pos = k_offset + jnp.arange(k.shape[1])
        mask = q_pos[:, None] >= k_pos[None, :]
        s = jnp.where(mask[None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1)  # [B,H,Sq]
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return acc, m.transpose(0, 2, 1), l.transpose(0, 2, 1)


def _merge(acc1, m1, l1, acc2, m2, l2):
    """Merge two partial online-softmax states."""
    m = jnp.maximum(m1, m2)
    a1 = jnp.exp(m1 - m)
    a2 = jnp.exp(m2 - m)
    # acc layout [B,Sq,H,D]; m/l are [B,Sq,H]
    acc = acc1 * a1[..., None] + acc2 * a2[..., None]
    l = l1 * a1 + l2 * a2
    return acc, m, l


def ring_attention(q, k, v, mesh, axis_name: str = "seq",
                   causal: bool = True):
    """Sequence-parallel attention. q/k/v: [B, S, H, D] GLOBALLY, sharded on
    dim 1 over ``axis_name``. Returns out with the same sharding.
    """
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis_name]
    B, S, H, D = q.shape
    if S % n:
        raise ValueError("Sequence length {} must divide over {} '{}' shards"
                         .format(S, n, axis_name))
    shard = S // n
    sm_scale = 1.0 / (D ** 0.5)

    def local_fn(q_blk, k_blk, v_blk):
        idx = jax.lax.axis_index(axis_name)
        q_off = idx * shard

        def ring_step(step, carry):
            acc, m, l, k_cur, v_cur = carry
            # Which global shard does k_cur hold? It started at `idx` and has
            # been passed backward `step` times: origin = (idx + step) % n.
            origin = (idx + step) % n
            k_off = origin * shard

            def attend(args):
                acc, m, l = args
                a2, m2, l2 = _block_attend(q_blk, k_cur, v_cur, q_off, k_off,
                                           causal, sm_scale)
                acc, m, l = _merge(acc, m, l, a2, m2, l2)
                return acc, m, l

            # Causal: skip shards strictly in the future (k_off > q end).
            if causal:
                acc, m, l = jax.lax.cond(
                    k_off > q_off + shard - 1, lambda a: a, attend, (acc, m, l))
            else:
                acc, m, l = attend((acc, m, l))
            # Pass K/V to the previous neighbor (receive from next) so the
            # ring sweeps forward through global shards.
            perm = [(i, (i - 1) % n) for i in range(n)]
            k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
            v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
            return acc, m, l, k_nxt, v_nxt

        acc0 = jnp.zeros((B, shard, H, D), jnp.float32)
        m0 = jnp.full((B, shard, H), NEG_INF, jnp.float32)
        l0 = jnp.zeros((B, shard, H), jnp.float32)
        acc, m, l, _, _ = jax.lax.fori_loop(
            0, n, ring_step, (acc0, m0, l0, k_blk, v_blk))
        l = jnp.maximum(l, 1e-30)
        return (acc / l[..., None]).astype(q_blk.dtype)

    spec = P(None, axis_name, None, None)
    out = jax.shard_map(
        local_fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )(q, k, v)
    return out
