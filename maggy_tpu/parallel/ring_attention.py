"""Ring attention: sequence/context parallelism over a "seq" mesh axis.

Long-context training support the reference entirely lacks (SURVEY.md §5.7).
Design (blockwise ring attention, Liu et al. 2023): Q/K/V are sharded along
the sequence axis across devices; each device holds its Q shard and, over
`seq`-axis ring steps, receives successive K/V shards via `jax.lax.ppermute`
(ICI neighbor exchange), accumulating attention with a numerically-stable
online softmax. Peak memory per device is O(S/n) and the K/V transfer
overlaps compute under XLA's async collectives.

Causal masking is block-aware: a device skips K/V shards strictly in its
future; the diagonal shard applies the intra-block triangular mask.
Implemented with `shard_map` so it runs identically on a CPU test mesh and a
TPU pod.

Two inner-block implementations:

- **flash** (default on TPU when shards tile): the Pallas kernels from
  `ops/attention.py` per (Q shard, K/V shard) pair — no [S/n, S/n] score
  materialization even per step, GQA without kv repetition. The diagonal
  step is peeled out of the ring loop so every kernel call has a STATIC
  causal flag (offset-free); off-diagonal visible shards run non-causal.
  Gradients are a ring of their own: with the final log-sum-exp and
  delta = sum(dO*O), each block's backward is independent and additive, so
  dK/dV partials simply ride the ring with their shard (custom VJP below).
- **xla**: plain einsum blocks (odd shapes, CPU tests); differentiable by
  autodiff through the fori_loop.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from maggy_tpu.ops.attention import (NEG_INF, flash_block_bwd,
                                     flash_block_fwd)
from maggy_tpu.parallel.mesh import shard_map as version_shard_map


# ------------------------------------------------------------------ xla path


def _block_attend(q, k, v, q_offset, k_offset, causal, sm_scale):
    """Online-softmax partial attention of one (Q shard, K/V shard) pair.

    q: [B,Sq,H,D], k/v: [B,Sk,Hkv,D]; returns (acc [B,Sq,H,D] fp32,
    m [B,Sq,H] fp32, l [B,Sq,H] fp32) partial-softmax statistics.
    """
    H, Hkv = q.shape[2], k.shape[2]
    if Hkv != H:
        k = jnp.repeat(k, H // Hkv, axis=2)
        v = jnp.repeat(v, H // Hkv, axis=2)
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    if causal:
        q_pos = q_offset + jnp.arange(q.shape[1])
        k_pos = k_offset + jnp.arange(k.shape[1])
        mask = q_pos[:, None] >= k_pos[None, :]
        s = jnp.where(mask[None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1)  # [B,H,Sq]
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return acc, m.transpose(0, 2, 1), l.transpose(0, 2, 1)


def _merge(acc1, m1, l1, acc2, m2, l2):
    """Merge two partial online-softmax states."""
    m = jnp.maximum(m1, m2)
    a1 = jnp.exp(m1 - m)
    a2 = jnp.exp(m2 - m)
    # acc layout [B,Sq,H,D]; m/l are [B,Sq,H]
    acc = acc1 * a1[..., None] + acc2 * a2[..., None]
    l = l1 * a1 + l2 * a2
    return acc, m, l


def _ring_xla_shard(q_blk, k_blk, v_blk, axis_name, n, causal):
    B, shard, H, D = q_blk.shape
    sm_scale = 1.0 / (D ** 0.5)
    idx = jax.lax.axis_index(axis_name)
    q_off = idx * shard

    def ring_step(step, carry):
        acc, m, l, k_cur, v_cur = carry
        # Which global shard does k_cur hold? It started at `idx` and has
        # been passed backward `step` times: origin = (idx + step) % n.
        origin = (idx + step) % n
        k_off = origin * shard

        def attend(args):
            acc, m, l = args
            a2, m2, l2 = _block_attend(q_blk, k_cur, v_cur, q_off, k_off,
                                       causal, sm_scale)
            acc, m, l = _merge(acc, m, l, a2, m2, l2)
            return acc, m, l

        # Causal: skip shards strictly in the future (k_off > q end).
        if causal:
            acc, m, l = jax.lax.cond(
                k_off > q_off + shard - 1, lambda a: a, attend, (acc, m, l))
        else:
            acc, m, l = attend((acc, m, l))
        # Pass K/V to the previous neighbor (receive from next) so the
        # ring sweeps forward through global shards.
        perm = [(i, (i - 1) % n) for i in range(n)]
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return acc, m, l, k_nxt, v_nxt

    acc0 = jnp.zeros((B, shard, H, D), jnp.float32)
    m0 = jnp.full((B, shard, H), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, shard, H), jnp.float32)
    acc, m, l, _, _ = jax.lax.fori_loop(
        0, n, ring_step, (acc0, m0, l0, k_blk, v_blk))
    l = jnp.maximum(l, 1e-30)
    return (acc / l[..., None]).astype(q_blk.dtype)


# ---------------------------------------------------------------- flash path


def _merge_lse(o1, lse1, o2, lse2):
    """Merge two NORMALIZED partial outputs via their log-sum-exps.
    o: [B,S,H,D] fp32; lse: [B,H,S] fp32. The global output is
    sum_i exp(lse_i - lse_global) * o_i."""
    m = jnp.maximum(lse1, lse2)
    w1 = jnp.exp(lse1 - m)
    w2 = jnp.exp(lse2 - m)
    denom = w1 + w2
    lse = m + jnp.log(denom)
    wt = lambda w: (w / denom).transpose(0, 2, 1)[..., None]  # noqa: E731
    return o1 * wt(w1) + o2 * wt(w2), lse


def _rotate(xs, axis_name, n):
    perm = [(i, (i - 1) % n) for i in range(n)]
    return [jax.lax.ppermute(x, axis_name, perm) for x in xs]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _ring_flash_shard(q_blk, k_blk, v_blk, axis_name, n, causal, interpret):
    out, _ = _ring_flash_fwd_impl(q_blk, k_blk, v_blk, axis_name, n, causal,
                                  interpret)
    return out


def _ring_flash_fwd_impl(q_blk, k_blk, v_blk, axis_name, n, causal, interpret):
    idx = jax.lax.axis_index(axis_name)
    # Step 0 is peeled: the resident K/V shard is the DIAGONAL block, the
    # only one needing the triangular mask — so every kernel call in the
    # ring has a static causal flag.
    o, lse = flash_block_fwd(q_blk, k_blk, v_blk, causal=causal,
                             interpret=interpret)
    o = o.astype(jnp.float32)
    k_cur, v_cur = _rotate([k_blk, v_blk], axis_name, n)

    def ring_step(step, carry):
        o_acc, lse_acc, k_cur, v_cur = carry
        origin = (idx + step) % n

        def attend(args):
            o_acc, lse_acc = args
            o2, lse2 = flash_block_fwd(q_blk, k_cur, v_cur, causal=False,
                                       interpret=interpret)
            return _merge_lse(o_acc, lse_acc, o2.astype(jnp.float32), lse2)

        if causal:
            # Visible iff the shard is strictly in the past (the diagonal
            # was step 0; future shards contribute nothing).
            o_acc, lse_acc = jax.lax.cond(
                origin < idx, attend, lambda a: a, (o_acc, lse_acc))
        else:
            o_acc, lse_acc = attend((o_acc, lse_acc))
        k_cur, v_cur = _rotate([k_cur, v_cur], axis_name, n)
        return o_acc, lse_acc, k_cur, v_cur

    o, lse, _, _ = jax.lax.fori_loop(1, n, ring_step, (o, lse, k_cur, v_cur))
    return o.astype(q_blk.dtype), lse


def _ring_flash_fwd_rule(q_blk, k_blk, v_blk, axis_name, n, causal, interpret):
    out, lse = _ring_flash_fwd_impl(q_blk, k_blk, v_blk, axis_name, n, causal,
                                    interpret)
    return out, (q_blk, k_blk, v_blk, out, lse)


def _ring_flash_bwd_rule(axis_name, n, causal, interpret, res, do):
    """Ring backward: dK/dV partials travel WITH their K/V shard. Each step
    adds the local device's gradient contribution to the resident shard;
    after n rotations every shard (and its fully-accumulated gradient) is
    home. dQ accumulates locally."""
    q_blk, k_blk, v_blk, out, lse = res
    idx = jax.lax.axis_index(axis_name)
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1).transpose(0, 2, 1)  # [B,H,Sq]

    dq, dk, dv = flash_block_bwd(q_blk, k_blk, v_blk, do, lse, delta,
                                 causal=causal, interpret=interpret)
    k_cur, v_cur, dk_cur, dv_cur = _rotate(
        [k_blk, v_blk, dk, dv], axis_name, n)

    def ring_step(step, carry):
        dq, k_cur, v_cur, dk_cur, dv_cur = carry
        origin = (idx + step) % n

        def attend(args):
            dq, dk_cur, dv_cur = args
            dq2, dk2, dv2 = flash_block_bwd(
                q_blk, k_cur, v_cur, do, lse, delta, causal=False,
                interpret=interpret)
            return dq + dq2, dk_cur + dk2, dv_cur + dv2

        if causal:
            dq, dk_cur, dv_cur = jax.lax.cond(
                origin < idx, attend, lambda a: a, (dq, dk_cur, dv_cur))
        else:
            dq, dk_cur, dv_cur = attend((dq, dk_cur, dv_cur))
        k_cur, v_cur, dk_cur, dv_cur = _rotate(
            [k_cur, v_cur, dk_cur, dv_cur], axis_name, n)
        return dq, k_cur, v_cur, dk_cur, dv_cur

    dq, _, _, dk_cur, dv_cur = jax.lax.fori_loop(
        1, n, ring_step, (dq, k_cur, v_cur, dk_cur, dv_cur))
    return (dq.astype(q_blk.dtype), dk_cur.astype(k_blk.dtype),
            dv_cur.astype(v_blk.dtype))


_ring_flash_shard.defvjp(_ring_flash_fwd_rule, _ring_flash_bwd_rule)


# ------------------------------------------------------------------- public


def ring_attention(q, k, v, mesh, axis_name: str = "seq",
                   causal: bool = True, impl: str = "auto",
                   interpret: bool = False):
    """Sequence-parallel attention. q: [B, S, H, D] GLOBALLY, k/v:
    [B, S, Hkv, D] (GQA: Hkv divides H), all sharded on dim 1 over
    ``axis_name``. Returns out with q's sharding.

    ``impl``: "flash" (Pallas blocks + ring VJP), "xla" (einsum blocks,
    autodiff), or "auto" (flash on a TPU backend when each [S/n] shard
    tiles by 128 and D >= 64; xla otherwise). ``interpret`` runs the
    Pallas path in interpret mode (CPU tests).
    """
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis_name]
    B, S, H, D = q.shape
    Hkv = k.shape[2]
    if S % n:
        raise ValueError("Sequence length {} must divide over {} '{}' shards"
                         .format(S, n, axis_name))
    if H % Hkv:
        raise ValueError("H={} not divisible by Hkv={}".format(H, Hkv))
    from maggy_tpu.ops.attention import resolve_seq_parallel_impl

    shard = S // n
    impl = resolve_seq_parallel_impl(shard, D, impl, interpret, "S/n")

    qspec = P(None, axis_name, None, None)
    if impl == "flash":
        # Positional pass-through: custom_vjp's nondiff_argnums are
        # positional-only.
        def fn(qb, kb, vb):
            return _ring_flash_shard(qb, kb, vb, axis_name, n, causal,
                                     interpret)
    else:
        def fn(qb, kb, vb):
            return _ring_xla_shard(qb, kb, vb, axis_name, n, causal)
    out = version_shard_map(
        fn, mesh=mesh, in_specs=(qspec, qspec, qspec), out_specs=qspec,
        check_vma=False,
    )(q, k, v)
    return out
