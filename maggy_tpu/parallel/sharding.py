"""Parameter/batch sharding rules: dp / fsdp / tp in one place.

The reference's only parallelism is DDP data parallelism
(`dist_executor.py:102`, SURVEY.md §2.8). TPU-native, the same and more fall
out of GSPMD sharding specs:

- dp:    params replicated, batch sharded on "data" -> XLA all-reduces grads
- fsdp:  params sharded on their largest divisible axis over "fsdp"
         (ZeRO-3-style; all-gather on use, reduce-scatter on grads)
- tp:    matmul weights sharded on "model" (Megatron-style column/row)

`shard_params` computes a NamedSharding pytree for a params pytree by simple,
robust rules (largest-divisible-axis) rather than per-model annotations; the
model zoo can override with explicit rules where it matters.
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np


def _best_axis(shape, axis_size: int, prefer_last: bool = True) -> Optional[int]:
    """Largest dimension divisible by axis_size (ties -> last/first)."""
    candidates = [(d, i) for i, d in enumerate(shape) if d % axis_size == 0 and d >= axis_size]
    if not candidates:
        return None
    best_d = max(d for d, _ in candidates)
    idxs = [i for d, i in candidates if d == best_d]
    return idxs[-1] if prefer_last else idxs[0]


def param_sharding(mesh, path_shape_leaf, strategy: str = "dp"):
    """NamedSharding for ONE param leaf under the given strategy."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    shape = np.shape(path_shape_leaf) if not hasattr(path_shape_leaf, "shape") \
        else path_shape_leaf.shape
    names = mesh.axis_names
    if strategy == "dp" or not shape:
        return NamedSharding(mesh, P())
    if strategy == "fsdp" and "fsdp" in names:
        ax = _best_axis(shape, mesh.shape["fsdp"])
        if ax is None:
            return NamedSharding(mesh, P())
        spec = [None] * len(shape)
        spec[ax] = "fsdp"
        return NamedSharding(mesh, P(*spec))
    if strategy == "tp" and "model" in names:
        ax = _best_axis(shape, mesh.shape["model"])
        if ax is None:
            return NamedSharding(mesh, P())
        spec = [None] * len(shape)
        spec[ax] = "model"
        return NamedSharding(mesh, P(*spec))
    if strategy in ("fsdp_tp", "dp_tp"):
        # model axis on the last divisible dim, fsdp on another if present
        spec = [None] * len(shape)
        if "model" in names:
            ax = _best_axis(shape, mesh.shape["model"])
            if ax is not None:
                spec[ax] = "model"
        if strategy == "fsdp_tp" and "fsdp" in names:
            free = [i for i, s in enumerate(spec) if s is None]
            cands = [i for i in free if shape[i] % mesh.shape["fsdp"] == 0]
            if cands:
                spec[max(cands, key=lambda i: shape[i])] = "fsdp"
        return NamedSharding(mesh, P(*spec))
    return NamedSharding(mesh, P())


def shard_params(mesh, params, strategy: str = "dp"):
    """Sharding pytree for a whole params pytree."""
    import jax

    return jax.tree_util.tree_map(
        lambda leaf: param_sharding(mesh, leaf, strategy), params
    )


def logical_axis_rules(strategy: str = "dp"):
    """Logical-axis -> mesh-axis rules for the model zoo's
    `nn.with_logical_partitioning` annotations (llama.py/bert.py/moe.py).

    ``strategy`` is underscore-composable from {"dp", "fsdp", "tp", "sp",
    "ep"} — e.g. "dp", "fsdp_tp", "dp_sp", "dp_sp_ep":

    - dp:   everything replicated (gradients all-reduced over "data")
    - fsdp: embed dim sharded over "fsdp" (ZeRO-3)
    - tp:   head/mlp/vocab dims sharded over "model" (Megatron)
    - sp:   no param sharding; activations' sequence dim shards via
            batch_sharding + ring attention over "seq"
    - ep:   MoE expert dim sharded over "expert"
    - zero: no param rules here; OPTIMIZER STATE shards over "data"
            (ZeRO-1 / cross-replica weight-update sharding,
            arXiv:2004.13336) — applied by the Trainer, see
            `zero_opt_sharding`
    """
    rules = {"embed": None, "mlp": None, "heads": None, "kv": None,
             "vocab": None, "expert": None}
    parts = set(strategy.split("_"))
    unknown = parts - {"dp", "fsdp", "tp", "sp", "ep", "zero"}
    if unknown:
        raise ValueError("Unknown strategy {!r} (bad parts: {})"
                         .format(strategy, sorted(unknown)))
    if "fsdp" in parts:
        rules["embed"] = "fsdp"
    if "tp" in parts:
        rules.update(mlp="model", heads="model", kv="model", vocab="model")
    if "ep" in parts:
        # Expert-parallel: the stacked expert dim of MoE weights shards over
        # the "expert" mesh axis; token dispatch becomes an XLA all-to-all.
        rules["expert"] = "expert"
    # "dp" and "sp" add no param sharding (sp shards activations' sequence
    # dim via batch_sharding + ring attention, params stay as above).
    return list(rules.items())


def batch_sharding(mesh, ndim: int = 2, shape=None):
    """Batch sharded over every data-like axis on dim 0, replicated after.

    If the mesh has a "seq" axis (sequence/context parallelism), dim 1 — the
    sequence dim of [B, S, ...] batches — is sharded over it, matching the
    ring-attention layout (parallel/ring_attention.py). When ``shape`` is
    given, the seq rule applies only if dim 1 divides evenly (non-sequence
    tensors like [B, features] stay replicated past dim 0).
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    if shape is not None:
        ndim = len(shape)
    data_axes = tuple(a for a in ("data", "fsdp") if a in mesh.axis_names)
    rest = [None] * (ndim - 1)
    if ndim >= 2 and "seq" in mesh.axis_names and (
            shape is None or shape[1] % mesh.shape["seq"] == 0):
        rest[0] = "seq"
    return NamedSharding(mesh, P(data_axes if data_axes else None, *rest))


import functools


@functools.lru_cache(maxsize=1024)
def _cached_batch_sharding(mesh, shape):
    return batch_sharding(mesh, shape=shape)


def cached_batch_sharding(mesh, shape):
    """``batch_sharding`` memoized by (mesh, leaf shape): the per-leaf
    spec re-derivation is pure in both, so steady-state training steps
    (Trainer.place_batch, data.py's device_put path) look the sharding up
    instead of rebuilding it for every leaf of every batch. Meshes hash by
    topology and the cache is bounded, so long-lived fleet runners hold at
    most 1024 (mesh, shape) entries."""
    return _cached_batch_sharding(mesh, tuple(shape))


def validate_zero_strategy(mesh, strategy: str) -> bool:
    """True iff the "zero" part is active; raises on configurations where
    it would silently do the wrong thing instead of degrading quietly."""
    parts = set(strategy.split("_"))
    if "zero" not in parts:
        return False
    overlapping = parts & {"fsdp", "tp", "ep"}
    if overlapping:
        raise ValueError(
            "strategy part 'zero' composes with dp/sp only (got {!r}): "
            "fsdp already de-duplicates moments (ZeRO-3), and forcing the "
            "data-axis layout would clobber tp/ep moment sharding.".format(
                strategy))
    if "data" not in mesh.axis_names:
        raise ValueError(
            "strategy part 'zero' needs a 'data' mesh axis to shard the "
            "optimizer state over; mesh has {}".format(mesh.axis_names))
    return True


def zero_opt_sharding(mesh, strategy: str, shape):
    """NamedSharding for ONE optimizer-state leaf under the "zero" strategy
    part (ZeRO-1 / automatic cross-replica sharding of the weight update,
    arXiv:2004.13336): the leaf's leading dim shards over "data" when it
    divides evenly; scalars and indivisible leaves stay replicated. Params
    stay replicated at init — only the redundant optimizer moments (2x
    params for Adam) are de-duplicated across data replicas; XLA turns the
    update into reduce-scatter -> sharded update -> all-gather. Returns
    None when the strategy has no "zero" part.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    if not validate_zero_strategy(mesh, strategy):
        return None
    n = mesh.shape["data"]
    shape = tuple(shape)
    if len(shape) >= 1 and shape[0] > 0 and shape[0] % n == 0:
        return NamedSharding(mesh, P("data", *([None] * (len(shape) - 1))))
    return NamedSharding(mesh, P())


def apply_zero_sharding(tree, mesh, strategy: str, placer):
    """Map every optimizer-state leaf through ``placer(leaf, sharding)``
    under the "zero" layout — the ONE place init-time placement
    (device_put) and step-time constraints (with_sharding_constraint)
    share, so they cannot drift. No-op without a "zero" part."""
    import jax
    import jax.numpy as jnp

    if not validate_zero_strategy(mesh, strategy):
        return tree

    def place(x):
        sh = zero_opt_sharding(mesh, strategy, jnp.shape(x))
        return placer(x, sh)

    return jax.tree_util.tree_map(place, tree)
