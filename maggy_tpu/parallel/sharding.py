"""Parameter/batch sharding rules: dp / fsdp / tp in one place.

The reference's only parallelism is DDP data parallelism
(`dist_executor.py:102`, SURVEY.md §2.8). TPU-native, the same and more fall
out of GSPMD sharding specs:

- dp:    params replicated, batch sharded on "data" -> XLA all-reduces grads
- fsdp:  params sharded on their largest divisible axis over "fsdp"
         (ZeRO-3-style; all-gather on use, reduce-scatter on grads)
- tp:    matmul weights sharded on "model" (Megatron-style column/row)

`shard_params` computes a NamedSharding pytree for a params pytree by simple,
robust rules (largest-divisible-axis) rather than per-model annotations; the
model zoo can override with explicit rules where it matters.
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np


def _best_axis(shape, axis_size: int, prefer_last: bool = True) -> Optional[int]:
    """Largest dimension divisible by axis_size (ties -> last/first)."""
    candidates = [(d, i) for i, d in enumerate(shape) if d % axis_size == 0 and d >= axis_size]
    if not candidates:
        return None
    best_d = max(d for d, _ in candidates)
    idxs = [i for d, i in candidates if d == best_d]
    return idxs[-1] if prefer_last else idxs[0]


def param_sharding(mesh, path_shape_leaf, strategy: str = "dp"):
    """NamedSharding for ONE param leaf under the given strategy."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    shape = np.shape(path_shape_leaf) if not hasattr(path_shape_leaf, "shape") \
        else path_shape_leaf.shape
    names = mesh.axis_names
    if strategy == "dp" or not shape:
        return NamedSharding(mesh, P())
    if strategy == "fsdp" and "fsdp" in names:
        ax = _best_axis(shape, mesh.shape["fsdp"])
        if ax is None:
            return NamedSharding(mesh, P())
        spec = [None] * len(shape)
        spec[ax] = "fsdp"
        return NamedSharding(mesh, P(*spec))
    if strategy == "tp" and "model" in names:
        ax = _best_axis(shape, mesh.shape["model"])
        if ax is None:
            return NamedSharding(mesh, P())
        spec = [None] * len(shape)
        spec[ax] = "model"
        return NamedSharding(mesh, P(*spec))
    if strategy in ("fsdp_tp", "dp_tp"):
        # model axis on the last divisible dim, fsdp on another if present
        spec = [None] * len(shape)
        if "model" in names:
            ax = _best_axis(shape, mesh.shape["model"])
            if ax is not None:
                spec[ax] = "model"
        if strategy == "fsdp_tp" and "fsdp" in names:
            free = [i for i, s in enumerate(spec) if s is None]
            cands = [i for i in free if shape[i] % mesh.shape["fsdp"] == 0]
            if cands:
                spec[max(cands, key=lambda i: shape[i])] = "fsdp"
        return NamedSharding(mesh, P(*spec))
    return NamedSharding(mesh, P())


def shard_params(mesh, params, strategy: str = "dp"):
    """Sharding pytree for a whole params pytree."""
    import jax

    return jax.tree_util.tree_map(
        lambda leaf: param_sharding(mesh, leaf, strategy), params
    )


def logical_axis_rules(strategy: str = "dp"):
    """Logical-axis -> mesh-axis rules for the model zoo's
    `nn.with_logical_partitioning` annotations (llama.py/bert.py).

    - dp:    everything replicated
    - fsdp:  embed dim sharded over "fsdp" (ZeRO-3)
    - tp:    head/mlp/vocab dims sharded over "model" (Megatron)
    - fsdp_tp: both
    """
    if strategy == "dp":
        return [("embed", None), ("mlp", None), ("heads", None),
                ("kv", None), ("vocab", None)]
    if strategy == "fsdp":
        return [("embed", "fsdp"), ("mlp", None), ("heads", None),
                ("kv", None), ("vocab", None)]
    if strategy == "tp":
        return [("embed", None), ("mlp", "model"), ("heads", "model"),
                ("kv", "model"), ("vocab", "model")]
    if strategy == "fsdp_tp":
        return [("embed", "fsdp"), ("mlp", "model"), ("heads", "model"),
                ("kv", "model"), ("vocab", "model")]
    raise ValueError("Unknown strategy {!r}".format(strategy))


def batch_sharding(mesh, ndim: int = 2):
    """Batch sharded over every data-like axis on dim 0, replicated after."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    data_axes = tuple(a for a in ("data", "fsdp") if a in mesh.axis_names)
    return NamedSharding(mesh, P(data_axes if data_axes else None,
                                 *([None] * (ndim - 1))))
