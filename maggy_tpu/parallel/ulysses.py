"""Ulysses-style sequence parallelism: all-to-all head/sequence swap.

The second of the two standard long-context schemes (SURVEY.md §5.7; the
reference has neither). Ring attention (`ring_attention.py`) keeps Q local
and streams K/V around a `ppermute` ring — O(S/n) memory, n ring steps.
Ulysses (DeepSpeed-Ulysses, arXiv:2309.14509) instead swaps WHICH dim is
sharded: inputs arrive sharded on sequence, one `all_to_all` over the ICI
re-shards them on heads, every device runs ordinary FULL-sequence attention
for its head subset, and a second `all_to_all` swaps back.

Trade-offs (why both exist):
- Ulysses does 2 collectives total (vs n-1 ring hops) and reuses the plain
  single-device flash kernel unmodified — including its causal handling —
  so it composes with any attention implementation.
- Its parallel degree is capped by the HEAD count (n must divide H; GQA
  caps it at the KV-head count), while the ring scales with sequence
  length alone. Memory is O(S) per device for the attention inputs, vs
  the ring's O(S/n).

Use the ring for extreme context on few heads; Ulysses when heads are
plentiful and collective count (latency) dominates.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from maggy_tpu.parallel.mesh import shard_map as version_shard_map


def ulysses_attention(q, k, v, mesh, axis_name: str = "seq",
                      causal: bool = True, impl: str = "auto",
                      interpret: bool = False):
    """Sequence-parallel attention via head/sequence all-to-all.

    q: [B, S, H, D] GLOBALLY, k/v: [B, S, Hkv, D] (GQA: Hkv divides H),
    all sharded on dim 1 over ``axis_name``. Returns out with q's
    sharding. The mesh degree n must divide Hkv (each device needs whole
    KV heads after the swap).

    ``impl``: "flash" (Pallas single-device kernel per head subset),
    "xla" (reference einsum attention), "auto" (flash on TPU when shapes
    tile). ``interpret`` runs Pallas in interpret mode (CPU tests).
    """
    from jax.sharding import PartitionSpec as P

    from maggy_tpu.ops.attention import (attention_reference, flash_attention,
                                         resolve_seq_parallel_impl)

    n = mesh.shape[axis_name]
    B, S, H, D = q.shape
    Hkv = k.shape[2]
    if S % n:
        raise ValueError("Sequence length {} must divide over {} '{}' shards"
                         .format(S, n, axis_name))
    if H % Hkv:
        raise ValueError("H={} not divisible by Hkv={}".format(H, Hkv))
    if Hkv % n:
        raise ValueError(
            "Ulysses needs the KV-head count ({}) divisible by the '{}' "
            "degree ({}); use ring_attention for more shards than heads."
            .format(Hkv, axis_name, n))

    # Shared dispatch policy with ring_attention — here the kernel sees the
    # FULL gathered sequence, so global S (not the shard) must tile.
    use_flash = resolve_seq_parallel_impl(S, D, impl, interpret, "S") == "flash"

    def local_fn(q_l, k_l, v_l):
        # [B, S/n, H, D] -> all_to_all splits heads n ways and gathers the
        # full sequence: [B, S, H/n, D]. One ICI collective each way.
        def seq_to_heads(x):
            return jax.lax.all_to_all(x, axis_name, split_axis=2,
                                      concat_axis=1, tiled=True)

        def heads_to_seq(x):
            return jax.lax.all_to_all(x, axis_name, split_axis=1,
                                      concat_axis=2, tiled=True)

        q_h = seq_to_heads(q_l)
        k_h = seq_to_heads(k_l)
        v_h = seq_to_heads(v_l)
        if use_flash:
            out = flash_attention(q_h, k_h, v_h, None, causal,
                                  interpret=interpret)
        else:
            out = attention_reference(q_h, k_h, v_h, causal=causal)
        return heads_to_seq(out.astype(q_l.dtype))

    spec = P(None, axis_name, None, None)
    return version_shard_map(
        local_fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )(q, k, v)
