"""Pruner plugin contract.

Parity: reference `maggy/pruner/abstractpruner.py:23-95`. A pruner owns the
multi-fidelity schedule; the optimizer delegates budget/promotion decisions to
`pruning_routine()` and reports spawned trial ids back via `report_trial()`.
The pruner reads trial outcomes through ``trial_metric_getter`` (the
optimizer's `get_metrics_dict`, direction-normalized so lower is better —
wired at `abstractoptimizer.py:312-315`).
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Callable, Dict, Optional


class AbstractPruner(ABC):
    def __init__(self, trial_metric_getter: Callable[..., Dict[str, float]]):
        self.trial_metric_getter = trial_metric_getter

    @abstractmethod
    def pruning_routine(self):
        """Return {"trial_id": parent_or_None, "budget": b}, "IDLE", or None."""

    @abstractmethod
    def report_trial(self, original_trial_id: Optional[str], new_trial_id: str) -> None:
        """Associate the trial the optimizer created with the slot just handed out."""

    @abstractmethod
    def finished(self) -> bool:
        """True once the full multi-fidelity schedule has been executed."""

    @abstractmethod
    def num_trials(self) -> int:
        """Total number of trial runs the schedule will execute."""
