"""Hyperband: multi-bracket successive halving (BOHB-style).

Parity: reference `maggy/pruner/hyperband.py` — geometric budget ladder and
max rung count (:114-125), bracket construction with per-bracket
(n_configs, budgets) (:197-218), `pruning_routine` scanning active iterations
then starting the next bracket, else IDLE, else None (:137-195),
`report_trial` routing (:266-279), `SHIteration` with INIT/RUNNING/FINISHED
states and rung bookkeeping {rung -> [{original, actual}]} (:299-594).

Bracket sizing follows HpBandSter/BOHB: bracket ``s`` runs
``n0 = ceil(max_rungs/(s+1) * eta^s)`` configs over ``s+1`` rungs with
``n_j = floor(n0 * eta^-j)`` survivors at rung j.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional

from maggy_tpu.pruner.abstractpruner import AbstractPruner


def _geometric_rungs(min_budget: float, max_budget: float, eta: float) -> int:
    """Number of rungs in the ladder min*eta^k <= max, computed exactly."""
    rungs, b = 1, float(min_budget)
    while b * eta <= max_budget * (1 + 1e-9):
        b *= eta
        rungs += 1
    return rungs


class SHIteration:
    """One successive-halving bracket."""

    INIT = "INIT"
    RUNNING = "RUNNING"
    FINISHED = "FINISHED"

    def __init__(self, iteration_id: int, n_configs: List[int], budgets: List[float]):
        assert len(n_configs) == len(budgets)
        self.iteration_id = iteration_id
        self.n_configs = n_configs  # survivors per rung
        self.budgets = budgets  # budget per rung
        self.state = SHIteration.INIT
        # rung -> list of slots {"original": rung0-lineage id, "actual": run id}
        self.configs: Dict[int, List[dict]] = {r: [] for r in range(len(budgets))}
        # Slot handed out by get_next_run but not yet bound via report_trial.
        self._pending: Optional[dict] = None

    @property
    def num_rungs(self) -> int:
        return len(self.budgets)

    def actual_ids(self, rung: int) -> List[str]:
        return [s["actual"] for s in self.configs[rung] if s["actual"] is not None]

    def rung_full(self, rung: int) -> bool:
        return len(self.configs[rung]) >= self.n_configs[rung]

    def rung_finalized(self, rung: int, metrics: Dict[str, float]) -> bool:
        ids = self.actual_ids(rung)
        return (
            self.rung_full(rung)
            and len(ids) == self.n_configs[rung]
            and all(tid in metrics for tid in ids)
        )

    def get_next_run(self, metrics: Dict[str, float]) -> Optional[dict]:
        """Return the next schedulable run in this bracket, or None.

        Rung-0 slots first ({"trial_id": None} → optimizer samples fresh);
        then promotions of finalized lower rungs (reference
        `hyperband.py:377-443,487-527`).
        """
        if self._pending is not None:
            return None  # one outstanding hand-out at a time
        self.state = SHIteration.RUNNING
        if not self.rung_full(0):
            self._pending = {"rung": 0, "original": None}
            return {"trial_id": None, "budget": self.budgets[0]}
        for rung in range(self.num_rungs - 1):
            if not self.rung_finalized(rung, metrics):
                continue
            if self.rung_full(rung + 1):
                continue
            promoted_originals = {s["original"] for s in self.configs[rung + 1]}
            # Top-k of this rung by normalized metric (lower is better).
            ranked = sorted(self.configs[rung], key=lambda s: metrics[s["actual"]])
            for slot in ranked[: self.n_configs[rung + 1]]:
                if slot["original"] not in promoted_originals:
                    self._pending = {"rung": rung + 1, "original": slot["original"]}
                    return {"trial_id": slot["actual"], "budget": self.budgets[rung + 1]}
        return None

    def report_trial(self, new_trial_id: str) -> None:
        assert self._pending is not None, "report_trial without a pending slot"
        rung = self._pending["rung"]
        original = self._pending["original"] or new_trial_id
        self.configs[rung].append({"original": original, "actual": new_trial_id})
        self._pending = None

    def check_finished(self, metrics: Dict[str, float]) -> bool:
        if self.state == SHIteration.FINISHED:
            return True
        if self._pending is None and self.rung_finalized(self.num_rungs - 1, metrics):
            self.state = SHIteration.FINISHED
            return True
        return False


class Hyperband(AbstractPruner):
    def __init__(
        self,
        trial_metric_getter,
        min_budget: float = 1,
        max_budget: float = 9,
        eta: int = 3,
        n_iterations: Optional[int] = None,
    ):
        super().__init__(trial_metric_getter)
        if eta < 2:
            raise ValueError("eta must be >= 2")
        if min_budget <= 0 or max_budget < min_budget:
            raise ValueError("Require 0 < min_budget <= max_budget")
        self.min_budget = min_budget
        self.max_budget = max_budget
        self.eta = eta
        # Geometric ladder ending at max_budget (reference `hyperband.py:114-125`).
        # Exact integer loop, not floor(log()): float error makes
        # math.log(243, 3) == 4.9999... and would drop a rung.
        self.max_sh_rungs = _geometric_rungs(min_budget, max_budget, eta)
        self.budgets = [
            max_budget * eta ** (-(self.max_sh_rungs - 1 - j)) for j in range(self.max_sh_rungs)
        ]
        self.n_iterations = n_iterations if n_iterations is not None else self.max_sh_rungs
        self.iterations: List[SHIteration] = []

    # ------------------------------------------------------------- schedule

    def _bracket_plan(self, iteration_id: int):
        """(n_configs, budgets) for bracket i, cycling s = max-1 ... 0."""
        s = self.max_sh_rungs - 1 - (iteration_id % self.max_sh_rungs)
        n0 = int(math.ceil(self.max_sh_rungs / (s + 1) * self.eta ** s))
        n_configs = [max(1, int(n0 * self.eta ** (-j))) for j in range(s + 1)]
        budgets = self.budgets[-(s + 1):]
        return n_configs, budgets

    def num_trials(self) -> int:
        return sum(sum(self._bracket_plan(i)[0]) for i in range(self.n_iterations))

    # -------------------------------------------------------------- routine

    def pruning_routine(self):
        metrics = self.trial_metric_getter()
        # Scan active iterations for a schedulable run (reference :137-195).
        for it in self.iterations:
            if it.check_finished(metrics):
                continue
            run = it.get_next_run(metrics)
            if run is not None:
                self._updating_iteration = it
                return run
        # Start the next bracket if any remain.
        if len(self.iterations) < self.n_iterations:
            n_configs, budgets = self._bracket_plan(len(self.iterations))
            it = SHIteration(len(self.iterations), n_configs, budgets)
            self.iterations.append(it)
            run = it.get_next_run(metrics)
            assert run is not None
            self._updating_iteration = it
            return run
        if self.finished():
            return None
        return "IDLE"

    def report_trial(self, original_trial_id: Optional[str], new_trial_id: str) -> None:
        self._updating_iteration.report_trial(new_trial_id)

    def report_failure(self, trial_id: str) -> None:
        """Remove a failed run's slot so its rung can be re-issued.

        Without this, a trial finalized without a metric (ERROR path) would
        block `rung_finalized` forever and hang the schedule in IDLE. The
        driver calls this when a trial lands in `Trial.ERROR`.
        """
        for it in self.iterations:
            for rung, slots in it.configs.items():
                for slot in slots:
                    if slot["actual"] == trial_id:
                        slots.remove(slot)
                        if it.state == SHIteration.FINISHED:
                            it.state = SHIteration.RUNNING
                        return

    def finished(self) -> bool:
        if len(self.iterations) < self.n_iterations:
            return False
        metrics = self.trial_metric_getter()
        return all(it.check_finished(metrics) for it in self.iterations)

    # ------------------------------------------------------ checkpoint/resume

    def state_dict(self) -> dict:
        """Bracket state as plain JSON-able data (SURVEY.md §5.4: the driver
        checkpoints this per scheduling transition so `resume=True` works
        with a pruner). The pending hand-out is deliberately NOT saved — at
        restore time an un-finalized hand-out is simply re-issued."""
        return {
            "iterations": [
                {
                    "iteration_id": it.iteration_id,
                    "n_configs": it.n_configs,
                    "budgets": it.budgets,
                    "state": it.state,
                    "configs": {str(r): list(slots)
                                for r, slots in it.configs.items()},
                }
                for it in self.iterations
            ]
        }

    def load_state_dict(self, state: dict) -> None:
        self.iterations = []
        for rec in state.get("iterations", []):
            it = SHIteration(rec["iteration_id"], list(rec["n_configs"]),
                             list(rec["budgets"]))
            it.state = rec["state"]
            it.configs = {int(r): list(slots)
                          for r, slots in rec["configs"].items()}
            self.iterations.append(it)

    def restore(self, finalized_ids) -> None:
        """Reconcile restored bracket state with the trials that actually
        finalized: slots bound to runs the interrupted experiment never
        finished are dropped (their rungs re-issue them), and each bracket's
        state is recomputed from the surviving metrics."""
        finalized_ids = set(finalized_ids)
        metrics = self.trial_metric_getter()
        for it in self.iterations:
            it._pending = None
            for rung in list(it.configs):
                it.configs[rung] = [s for s in it.configs[rung]
                                    if s["actual"] in finalized_ids]
            it.state = (SHIteration.INIT if not any(it.configs.values())
                        else SHIteration.RUNNING)
            it.check_finished(metrics)
