"""Remote trial-runner agent: ``python -m maggy_tpu.runner``.

The DCN half of cross-host trial parallelism. The driver (pool="remote")
publishes a join ticket — advertised address + shared secret — to the
experiment directory; an agent on any reachable host (typically each TPU VM
of a pod slice) dials in, JOINs to receive its partition id and executor
config, then runs the standard trial-executor loop: register -> heartbeat ->
get_suggestion -> train -> finalize, until GSTOP.

The reference ships the train function to Spark executors by cloudpickling a
closure (`driver.py:96-106`) — arbitrary code on the wire. Here the train
function is named by a dotted path (``pkg.module:fn``) and imported locally
on the agent; only declarative data crosses the network.

Usage (on each runner host):

    python -m maggy_tpu.runner --ticket /shared/exp_dir/runner_ticket.json \
        --train my_project.train:train_fn

or, without a shared filesystem:

    python -m maggy_tpu.runner --driver 10.0.0.2:41234 --secret-file s.txt \
        --train my_project.train:train_fn
"""

from __future__ import annotations

import argparse
import importlib
import json
import os
import socket
import sys
import time
from typing import Callable, Optional, Tuple

from maggy_tpu import constants
from maggy_tpu.core.executors.trial_executor import TrialExecutor
from maggy_tpu.core.rpc import MessageSocket


def load_train_fn(spec: str) -> Callable:
    """Resolve ``pkg.module:fn`` to the callable it names."""
    mod_name, sep, fn_name = spec.partition(":")
    if not sep or not mod_name or not fn_name:
        raise ValueError(
            "--train must be 'package.module:function', got {!r}".format(spec))
    module = importlib.import_module(mod_name)
    fn = module
    for part in fn_name.split("."):
        fn = getattr(fn, part)
    if not callable(fn):
        raise TypeError("{!r} resolved to non-callable {!r}".format(spec, fn))
    return fn


def join_experiment(
    addr: Tuple[str, int], secret: str, partition_id: Optional[int] = None,
    timeout: float = 30.0,
) -> dict:
    """One-shot JOIN: ask the driver for a partition id + executor config."""
    key = secret.encode() if isinstance(secret, str) else secret
    sock = socket.create_connection(addr, timeout=timeout)
    try:
        MessageSocket.send_msg(
            sock,
            {"type": "JOIN",
             "partition_id": -1 if partition_id is None else partition_id},
            key,
        )
        resp = MessageSocket.recv_msg(sock, key)
    finally:
        sock.close()
    if resp.get("type") != "JOIN":
        raise RuntimeError("JOIN rejected: {}".format(resp.get("error", resp)))
    return resp


def read_ticket(path: str, wait_s: float = 0.0) -> dict:
    """Load the driver's join ticket, optionally waiting for it to appear
    (the driver writes it when the experiment starts)."""
    deadline = time.monotonic() + wait_s
    while True:
        if os.path.exists(path):
            try:
                with open(path) as f:
                    ticket = json.load(f)
                # Validate before use: the writer may not be atomic on a
                # shared fs, so a partial read must retry, not crash.
                ticket["host"], ticket["port"], ticket["secret"]
                return ticket
            except (json.JSONDecodeError, KeyError, OSError):
                pass
        if time.monotonic() >= deadline:
            raise FileNotFoundError("No join ticket at {}".format(path))
        time.sleep(0.5)


def run_agent(
    driver_addr: Tuple[str, int],
    secret: str,
    train_fn: Callable,
    partition_id: Optional[int] = None,
    profile: bool = False,
    config_factory: Optional[Callable] = None,
) -> int:
    """Join the experiment and run the matching executor loop to completion
    — the trial loop for HPO experiments, or one SPMD worker of the training
    world for distributed experiments (the JOIN reply's trial_type decides).
    Returns the partition id served."""
    info = join_experiment(driver_addr, secret, partition_id)
    if info.get("trial_type") == "distributed":
        from maggy_tpu.config import DistributedConfig
        from maggy_tpu.core.executors.dist_executor import DistExecutor

        # Model/dataset objects cannot travel over the wire; a config
        # factory builds them locally. Without one, mesh/strategy come from
        # the JOIN reply and the train_fn sees only the sharding_env.
        config = config_factory() if config_factory else DistributedConfig(
            num_workers=info["num_workers"],
            mesh_shape=info.get("mesh_shape") or {},
            strategy=info.get("strategy", "dp"),
        )
        executor = DistExecutor(
            server_addr=driver_addr,
            secret=secret,
            hb_interval=info["hb_interval"],
            exp_dir=info["exp_dir"],
            train_fn=train_fn,
            config=config,
            num_workers=info["num_workers"],
            profile=profile,
        )
    else:
        executor = TrialExecutor(
            server_addr=driver_addr,
            secret=secret,
            hb_interval=info["hb_interval"],
            exp_dir=info["exp_dir"],
            optimization_key=info["optimization_key"],
            train_fn=train_fn,
            trial_type=info.get("trial_type", "optimization"),
            profile=profile,
            warm_start=info.get("warm_start", True),
        )
    executor(info["partition_id"])
    return info["partition_id"]


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="maggy_tpu.runner", description="Remote trial-runner agent.")
    p.add_argument("--ticket", help="path to the driver's runner_ticket.json")
    p.add_argument("--wait-ticket", type=float, default=float(
        os.environ.get("MAGGY_TPU_TICKET_WAIT_S", constants.REGISTRATION_TIMEOUT_S)),
        help="seconds to wait for the ticket file to appear")
    p.add_argument("--driver", help="driver control-plane address HOST:PORT")
    p.add_argument("--secret", help="shared experiment secret (hex)")
    p.add_argument("--secret-file", help="file containing the shared secret")
    p.add_argument("--train", required=True,
                   help="train function as 'package.module:function'")
    p.add_argument("--config",
                   help="for distributed experiments: a zero-arg factory "
                        "'package.module:function' returning the local "
                        "DistributedConfig (model/datasets built on the agent)")
    p.add_argument("--partition-id", type=int, default=None,
                   help="reclaim a specific runner slot (restart recovery)")
    p.add_argument("--profile", action="store_true",
                   help="capture a jax.profiler trace per trial")
    p.add_argument("--chips-per-agent", type=int, default=None,
                   help="pin this agent to a disjoint TPU chip subset of "
                        "its host: agent sees chips [agent-index*K, "
                        "(agent-index+1)*K). Launch one agent per subset "
                        "on each pod VM for per-trial chip parallelism.")
    p.add_argument("--agent-index", type=int, default=0,
                   help="this agent's index AMONG THE AGENTS ON THIS HOST "
                        "(0..hosts_agents-1); selects its chip subset")
    args = p.parse_args(argv)

    if args.chips_per_agent is not None:
        # Must precede the first jax/libtpu initialization in this process
        # (the executor's first device touch) — same pinning the local
        # TPURunnerPool applies to its spawned processes.
        from maggy_tpu.core.runner_pool import chip_env

        if args.chips_per_agent <= 0:
            p.error("--chips-per-agent must be >= 1")
        if args.agent_index < 0:
            p.error("--agent-index must be >= 0")
        for key, value in chip_env(args.agent_index,
                                   args.chips_per_agent).items():
            os.environ[key] = value

    if args.ticket:
        ticket = read_ticket(args.ticket, wait_s=args.wait_ticket)
        addr = (ticket["host"], int(ticket["port"]))
        secret = ticket["secret"]
    elif args.driver:
        host, _, port = args.driver.rpartition(":")
        addr = (host, int(port))
        if args.secret_file:
            with open(args.secret_file) as f:
                secret = f.read().strip()
        elif args.secret:
            secret = args.secret
        else:
            p.error("--driver requires --secret or --secret-file")
    else:
        p.error("one of --ticket or --driver is required")

    train_fn = load_train_fn(args.train)
    config_factory = load_train_fn(args.config) if args.config else None
    pid = run_agent(addr, secret, train_fn,
                    partition_id=args.partition_id, profile=args.profile,
                    config_factory=config_factory)
    print("runner {} done".format(pid))
    return 0


if __name__ == "__main__":
    sys.exit(main())
