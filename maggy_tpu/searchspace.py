"""Typed hyperparameter search space with a unit-cube normalization codec.

Parity: reference `maggy/searchspace.py` (types at :60-63, validation at
:71-150, sampling at :180-208, container protocol at :210-264, transform codec
at :266-443, dict/list converters at :445-479). Re-designed, not translated:

- sampling uses an explicit seedable ``numpy.random.Generator`` (the reference
  uses the global numpy RNG, which makes experiments unreproducible),
- the codec vectorizes over trial batches so Bayesian-optimization surrogates
  can encode/decode entire observation matrices at once (useful for the
  jax-accelerated GP in `optimizers/bayes/gp.py`).
"""

from __future__ import annotations

import json
from typing import Any, Dict, Iterator, List, Sequence, Tuple

import numpy as np

# Reserved names injected by the framework into trial parameter dicts.
RESERVED_NAMES = ("budget", "ablated_feature", "ablated_layer", "dataset_function", "model_function")


class Searchspace:
    """A collection of typed hyperparameters.

    Supported types (reference `searchspace.py:60-63`):

    - ``DOUBLE``: continuous, ``(low, high)`` with ``low < high``
    - ``DOUBLE_LOG``: continuous sampled/encoded log-uniformly, ``(low,
      high)`` with ``0 < low < high`` — the right prior for scale
      hyperparameters (learning rate, weight decay); a TPU-build extension
      beyond the reference's four types (`searchspace.py:60-63`)
    - ``INTEGER``: integer range, ``(low, high)`` inclusive with ``low < high``
    - ``DISCRETE``: explicit list of numeric values
    - ``CATEGORICAL``: explicit list of string values
    - ``GANG``: explicit list of multi-chip trial shapes
      (``maggy_tpu.gang.GangSpec`` instances or their dict form) — the
      sweep searches over chip count / mesh axes / sharding strategy,
      and the driver gang-schedules each sampled shape onto the fleet.
      Index-encoded like CATEGORICAL for BO surrogates; stored (and
      delivered to the train function) as plain dicts so trial params
      stay wire- and JSON-serializable.

    Construct with kwargs or :meth:`add`::

        sp = Searchspace(lr=("DOUBLE", [1e-5, 1e-1]), layers=("INTEGER", [1, 8]))
        sp.add("activation", ("CATEGORICAL", ["relu", "gelu"]))
    """

    DOUBLE = "DOUBLE"
    DOUBLE_LOG = "DOUBLE_LOG"
    INTEGER = "INTEGER"
    DISCRETE = "DISCRETE"
    CATEGORICAL = "CATEGORICAL"
    GANG = "GANG"

    _TYPES = (DOUBLE, DOUBLE_LOG, INTEGER, DISCRETE, CATEGORICAL, GANG)
    # Continuous kinds (shared by optimizers for guards/perturbations).
    CONTINUOUS_TYPES = (DOUBLE, DOUBLE_LOG, INTEGER)

    def __init__(self, **kwargs):
        self._hparam_types: Dict[str, str] = {}
        self._hparams: Dict[str, list] = {}
        for name, value in kwargs.items():
            self.add(name, value)

    # ------------------------------------------------------------------ build

    def add(self, name: str, value: Sequence) -> None:
        """Add one hyperparameter; validates like reference `searchspace.py:96-150`."""
        if not isinstance(name, str):
            raise ValueError("Hyperparameter name must be a string, got {}.".format(type(name)))
        if name in RESERVED_NAMES:
            raise ValueError(
                "'{}' is a reserved parameter name (reserved: {}).".format(name, RESERVED_NAMES)
            )
        if name in self._hparam_types:
            raise ValueError("Hyperparameter '{}' already exists.".format(name))
        if not isinstance(value, (tuple, list)) or len(value) != 2:
            raise ValueError(
                "Hyperparameter '{}' must be a (type, feasible_region) pair, got {!r}.".format(
                    name, value
                )
            )
        hp_type, region = value[0], value[1]
        if not isinstance(hp_type, str) or hp_type.upper() not in self._TYPES:
            raise ValueError(
                "Hyperparameter type for '{}' must be one of {}, got {!r}.".format(
                    name, self._TYPES, hp_type
                )
            )
        hp_type = hp_type.upper()
        if not isinstance(region, (tuple, list)) or len(region) == 0:
            raise ValueError(
                "Feasible region of '{}' must be a non-empty list, got {!r}.".format(name, region)
            )
        region = list(region)

        if hp_type == Searchspace.DOUBLE:
            self._validate_bounds(name, region, (int, float), "DOUBLE")
        elif hp_type == Searchspace.DOUBLE_LOG:
            self._validate_bounds(name, region, (int, float), "DOUBLE_LOG")
            if region[0] <= 0:
                raise ValueError(
                    "DOUBLE_LOG bounds of '{}' must be positive, got {!r}.".format(name, region))
        elif hp_type == Searchspace.INTEGER:
            self._validate_bounds(name, region, (int,), "INTEGER")
        elif hp_type == Searchspace.DISCRETE:
            for v in region:
                if not isinstance(v, (int, float)):
                    raise ValueError(
                        "DISCRETE values of '{}' must be numeric, got {!r}.".format(name, v)
                    )
        elif hp_type == Searchspace.CATEGORICAL:
            for v in region:
                if not isinstance(v, str):
                    raise ValueError(
                        "CATEGORICAL values of '{}' must be strings, got {!r}.".format(name, v)
                    )
        elif hp_type == Searchspace.GANG:
            from maggy_tpu.gang import GangSpec

            # Normalize every entry through GangSpec (validating chips/
            # mesh/strategy) and STORE the dict form: trial params must
            # stay msgpack/JSON-serializable end to end.
            region = [GangSpec.from_value(v).to_dict() for v in region]
        self._hparam_types[name] = hp_type
        self._hparams[name] = region

    @staticmethod
    def _validate_bounds(name, region, scalar_types, label):
        if len(region) != 2:
            raise ValueError(
                "{} '{}' requires [low, high] bounds, got {!r}.".format(label, name, region)
            )
        low, high = region
        for v in (low, high):
            if not isinstance(v, scalar_types) or isinstance(v, bool):
                raise ValueError(
                    "{} bounds of '{}' must be {}, got {!r}.".format(label, name, scalar_types, v)
                )
        if low >= high:
            raise ValueError(
                "{} '{}' lower bound {} must be < upper bound {}.".format(label, name, low, high)
            )

    # --------------------------------------------------------------- protocol

    def names(self) -> List[str]:
        return list(self._hparam_types)

    def get(self, name: str, default=None):
        return self._hparams.get(name, default)

    def get_type(self, name: str) -> str:
        return self._hparam_types[name]

    def keys(self):
        return self._hparams.keys()

    def values(self):
        return self._hparams.values()

    def items(self) -> Iterator[Dict[str, Any]]:
        """Yield dicts of (name, type, values) like reference `searchspace.py:240-253`."""
        for name in self._hparams:
            yield {"name": name, "type": self._hparam_types[name], "values": self._hparams[name]}

    def __contains__(self, name) -> bool:
        return name in self._hparam_types

    def __len__(self) -> int:
        return len(self._hparam_types)

    def __iter__(self):
        return iter(self.items())

    def __getitem__(self, name):
        return self._hparams[name]

    def __str__(self):
        return json.dumps(self.to_dict(), indent=None)

    def to_dict(self) -> Dict[str, Any]:
        return {
            name: {"type": self._hparam_types[name], "values": self._hparams[name]}
            for name in self._hparams
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Searchspace":
        sp = cls()
        for name, spec in d.items():
            sp.add(name, (spec["type"], spec["values"]))
        return sp

    # --------------------------------------------------------------- sampling

    def get_random_parameter_values(
        self, num: int, rng: np.random.Generator | None = None
    ) -> List[Dict[str, Any]]:
        """Draw ``num`` iid parameter dicts (reference `searchspace.py:180-208`)."""
        rng = rng if rng is not None else np.random.default_rng()
        out = []
        for _ in range(num):
            params = {}
            for name, hp_type in self._hparam_types.items():
                region = self._hparams[name]
                if hp_type == Searchspace.DOUBLE:
                    params[name] = float(rng.uniform(region[0], region[1]))
                elif hp_type == Searchspace.DOUBLE_LOG:
                    params[name] = float(np.exp(rng.uniform(
                        np.log(region[0]), np.log(region[1]))))
                elif hp_type == Searchspace.INTEGER:
                    params[name] = int(rng.integers(region[0], region[1] + 1))
                else:  # DISCRETE / CATEGORICAL
                    params[name] = region[int(rng.integers(0, len(region)))]
            out.append(params)
        return out

    def grid(self) -> List[Dict[str, Any]]:
        """Cartesian product over DISCRETE/CATEGORICAL axes (reference
        `gridsearch.py:72-79`). Raises on continuous axes."""
        import itertools

        axes = []
        for name, hp_type in self._hparam_types.items():
            if hp_type in Searchspace.CONTINUOUS_TYPES:
                raise ValueError(
                    "Grid search requires DISCRETE/CATEGORICAL parameters only; "
                    "'{}' is {}.".format(name, hp_type)
                )
            axes.append([(name, v) for v in self._hparams[name]])
        return [dict(combo) for combo in itertools.product(*axes)]

    # ------------------------------------------------------------------ codec
    #
    # Normalization codec used by BO surrogates: every hyperparameter maps to
    # [0, 1]. DOUBLE/INTEGER min-max normalize; DISCRETE/CATEGORICAL index-
    # encode then normalize by cardinality (reference `searchspace.py:266-443`,
    # vectorized here).

    def encode_continuous(self, name: str, v) -> float:
        """One continuous value -> [0, 1] (the single source of truth for
        the per-type scalar codec; TPE's surrogate encoding reuses it)."""
        hp_type, region = self._hparam_types[name], self._hparams[name]
        if hp_type == Searchspace.DOUBLE:
            return (float(v) - region[0]) / (region[1] - region[0])
        if hp_type == Searchspace.DOUBLE_LOG:
            lo, hi = np.log(region[0]), np.log(region[1])
            return float((np.log(float(v)) - lo) / (hi - lo))
        if hp_type == Searchspace.INTEGER:
            # map integers to bin centers so inverse rounding is stable
            return (float(v) - region[0] + 0.5) / (region[1] - region[0] + 1)
        raise ValueError("'{}' is not a continuous hyperparameter.".format(name))

    def decode_continuous(self, name: str, x: float):
        """[0, 1] -> a continuous value (inverse of encode_continuous)."""
        hp_type, region = self._hparam_types[name], self._hparams[name]
        x = float(np.clip(x, 0.0, 1.0))
        if hp_type == Searchspace.DOUBLE:
            return float(region[0] + x * (region[1] - region[0]))
        if hp_type == Searchspace.DOUBLE_LOG:
            lo, hi = np.log(region[0]), np.log(region[1])
            return float(np.exp(lo + x * (hi - lo)))
        if hp_type == Searchspace.INTEGER:
            n = region[1] - region[0] + 1
            return int(min(region[1], region[0] + int(x * n)))
        raise ValueError("'{}' is not a continuous hyperparameter.".format(name))

    def transform(self, params: Dict[str, Any]) -> np.ndarray:
        """Encode one parameter dict to a point in the unit hypercube."""
        x = np.empty(len(self._hparam_types), dtype=np.float64)
        for i, (name, hp_type) in enumerate(self._hparam_types.items()):
            if hp_type in Searchspace.CONTINUOUS_TYPES:
                x[i] = self.encode_continuous(name, params[name])
            else:
                region = self._hparams[name]
                idx = region.index(params[name])
                x[i] = (idx + 0.5) / len(region)
        return x

    def inverse_transform(self, x: np.ndarray) -> Dict[str, Any]:
        """Decode a unit-hypercube point back to a parameter dict."""
        x = np.clip(np.asarray(x, dtype=np.float64), 0.0, 1.0)
        params: Dict[str, Any] = {}
        for i, (name, hp_type) in enumerate(self._hparam_types.items()):
            if hp_type in Searchspace.CONTINUOUS_TYPES:
                params[name] = self.decode_continuous(name, x[i])
            else:
                region = self._hparams[name]
                n = len(region)
                params[name] = region[min(n - 1, int(x[i] * n))]
        return params

    def transform_batch(self, params_list: Sequence[Dict[str, Any]]) -> np.ndarray:
        """Encode a list of parameter dicts into an (N, D) matrix."""
        if not params_list:
            return np.zeros((0, len(self._hparam_types)))
        return np.stack([self.transform(p) for p in params_list])

    def inverse_transform_batch(self, X: np.ndarray) -> List[Dict[str, Any]]:
        return [self.inverse_transform(row) for row in np.atleast_2d(X)]

    def var_types(self) -> List[str]:
        """Per-dimension kind for surrogates: 'c' continuous / 'u' unordered
        (reference TPE var_type construction, `tpe.py:180-189`)."""
        out = []
        for hp_type in self._hparam_types.values():
            out.append("c" if hp_type in Searchspace.CONTINUOUS_TYPES else "u")
        return out

    @staticmethod
    def dict_to_list(params: Dict[str, Any], names: Sequence[str]) -> List[Any]:
        return [params[n] for n in names]

    @staticmethod
    def list_to_dict(values: Sequence[Any], names: Sequence[str]) -> Dict[str, Any]:
        if len(values) != len(names):
            raise ValueError("Length mismatch between values and names.")
        return dict(zip(names, values))
