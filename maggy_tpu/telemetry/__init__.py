"""Unified telemetry: metrics registry, trial-span tracing, event journal.

The subsystem VERDICT round 5 asked for: the paper's scheduling-efficiency
claim (early-stop a trial, hand the freed runner new work with near-zero
gap) becomes a queryable artifact instead of ad-hoc timers. Three pieces:

- ``MetricsRegistry`` (metrics.py): counters / gauges / fixed-bound
  histograms, thread-safe, snapshot-able to plain dicts.
- ``SpanTracker`` + ``derive`` (spans.py): per-trial phase timestamps
  (queued -> assigned -> running -> first_metric -> stop_flagged ->
  finalized) and the PURE derivation of hand-off gap and early-stop
  reaction latency from them.
- ``TelemetryJournal`` (journal.py): batched JSONL persistence through the
  environment abstraction — crash/resume-safe, zero blocking I/O on the
  RPC hot path.

``Telemetry`` is the facade the drivers own; the RPC server exposes its
snapshot via the TELEM verb (``maggy_tpu.monitor --telem``), and bench.py
replays the journal offline via ``replay_journal`` to reproduce the
driver's numbers exactly.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from maggy_tpu.telemetry.journal import TelemetryJournal, read_events
from maggy_tpu.telemetry.metrics import (Counter, Gauge, Histogram,
                                         MetricsRegistry)
from maggy_tpu.telemetry.spans import (HANDOFF_CAP_S, PHASES, SpanTracker,
                                       TrialSpan, derive)

#: Journal filename inside an experiment directory.
JOURNAL_NAME = "telemetry.jsonl"


class Telemetry:
    """Facade tying registry + spans + journal to one experiment.

    All record paths are buffer-only (thread-safe, no I/O); persistence
    happens on the journal's flusher thread. ``enabled=False`` turns every
    method into a cheap no-op so experiments can opt out wholesale.
    """

    def __init__(self, env=None, journal_path: Optional[str] = None,
                 enabled: bool = True, flush_interval_s: float = 1.0,
                 sink=None, sink_source: Optional[str] = None,
                 fsync: Optional[bool] = None):
        self.enabled = enabled
        self.metrics = MetricsRegistry()
        self.spans = SpanTracker()
        self.journal: Optional[TelemetryJournal] = None
        if enabled and env is not None and journal_path:
            if sink is not None:
                # Fleet journal-sink routing (telemetry/sink.py): events
                # ship to the fleet's sink service instead of a private
                # flusher thread; journal_path stays the LOCAL fallback
                # file the shipper degrades to when the sink is down.
                from maggy_tpu.telemetry.sink import SinkJournal

                self.journal = SinkJournal(
                    env, journal_path, binding=sink,
                    source=sink_source or journal_path,
                    metrics_fn=self.metrics.snapshot)
            else:
                self.journal = TelemetryJournal(
                    env, journal_path, flush_interval_s=flush_interval_s,
                    fsync=fsync)
        # Journal-less fallback buffer (no env/path given): spans still
        # derive for the TELEM verb, just without persistence.
        self._local_lock = threading.Lock()
        self._local_events: List[Dict[str, Any]] = []  # guarded-by: _local_lock
        # snapshot() runs on the RPC event loop; derive() is O(events), so
        # cache it: (monotonic t, event count, derived). Recomputed only
        # when events arrived AND the cache is older than a second —
        # bounds a monitor poller's cost to one derivation/second no
        # matter how long the sweep or how fast the polls.
        self._derive_cache = (0.0, -1, {})
        # Optional phase-transition listener, set by the chaos engine when
        # armed (on-state-transition fault triggers). Telemetry knows
        # nothing about chaos semantics — it just forwards journaled
        # trial-phase occurrences.
        self.chaos_hook = None
        # Live health engine (telemetry.health.HealthEngine), attached by
        # the driver; None = no health section in the snapshot.
        self.health = None
        # Runner-side stats (runnerstats.RunnerStats deltas shipped on
        # heartbeat METRIC payloads), merged per partition, plus the
        # per-partition trial-progress stamps the hang watchdog reads.
        self._runner_lock = threading.Lock()
        self._runner_state: Dict[int, Dict[str, Any]] = {}  # guarded-by: _runner_lock
        self._progress: Dict[int, float] = {}  # guarded-by: _runner_lock
        # Trials whose compiled record already bumped the live registry
        # counters (the journal itself is deduped by once=True).
        self._compiled_seen: set = set()

    # ------------------------------------------------------------ recording

    def trial_event(self, trial_id: Optional[str], phase: str,
                    once: bool = False, **fields: Any) -> Optional[str]:
        """Mark ``phase`` on the trial's span (minting it on first sight)
        and journal the occurrence. ``once=True`` journals/counts only the
        phase's FIRST occurrence — for phases a heartbeat loop would
        otherwise repeat until the runner reacts (e.g. stop_sent). Returns
        the span id."""
        if not self.enabled or not trial_id:
            return None
        t = time.time()
        span_id, first = self.spans.mark(trial_id, phase, t=t,
                                         partition=fields.get("partition"))
        if once and not first:
            return span_id
        self._record({"t": t, "ev": "trial", "trial": trial_id,
                      "span": span_id, "phase": phase, **fields})
        self.metrics.counter("trial.phase.{}".format(phase)).inc()
        if fields.get("partition") is not None:
            self._note_progress(int(fields["partition"]))
        hook = self.chaos_hook
        if hook is not None:
            try:
                hook(trial_id, phase, fields.get("partition"))
            except Exception:  # noqa: BLE001 - chaos must never break telemetry
                pass
        return span_id

    def event(self, ev: str, **fields: Any) -> None:
        """Journal a non-trial event (runner/experiment lifecycle)."""
        if not self.enabled:
            return
        self._record({"t": time.time(), "ev": ev, **fields})

    def _record(self, event: Dict[str, Any]) -> None:
        if self.journal is not None:
            self.journal.record(event)
        else:
            with self._local_lock:
                self._local_events.append(event)

    def record_runner_stats(self, partition, stats: Dict[str, Any]) -> None:
        """Merge one runner's shipped stats delta (the ``rstats`` field a
        heartbeat METRIC piggybacked): update the live per-partition state
        + registry gauges, journal the delta with partition attribution,
        and journal a ``profile_skipped`` trial event for any trial the
        runner reported running untraced. Buffer-only, like every record
        path — this runs on the RPC event loop."""
        if not self.enabled or partition is None or not stats:
            return
        pid = int(partition)
        stats = dict(stats)
        skipped = stats.pop("profile_skipped", None) or []
        compile_events = stats.pop("compile_events", None) or []
        ckpt_events = stats.pop("ckpt_events", None) or []
        if stats:
            with self._runner_lock:
                merged = self._runner_state.setdefault(pid, {})
                merged.update(stats)
                merged["updated_t"] = time.time()
            for key in ("hb_rtt_ms", "rss_mb", "dev_mem_mb", "cadence_ms",
                        "ttfm_ms", "warm_hits", "warm_misses",
                        "xla_cache_hits", "xla_cache_misses"):
                if stats.get(key) is not None:
                    self.metrics.gauge(
                        "runner.{}.p{}".format(key, pid)).set(stats[key])
            # Liveness-only updates (RTT/RSS keep changing on a wedged
            # runner whose heartbeat thread survives) must NOT reset the
            # hang watchdog — only evidence of trial progress does.
            from maggy_tpu.telemetry.runnerstats import PROGRESS_KEYS

            if any(k in stats for k in PROGRESS_KEYS):
                self._note_progress(pid)
            self._record({"t": time.time(), "ev": "runner_stats",
                          "partition": pid, **stats})
        for trial_id in skipped:
            self.trial_event(trial_id, "profile_skipped", partition=pid)
        for record in compile_events:
            # The runner's ttfm breakdown (warm/init_ms/trace_ms/
            # compile_ms/first_step_ms) journaled as the trial's
            # ``compiled`` span phase — once per span, so a re-delivered
            # delta (requeued after a failed beat racing a successful
            # one) cannot double-count the warm hit.
            record = dict(record)
            trial_id = record.pop("trial", None)
            if not trial_id:
                continue
            self.trial_event(trial_id, "compiled", partition=pid,
                             once=True, **record)
            with self._runner_lock:
                first = trial_id not in self._compiled_seen
                self._compiled_seen.add(trial_id)
            if first:
                self.metrics.counter(
                    "compile.warm_hits" if record.get("warm")
                    else "compile.warm_misses").inc()
        for record in ckpt_events:
            # The runner's checkpoint I/O totals (save_ms/restore_ms/
            # saves/restores) journaled as the trial's ``ckpt_saved``
            # span phase — once per span, same re-delivery dedup as
            # ``compiled``. The goodput ledger's ckpt_save/ckpt_restore
            # buckets fold from exactly this record.
            record = dict(record)
            trial_id = record.pop("trial", None)
            if not trial_id:
                continue
            self.trial_event(trial_id, "ckpt_saved", partition=pid,
                             once=True, **record)

    def prune_partition(self, partition) -> None:
        """Forget a dead/replaced partition's live state: its
        ``runner.<field>.p<pid>`` gauges, merged runner-stats entry, and
        progress stamp. Called by the driver on the LOST/BLACK/GANG_LOST
        paths — a reaped runner's last RSS/cadence must not sit in the
        registry (and the /metrics exposition) forever, nor skew the
        health engine's fleet medians. The journal keeps the history;
        this only clears the LIVE view. A re-registered partition
        repopulates on its next heartbeat."""
        if not self.enabled or partition is None:
            return
        pid = int(partition)
        suffix = ".p{}".format(pid)
        self.metrics.prune(
            lambda name: name.startswith("runner.")
            and name.endswith(suffix))
        with self._runner_lock:
            self._runner_state.pop(pid, None)
            self._progress.pop(pid, None)

    def _note_progress(self, pid: int) -> None:
        with self._runner_lock:
            self._progress[pid] = time.monotonic()

    def last_progress(self, partition) -> Optional[float]:
        """Monotonic timestamp of the partition's last trial progress
        (phase event or runner-reported step movement), or None."""
        with self._runner_lock:
            return self._progress.get(int(partition))

    def runner_state(self) -> Dict[int, Dict[str, Any]]:
        """Per-partition merged runner stats (copies)."""
        with self._runner_lock:
            return {pid: dict(s) for pid, s in self._runner_state.items()}

    def observe_ms(self, name: str, ms: float) -> None:
        if self.enabled:
            self.metrics.histogram(name).observe(ms)

    # ------------------------------------------------------------- querying

    def events(self) -> List[Dict[str, Any]]:
        if self.journal is not None:
            return self.journal.events()
        with self._local_lock:
            return list(self._local_events)

    def _num_events(self) -> int:
        if self.journal is not None:
            return len(self.journal)
        with self._local_lock:
            return len(self._local_events)

    def _derived_spans(self, max_age_s: float = 1.0) -> Dict[str, Any]:
        t0, n0, cached = self._derive_cache
        now = time.monotonic()
        n = self._num_events()
        if n == n0 or (now - t0 < max_age_s and n0 >= 0):
            return cached
        derived = derive(self.events())
        self._derive_cache = (now, n, derived)
        return derived

    def refresh_goodput_gauges(self) -> Dict[str, Any]:
        """Fold the journal's goodput ledger (via the ~1 Hz derive cache)
        into live registry gauges — ``goodput.fraction``, ``goodput.
        unaccounted_fraction``, ``goodput.held_chip_s`` and per-partition
        ``goodput.fraction.p<pid>`` — so a /metrics scrape (and the
        fleet's federated exposition) carries the current ledger without
        a second fold path. Returns the ledger block. The obs server
        calls this just before rendering an exposition; anything else
        reading the gauges gets at-most-a-second-stale numbers."""
        if not self.enabled:
            return {}
        block = self._derived_spans().get("goodput") or {}
        if not block:
            return block
        self.metrics.gauge("goodput.fraction").set(
            block.get("goodput_fraction") or 0.0)
        self.metrics.gauge("goodput.unaccounted_fraction").set(
            block.get("unaccounted_fraction") or 0.0)
        self.metrics.gauge("goodput.held_chip_s").set(
            round(block.get("held_chip_s") or 0.0, 3))
        for pid, p in (block.get("per_partition") or {}).items():
            if p.get("goodput_fraction") is not None:
                self.metrics.gauge(
                    "goodput.fraction.p{}".format(pid)).set(
                    p["goodput_fraction"])
        return block

    def snapshot(self, fresh: bool = False) -> Dict[str, Any]:
        """Plain-dict snapshot: live metrics + span-derived scheduling
        numbers (derivation cached, at most ~1 Hz — pass ``fresh=True``
        for a finalize-time snapshot that must include the last events).
        This is the TELEM RPC reply body."""
        if not self.enabled:
            return {"enabled": False}
        snap = {"enabled": True,
                "metrics": self.metrics.snapshot(),
                "spans": self._derived_spans(max_age_s=0.0 if fresh else 1.0),
                "num_spans": len(self.spans),
                "runners": self.runner_state(),
                "journal": {"torn_lines": self.journal.torn_lines
                            if self.journal is not None else 0}}
        if self.health is not None:
            snap["health"] = self.health.snapshot()
        return snap

    def restore_spans(self) -> int:
        """Rebuild the span tracker from the journal's restored trial
        events (crash-only recovery / resume): each trial keeps its
        pre-crash span id and first-occurrence phase timestamps, so the
        recovered driver's later phase events continue the SAME spans —
        and ``once=True`` dedup (stop_sent, prefetch hit/miss, compiled)
        holds across incarnations. Returns the number of trial events
        replayed into the tracker."""
        if not self.enabled:
            return 0
        n = 0
        for ev in self.events():
            if ev.get("ev") != "trial" or not ev.get("trial"):
                continue
            self.spans.restore(ev["trial"], ev.get("span"),
                               ev.get("phase"), ev.get("t"),
                               partition=ev.get("partition"))
            n += 1
        return n

    # ------------------------------------------------------------ lifecycle

    def flush(self) -> None:
        if self.journal is not None:
            self.journal.flush()

    def barrier(self) -> None:
        """Terminal-event durability barrier (crash-only recovery): make
        the buffered journal suffix durable NOW — called by the FINAL
        path before its RPC reply is written, so an acknowledged FINAL
        can never be absent from the recovery source of truth. Journals
        that own no local durability (the fleet sink's SinkJournal ships
        at-least-once with a local fallback spool) expose no barrier and
        are a no-op here."""
        j = self.journal
        b = getattr(j, "barrier", None)
        if b is not None:
            b()

    def close(self) -> None:
        if self.journal is not None:
            self.journal.close()


def replay_journal(path: str, env=None) -> Dict[str, Any]:
    """Offline replay: journal file -> derived scheduling metrics. Pure —
    the same journal always reproduces the same numbers (bench.py's
    hand-off / early-stop detail block is exactly this call). The output
    additionally carries ``torn_lines``: corrupt journal lines the reader
    skipped, so corruption is visible instead of quietly shrinking the
    dataset."""
    events = read_events(path, env=env)
    out = derive(events)
    out["torn_lines"] = getattr(events, "torn_lines", 0)
    return out


__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "SpanTracker", "TrialSpan", "PHASES", "HANDOFF_CAP_S", "derive",
    "TelemetryJournal", "read_events", "replay_journal",
    "Telemetry", "JOURNAL_NAME",
]
