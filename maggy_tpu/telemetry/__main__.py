"""Telemetry CLI: ``python -m maggy_tpu.telemetry <command>``.

    trace <exp_dir|journal.jsonl> [-o OUT]   journal -> Perfetto JSON
    replay <exp_dir|journal.jsonl>           journal -> derived numbers
    goodput <exp_dir|journal.jsonl|fleet home>  chip-time ledger

``trace`` writes Chrome-trace-event JSON loadable in https://ui.perfetto.dev
or chrome://tracing (one track per partition, trial slices with phase
sub-slices, instant markers for stops/requeues/chaos/health — see
docs/telemetry.md for a walkthrough of reading a hand-off gap). ``replay``
prints the same derived scheduling numbers the driver/TELEM verb computes,
plus the journal's ``torn_lines`` count so corruption is visible.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from maggy_tpu.telemetry import JOURNAL_NAME, read_events, replay_journal
from maggy_tpu.telemetry.trace import write_trace


def _resolve_journal(path: str) -> str:
    """Accept an experiment dir or a journal file path."""
    if os.path.isdir(path):
        path = os.path.join(path, JOURNAL_NAME)
    if not os.path.exists(path):
        raise FileNotFoundError("no telemetry journal at {}".format(path))
    return path


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="maggy_tpu.telemetry",
        description="Offline telemetry tools over a journal artifact.")
    sub = p.add_subparsers(dest="command", required=True)
    pt = sub.add_parser("trace",
                        help="export a Perfetto/Chrome-trace timeline")
    pt.add_argument("path", help="experiment dir or telemetry.jsonl path")
    pt.add_argument("-o", "--out",
                    help="output file (default: <exp_dir>/trace.json)")
    pt.add_argument("--unified", action="store_true",
                    help="fleet home dirs only: merge fleet.jsonl, the "
                         "journal sink's per-source segments "
                         "(<home>/journal/), and surviving local "
                         "journals into ONE trace — agent process "
                         "groups, clock-offset-corrected cross-process "
                         "timestamps, and ABIND->execution->FINAL flow "
                         "arrows (docs/user.md walkthrough)")
    pr = sub.add_parser("replay", help="print journal-derived scheduling "
                                       "numbers as JSON")
    pr.add_argument("path", help="experiment dir or telemetry.jsonl path")
    pg = sub.add_parser("goodput",
                        help="print the chip-time goodput ledger (where "
                             "every held chip-second went); a fleet home "
                             "dir rolls up per tenant")
    pg.add_argument("path", help="experiment dir, telemetry.jsonl path, "
                                 "or a fleet home dir (fleet.jsonl)")
    pg.add_argument("--json", action="store_true",
                    help="emit the full ledger as JSON instead of the "
                         "human summary")
    args = p.parse_args(argv)

    if args.command == "goodput":
        return _goodput(args)

    # A fleet home dir (fleet.jsonl present) renders the multiplexed
    # timeline: one track per fleet RUNNER with a lane per experiment,
    # built from the fleet journal + every leased experiment's journal.
    # --unified additionally folds in the journal sink's per-source
    # segments and the agents' clock-corrected journals.
    if args.command == "trace" and os.path.isdir(args.path) and \
            os.path.exists(os.path.join(args.path, "fleet.jsonl")):
        return _fleet_trace(args)
    if args.command == "trace" and getattr(args, "unified", False):
        raise SystemExit("--unified needs a fleet home dir (a directory "
                         "containing fleet.jsonl)")

    journal = _resolve_journal(args.path)
    if args.command == "replay":
        print(json.dumps(replay_journal(journal), indent=2, default=str))
        return 0

    events = read_events(journal)
    out = args.out or os.path.join(os.path.dirname(journal), "trace.json")
    n = write_trace(events, out)
    torn = getattr(events, "torn_lines", 0)
    msg = ("trace: {} journal events -> {} trace events -> {}"
           .format(len(events), n, out))
    if torn:
        msg += " ({} torn line(s) skipped)".format(torn)
    print(msg)
    print("open in https://ui.perfetto.dev or chrome://tracing")
    return 0


def _goodput(args) -> int:
    """The chip-time ledger, offline. An experiment dir/journal folds
    directly; a fleet home dir (fleet.jsonl present) prints the fleet
    replay's per-tenant roll-up — lease-derived chip-seconds plus each
    tenant's own journal fold, clock-offset-corrected."""
    import json as _json

    from maggy_tpu.telemetry.goodput import compute_goodput, render_goodput

    if os.path.isdir(args.path) and \
            os.path.exists(os.path.join(args.path, "fleet.jsonl")):
        from maggy_tpu.fleet.scheduler import replay_fleet_journal

        replay = replay_fleet_journal(args.path)
        block = replay.get("goodput") or {}
        if args.json:
            print(_json.dumps(block, indent=2, default=str))
            return 0
        for tenant, tb in sorted((block.get("tenants") or {}).items()):
            print("tenant {}: {:.1f} leased chip-seconds".format(
                tenant, tb.get("chip_seconds") or 0.0))
            for line in render_goodput(tb.get("goodput") or {}):
                print("  " + line)
        return 0
    events = read_events(_resolve_journal(args.path))
    block = compute_goodput(events)
    if args.json:
        print(_json.dumps(block, indent=2, default=str))
        return 0
    for line in render_goodput(block):
        print(line)
    return 0


def _fleet_trace(args) -> int:
    """Fleet-mode trace: experiment journals are discovered from the
    fleet journal's lease events (each carries its experiment's
    exp_dir). With ``--unified``, each experiment's stream is the
    exactly-once MERGE of its sink segment and any surviving local
    journal (deduped by event id), agents' sink-shipped journals join
    as clock-corrected process groups, and ABIND->execution->FINAL flow
    arrows cross the process boundary."""
    from maggy_tpu.telemetry.trace import (build_fleet_trace,
                                           build_unified_trace,
                                           validate_trace)

    fleet_journal = os.path.join(args.path, "fleet.jsonl")
    fleet_events = read_events(fleet_journal)
    exp_dirs = {}
    for ev in fleet_events:
        if ev.get("exp") and ev.get("exp_dir"):
            exp_dirs[ev["exp"]] = ev["exp_dir"]
    unified = getattr(args, "unified", False)
    sink = {}
    if unified:
        from maggy_tpu.telemetry.sink import SINK_DIR_NAME, read_sink_dir

        sink = read_sink_dir(os.path.join(args.path, SINK_DIR_NAME))
    experiments = {}
    for name, exp_dir in exp_dirs.items():
        jp = os.path.join(exp_dir, JOURNAL_NAME)
        local = read_events(jp) if os.path.exists(jp) else None
        if unified:
            from maggy_tpu.telemetry.sink import (merge_source_events,
                                                  sanitize_source)

            shipped = sink.pop(sanitize_source(name), None)
            if shipped is not None or local is not None:
                experiments[name] = merge_source_events(shipped, local)
        elif local is not None:
            experiments[name] = local
    if unified:
        # Every remaining sink source that matches a joined agent is
        # that agent's own journal.
        agent_ids = {str(ev.get("agent")) for ev in fleet_events
                     if ev.get("ev") == "agent"
                     and ev.get("phase") == "join" and ev.get("agent")}
        agent_journals = {src: evs for src, evs in sink.items()
                          if src in agent_ids}
        trace = build_unified_trace(fleet_events, experiments,
                                    agent_journals=agent_journals)
        default_out = "unified_trace.json"
    else:
        trace = build_fleet_trace(fleet_events, experiments)
        default_out = "fleet_trace.json"
    n = validate_trace(trace)
    out = args.out or os.path.join(args.path, default_out)
    with open(out, "w") as f:
        json.dump(trace, f)
    print("{} trace: {} fleet events + {} experiment journal(s) -> {} "
          "trace events -> {}".format("unified" if unified else "fleet",
                                      len(fleet_events), len(experiments),
                                      n, out))
    print("open in https://ui.perfetto.dev or chrome://tracing")
    return 0


if __name__ == "__main__":
    sys.exit(main())
