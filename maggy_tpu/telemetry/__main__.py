"""Telemetry CLI: ``python -m maggy_tpu.telemetry <command>``.

    trace <exp_dir|journal.jsonl> [-o OUT]   journal -> Perfetto JSON
    replay <exp_dir|journal.jsonl>           journal -> derived numbers

``trace`` writes Chrome-trace-event JSON loadable in https://ui.perfetto.dev
or chrome://tracing (one track per partition, trial slices with phase
sub-slices, instant markers for stops/requeues/chaos/health — see
docs/telemetry.md for a walkthrough of reading a hand-off gap). ``replay``
prints the same derived scheduling numbers the driver/TELEM verb computes,
plus the journal's ``torn_lines`` count so corruption is visible.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from maggy_tpu.telemetry import JOURNAL_NAME, read_events, replay_journal
from maggy_tpu.telemetry.trace import write_trace


def _resolve_journal(path: str) -> str:
    """Accept an experiment dir or a journal file path."""
    if os.path.isdir(path):
        path = os.path.join(path, JOURNAL_NAME)
    if not os.path.exists(path):
        raise FileNotFoundError("no telemetry journal at {}".format(path))
    return path


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="maggy_tpu.telemetry",
        description="Offline telemetry tools over a journal artifact.")
    sub = p.add_subparsers(dest="command", required=True)
    pt = sub.add_parser("trace",
                        help="export a Perfetto/Chrome-trace timeline")
    pt.add_argument("path", help="experiment dir or telemetry.jsonl path")
    pt.add_argument("-o", "--out",
                    help="output file (default: <exp_dir>/trace.json)")
    pr = sub.add_parser("replay", help="print journal-derived scheduling "
                                       "numbers as JSON")
    pr.add_argument("path", help="experiment dir or telemetry.jsonl path")
    args = p.parse_args(argv)

    # A fleet home dir (fleet.jsonl present) renders the multiplexed
    # timeline: one track per fleet RUNNER with a lane per experiment,
    # built from the fleet journal + every leased experiment's journal.
    if args.command == "trace" and os.path.isdir(args.path) and \
            os.path.exists(os.path.join(args.path, "fleet.jsonl")):
        return _fleet_trace(args)

    journal = _resolve_journal(args.path)
    if args.command == "replay":
        print(json.dumps(replay_journal(journal), indent=2, default=str))
        return 0

    events = read_events(journal)
    out = args.out or os.path.join(os.path.dirname(journal), "trace.json")
    n = write_trace(events, out)
    torn = getattr(events, "torn_lines", 0)
    msg = ("trace: {} journal events -> {} trace events -> {}"
           .format(len(events), n, out))
    if torn:
        msg += " ({} torn line(s) skipped)".format(torn)
    print(msg)
    print("open in https://ui.perfetto.dev or chrome://tracing")
    return 0


def _fleet_trace(args) -> int:
    """Fleet-mode trace: experiment journals are discovered from the
    fleet journal's lease events (each carries its experiment's
    exp_dir)."""
    from maggy_tpu.telemetry.trace import build_fleet_trace, validate_trace

    fleet_journal = os.path.join(args.path, "fleet.jsonl")
    fleet_events = read_events(fleet_journal)
    exp_dirs = {}
    for ev in fleet_events:
        if ev.get("exp") and ev.get("exp_dir"):
            exp_dirs[ev["exp"]] = ev["exp_dir"]
    experiments = {}
    for name, exp_dir in exp_dirs.items():
        jp = os.path.join(exp_dir, JOURNAL_NAME)
        if os.path.exists(jp):
            experiments[name] = read_events(jp)
    trace = build_fleet_trace(fleet_events, experiments)
    n = validate_trace(trace)
    out = args.out or os.path.join(args.path, "fleet_trace.json")
    with open(out, "w") as f:
        json.dump(trace, f)
    print("fleet trace: {} fleet events + {} experiment journal(s) -> {} "
          "trace events -> {}".format(len(fleet_events), len(experiments),
                                      n, out))
    print("open in https://ui.perfetto.dev or chrome://tracing")
    return 0


if __name__ == "__main__":
    sys.exit(main())
