"""Chip-time goodput ledger: where every held runner-second went.

The operator's first question — *of every chip-second the fleet held, how
many trained the model?* — answered as a pure fold over the journal.
``compute_goodput(events)`` classifies every runner-second between a
partition's registration and the experiment's end into the closed,
vocab-pinned taxonomy ``vocab.GOODPUT_BUCKETS``:

- ``train``    — goodput: inside train_fn, first-run productive steps;
- ``init`` / ``trace`` / ``compile`` — the attributed ttfm phases from the
  runner's ``compiled`` record (telemetry/runnerstats.py);
- ``ckpt_save`` / ``ckpt_restore`` — checkpoint I/O from the runner's
  ``ckpt_saved`` record (the checkpoint-save edge journaled per trial);
- ``fork_stage`` — parent-checkpoint staging (``fork_load_ms``);
- ``rework``   — re-trained compute: a dead attempt's whole duration
  (requeue / runner loss re-runs it) plus the parent-prefix a
  from-scratch promotion re-trains (a fork would have skipped it);
- ``handoff``  — a partition's FINAL -> next-running gap (< the spans.py
  ``HANDOFF_CAP_S`` bound, same cap as the handoff stats);
- ``queue_wait`` — runner registered -> its first trial running;
- ``idle``     — reserved but trial-less (rung barriers, drain, gaps at
  or above the handoff cap);
- ``unaccounted`` — the explicit residual: assigned-but-never-running
  windows and whatever the fold could not attribute. Never silently
  folded into another bucket — bench gates bound it.

Gang-aware: a gang's member partitions mirror the leader attempt's
bucket proportions over the assembled window, so an N-chip trial costs N
chip-seconds per wall second and per-partition bucket sums still equal
held time exactly (``sum(buckets) == held_s`` is a tested identity).

Like everything in spans.py, this is a PURE function over journal
events: the same journal always reproduces the same ledger, live (the
driver's TELEM snapshot / metrics gauges), over RPC, or replayed offline
(``python -m maggy_tpu.telemetry goodput <dir>``, bench's
``detail.goodput``). Multi-source fleet directories merge through
``merge_corrected`` with the sink's per-agent clock offsets first.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from maggy_tpu.telemetry.vocab import GOODPUT_BUCKETS

#: Same bound spans.derive uses for the handoff stats: gaps at/above it
#: are deliberate scheduling idle (rung barriers), below it are hand-off
#: overhead. (spans imports this module lazily, so the top-level import
#: is cycle-free.)
from maggy_tpu.telemetry.spans import HANDOFF_CAP_S

#: compiled-record millisecond field -> badput bucket.
_COMPILE_SUBS = (("init_ms", "init"), ("trace_ms", "trace"),
                 ("compile_ms", "compile"), ("fork_load_ms", "fork_stage"))
#: ckpt_saved-record millisecond field -> badput bucket.
_CKPT_SUBS = (("save_ms", "ckpt_save"), ("restore_ms", "ckpt_restore"))


def _zero() -> Dict[str, float]:
    return {b: 0.0 for b in GOODPUT_BUCKETS}


def _add(into: Dict[str, float], frm: Dict[str, float]) -> None:
    for k, v in frm.items():
        if v:
            into[k] = into.get(k, 0.0) + v


def merge_corrected(events_by_source: Dict[str, List[Dict[str, Any]]],
                    offsets: Optional[Dict[str, Any]] = None
                    ) -> List[Dict[str, Any]]:
    """Merge per-source event lists into one time-ordered stream,
    correcting each source's clock by its estimated offset
    (``corrected_t = t - offset_s`` — the sink's Cristian estimate says
    the source's clock reads ``offset_s`` AHEAD of the fleet host).
    ``offsets`` accepts either ``{source: offset_s}`` floats or the
    fleet replay's ``clock_offsets`` entries (``{source: {"offset_s":
    ...}}``). Sources without an estimate pass through uncorrected."""
    merged: List[Dict[str, Any]] = []
    offsets = offsets or {}
    for source, events in events_by_source.items():
        off = offsets.get(source)
        if isinstance(off, dict):
            off = off.get("offset_s")
        off = float(off or 0.0)
        for ev in events:
            if off and ev.get("t") is not None:
                ev = dict(ev)
                ev["t"] = float(ev["t"]) - off
            merged.append(ev)
    merged.sort(key=lambda e: e.get("t") or 0.0)
    return merged


def compute_goodput(events: List[Dict[str, Any]],
                    handoff_cap_s: float = HANDOFF_CAP_S) -> Dict[str, Any]:
    """The ledger: journal events -> chip-time buckets (pure function).

    Returns ``{}`` for journals with no runner activity; otherwise::

        {"held_chip_s": float,          # sum of per-partition windows
         "buckets": {bucket: seconds},  # sums exactly to held_chip_s
         "goodput_fraction": float,     # train / held
         "unaccounted_fraction": float,
         "badput_top": [{"bucket", "s", "fraction"}, ...],  # top 3
         "per_partition": {pid: {"held_s", "buckets",
                                 "goodput_fraction"}},
         "per_trial": {tid: {bucket: seconds}},  # nonzero only
         "partition_samples": {pid: [[t, cumulative_fraction], ...]}}
    """
    # ---------------------------------------------------------- pass 1
    reg_t: Dict[int, float] = {}
    exp_end: Optional[float] = None
    # trial -> ordered lifecycle: (t, seq, phase, partition, reason)
    life: Dict[str, List[Tuple[float, int, str, Optional[int],
                               Optional[str]]]] = {}
    assigned: Dict[str, List[Tuple[float, Optional[int]]]] = {}
    compiled: Dict[str, Dict[str, Any]] = {}
    ckpts: Dict[str, Dict[str, Any]] = {}
    parent_of: Dict[str, str] = {}
    block_of: Dict[str, str] = {}
    forked: set = set()
    gangs: List[Dict[str, Any]] = []
    open_gangs: Dict[str, Dict[str, Any]] = {}
    for seq, ev in enumerate(events):
        t = ev.get("t")
        if t is None:
            continue
        t = float(t)
        kind = ev.get("ev")
        phase = ev.get("phase")
        if kind == "runner" and phase == "registered":
            pid = ev.get("partition")
            if pid is not None:
                reg_t.setdefault(int(pid), t)
            continue
        if kind == "experiment" and phase in ("finalized", "end"):
            exp_end = t if exp_end is None else max(exp_end, t)
            continue
        if kind != "trial":
            continue
        trial = ev.get("trial")
        if not trial:
            continue
        pid = ev.get("partition")
        pid = int(pid) if pid is not None else None
        if phase == "queued":
            parent = (ev.get("info") or {}).get("parent")
            if parent is not None:
                parent_of[trial] = parent
        elif phase == "assigned":
            assigned.setdefault(trial, []).append((t, pid))
            if ev.get("block") is not None:
                # Vectorized block lane (config.vmap_lanes): this trial's
                # FIRST attempt shares one chip with its block siblings.
                block_of[trial] = ev["block"]
        elif phase in ("running", "finalized", "preempted", "requeued",
                       "lost"):
            life.setdefault(trial, []).append(
                (t, seq, phase, pid, ev.get("reason")))
        elif phase == "compiled":
            compiled.setdefault(trial, dict(ev))
        elif phase == "ckpt_saved":
            ckpts.setdefault(trial, dict(ev))
        elif phase == "forked_from":
            forked.add(trial)
        elif phase == "gang_assembled":
            open_gangs[trial] = {
                "trial": trial, "leader": pid, "t0": t,
                "members": [int(m) for m in (ev.get("members") or [])]}
        elif phase == "gang_released":
            g = open_gangs.pop(trial, None)
            if g is not None:
                g["t1"] = t
                gangs.append(g)
    if not life and not reg_t:
        return {}
    last_life = max((t for seq_l in life.values() for (t, _s, _p, _pid, _r)
                     in seq_l), default=None)
    candidates = [x for x in (exp_end, last_life) if x is not None]
    if not candidates:
        return {}
    t_end = max(candidates)

    # --------------------------------------------------- attempt building
    # An attempt = one [running, terminal] stay of a trial on a partition.
    # finalized / preempted (checkpoint preserved) / requeued with
    # reason=preempted close it productively ("final"); requeued for any
    # other reason and lost close it as a dead attempt whose work is
    # re-trained ("dead" -> rework). A terminal with no open attempt but
    # a fresh preceding assignment marks an assigned-but-never-running
    # window: explicit unaccounted, never silently dropped.
    attempts: List[Dict[str, Any]] = []
    pseudo: List[Tuple[int, float, float]] = []
    for trial, seq_l in life.items():
        seq_l.sort(key=lambda x: (x[0], x[1]))
        marks = sorted(assigned.get(trial, []))
        open_a: Optional[Dict[str, Any]] = None
        n_done = 0
        last_end: Optional[float] = None
        for t, _seq, phase, pid, reason in seq_l:
            if phase == "running":
                if open_a is not None:
                    # Missing terminal (torn journal): close conservatively
                    # as productive at the next dispatch.
                    open_a.update(t1=t, status="final")
                    attempts.append(open_a)
                    last_end = t
                if pid is not None:
                    open_a = {"trial": trial, "pid": pid, "t0": t,
                              "index": n_done}
                    n_done += 1
                continue
            preserved = phase in ("finalized", "preempted") or (
                phase == "requeued" and reason == "preempted")
            if open_a is not None:
                open_a.update(t1=t, status="final" if preserved else "dead")
                attempts.append(open_a)
                open_a = None
                last_end = t
            else:
                hit = None
                for ta, pa in marks:
                    if ta > t:
                        break
                    if pa is not None and (last_end is None
                                           or ta >= last_end):
                        hit = (ta, pa)
                if hit is not None:
                    pseudo.append((hit[1], hit[0], t))
                    last_end = t
        if open_a is not None:
            # Still running at journal end: the remainder trained.
            open_a.update(t1=max(t_end, open_a["t0"]), status="final")
            attempts.append(open_a)

    # ------------------------------------------------------ classification
    per_partition: Dict[int, Dict[str, float]] = {}
    per_trial: Dict[str, Dict[str, float]] = {}
    coverage: Dict[int, List[Tuple[float, float]]] = {}
    samples_src: Dict[int, List[Tuple[float, Dict[str, float]]]] = {}
    trial_train: Dict[str, float] = {}
    carved: Dict[str, float] = {}
    scratch = set(parent_of) - forked
    subs_done: set = set()
    attempts.sort(key=lambda a: a["t0"])
    # Vectorized blocks (config.vmap_lanes > 1): a block's K lanes share
    # ONE chip for the block's window, so each lane attempt carries 1/K
    # of the wall-seconds it spans. Blocks only assemble at fresh
    # dispatch and a requeued lane re-runs scalar, so a lane's FIRST
    # attempt is its block stay — attempts at index 0 of a block-stamped
    # trial split K ways, and once a lane finalizes early (masked) its
    # 1/K share of the remaining block window accrues to ``lane_idle``.
    # Sum over lanes of (live + idle)/K == the block's wall window, so
    # the per-partition closure identity stays exact.
    block_attempts: Dict[str, List[Dict[str, Any]]] = {}
    for a in attempts:
        blk = block_of.get(a["trial"])
        if blk is not None and a["index"] == 0:
            a["vmap_block"] = blk
            block_attempts.setdefault(blk, []).append(a)
    for a in attempts:
        trial, pid = a["trial"], a["pid"]
        t0, t1 = a["t0"], min(a["t1"], t_end)
        dur = max(0.0, t1 - t0)
        bk: Dict[str, float] = {}
        if a["status"] == "dead":  # vocab-ok: internal attempt status, not a journal field
            bk["rework"] = dur
        else:
            subs: Dict[str, float] = {}
            if trial not in subs_done:
                subs_done.add(trial)
                rec = compiled.get(trial) or {}
                for key, bucket in _COMPILE_SUBS:
                    if rec.get(key):
                        subs[bucket] = subs.get(bucket, 0.0) \
                            + float(rec[key]) / 1e3
                rec = ckpts.get(trial) or {}
                for key, bucket in _CKPT_SUBS:
                    if rec.get(key):
                        subs[bucket] = subs.get(bucket, 0.0) \
                            + float(rec[key]) / 1e3
            sub_total = sum(subs.values())
            if sub_total > dur:
                # Measured phases exceed the attempt's wall window (clock
                # skew / sub-ms attempts): scale down, no train remains.
                scale = dur / sub_total if sub_total else 0.0
                subs = {k: v * scale for k, v in subs.items()}
                train = 0.0
            else:
                train = dur - sub_total
            if trial in scratch:
                # From-scratch promotion: it re-trains its parent's
                # prefix before producing new work — a fork would have
                # resumed instead. Carve the parent's measured train
                # time (once per trial) into rework.
                budget = trial_train.get(parent_of[trial], 0.0) \
                    - carved.get(trial, 0.0)
                carve = min(max(0.0, budget), train)
                if carve > 0:
                    train -= carve
                    subs["rework"] = subs.get("rework", 0.0) + carve
                    carved[trial] = carved.get(trial, 0.0) + carve
            trial_train[trial] = trial_train.get(trial, 0.0) + train
            bk = subs
            bk["train"] = bk.get("train", 0.0) + train
        blk = a.get("vmap_block")
        if blk is not None and len(block_attempts[blk]) > 1:
            k = len(block_attempts[blk])
            bk = {key: v / k for key, v in bk.items()}
        a["buckets"] = bk
        _add(per_partition.setdefault(pid, _zero()), bk)
        _add(per_trial.setdefault(trial, {}), bk)
        coverage.setdefault(pid, []).append((t0, t1))
        samples_src.setdefault(pid, []).append((t1, bk))
    # Masked-lane idle: after a lane's own FINAL the block keeps running
    # on the survivors — the retired lane's 1/K share of that tail is
    # badput the masked lane "holds" (``lane_idle``), closing each lane's
    # share at exactly (block_end - block_start) / K.
    for blk, group in block_attempts.items():
        k = len(group)
        if k < 2:
            continue
        t_last = min(max(x["t1"] for x in group), t_end)
        for a in group:
            idle = max(0.0, t_last - min(a["t1"], t_end)) / k
            if idle > 0:
                share = {"lane_idle": idle}
                _add(a["buckets"], share)
                _add(per_partition.setdefault(a["pid"], _zero()), share)
                _add(per_trial.setdefault(a["trial"], {}), share)
    for pid, ta, t1 in pseudo:
        t1 = min(t1, t_end)
        dur = max(0.0, t1 - ta)
        per_partition.setdefault(pid, _zero())["unaccounted"] += dur
        coverage.setdefault(pid, []).append((ta, t1))
    # Gang members mirror the leader attempt's proportions: an N-chip
    # trial costs N chip-seconds per wall second, each member's window
    # classified like the leader's (it ran the same program).
    for g in gangs + list(open_gangs.values()):
        t0, t1 = g["t0"], min(g.get("t1", t_end), t_end)
        if t1 <= t0:
            continue
        leader = g.get("leader")
        lead = next((a for a in attempts
                     if a["trial"] == g["trial"]
                     and a["t1"] >= t0 and a["t0"] <= t1), None)
        lead_bk = (lead or {}).get("buckets") or {}
        total = sum(lead_bk.values())
        for m in g["members"]:
            if m == leader:
                continue
            dur = t1 - t0
            if total > 0:
                bk = {k: v / total * dur for k, v in lead_bk.items()}
            else:
                bk = {"idle": dur}
            _add(per_partition.setdefault(m, _zero()), bk)
            _add(per_trial.setdefault(g["trial"], {}), bk)
            coverage.setdefault(m, []).append((t0, t1))

    # ------------------------------------------- gaps + residual closure
    fleet = _zero()
    held_total = 0.0
    per_partition_out: Dict[int, Dict[str, Any]] = {}
    samples: Dict[int, List[List[float]]] = {}
    for pid in sorted(set(per_partition) | set(reg_t)):
        bk = per_partition.get(pid) or _zero()
        cov = sorted(coverage.get(pid, []))
        starts = [s for s, _e in cov]
        h0_candidates = [x for x in [reg_t.get(pid)] + starts
                         if x is not None]
        if not h0_candidates:
            continue
        h0 = min(h0_candidates)
        held = max(0.0, t_end - h0)
        # Complement of the merged coverage: leading gap = queue_wait
        # (registered, waiting for the first trial), interior gaps split
        # handoff/idle on the spans.py cap, trailing = idle (drain).
        prev = h0
        first_gap = True
        for s, e in cov:
            s, e = max(s, h0), min(e, t_end)
            if s > prev:
                gap = s - prev
                if first_gap:
                    bk["queue_wait"] += gap
                elif gap < handoff_cap_s:
                    bk["handoff"] += gap
                else:
                    bk["idle"] += gap
            if e > prev or s > prev:
                first_gap = False
            prev = max(prev, e)
        if prev < t_end:
            bk["idle"] += t_end - prev
        # Exact closure: whatever remains (overlapping attempts, float
        # dust) is unaccounted — sum(buckets) == held is an identity the
        # tests pin, so drift is visible instead of silently absorbed.
        bk["unaccounted"] += held - sum(bk.values())
        held_total += held
        _add(fleet, bk)
        per_partition_out[pid] = {
            "held_s": held, "buckets": bk,
            "goodput_fraction": round(bk["train"] / held, 4)
            if held > 0 else None}
        cum = 0.0
        pts: List[List[float]] = []
        for t1, abk in sorted(samples_src.get(pid, []),
                              key=lambda x: x[0]):
            cum += abk.get("train", 0.0)
            if t1 > h0:
                pts.append([round(t1, 3), round(cum / (t1 - h0), 4)])
        if pts:
            samples[pid] = pts
    if held_total <= 0:
        return {}
    badput = sorted(((b, s) for b, s in fleet.items()
                     if b != "train" and s > 0),
                    key=lambda x: -x[1])[:3]
    return {
        "held_chip_s": held_total,
        "buckets": fleet,
        "goodput_fraction": round(fleet["train"] / held_total, 4),
        "unaccounted_fraction": round(fleet["unaccounted"] / held_total, 4),
        "badput_top": [{"bucket": b, "s": round(s, 3),
                        "fraction": round(s / held_total, 4)}
                       for b, s in badput],
        "per_partition": per_partition_out,
        "per_trial": {tid: {k: v for k, v in bk.items() if v}
                      for tid, bk in per_trial.items()},
        "partition_samples": samples,
    }


def render_goodput(block: Dict[str, Any]) -> List[str]:
    """Human-readable ledger lines (monitor --goodput / CLI output)."""
    if not block:
        return ["goodput: no runner activity in journal"]
    lines = ["goodput: {:.1%} of {:.1f} held chip-seconds".format(
        block.get("goodput_fraction") or 0.0,
        block.get("held_chip_s") or 0.0)]
    for item in block.get("badput_top") or []:
        lines.append("  badput {:<12} {:>8.1f}s  ({:.1%})".format(
            item["bucket"], item["s"], item["fraction"]))
    lines.append("  unaccounted  {:.1%}".format(
        block.get("unaccounted_fraction") or 0.0))
    for pid, p in sorted((block.get("per_partition") or {}).items()):
        lines.append("  p{:<3} {:>7.1f}s held, goodput {}".format(
            pid, p.get("held_s") or 0.0,
            "{:.1%}".format(p["goodput_fraction"])
            if p.get("goodput_fraction") is not None else "n/a"))
    return lines


__all__ = ["compute_goodput", "merge_corrected", "render_goodput",
           "GOODPUT_BUCKETS", "HANDOFF_CAP_S"]
