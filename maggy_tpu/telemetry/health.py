"""Live health engine: driver-side straggler / hang / RTT-degradation
detection over trial spans + runner stats.

PR 2's chaos engine can deterministically inject a stalled runner; nothing
watched a LIVE run for one. ``HealthEngine`` closes that loop: a periodic
analyzer (own daemon thread, ``telemetry-health``) over the telemetry
facade's in-memory state — spans, merged runner stats, per-partition
progress stamps — computing three checks:

- **straggler**: median-absolute-deviation outliers across partitions, on
  (a) first-metric latency (running → first_metric per span — the
  compile/startup cost) and (b) runner-reported broadcast cadence. MAD is
  robust to the one runner being slow (the case under test); a zero-MAD
  fleet (all identical) is guarded by an absolute excess floor so healthy
  uniform runs can never divide their way into a flag.
- **hb_rtt**: a partition whose heartbeat round-trip EWMA exceeds
  ``rtt_factor`` x the fleet median (with an absolute floor) — control
  plane degradation localized to one runner's path.
- **hang**: a partition holding a trial whose journal progress (trial
  phase events, runner-reported steps — NOT liveness-only fields like
  RTT) stalled for longer than ``hang_factor`` x the heartbeat interval.
  On raise, the engine journals a faulthandler thread dump alongside the
  flag (in-process pools: the wedged runner thread's stack is IN the
  dump; process pools: the driver side's, still timestamped evidence).
  This catches sub-``hb_loss_timeout`` stalls the loss scan is blind to —
  a runner can stall for 80% of the loss bound forever without ever
  being declared lost.

Findings are journaled as ``health`` events (``status: raised|cleared``),
surfaced in the TELEM snapshot (``monitor --health`` renders them), and
asserted by the chaos harness's stall invariant: an injected
``stall_runner`` fault must produce a straggler/hang flag for the stalled
partition within bounded time.

All record paths stay buffer-only: the engine reads in-memory state and
journals through ``Telemetry.event`` — no I/O on any hot path.
"""

from __future__ import annotations

import threading
import time
from statistics import median as _median
from typing import Any, Dict, List, Optional

#: Default number of heartbeat intervals without trial progress before a
#: partition holding a trial is flagged as hung.
DEFAULT_HANG_FACTOR = 25.0

#: Default hang-bound multiplier for trials still pre-first_metric (the
#: silent first-step XLA compile window). Shared with the chaos harness's
#: invariant-5 bound so the watchdog and its verifier can't diverge.
DEFAULT_STARTUP_FACTOR = 4.0


def default_interval_s(hb_interval: float) -> float:
    """The engine's check cadence when none is configured. One home —
    the chaos harness derives its flag bound from the same rule."""
    return max(0.25, float(hb_interval))

#: Default modified-z-score threshold for MAD straggler flags (3.5 is the
#: textbook Iglewicz-Hoaglin cut).
DEFAULT_MAD_THRESHOLD = 3.5

#: Checks the chaos stall invariant accepts as "the health engine saw the
#: stalled partition".
STALL_CHECKS = ("hang", "straggler")


def thread_dump(max_bytes: int = 8192) -> str:
    """All-threads stack dump via faulthandler (needs a real fd; staged
    through a tempfile), falling back to sys._current_frames. Returns at
    most ``max_bytes`` of the tail — journal events must stay bounded."""
    try:
        import faulthandler
        import tempfile

        with tempfile.TemporaryFile(mode="w+") as f:
            faulthandler.dump_traceback(file=f, all_threads=True)
            f.seek(0)
            return f.read()[-max_bytes:]
    except Exception:  # noqa: BLE001 - restricted environments
        try:
            import sys
            import traceback

            parts = []
            for tid, frame in sys._current_frames().items():
                parts.append("Thread 0x{:x}:\n{}".format(
                    tid, "".join(traceback.format_stack(frame))))
            return "\n".join(parts)[-max_bytes:]
        except Exception:  # noqa: BLE001
            return "<thread dump unavailable>"


class HealthEngine:
    """Periodic analyzer; ``check()`` is also directly callable (tests run
    it deterministically without the thread)."""

    def __init__(self, telemetry, hb_interval: float = 1.0,
                 interval_s: Optional[float] = None,
                 hang_factor: float = DEFAULT_HANG_FACTOR,
                 mad_threshold: float = DEFAULT_MAD_THRESHOLD,
                 min_partitions: int = 3,
                 straggler_min_excess_ms: float = 500.0,
                 rtt_factor: float = 4.0, rtt_floor_ms: float = 50.0,
                 startup_factor: float = DEFAULT_STARTUP_FACTOR,
                 dump_threads_on_hang: bool = True):
        self.telemetry = telemetry
        self.hb_interval = float(hb_interval)
        self.interval_s = float(interval_s) if interval_s is not None \
            else default_interval_s(self.hb_interval)
        self.hang_factor = float(hang_factor)
        self.mad_threshold = float(mad_threshold)
        self.min_partitions = int(min_partitions)
        self.straggler_min_excess_ms = float(straggler_min_excess_ms)
        self.rtt_factor = float(rtt_factor)
        self.rtt_floor_ms = float(rtt_floor_ms)
        #: Hang-bound multiplier while a trial is still PRE-first_metric:
        #: the first step legitimately compiles for a long time with zero
        #: broadcasts, and that silence must not read as a hang at the
        #: steady-state bound (a true startup wedge still flags, just
        #: later).
        self.startup_factor = float(startup_factor)
        self.dump_threads_on_hang = dump_threads_on_hang
        self.reservations = None
        #: Optional telemetry.profiling.ProfileCapturer: the FIRST
        #: straggler/hang raise per partition triggers a device-profile
        #: capture (rate-limited there), so a flagged anomaly yields an
        #: inspectable artifact, not just a journal line. Attached by
        #: the driver when the observability plane is on.
        self.profiler = None
        self._lock = threading.Lock()
        #: (check, metric, partition) -> active flag dict.
        self._active: Dict[tuple, Dict[str, Any]] = {}  # guarded-by: _lock
        self.raised_total = 0  # guarded-by: _lock
        self.checks_run = 0  # guarded-by: _lock
        self._last_check_t: Optional[float] = None  # guarded-by: _lock
        self._check_failed = False  # unguarded-ok: engine-loop-private latch, single writer thread
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def attach(self, reservations=None, profiler=None) -> None:
        """Late-bind the authoritative partition->trial assignment view
        (the server's Reservations) for the hang watchdog, and/or the
        profile capturer for health-triggered captures."""
        if reservations is not None:
            self.reservations = reservations
        if profiler is not None:
            self.profiler = profiler

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        if self._thread is None:
            # Liveness marker: the journal must SAY the engine ran, so an
            # offline invariant check (chaos harness invariant 5) can tell
            # "stall went unflagged" apart from "nothing was watching"
            # (health=False runs, pre-health journals).
            self.telemetry.event(
                "health", check="engine", status="started",
                interval_s=round(self.interval_s, 3),
                hang_factor=self.hang_factor)
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="telemetry-health")
            self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.check()
            except Exception as e:  # noqa: BLE001 - must never kill the engine
                if not self._check_failed:
                    self._check_failed = True
                    try:
                        self.telemetry.event("health", check="engine",
                                             status="error", error=repr(e))
                    except Exception:  # noqa: BLE001
                        pass

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    # --------------------------------------------------------------- checks

    def check(self) -> List[Dict[str, Any]]:
        """Run every check once; reconcile with the active-flag set
        (journal newly-raised and newly-cleared findings exactly once).
        Returns the currently-active flags."""
        findings: List[Dict[str, Any]] = []
        findings += self._check_hang()
        findings += self._check_stragglers()
        findings += self._check_rtt()
        desired = {(f["check"], f.get("metric"), f["partition"]): f
                   for f in findings}
        raised: List[Dict[str, Any]] = []
        cleared: List[Dict[str, Any]] = []
        with self._lock:
            self.checks_run += 1
            self._last_check_t = time.time()
            for key, f in desired.items():
                if key not in self._active:
                    f = dict(f, since=time.time())
                    self._active[key] = f
                    self.raised_total += 1
                    raised.append(f)
                else:
                    # Keep the live detail fresh (monitor shows current
                    # values), without re-journaling.
                    self._active[key].update(
                        {k: v for k, v in f.items() if k != "since"})
            for key in list(self._active):
                if key not in desired:
                    cleared.append(self._active.pop(key))
            active = [dict(f) for f in self._active.values()]
        for f in raised:
            fields = {k: v for k, v in f.items() if k != "since"}
            if f["check"] == "hang" and self.dump_threads_on_hang:
                fields["stacks"] = thread_dump()
            self.telemetry.event("health", status="raised", **fields)
            profiler = self.profiler
            if profiler is not None:
                # First straggler/hang raise per partition -> capture a
                # device profile at the moment of the anomaly (rate
                # limiting lives in the capturer; runs on its own
                # thread, so the check cadence is unaffected).
                profiler.auto_capture(check=f["check"],
                                      partition=f.get("partition"),
                                      trial=f.get("trial"))
        for f in cleared:
            self.telemetry.event(
                "health", status="cleared", check=f["check"],
                metric=f.get("metric"), partition=f["partition"])
        return active

    def _check_hang(self) -> List[Dict[str, Any]]:
        base_bound = self.hang_factor * self.hb_interval
        now = time.monotonic()
        # Trials still compiling (no first_metric yet) get startup_factor
        # x the bound: a long first-step XLA compile is silent by nature.
        # A REQUEUED trial stays in the startup window too — its span
        # keeps the dead attempt's first_metric (first-occurrence
        # semantics), but the rescue partition recompiles from scratch
        # and deserves the same leash the first attempt had.
        started = set()
        for span in self.telemetry.spans.all():
            phases = span.get("phases") or {}
            if "first_metric" in phases and "requeued" not in phases:
                started.add(span.get("trial"))
        out: List[Dict[str, Any]] = []
        for pid, trial_id in self._assignments():
            last = self.telemetry.last_progress(pid)
            if last is None:
                continue
            window = "steady" if trial_id in started else "startup"
            bound_s = base_bound if window == "steady" \
                else base_bound * self.startup_factor
            silent = now - last
            if silent > bound_s:
                out.append({"check": "hang", "metric": "progress",
                            "partition": pid, "trial": trial_id,
                            "window": window,
                            "silent_s": round(silent, 2),
                            "bound_s": round(bound_s, 2)})
        return out

    def _assignments(self) -> List[tuple]:
        """(partition, trial) pairs currently holding work. Authoritative
        via the attached Reservations; span-derived fallback otherwise
        (in-flight spans: running seen, finalized not)."""
        res = self.reservations
        if res is not None:
            try:
                return [(pid, rec.get("trial_id"))
                        for pid, rec in res.all().items()
                        if rec.get("trial_id") is not None
                        and not rec.get("released")]
            except Exception:  # noqa: BLE001
                return []
        out = []
        for span in self.telemetry.spans.all():
            phases = span.get("phases") or {}
            if "running" in phases and "finalized" not in phases \
                    and span.get("partition") is not None:
                out.append((int(span["partition"]), span.get("trial")))
        return out

    def _mad_outliers(self, per_partition: Dict[int, float], metric: str,
                      check: str = "straggler") -> List[Dict[str, Any]]:
        """One-sided (slower-than-fleet) modified-z-score outliers with an
        absolute excess floor (a zero-MAD fleet must not flag jitter)."""
        if len(per_partition) < self.min_partitions:
            return []
        values = list(per_partition.values())
        med = _median(values)
        sigma = 1.4826 * _median([abs(v - med) for v in values])
        out = []
        for pid, v in per_partition.items():
            excess = v - med
            if excess <= max(self.mad_threshold * sigma,
                             self.straggler_min_excess_ms):
                continue
            score = excess / sigma if sigma > 0 else float("inf")
            out.append({"check": check, "metric": metric, "partition": pid,
                        "value_ms": round(v, 1),
                        "fleet_median_ms": round(med, 1),
                        "score": round(min(score, 999.0), 2)})
        return out

    def _check_stragglers(self) -> List[Dict[str, Any]]:
        # (a) first-metric latency per partition, from the span timelines.
        # Requeued/lost trials are EXCLUDED: a span keeps its FIRST
        # 'running' timestamp but its LAST partition, so a trial that died
        # on partition A and reached first_metric on its rescue partition
        # B would charge the whole death + loss-timeout + re-dispatch
        # interval to healthy B — a false straggler against the rescuer.
        ttfm: Dict[int, List[float]] = {}
        for span in self.telemetry.spans.all():
            phases = span.get("phases") or {}
            if "requeued" in phases or "lost" in phases:
                continue
            t_run, t_fm = phases.get("running"), phases.get("first_metric")
            pid = span.get("partition")
            if t_run is not None and t_fm is not None and pid is not None \
                    and t_fm >= t_run:
                ttfm.setdefault(int(pid), []).append((t_fm - t_run) * 1e3)
        findings = self._mad_outliers(
            {pid: _median(v) for pid, v in ttfm.items()}, "first_metric_ms")
        # (b) runner-reported broadcast cadence per partition.
        cadence = {pid: float(stats["cadence_ms"])
                   for pid, stats in self._fresh_runner_stats().items()
                   if stats.get("cadence_ms") is not None}
        findings += self._mad_outliers(cadence, "cadence_ms")
        return findings

    def _fresh_runner_stats(self) -> Dict[int, Dict[str, Any]]:
        """Per-partition runner stats EXCLUDING stale entries: a dead or
        released runner's last EWMA values would otherwise sit in
        ``_runner_state`` forever, skewing every fleet median and holding
        an uncloseable flag against a partition that no longer exists. A
        live runner refreshes ``updated_t`` on nearly every beat."""
        stale_s = max(10 * self.hb_interval, 3 * self.interval_s)
        now = time.time()
        return {pid: stats
                for pid, stats in self.telemetry.runner_state().items()
                if now - stats.get("updated_t", 0.0) <= stale_s}

    def _check_rtt(self) -> List[Dict[str, Any]]:
        rtts = {pid: float(stats["hb_rtt_ms"])
                for pid, stats in self._fresh_runner_stats().items()
                if stats.get("hb_rtt_ms") is not None}
        if len(rtts) < self.min_partitions:
            return []
        med = _median(list(rtts.values()))
        out = []
        for pid, v in rtts.items():
            if v > max(self.rtt_factor * med, self.rtt_floor_ms):
                out.append({"check": "hb_rtt", "metric": "hb_rtt_ms",
                            "partition": pid, "value_ms": round(v, 2),
                            "fleet_median_ms": round(med, 2)})
        return out

    # ------------------------------------------------------------- querying

    def snapshot(self) -> Dict[str, Any]:
        """Plain-dict state for the TELEM reply: active flags + totals."""
        with self._lock:
            flags = [{k: v for k, v in f.items() if k != "stacks"}
                     for f in self._active.values()]
            return {"flags": flags, "raised_total": self.raised_total,
                    "checks_run": self.checks_run,
                    "last_check_t": self._last_check_t}
