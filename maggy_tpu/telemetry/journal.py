"""JSONL telemetry journal, written through the environment abstraction.

``record()`` only appends to an in-memory buffer — NO I/O on the caller's
thread, so the RPC hot path (METRIC/FINAL handlers on the server event
loop) never blocks on a disk or GCS write. A daemon flusher thread
persists the journal every ``flush_interval_s``: the FIRST flush is a full
atomic rewrite via ``env.dump`` (truncating any stale file from an
unrelated earlier run at this path), subsequent flushes append only the
new events through ``env.open_file(path, "a")`` — O(new events), not
O(journal), per flush. Backends without append semantics (object stores)
fall back to the full rewrite automatically. A hard kill mid-append can
leave a torn tail LINE; readers skip it (``_parse_jsonl``), so the journal
stays old-or-new at event granularity. A crashed experiment therefore
retains its telemetry up to the last flush; a resumed one loads the prior
events and keeps appending, so the journal covers the whole logical
experiment.

Events are plain dicts with at least ``{"t": <unix s>, "ev": <kind>}``;
trial events add ``{"trial", "span", "phase"}`` (see spans.PHASES).
"""

from __future__ import annotations

import json
import threading
from typing import Any, Dict, List, Optional

FLUSHER_THREAD_NAME = "telemetry-flush"


class TelemetryJournal:
    def __init__(self, env, path: str, flush_interval_s: float = 1.0):
        self.env = env
        self.path = path
        self.flush_interval_s = flush_interval_s
        self._lock = threading.Lock()
        # Serializes whole flush cycles (read-suffix -> write -> advance
        # _flushed): a finalize-path flush() racing the flusher thread's
        # tick would otherwise both read the same unflushed suffix and
        # append it twice — duplicated events break replay's
        # same-journal-same-numbers contract.
        self._flush_lock = threading.Lock()
        self._events: List[Dict[str, Any]] = []  # guarded-by: _lock
        # How many leading events are already on disk. 0 forces the first
        # flush to be a full rewrite (truncates a stale journal from an
        # unrelated earlier run at the same path); afterwards flushes
        # append only events[_flushed:].
        self._flushed = 0  # guarded-by: _lock
        # None = untried, False = backend rejected append mode (object
        # stores): every flush falls back to the full atomic rewrite.
        self._append_ok: Optional[bool] = None  # guarded-by: _flush_lock
        self._dirty = False  # guarded-by: _lock
        self._closed = False  # guarded-by: _lock
        #: Corrupt/torn lines skipped when loading a previous run's journal
        #: (load_existing). Exposed in the TELEM snapshot so journal
        #: corruption is visible instead of quietly shrinking the dataset.
        self.torn_lines = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._flusher, daemon=True, name=FLUSHER_THREAD_NAME)
        self._thread.start()

    # ------------------------------------------------------------- hot path

    def record(self, event: Dict[str, Any]) -> None:
        """Buffer one event. Never touches the filesystem."""
        with self._lock:
            if self._closed:
                return
            self._events.append(event)
            self._dirty = True

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    # ----------------------------------------------------------- durability

    def load_existing(self) -> int:
        """Prepend events persisted by a previous (crashed/interrupted) run
        of this experiment, so resume keeps one continuous journal. Returns
        the number of restored events."""
        try:
            if not self.env.exists(self.path):
                return 0
            restored = _parse_jsonl(self.env.load(self.path))
        except Exception:  # noqa: BLE001 - a torn journal must not block resume
            return 0
        with self._lock:
            self.torn_lines += restored.torn_lines
            self._events = restored + self._events
            # _flushed deliberately stays 0: the next flush takes the
            # full-rewrite path, which re-persists the restored prefix AND
            # truncates any torn tail line the crashed writer left —
            # appending after a partial line would glue the first new
            # event onto it, corrupting both forever.
            self._dirty = True
        return len(restored)

    def flush(self) -> None:
        """Persist now: append the unflushed suffix when the backend
        supports it, else a full atomic rewrite via env.dump. One flush
        cycle at a time (see _flush_lock)."""
        with self._flush_lock:
            self._flush_locked()

    # locked-by: _flush_lock
    def _flush_locked(self) -> None:
        with self._lock:
            if not self._dirty:
                return
            start = self._flushed
            new = self._events[start:]
            total = len(self._events)
            self._dirty = False
        if start > 0 and self._append_ok is not False:
            payload = "".join(json.dumps(e, default=str) + "\n" for e in new)
            try:
                with self.env.open_file(self.path, "a") as f:
                    f.write(payload)
                self._append_ok = True
                with self._lock:
                    self._flushed = max(self._flushed, total)
                return
            except Exception:  # noqa: BLE001 - backend without append
                self._append_ok = False
                # Fall through to the full rewrite, which also repairs any
                # partial line the failed append may have left.
        with self._lock:
            # Copy the refs under the lock, serialize OUTSIDE it: on
            # backends without append support this path runs every flush,
            # and O(journal) json.dumps under the buffer lock would stall
            # record() — i.e. the RPC hot path — for the duration.
            snapshot = list(self._events[:total])
        payload = "".join(json.dumps(e, default=str) + "\n" for e in snapshot)
        try:
            self.env.dump(payload, self.path)
            with self._lock:
                self._flushed = max(self._flushed, total)
        except Exception:  # noqa: BLE001 - telemetry must never fail a run
            with self._lock:
                self._dirty = True

    def _flusher(self) -> None:
        while not self._stop.wait(self.flush_interval_s):
            self.flush()

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._stop.set()
        self._thread.join(timeout=5)
        self.flush()


class JournalEvents(list):
    """Parsed journal events, plus ``torn_lines``: how many corrupt lines
    the parser had to skip. A torn tail line from a hard kill is expected
    (at most 1); more than that means real corruption silently shrinking
    the dataset — callers surface the count instead of hiding it."""

    torn_lines: int = 0


def _parse_jsonl(text: str) -> JournalEvents:
    events = JournalEvents()
    torn = 0
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            ev = json.loads(line)
        except ValueError:
            torn += 1  # torn tail line from a hard kill mid-flush
            continue
        if isinstance(ev, dict):
            events.append(ev)
        else:
            torn += 1  # valid JSON but not an event object
    events.torn_lines = torn
    return events


def read_events(path: str, env=None) -> JournalEvents:
    """Load a journal's events: through ``env`` when given, else the local
    filesystem (offline replay of a copied artifact). The returned list
    carries ``torn_lines`` — the count of corrupt/torn lines skipped."""
    if env is not None:
        return _parse_jsonl(env.load(path))
    with open(path) as f:
        return _parse_jsonl(f.read())
