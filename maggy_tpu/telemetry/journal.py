"""JSONL telemetry journal, written through the environment abstraction.

``record()`` only appends to an in-memory buffer — NO I/O on the caller's
thread, so the RPC hot path (METRIC/FINAL handlers on the server event
loop) never blocks on a disk or GCS write. A daemon flusher thread
persists the journal every ``flush_interval_s``: the FIRST flush is a full
atomic rewrite via ``env.dump`` (truncating any stale file from an
unrelated earlier run at this path), subsequent flushes append only the
new events through ``env.open_file(path, "a")`` — O(new events), not
O(journal), per flush. Backends without append semantics (object stores)
fall back to the full rewrite automatically. A hard kill mid-append can
leave a torn tail LINE; readers skip it (``_parse_jsonl``), so the journal
stays old-or-new at event granularity. A crashed experiment therefore
retains its telemetry up to the last flush; a resumed one loads the prior
events and keeps appending, so the journal covers the whole logical
experiment.

Events are plain dicts with at least ``{"t": <unix s>, "ev": <kind>}``;
trial events add ``{"trial", "span", "phase"}`` (see spans.PHASES).

**Rotation** (``MAGGY_TPU_JOURNAL_MAX_MB``, or the ``max_mb`` argument;
off by default): a multi-day sweep's journal grows without bound, and a
single multi-GB JSONL file is exactly what an operator cannot tail or
copy mid-run. With a size cap set, a flush that leaves the ACTIVE file
over the cap seals it into a numbered segment
(``telemetry.jsonl.000001``, ``.000002``, ... — ascending = older) and
starts a fresh active file; ``read_events`` transparently reads the
segments in order followed by the active file, so replay, resume
(``load_existing``) and every journal consumer see one continuous
event stream regardless of how it is sharded on disk.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, List, Optional, Tuple

FLUSHER_THREAD_NAME = "telemetry-flush"

#: Env var naming the active-file size cap in MB (float ok); unset/empty
#: or <= 0 disables rotation.
ROTATE_ENV = "MAGGY_TPU_JOURNAL_MAX_MB"

#: Env var arming fsync durability (``1``/``true``): the journal fsyncs
#: on segment SEAL and on ``barrier()`` (the terminal-event flush the
#: FINAL path runs before its RPC reply) — never on the periodic flusher
#: tick. Off by default: the flusher's cadence already bounds loss to
#: ~1 s of TAIL events, and crash-only recovery tolerates a torn tail
#: line by design (docs/telemetry.md, "torn-tail tolerance"). Chaos
#: ``kill_driver`` soaks turn it on so an acknowledged FINAL can never
#: be lost to the page cache.
FSYNC_ENV = "MAGGY_TPU_JOURNAL_FSYNC"


def _resolved_fsync(fsync) -> bool:
    if fsync is not None:
        return bool(fsync)
    return os.environ.get(FSYNC_ENV, "").strip().lower() in ("1", "true",
                                                             "on", "yes")


def _fsync_path(path: str) -> None:
    """Best-effort fsync of a local file (object-store backends have no
    fd to sync — their dump() durability is the PUT's)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _segment_path(path: str, index: int) -> str:
    return "{}.{:06d}".format(path, index)


def _resolved_max_bytes(max_mb: Optional[float]) -> Optional[int]:
    if max_mb is None:
        raw = os.environ.get(ROTATE_ENV, "").strip()
        if not raw:
            return None
        try:
            max_mb = float(raw)
        except ValueError:
            return None
    return int(max_mb * 1024 * 1024) if max_mb and max_mb > 0 else None


class TelemetryJournal:
    def __init__(self, env, path: str, flush_interval_s: float = 1.0,
                 max_mb: Optional[float] = None,
                 start_flusher: bool = True,
                 fsync: Optional[bool] = None):
        self.env = env
        self.path = path
        self.flush_interval_s = flush_interval_s
        #: Active-file rotation threshold in bytes; None = never rotate.
        self._max_bytes = _resolved_max_bytes(max_mb)
        #: Durability knob (MAGGY_TPU_JOURNAL_FSYNC / fsync=): fsync on
        #: segment seal and on barrier() only — the periodic flusher
        #: never pays it.
        self._fsync = _resolved_fsync(fsync)
        self._lock = threading.Lock()
        # Serializes whole flush cycles (read-suffix -> write -> advance
        # _flushed): a finalize-path flush() racing the flusher thread's
        # tick would otherwise both read the same unflushed suffix and
        # append it twice — duplicated events break replay's
        # same-journal-same-numbers contract.
        self._flush_lock = threading.Lock()
        self._events: List[Dict[str, Any]] = []  # guarded-by: _lock
        # How many leading events are already on disk. 0 forces the first
        # flush to be a full rewrite (truncates a stale journal from an
        # unrelated earlier run at the same path); afterwards flushes
        # append only events[_flushed:].
        self._flushed = 0  # guarded-by: _lock
        # Leading events that live in SEALED rotation segments (always <=
        # _flushed): the full-rewrite flush path must rewrite only the
        # active file's share, events[_rotated:], or every rewrite would
        # resurrect the rotated prefix into the active file and replay
        # would see each rotated event twice.
        self._rotated = 0  # guarded-by: _lock
        # Sealed segment count / bytes currently in the active file.
        # Flush-cycle state, mutated only with _flush_lock held.
        self._segments = 0  # guarded-by: _flush_lock
        self._active_bytes = 0  # guarded-by: _flush_lock
        # None = untried, False = backend rejected append mode (object
        # stores): every flush falls back to the full atomic rewrite.
        self._append_ok: Optional[bool] = None  # guarded-by: _flush_lock
        self._dirty = False  # guarded-by: _lock
        self._closed = False  # guarded-by: _lock
        #: Corrupt/torn lines skipped when loading a previous run's journal
        #: (load_existing). Exposed in the TELEM snapshot so journal
        #: corruption is visible instead of quietly shrinking the dataset.
        self.torn_lines = 0
        self._stop = threading.Event()
        # ``start_flusher=False`` skips the per-journal flusher thread:
        # the caller owns the flush cadence (the fleet journal sink runs
        # ONE flusher over its per-source writers — one thread for 500
        # sources, not 500 threads).
        self._thread: Optional[threading.Thread] = None
        if start_flusher:
            self._thread = threading.Thread(
                target=self._flusher, daemon=True, name=FLUSHER_THREAD_NAME)
            self._thread.start()

    # ------------------------------------------------------------- hot path

    def record(self, event: Dict[str, Any]) -> None:
        """Buffer one event. Never touches the filesystem."""
        with self._lock:
            if self._closed:
                return
            self._events.append(event)
            self._dirty = True

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    # ----------------------------------------------------------- durability

    def load_existing(self) -> int:
        """Prepend events persisted by a previous (crashed/interrupted) run
        of this experiment — rotated segments first, then the active file —
        so resume keeps one continuous journal. Returns the number of
        restored events."""
        try:
            segments, active, n_segments, torn = _load_parts(
                self.path, env=self.env)
        except Exception:  # noqa: BLE001 - a torn journal must not block resume
            return 0
        if not segments and active is None:
            return 0
        active_events = active if active is not None else []
        with self._flush_lock:
            with self._lock:
                self.torn_lines += torn
                self._events = segments + active_events + self._events
                # The rotated prefix is sealed on disk — only the ACTIVE
                # file's events are ever rewritten. _flushed deliberately
                # stays 0: the next flush takes the full-rewrite path,
                # which re-persists the restored ACTIVE suffix AND
                # truncates any torn tail line the crashed writer left —
                # appending after a partial line would glue the first new
                # event onto it, corrupting both forever.
                self._rotated = len(segments)
                self._dirty = True
            self._segments = n_segments
        return len(segments) + len(active_events)

    def flush(self) -> None:
        """Persist now: append the unflushed suffix when the backend
        supports it, else a full atomic rewrite via env.dump. One flush
        cycle at a time (see _flush_lock)."""
        with self._flush_lock:
            self._flush_locked()

    def barrier(self) -> None:
        """Durability barrier for terminal events (crash-only recovery):
        flush the buffered suffix NOW — and fsync it when the durability
        knob is armed — so the journal, the recovery source of truth, can
        never trail an event the caller is about to acknowledge on the
        wire (the FINAL path runs this before its RPC reply is written).
        Without fsync the barrier still moves the events out of process
        memory into the page cache: a driver crash (the fault being
        defended against) cannot lose them; only a whole-host power loss
        can, which is what the fsync knob buys."""
        with self._flush_lock:
            self._flush_locked(fsync=self._fsync)

    # locked-by: _flush_lock
    def _flush_locked(self, fsync: bool = False) -> None:
        with self._lock:
            if not self._dirty:
                return
            start = self._flushed
            rotated = self._rotated
            new = self._events[start:]
            total = len(self._events)
            self._dirty = False
        if start > rotated and self._append_ok is not False:
            # Append only applies to a non-empty ACTIVE file: right after
            # a rotation the active file is fresh, and the rewrite path
            # below (O(active), not O(journal)) re-creates it cleanly.
            payload = "".join(json.dumps(e, default=str) + "\n" for e in new)
            try:
                with self.env.open_file(self.path, "a") as f:
                    f.write(payload)
                self._append_ok = True
                with self._lock:
                    self._flushed = max(self._flushed, total)
                self._active_bytes += len(payload)
                if fsync:
                    _fsync_path(self.path)
                self._maybe_rotate(total)
                return
            except Exception:  # noqa: BLE001 - backend without append
                self._append_ok = False
                # Fall through to the full rewrite, which also repairs any
                # partial line the failed append may have left.
        with self._lock:
            # Copy the refs under the lock, serialize OUTSIDE it: on
            # backends without append support this path runs every flush,
            # and O(journal) json.dumps under the buffer lock would stall
            # record() — i.e. the RPC hot path — for the duration. Only
            # the ACTIVE file's share is rewritten; the rotated prefix is
            # sealed in its segments.
            snapshot = list(self._events[rotated:total])
        payload = "".join(json.dumps(e, default=str) + "\n" for e in snapshot)
        try:
            self.env.dump(payload, self.path)
            with self._lock:
                self._flushed = max(self._flushed, total)
            self._active_bytes = len(payload)
            if fsync:
                _fsync_path(self.path)
            self._maybe_rotate(total)
        except Exception:  # noqa: BLE001 - telemetry must never fail a run
            with self._lock:
                self._dirty = True

    # locked-by: _flush_lock
    def _maybe_rotate(self, total: int) -> None:
        """Seal the active file into the next numbered segment when it
        outgrew the cap. Runs inside the flush cycle, so rotation can
        never interleave with a write. Failure is non-fatal: the active
        file just keeps growing until a later rotation succeeds."""
        if self._max_bytes is None or self._active_bytes < self._max_bytes:
            return
        with self._lock:
            rotated = self._rotated
        segment = _segment_path(self.path, self._segments + 1)
        snapshot = self._events_slice(rotated, total)
        payload = "".join(json.dumps(e, default=str) + "\n"
                          for e in snapshot)
        try:
            # Copy-then-truncate (no rename in the env abstraction, and
            # object stores have none anyway). A FAILED truncate deletes
            # the just-written segment below, so in-process errors never
            # leave the sealed window on disk twice; only a hard kill
            # exactly between the two writes can duplicate one rotation
            # window — the same old-or-new granularity bound the
            # unrotated journal already accepts for its tail line.
            self.env.dump(payload, segment)
            if self._fsync:
                # Seal durability (the fsync knob's other half): a sealed
                # segment is immutable recovery input — it must survive a
                # host crash, not just a process one.
                _fsync_path(segment)
            self.env.dump("", self.path)
        except Exception:  # noqa: BLE001 - telemetry must never fail a run
            try:
                self.env.delete(segment)
            except Exception:  # noqa: BLE001
                pass
            return
        self._segments += 1
        self._active_bytes = 0
        with self._lock:
            self._rotated = max(self._rotated, total)

    def _events_slice(self, start: int, stop: int) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events[start:stop])

    def _flusher(self) -> None:
        while not self._stop.wait(self.flush_interval_s):
            self.flush()

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self.flush()


class JournalEvents(list):
    """Parsed journal events, plus ``torn_lines``: how many corrupt lines
    the parser had to skip. A torn tail line from a hard kill is expected
    (at most 1); more than that means real corruption silently shrinking
    the dataset — callers surface the count instead of hiding it."""

    torn_lines: int = 0


def _parse_jsonl(text: str) -> JournalEvents:
    events = JournalEvents()
    torn = 0
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            ev = json.loads(line)
        except ValueError:
            torn += 1  # torn tail line from a hard kill mid-flush
            continue
        if isinstance(ev, dict):
            events.append(ev)
        else:
            torn += 1  # valid JSON but not an event object
    events.torn_lines = torn
    return events


def _load_parts(path: str, env=None) -> Tuple[List[Dict[str, Any]],
                                              Optional[JournalEvents],
                                              int, int]:
    """Read a (possibly rotated) journal from disk: ``(segment_events,
    active_events_or_None, n_segments, torn_lines)``. Segments are read
    in ascending index order — the order they were sealed — so the
    concatenation is the original event stream."""
    if env is not None:
        exists, load = env.exists, env.load
    else:
        exists = os.path.exists

        def load(p):
            with open(p) as f:
                return f.read()

    segments: List[Dict[str, Any]] = []
    torn = 0
    n_segments = 0
    while True:
        seg = _segment_path(path, n_segments + 1)
        if not exists(seg):
            break
        parsed = _parse_jsonl(load(seg))
        segments.extend(parsed)
        torn += parsed.torn_lines
        n_segments += 1
    active: Optional[JournalEvents] = None
    if exists(path):
        active = _parse_jsonl(load(path))
        torn += active.torn_lines
    return segments, active, n_segments, torn


def read_events(path: str, env=None) -> JournalEvents:
    """Load a journal's events: through ``env`` when given, else the local
    filesystem (offline replay of a copied artifact). Rotated segments
    (``<path>.000001`` ...) are read first, in order, then the active
    file — consumers see one continuous stream. The returned list
    carries ``torn_lines`` — the count of corrupt/torn lines skipped."""
    segments, active, _, torn = _load_parts(path, env=env)
    if not segments and active is None:
        # Preserve the unrotated contract: a missing journal raises the
        # backend's error instead of silently returning an empty list.
        if env is not None:
            env.load(path)
        else:
            with open(path) as f:
                f.read()
    events = JournalEvents(segments + (active if active is not None else []))
    events.torn_lines = torn
    return events
