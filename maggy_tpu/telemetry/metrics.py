"""Low-overhead in-process metrics: counters, gauges, latency histograms.

No dependencies, thread-safe, snapshot-able to plain dicts (msgpack/json
friendly — the TELEM RPC verb ships snapshots verbatim). Modeled on the
measurement discipline Podracer-style systems apply to actor/learner
hand-off utilization (arxiv 2104.06272): the scheduler's perf claims must
be queryable counters, not ad-hoc timers.

Histograms use FIXED bucket bounds chosen at creation (cumulative counts
per bound, like Prometheus): observation is O(#buckets) worst case with no
allocation, and two snapshots subtract cleanly. Percentiles read from the
bucket CDF are upper-bound estimates — good enough to steer by, cheap
enough for the RPC hot path.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence

# Default bounds for latency histograms, in milliseconds. Spans the sub-ms
# RPC handler times up to the multi-second compile stalls the control plane
# must notice.
DEFAULT_LATENCY_BOUNDS_MS = (
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0,
    100.0, 250.0, 500.0, 1000.0, 2500.0, 5000.0, 10000.0,
)


class Counter:
    """Monotonically increasing count."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        with self._lock:
            return self._value


class Gauge:
    """Last-written value."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value: Optional[float] = None

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    @property
    def value(self) -> Optional[float]:
        with self._lock:
            return self._value


class Histogram:
    """Fixed-bound histogram with cumulative bucket counts.

    ``bounds`` are the inclusive upper edges of the finite buckets; one
    implicit +inf bucket catches the rest.
    """

    __slots__ = ("_lock", "bounds", "_counts", "_sum", "_count", "_min", "_max")

    def __init__(self, bounds: Sequence[float] = DEFAULT_LATENCY_BOUNDS_MS):
        if not bounds or list(bounds) != sorted(bounds):
            raise ValueError("Histogram bounds must be non-empty and sorted.")
        self._lock = threading.Lock()
        self.bounds = tuple(float(b) for b in bounds)
        self._counts = [0] * (len(self.bounds) + 1)  # last = +inf
        self._sum = 0.0
        self._count = 0
        self._min: Optional[float] = None
        self._max: Optional[float] = None

    def observe(self, value: float) -> None:
        value = float(value)
        idx = len(self.bounds)
        for i, bound in enumerate(self.bounds):
            if value <= bound:
                idx = i
                break
        with self._lock:
            self._counts[idx] += 1
            self._sum += value
            self._count += 1
            if self._min is None or value < self._min:
                self._min = value
            if self._max is None or value > self._max:
                self._max = value

    def percentile(self, q: float) -> Optional[float]:
        """Upper-bound estimate of the q-quantile (0 < q <= 1) from the
        bucket CDF; the observed max for the +inf bucket."""
        with self._lock:
            if self._count == 0:
                return None
            return self._percentile_from(self._counts, self._count,
                                         self._max, q)

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            counts = list(self._counts)
            total, s = self._count, self._sum
            lo, hi = self._min, self._max
        snap: Dict[str, object] = {
            "count": total,
            "sum": round(s, 3),
            "min": None if lo is None else round(lo, 3),
            "max": None if hi is None else round(hi, 3),
            "buckets": {str(b): c for b, c in zip(self.bounds, counts)},
            "overflow": counts[-1],
        }
        if total:
            snap["p50"] = self._percentile_from(counts, total, hi, 0.5)
            snap["p95"] = self._percentile_from(counts, total, hi, 0.95)
        return snap

    def _percentile_from(self, counts: List[int], total: int,
                         observed_max: Optional[float], q: float):
        target = q * total
        cum = 0
        for i, bound in enumerate(self.bounds):
            cum += counts[i]
            if cum >= target:
                return bound
        return observed_max


class MetricsRegistry:
    """Named metric store: get-or-create accessors, one flat namespace.

    Creation takes the registry lock; recording takes only the metric's own
    lock — the message hot path never contends on the registry once its
    metrics exist.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}  # guarded-by: _lock
        self._gauges: Dict[str, Gauge] = {}  # guarded-by: _lock
        self._histograms: Dict[str, Histogram] = {}  # guarded-by: _lock

    def counter(self, name: str) -> Counter:
        with self._lock:
            metric = self._counters.get(name)
            if metric is None:
                metric = self._counters[name] = Counter()
            return metric

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            metric = self._gauges.get(name)
            if metric is None:
                metric = self._gauges[name] = Gauge()
            return metric

    def histogram(self, name: str,
                  bounds: Sequence[float] = DEFAULT_LATENCY_BOUNDS_MS) -> Histogram:
        with self._lock:
            metric = self._histograms.get(name)
            if metric is None:
                metric = self._histograms[name] = Histogram(bounds)
            return metric

    def prune(self, predicate) -> int:
        """Drop every metric whose NAME satisfies ``predicate``. The
        registry grows one gauge per live runner field per partition
        (``runner.<field>.p<pid>``); without pruning, a reaped or
        replaced partition's gauges linger forever — skewing snapshots
        and polluting the /metrics exposition with dead series. Returns
        the number of metrics removed. Callers must not hold metric
        refs across a prune (get-or-create re-mints them)."""
        removed = 0
        with self._lock:
            for table in (self._counters, self._gauges, self._histograms):
                for name in [n for n in table if predicate(n)]:
                    del table[name]
                    removed += 1
        return removed

    def snapshot(self) -> Dict[str, Dict[str, object]]:
        """Plain-dict snapshot of every metric (json/msgpack-serializable)."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            histograms = dict(self._histograms)
        return {
            "counters": {k: c.value for k, c in sorted(counters.items())},
            "gauges": {k: g.value for k, g in sorted(gauges.items())},
            "histograms": {k: h.snapshot() for k, h in sorted(histograms.items())},
        }
