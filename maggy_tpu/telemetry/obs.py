"""Live observability plane: /metrics, /status, /healthz, /profilez.

Everything the telemetry stack knows today is learned after the fact by
replaying ``telemetry.jsonl`` — fine for bench, useless for OPERATING a
long-lived sweep or a multi-tenant fleet, where share allocation, warm-hit
rates, gang packing stalls and straggler flags must be visible while the
run is live, from standard tooling. This module is the stdlib-only HTTP
server that closes the loop (``http.server.ThreadingHTTPServer`` — no new
dependencies):

- ``GET /metrics``: the live ``MetricsRegistry`` of every registered
  experiment rendered in Prometheus text exposition format, every sample
  labeled ``experiment=".."``/``run=".."`` so one scrape config covers a
  whole fleet process. Well-known metric families get structured labels
  (``runner.<field>.p<pid>`` gauges -> a ``partition`` label,
  ``rpc.handle_ms.<verb>`` histograms -> a ``verb`` label,
  ``trial.phase.<phase>`` counters -> a ``phase`` label).
- ``GET /status``: one JSON document per registered experiment — the
  TELEM snapshot (the same body the TELEM RPC verb ships) plus the
  driver's live control-plane state: trial store / requeue backlog,
  reservation table, assembled gangs + placer blocks, and the fleet
  scheduler's share snapshot when fleet-attached.
- ``GET /healthz``: 200 when no registered experiment's HealthEngine has
  an active raised finding, 503 (with the flags as JSON) otherwise — the
  shape load balancers and k8s probes expect.
- ``GET /profilez?duration_s=N``: trigger an on-demand device profile
  (telemetry.profiling.ProfileCapturer) saved under
  ``<exp_dir>/profiles/`` and journaled as a ``profile_captured`` event.

One obs server per PROCESS: the first experiment (or fleet) that asks
starts it, later experiments register into the same listener and
deregister on stop; the listener closes when the last registration
leaves. Binding is loopback (127.0.0.1) by default — the endpoints are
unauthenticated by design (Prometheus-style), so exposing them beyond
the host is an explicit operator decision (``config.obs_host``).

Off by default: with ``config.obs_port`` unset and ``MAGGY_TPU_OBS_PORT``
absent, no socket is opened and nothing in this module runs.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlparse

__all__ = [
    "ObsRegistration", "ObsServer", "register", "deregister",
    "active_server", "render_prometheus",
]


# ------------------------------------------------------- prometheus text

def _sanitize(name: str) -> str:
    """Prometheus metric names are [a-zA-Z_:][a-zA-Z0-9_:]*."""
    out = []
    for i, ch in enumerate(name):
        if ch.isalnum() or ch in "_:":
            out.append(ch)
        else:
            out.append("_")
    s = "".join(out)
    if s and s[0].isdigit():
        s = "_" + s
    return s


def _escape_label(value: Any) -> str:
    return str(value).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _fmt_labels(labels: Dict[str, Any]) -> str:
    if not labels:
        return ""
    inner = ",".join('{}="{}"'.format(k, _escape_label(v))
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _split_family(name: str) -> Tuple[str, Dict[str, str]]:
    """Map well-known registry names to a (family, extra-labels) pair so
    per-partition/per-verb/per-phase series become one labeled family
    instead of an unbounded set of metric names. Everything else keeps
    its (sanitized) name."""
    if name.startswith("runner."):
        # runner.<field>.p<pid> gauges (telemetry.record_runner_stats).
        parts = name.split(".")
        if len(parts) == 3 and parts[2].startswith("p") \
                and parts[2][1:].isdigit():
            return "runner_" + _sanitize(parts[1]), \
                {"partition": parts[2][1:]}
    if name.startswith("rpc.handle_ms."):
        return "rpc_handle_ms", {"verb": name[len("rpc.handle_ms."):]}
    if name.startswith("trial.phase."):
        return "trial_phase_total", {"phase": name[len("trial.phase."):]}
    if name.startswith("goodput.fraction.p") \
            and name[len("goodput.fraction.p"):].isdigit():
        # goodput.fraction.p<pid> gauges (Telemetry.refresh_goodput_
        # gauges) -> one labeled family, like the runner gauges.
        return "goodput_fraction", \
            {"partition": name[len("goodput.fraction.p"):]}
    if name.startswith("tenant.chip_seconds."):
        # Fleet scheduler per-tenant chip-second totals -> one family
        # labeled by tenant experiment (the autoscaler-ready signal).
        return "tenant_chip_seconds", \
            {"tenant": name[len("tenant.chip_seconds."):]}
    return _sanitize(name), {}


def render_prometheus(snapshots: List[Tuple[Dict[str, str],
                                            Dict[str, Any]]],
                      prefix: str = "maggy_tpu_") -> str:
    """Render ``[(labels, MetricsRegistry.snapshot()), ...]`` to the
    Prometheus text exposition format (version 0.0.4). Pure function —
    unit-testable without a socket."""
    # family -> type -> [(labels, payload)]
    counters: Dict[str, List] = {}
    gauges: Dict[str, List] = {}
    hists: Dict[str, List] = {}
    for base_labels, snap in snapshots:
        for name, value in (snap.get("counters") or {}).items():
            fam, extra = _split_family(name)
            counters.setdefault(fam, []).append(
                ({**base_labels, **extra}, value))
        for name, value in (snap.get("gauges") or {}).items():
            if value is None:
                continue
            fam, extra = _split_family(name)
            gauges.setdefault(fam, []).append(
                ({**base_labels, **extra}, value))
        for name, h in (snap.get("histograms") or {}).items():
            fam, extra = _split_family(name)
            hists.setdefault(fam, []).append(
                ({**base_labels, **extra}, h))
    lines: List[str] = []
    for fam in sorted(counters):
        full = prefix + fam + ("" if fam.endswith("_total") else "_total")
        lines.append("# TYPE {} counter".format(full))
        for labels, value in counters[fam]:
            lines.append("{}{} {}".format(full, _fmt_labels(labels), value))
    for fam in sorted(gauges):
        full = prefix + fam
        lines.append("# TYPE {} gauge".format(full))
        for labels, value in gauges[fam]:
            lines.append("{}{} {}".format(full, _fmt_labels(labels), value))
    for fam in sorted(hists):
        full = prefix + fam
        lines.append("# TYPE {} histogram".format(full))
        for labels, h in hists[fam]:
            # Registry buckets are per-bound occupancy; Prometheus wants
            # the cumulative CDF.
            cum = 0
            for bound, count in (h.get("buckets") or {}).items():
                cum += count
                lines.append('{}_bucket{} {}'.format(
                    full, _fmt_labels({**labels, "le": bound}), cum))
            cum += h.get("overflow", 0)
            lines.append('{}_bucket{} {}'.format(
                full, _fmt_labels({**labels, "le": "+Inf"}), cum))
            lines.append("{}_sum{} {}".format(
                full, _fmt_labels(labels), h.get("sum", 0)))
            lines.append("{}_count{} {}".format(
                full, _fmt_labels(labels), h.get("count", 0)))
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------- registrations

class ObsRegistration:
    """One experiment's (or fleet's) hookup into the process obs server.

    Everything is a callable/reference the server reads on demand — the
    registration holds no state of its own, so a scrape always reflects
    the live system.
    """

    def __init__(self, key: str, labels: Dict[str, str], telemetry,
                 status_fn: Optional[Callable[[], Dict[str, Any]]] = None,
                 health=None, profiler=None,
                 snapshots_fn: Optional[Callable[
                     [], List[Tuple[Dict[str, str],
                                    Dict[str, Any]]]]] = None):
        self.key = key
        self.labels = dict(labels)
        self.telemetry = telemetry
        self.status_fn = status_fn
        self.health = health
        self.profiler = profiler
        #: Extra ``[(labels, registry-snapshot), ...]`` pairs rendered
        #: into /metrics alongside this registration's own registry —
        #: the fleet plugs its journal sink's FEDERATED per-source
        #: counters in here, so one scrape of the fleet host exposes
        #: every remote agent's and churn tenant's shipped counters.
        self.snapshots_fn = snapshots_fn


class ObsServer:
    """ThreadingHTTPServer wrapper serving the four routes over every
    registered experiment. Handlers run on per-request daemon threads, so
    a slow scrape (or a /profilez capture) never blocks the next one —
    and never blocks any driver thread: the server only READS through
    snapshot methods that take per-structure locks briefly."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._lock = threading.Lock()
        self._regs: Dict[str, ObsRegistration] = {}  # guarded-by: _lock
        self._httpd = ThreadingHTTPServer((host, port), _ObsHandler)
        self._httpd.daemon_threads = True
        self._httpd.obs = self
        self.address: Tuple[str, int] = self._httpd.server_address[:2]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.2},
            daemon=True, name="telemetry-obs")
        self._thread.start()

    # ------------------------------------------------------------- registry

    def add(self, reg: ObsRegistration) -> None:
        with self._lock:
            self._regs[reg.key] = reg

    def remove(self, key: str) -> int:
        """Drop a registration; returns how many remain."""
        with self._lock:
            self._regs.pop(key, None)
            return len(self._regs)

    def registrations(self) -> List[ObsRegistration]:
        with self._lock:
            return list(self._regs.values())

    # ------------------------------------------------------------ documents

    def metrics_text(self) -> str:
        snaps = []
        for reg in self.registrations():
            try:
                # Pre-scrape hook: fold the goodput ledger into gauges so
                # the exposition carries the CURRENT chip-time accounting
                # (the registry is otherwise only written on events).
                refresh = getattr(reg.telemetry,
                                  "refresh_goodput_gauges", None)
                if refresh is not None:
                    refresh()
                snaps.append((reg.labels,
                              reg.telemetry.metrics.snapshot()))
            except Exception:  # noqa: BLE001 - one experiment must not break the scrape
                continue
            if reg.snapshots_fn is not None:
                try:
                    snaps.extend(reg.snapshots_fn())
                except Exception:  # noqa: BLE001 - federation must not break the scrape
                    pass
        return render_prometheus(snaps)

    def status_doc(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"t": time.time(), "experiments": {}}
        for reg in self.registrations():
            doc: Dict[str, Any] = {"labels": reg.labels}
            try:
                doc["telem"] = reg.telemetry.snapshot()
                # Operator headline: the ledger's roll-up hoisted out of
                # the full spans block (which carries the detail).
                gp = (doc["telem"].get("spans") or {}).get("goodput") or {}
                if gp:
                    doc["goodput"] = {
                        "fraction": gp.get("goodput_fraction"),
                        "unaccounted_fraction":
                            gp.get("unaccounted_fraction"),
                        "held_chip_s": round(
                            gp.get("held_chip_s") or 0.0, 1),
                        "badput_top": gp.get("badput_top") or []}
            except Exception as e:  # noqa: BLE001 - scrape must degrade, not die
                doc["telem"] = {"error": repr(e)}
            if reg.status_fn is not None:
                try:
                    doc["status"] = reg.status_fn()
                except Exception as e:  # noqa: BLE001
                    doc["status"] = {"error": repr(e)}
            out["experiments"][reg.key] = doc
        return out

    def health_doc(self) -> Tuple[int, Dict[str, Any]]:
        """(http_status, body): 503 when any registered experiment has an
        active raised finding, 200 otherwise (200/"idle" with nothing
        registered — an empty fleet host is healthy)."""
        regs = self.registrations()
        if not regs:
            return 200, {"status": "idle", "experiments": {}}
        exps: Dict[str, Any] = {}
        unhealthy = False
        for reg in regs:
            if reg.health is None:
                exps[reg.key] = {"flags": [], "engine": "off"}
                continue
            try:
                snap = reg.health.snapshot()
            except Exception as e:  # noqa: BLE001
                exps[reg.key] = {"error": repr(e)}
                continue
            flags = snap.get("flags") or []
            unhealthy |= bool(flags)
            exps[reg.key] = {"flags": flags,
                             "raised_total": snap.get("raised_total")}
        return (503 if unhealthy else 200), \
            {"status": "unhealthy" if unhealthy else "ok",
             "experiments": exps}

    def profile(self, params: Dict[str, List[str]]) -> Tuple[int,
                                                             Dict[str, Any]]:
        want = (params.get("experiment") or [None])[0]
        try:
            duration = float((params.get("duration_s") or ["2.0"])[0])
        except ValueError:
            return 400, {"error": "duration_s must be a number"}
        duration = max(0.05, min(duration, 60.0))
        reg = next((r for r in self.registrations()
                    if r.profiler is not None
                    and (want is None or r.key == want)), None)
        if reg is None:
            return 404, {"error": "no registered experiment with a "
                                  "profiler (experiment={!r})".format(want)}
        record = reg.profiler.capture(duration_s=duration, reason="manual")
        if record.get("skipped"):
            return 409, record
        return 200, {"experiment": reg.key, **record}

    # ------------------------------------------------------------ lifecycle

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


class _ObsHandler(BaseHTTPRequestHandler):
    # Scrapers poll at Hz rates; default per-request stderr logging would
    # drown the driver's own output.
    def log_message(self, format, *args):  # noqa: A002 - stdlib signature
        pass

    def _send(self, code: int, body: bytes, content_type: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, code: int, doc: Dict[str, Any]) -> None:
        self._send(code, json.dumps(doc, default=str).encode(),
                   "application/json")

    def do_GET(self):  # noqa: N802 - stdlib casing
        obs: ObsServer = self.server.obs
        parsed = urlparse(self.path)
        try:
            if parsed.path == "/metrics":
                self._send(200, obs.metrics_text().encode(),
                           "text/plain; version=0.0.4; charset=utf-8")
            elif parsed.path == "/status":
                self._send_json(200, obs.status_doc())
            elif parsed.path == "/healthz":
                code, doc = obs.health_doc()
                self._send_json(code, doc)
            elif parsed.path == "/profilez":
                code, doc = obs.profile(parse_qs(parsed.query))
                self._send_json(code, doc)
            else:
                self._send_json(404, {
                    "error": "unknown route",
                    "routes": ["/metrics", "/status", "/healthz",
                               "/profilez?duration_s=N"]})
        except (BrokenPipeError, ConnectionResetError):
            pass  # scraper went away mid-reply
        except Exception as e:  # noqa: BLE001 - a scrape bug must not kill the thread
            try:
                self._send_json(500, {"error": repr(e)})
            except OSError:
                pass


# ------------------------------------------------- process-wide singleton

_LOCK = threading.Lock()
_SERVER: Optional[ObsServer] = None


def register(reg: ObsRegistration, port: int,
             host: str = "127.0.0.1") -> ObsServer:
    """Register an experiment with the process obs server, starting it on
    first use. ``port`` 0 binds an ephemeral port (the caller journals
    the bound address as an ``obs_started`` event so tools can discover
    it). A server already running keeps ITS bind — one obs server per
    process is the contract, so a second experiment's differing
    port/host request joins the existing listener rather than opening a
    second socket."""
    global _SERVER
    with _LOCK:
        if _SERVER is None:
            _SERVER = ObsServer(host=host, port=int(port))
        server = _SERVER
        # add() must happen under the module lock: a concurrent
        # deregister() of the last OTHER registration would otherwise
        # stop the server between our read and our add, leaving this
        # experiment attached to a closed socket.
        server.add(reg)
    return server


def deregister(reg: ObsRegistration) -> None:
    """Remove a registration; the listener closes when the last one
    leaves (tests and short-lived drivers must not leak sockets)."""
    global _SERVER
    with _LOCK:
        server = _SERVER
        if server is None:
            return
        remaining = server.remove(reg.key)
        if remaining > 0:
            return
        _SERVER = None
    server.stop()


def active_server() -> Optional[ObsServer]:
    """The process's running obs server, or None. Discovery hook for
    in-process tooling (the chaos soak scraper, tests)."""
    with _LOCK:
        return _SERVER
