"""On-demand and health-triggered device profiling.

The TPUv4 scaling experience (PAPERS.md) is that step-time regressions
are only diagnosable from a device profile captured AT the anomaly — a
post-hoc journal line says a partition straggled, not why. This module
owns that capture:

- ``ProfileCapturer.capture``: one bounded ``jax.profiler`` trace window
  written under ``<exp_dir>/profiles/<stamp>/`` together with a
  faulthandler all-threads dump (``threads.txt``). The dump lands FIRST
  and the ``profile_captured`` journal event is recorded as soon as the
  artifact directory is real — a hard kill mid-trace still leaves a
  linked, inspectable artifact. ``jax.profiler`` being unavailable (or
  already tracing for ``config.profile``) degrades to the dump alone,
  recorded as ``profiler: "unavailable"``.
- ``ProfileCapturer.auto_capture``: the HealthEngine's hook — the FIRST
  ``straggler``/``hang`` raise per partition triggers a background
  capture, rate-limited to one per partition and ``AUTO_CAPTURE_LIMIT``
  per run so a flapping fleet cannot profile itself to death. Runs on
  its own daemon thread: the health check cadence never blocks on a
  trace window.

Captures journal a ``profile_captured`` event (path, reason, check,
partition, duration) so ``monitor`` and the Perfetto export can link the
artifact to the moment of the anomaly.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, Optional

#: Auto (health-triggered) captures per run, across all partitions.
AUTO_CAPTURE_LIMIT = 2

#: Trace window for auto captures, seconds. Short on purpose: the stalled
#: partition is stalled NOW, and the capture must land in the journal
#: before the experiment can wind down.
AUTO_CAPTURE_DURATION_S = 0.5

#: Health checks that trigger an auto capture (mirrors the stall checks
#: the chaos harness asserts on).
AUTO_CAPTURE_CHECKS = ("straggler", "hang")


class ProfileCapturer:
    """Capture coordinator for one experiment. Thread-safe; at most one
    capture in flight at a time (the device profiler is a global)."""

    def __init__(self, telemetry, profile_dir: str,
                 auto_limit: int = AUTO_CAPTURE_LIMIT,
                 auto_duration_s: float = AUTO_CAPTURE_DURATION_S):
        self.telemetry = telemetry
        self.profile_dir = profile_dir
        self.auto_limit = int(auto_limit)
        self.auto_duration_s = float(auto_duration_s)
        self._lock = threading.Lock()
        self._busy = False  # guarded-by: _lock
        self._seq = 0  # guarded-by: _lock
        #: Partitions whose first straggler/hang raise already captured.
        self._auto_partitions: set = set()  # guarded-by: _lock
        self._auto_count = 0  # guarded-by: _lock

    # -------------------------------------------------------------- capture

    def capture(self, duration_s: float = 2.0, reason: str = "manual",
                check: Optional[str] = None, partition=None,
                trial: Optional[str] = None) -> Dict[str, Any]:
        """Run one capture window synchronously on the CALLER's thread
        (obs /profilez handlers run per-request threads; auto captures
        come through ``auto_capture``'s worker). Returns the journaled
        record, or ``{"skipped": ...}`` when a capture is already in
        flight."""
        with self._lock:
            if self._busy:
                return {"skipped": "capture already in flight"}
            self._busy = True
            self._seq += 1
            seq = self._seq
        try:
            stamp = "{}_{:03d}_{}".format(int(time.time()), seq, reason)
            if partition is not None:
                stamp += "_p{}".format(partition)
            target = os.path.join(self.profile_dir, stamp)
            os.makedirs(target, exist_ok=True)
            # The thread dump is the cheap, always-available half of the
            # artifact — written before the trace so even a failed or
            # interrupted profiler leaves evidence.
            from maggy_tpu.telemetry.health import thread_dump

            try:
                with open(os.path.join(target, "threads.txt"), "w") as f:
                    f.write(thread_dump(max_bytes=1 << 20))
            except OSError:
                pass
            record: Dict[str, Any] = {
                "path": target, "reason": reason,
                "duration_s": round(float(duration_s), 3)}
            if check is not None:
                record["check"] = check
            if partition is not None:
                record["partition"] = partition
            if trial is not None:
                record["trial"] = trial
            # Journal BEFORE the trace attempt: the artifact directory
            # (with the dump) is already real, and jax.profiler's FIRST
            # start_trace can take ~10 s of one-time init — a journal
            # write deferred past it can miss a winding-down experiment
            # entirely (and a crash inside the trace window must not
            # orphan the artifact either way).
            self.telemetry.event("profile_captured", **record)
            started = self._start_trace(target)
            record["profiler"] = "jax" if started is True \
                else "unavailable"
            if started is not True:
                record["profiler_error"] = started
            if started is True:
                time.sleep(float(duration_s))
                self._stop_trace()
            return record
        finally:
            with self._lock:
                self._busy = False

    @staticmethod
    def _start_trace(target: str):
        """True on success, else the error repr (jax absent, profiler
        already active for config.profile, unsupported backend...)."""
        try:
            import jax

            jax.profiler.start_trace(target)
            return True
        except Exception as e:  # noqa: BLE001 - capture must degrade, never raise
            return repr(e)

    @staticmethod
    def _stop_trace() -> None:
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception:  # noqa: BLE001
            pass

    # --------------------------------------------------------- auto capture

    def auto_capture(self, check: str, partition,
                     trial: Optional[str] = None) -> bool:
        """Health-engine hook: capture on the first straggler/hang raise
        per partition (max ``auto_limit`` per run). Returns whether a
        capture was started; the capture itself runs on a daemon thread
        so the health-check loop keeps its cadence."""
        if check not in AUTO_CAPTURE_CHECKS or partition is None:
            return False
        with self._lock:
            if partition in self._auto_partitions \
                    or self._auto_count >= self.auto_limit:
                return False
            self._auto_partitions.add(partition)
            self._auto_count += 1
        threading.Thread(
            target=self._auto_worker, args=(check, partition, trial),
            daemon=True, name="telemetry-profile").start()
        return True

    def _auto_worker(self, check: str, partition, trial) -> None:
        """Capture for one health-flagged partition, WAITING OUT a busy
        capturer instead of losing the slot: correlated stalls flag two
        partitions in one health pass, and the first capture can hold
        ``_busy`` for ~10 s (profiler init) — a skip here would burn the
        second partition's once-per-run slot with no artifact. If the
        capturer is still busy after the wait window, the slot is rolled
        back so a later re-raise can try again."""
        deadline = time.monotonic() + 30.0
        while True:
            record = self.capture(duration_s=self.auto_duration_s,
                                  reason="auto", check=check,
                                  partition=partition, trial=trial)
            if not record.get("skipped"):
                return
            if time.monotonic() > deadline:
                break
            time.sleep(0.25)
        with self._lock:
            self._auto_partitions.discard(partition)
            self._auto_count -= 1
