"""Runner-side telemetry: the worker's half of the observability stack.

The driver's spans (spans.py) see every control-plane hop, but until now
the runners themselves were blind — no step cadence, compile-stall signal,
heartbeat round-trip time, or memory attribution ever left the worker.
``RunnerStats`` is the lightweight buffer each trial executor owns:

- **train_fn start/end** (``trial_start``/``trial_end``) — wall attribution
  for the time the runner actually spent inside user code;
- **metric-broadcast cadence** (``on_broadcast``, hooked from
  ``Reporter.broadcast``) — an EWMA of the inter-broadcast gap, the
  runner-observed step rate the health engine's straggler scoring feeds on;
- **time-to-first-metric** — trial start to first broadcast, the
  compile-stall proxy (XLA compiles inside the first step);
- **heartbeat round-trip time** (``observe_hb_rtt``, measured in
  ``Client.start_heartbeat``) — control-plane latency as the runner
  experiences it, retries and backoff included;
- **process RSS / device memory** — sampled at most every
  ``mem_interval_s`` via /proc (no psutil) and, when a JAX backend is
  already initialized in this process, ``device.memory_stats()``;
- **compile attribution** (``note_compile``, fed by the warm harness in
  train/warm.py) — the opaque ttfm split into phases: ``init_ms`` (sharded
  state init), ``trace_ms``/``compile_ms`` (the AOT-split jaxpr trace and
  XLA compile of the train step), ``first_step_ms`` (the residual at first
  broadcast: dispatch + the first steps' device execution + input
  staging), plus the trial's ``warm`` flag. Shipped once per trial as a
  ``compile_events`` record (drained like ``profile_skipped``, requeued on
  a failed beat) and journaled by the driver as a ``compiled`` span phase;
- **warm/cache counters** (``note_counter``) — cumulative warm-slot and
  persistent-compilation-cache hits/misses, attributed to THIS runner (a
  thread-pooled process shares jax.monitoring globals, so the warm
  harness routes counts through the trial scope to the right executor's
  buffer).

Shipping is piggybacked on the existing heartbeat METRIC payload
(``rstats`` field) — no new socket, no new verb. ``snapshot_delta()``
returns only the fields that changed since the last successful ship
(delta-encoded, bounded to a handful of scalars), so a steady-state
runner adds a few bytes per beat. Every record path is in-memory
arithmetic under one small lock; the only syscalls are the rate-limited
memory probes on the heartbeat thread.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional

#: EWMA smoothing for cadence / RTT (~last 10 observations dominate).
_EWMA_ALPHA = 0.2

#: Keys in a shipped delta that evidence TRIAL progress (new broadcasts /
#: a trial boundary), as opposed to liveness-only fields (hb_rtt_ms, rss)
#: a wedged-but-beating runner keeps updating. The driver's hang watchdog
#: counts only these as progress.
PROGRESS_KEYS = ("trial", "steps", "ttfm_ms", "cadence_ms", "trials_done")

#: Sentinel distinguishing "never shipped" from "shipped as None" in the
#: delta ledger: trial/ttfm_ms legitimately TRANSITION to None, and a
#: plain .get(k) would read a requeued (deleted) key as already-None and
#: silently drop the re-send.
_NEVER_SHIPPED = object()


def _rss_mb() -> Optional[float]:
    """Resident set size in MB, dependency-free (Linux /proc, getrusage
    fallback)."""
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return pages * os.sysconf("SC_PAGE_SIZE") / 1e6
    except Exception:  # noqa: BLE001 - non-Linux
        try:
            import resource

            # ru_maxrss: KB on Linux, bytes on macOS — close enough for a
            # fallback gauge (the primary path is /proc).
            ru = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
            return ru / 1024.0 if sys.platform != "darwin" else ru / 1e6
        except Exception:  # noqa: BLE001
            return None


def _device_mem_mb() -> Optional[float]:
    """bytes_in_use of the first local device, when a JAX backend already
    lives in this process. NEVER triggers a jax import or backend init —
    a heartbeat thread must not pay a multi-second TPU client startup for
    a gauge (a blocked beat reads as runner death)."""
    jax = sys.modules.get("jax")
    if jax is None:
        return None
    try:
        # Peek at the backend registry WITHOUT initializing: local_devices()
        # on a cold process would bring the whole TPU client up.
        xla_bridge = sys.modules.get("jax._src.xla_bridge")
        if xla_bridge is None or not getattr(xla_bridge, "_backends", None):
            return None
        devices = jax.local_devices()
        if not devices:
            return None
        stats = devices[0].memory_stats()
        if stats and stats.get("bytes_in_use") is not None:
            return round(stats["bytes_in_use"] / 1e6, 1)
    except Exception:  # noqa: BLE001 - backend without memory_stats
        return None
    return None


class RunnerStats:
    """Thread-safe runner-side stat buffer with delta-encoded shipping."""

    def __init__(self, mem_interval_s: float = 2.0):
        self._lock = threading.Lock()
        self.mem_interval_s = mem_interval_s
        self._trial_id: Optional[str] = None  # guarded-by: _lock
        self._trial_t0: Optional[float] = None  # guarded-by: _lock # monotonic train start
        self._last_broadcast: Optional[float] = None  # guarded-by: _lock
        self._steps = 0  # guarded-by: _lock # broadcasts within the current trial
        self._trials_done = 0  # guarded-by: _lock
        self._cadence_ms: Optional[float] = None  # guarded-by: _lock
        self._ttfm_ms: Optional[float] = None  # guarded-by: _lock
        self._hb_rtt_ms: Optional[float] = None  # guarded-by: _lock
        self._rss_mb: Optional[float] = None  # guarded-by: _lock
        self._dev_mem_mb: Optional[float] = None  # guarded-by: _lock
        self._last_mem_sample = 0.0  # guarded-by: _lock
        self._profile_skipped: List[str] = []  # guarded-by: _lock
        self._last_shipped: Dict[str, Any] = {}  # guarded-by: _lock
        # Compile attribution for the CURRENT trial (merged by
        # note_compile; *_ms fields accumulate across e.g. the per-shape
        # AOT compiles of one trial) and the finished records awaiting
        # shipment (ship-once channel, requeued on a failed beat).
        self._compile: Dict[str, Any] = {}  # guarded-by: _lock
        self._compile_final = False  # guarded-by: _lock
        self._ttfm_accounted: Optional[float] = None  # guarded-by: _lock
        self._compile_events: List[Dict[str, Any]] = []  # guarded-by: _lock
        # Checkpoint I/O attribution for the CURRENT trial (merged by
        # note_ckpt; save_ms/restore_ms accumulate across the trial's
        # saves/restores) and the finished records awaiting shipment —
        # the goodput ledger's ckpt_save/ckpt_restore buckets fold from
        # the journaled "ckpt_saved" span phase this becomes.
        self._ckpt: Dict[str, Any] = {}  # guarded-by: _lock
        self._ckpt_final = False  # guarded-by: _lock
        self._ckpt_events: List[Dict[str, Any]] = []  # guarded-by: _lock
        # Cumulative warm-slot / compilation-cache counters for THIS
        # runner (train/warm.py routes them here through the trial scope).
        self._counters: Dict[str, int] = {}  # guarded-by: _lock

    # ----------------------------------------------------------- recording

    def trial_start(self, trial_id: str) -> None:
        """The executor accepted a trial and is about to enter train_fn."""
        with self._lock:
            self._trial_id = trial_id
            self._trial_t0 = time.monotonic()
            self._last_broadcast = None
            self._steps = 0
            self._ttfm_ms = None
            self._compile = {}
            self._compile_final = False
            self._ttfm_accounted = None
            self._ckpt = {}
            self._ckpt_final = False

    def trial_end(self, trial_id: Optional[str] = None) -> None:
        with self._lock:
            if trial_id is not None and trial_id != self._trial_id:
                return
            # The record ships at trial END, not first metric: phases
            # recorded AFTER the first broadcast (a second batch shape
            # compiling mid-trial) still accumulate into the one record.
            # A trial that never broadcast (errored / metric-free) ships
            # too — without the ttfm-derived first_step_ms residual.
            self._finalize_compile_locked()
            self._finalize_ckpt_locked()
            self._trials_done += 1
            self._trial_id = None
            self._trial_t0 = None

    # locked-by: _lock
    def _finalize_compile_locked(self) -> None:
        if self._compile_final or not self._compile:
            return
        record = dict(self._compile)
        record["trial"] = self._trial_id
        if self._ttfm_ms is not None:
            record["ttfm_ms"] = round(self._ttfm_ms, 1)
            # Residual vs the phases accounted BEFORE the first metric
            # (snapshotted in on_broadcast) — a post-first-metric compile
            # is not part of ttfm and must not eat into the residual.
            record["first_step_ms"] = round(
                max(0.0, self._ttfm_ms - (self._ttfm_accounted or 0.0)), 1)
        for k in ("init_ms", "trace_ms", "compile_ms"):
            if k in record:
                record[k] = round(record[k], 1)
        self._compile_events.append(record)
        self._compile_final = True

    # locked-by: _lock
    def _finalize_ckpt_locked(self) -> None:
        if self._ckpt_final or not self._ckpt:
            return
        record = dict(self._ckpt)
        record["trial"] = self._trial_id
        for k in ("save_ms", "restore_ms"):
            if k in record:
                record[k] = round(record[k], 1)
        self._ckpt_events.append(record)
        self._ckpt_final = True

    def note_ckpt(self, **fields: Any) -> None:
        """Merge checkpoint I/O attribution for the current trial.
        ``*_ms`` fields and the ``saves``/``restores`` counts ACCUMULATE
        (a trial checkpoints many times); others are first-write-wins."""
        with self._lock:
            for k, v in fields.items():
                if k.endswith("_ms"):
                    self._ckpt[k] = self._ckpt.get(k, 0.0) + float(v)
                elif k in ("saves", "restores"):
                    self._ckpt[k] = int(self._ckpt.get(k, 0)) + int(v)
                else:
                    self._ckpt.setdefault(k, v)

    def note_compile(self, **fields: Any) -> None:
        """Merge compile-phase attribution for the current trial.
        ``*_ms`` fields ACCUMULATE (a trial may compile several batch
        shapes, before or after its first metric); others are
        first-write-wins."""
        with self._lock:
            for k, v in fields.items():
                if k.endswith("_ms"):
                    self._compile[k] = self._compile.get(k, 0.0) + float(v)
                else:
                    self._compile.setdefault(k, v)

    def note_counter(self, key: str, n: int = 1) -> None:
        """Bump a cumulative runner counter (warm_hits/warm_misses/
        xla_cache_hits/xla_cache_misses)."""
        with self._lock:
            self._counters[key] = self._counters.get(key, 0) + n

    def on_broadcast(self, step: Optional[int] = None) -> None:
        """One reporter.broadcast from the training loop. Pure arithmetic —
        this rides the user's step cadence."""
        now = time.monotonic()
        with self._lock:
            self._steps += 1
            if self._ttfm_ms is None and self._trial_t0 is not None:
                self._ttfm_ms = (now - self._trial_t0) * 1e3
                # First metric: snapshot the phase time attributed so far
                # — the residual (ttfm minus this) is the first steps'
                # actual execution (+ input staging). The record itself
                # ships at trial end so later compiles still accumulate.
                self._ttfm_accounted = sum(
                    self._compile.get(k) or 0.0
                    for k in ("init_ms", "trace_ms", "compile_ms"))
            if self._last_broadcast is not None:
                gap_ms = (now - self._last_broadcast) * 1e3
                self._cadence_ms = gap_ms if self._cadence_ms is None else \
                    (1 - _EWMA_ALPHA) * self._cadence_ms + _EWMA_ALPHA * gap_ms
            self._last_broadcast = now

    def observe_hb_rtt(self, rtt_ms: float) -> None:
        with self._lock:
            self._hb_rtt_ms = rtt_ms if self._hb_rtt_ms is None else \
                (1 - _EWMA_ALPHA) * self._hb_rtt_ms + _EWMA_ALPHA * rtt_ms

    def note_profile_skipped(self, trial_id: Optional[str]) -> None:
        """The profiler lock was contended: this trial runs untraced.
        Shipped to the driver so the missing TensorBoard trace is
        explainable from the journal."""
        if trial_id:
            with self._lock:
                self._profile_skipped.append(trial_id)

    # ------------------------------------------------------------ shipping

    def _maybe_sample_memory(self) -> None:
        """Rate-limited memory probes, performed OUTSIDE the lock: the
        /proc read and device.memory_stats() can block, and broadcast()
        on the training hot path takes the same lock — the probe must
        never inject stalls into the cadence it measures."""
        now = time.monotonic()
        with self._lock:
            if now - self._last_mem_sample < self.mem_interval_s:
                return
            self._last_mem_sample = now
        rss = _rss_mb()
        dev = _device_mem_mb()
        with self._lock:
            if rss is not None:
                self._rss_mb = round(rss, 1)
            if dev is not None:
                self._dev_mem_mb = dev

    def snapshot(self) -> Dict[str, Any]:
        """Full current stat dict (rounded). ``trial`` and ``ttfm_ms`` are
        kept even when None — they legitimately TRANSITION to None at a
        trial boundary, and the delta encoding must be able to ship that
        transition (or the driver's merged state would claim a finished
        trial forever). The remaining fields only ever go None -> value,
        so their Nones are omitted as start-up noise."""
        self._maybe_sample_memory()
        with self._lock:
            snap: Dict[str, Any] = {
                "trial": self._trial_id,
                "steps": self._steps,
                "trials_done": self._trials_done,
                "ttfm_ms": None if self._ttfm_ms is None
                else round(self._ttfm_ms, 1),
                "cadence_ms": None if self._cadence_ms is None
                else round(self._cadence_ms, 1),
                "hb_rtt_ms": None if self._hb_rtt_ms is None
                else round(self._hb_rtt_ms, 2),
                "rss_mb": self._rss_mb,
                "dev_mem_mb": self._dev_mem_mb,
            }
            snap.update(self._counters)
        return {k: v for k, v in snap.items()
                if v is not None or k in ("trial", "ttfm_ms")}

    def snapshot_delta(self) -> Dict[str, Any]:
        """Fields changed since the last ship, plus any pending
        profile_skipped trial ids and finished compile records (both
        drained, ship-once). Empty dict = nothing to ship (the caller
        omits the ``rstats`` payload field entirely)."""
        current = self.snapshot()
        with self._lock:
            delta = {k: v for k, v in current.items()
                     if self._last_shipped.get(k, _NEVER_SHIPPED) != v}
            self._last_shipped.update(delta)
            if self._profile_skipped:
                delta["profile_skipped"] = self._profile_skipped
                self._profile_skipped = []
            if self._compile_events:
                delta["compile_events"] = self._compile_events
                self._compile_events = []
            if self._ckpt_events:
                delta["ckpt_events"] = self._ckpt_events
                self._ckpt_events = []
        return delta

    def requeue_delta(self, delta: Dict[str, Any]) -> None:
        """A ship failed (heartbeat ConnectionError): put the delta back so
        the next beat re-sends it instead of silently losing the fields."""
        if not delta:
            return
        with self._lock:
            skipped = delta.get("profile_skipped") or []
            self._profile_skipped = list(skipped) + self._profile_skipped
            events = delta.get("compile_events") or []
            self._compile_events = list(events) + self._compile_events
            ckpts = delta.get("ckpt_events") or []
            self._ckpt_events = list(ckpts) + self._ckpt_events
            for k, v in delta.items():
                if k not in ("profile_skipped", "compile_events",
                             "ckpt_events") \
                        and self._last_shipped.get(k) == v:
                    del self._last_shipped[k]
