"""Fleet-wide telemetry fan-in: the journal SINK service + client shipper.

PR 12 made the fleet span processes and hosts, but every journal stayed
local: each agent wrote a private ``agent.jsonl``, each tenant a private
``telemetry.jsonl`` with its own flusher thread, and the 500-tenant
churn bench simply disabled telemetry because 500 live journals measure
journal fan-out, not the scheduler. This module gives the fleet ONE
causally-consistent telemetry plane:

- **Client side** — ``SinkJournal`` is a drop-in for
  ``TelemetryJournal`` inside the ``Telemetry`` facade: ``record()``
  stamps every event with a per-source monotonic ``sid`` (the event id
  the exactly-once contract is keyed on) and buffers it; a process-wide
  ``SinkShipper`` (ONE thread no matter how many tenants share it)
  batches the unshipped suffix of every attached journal and ships it
  over the fleet's existing shared socket as a ``JSINK`` frame —
  HMAC-routed to the fleet's ``SinkServer`` tenant like every other
  verb. Cheap churn tenants get telemetry back for free: no per-tenant
  flusher thread, no per-tenant file.
- **Fleet side** — ``JournalSink`` demuxes each batch into per-source
  journals under ``<home>/journal/<source>.jsonl`` (PR 9's rotation, one
  shared flusher for all sources), dedupes re-shipped events by ``sid``,
  journals a ``jsink`` ingest record per batch into the fleet journal
  (ingest lag is replayable offline), and FEDERATES each source's
  shipped metric counters so one Prometheus scrape of the fleet host's
  ``/metrics`` sees the whole fleet.
- **Degradation, not domination** — a dead or backpressured sink makes
  the shipper fall back to the source's LOCAL journal file (journaled
  ``sink_degraded``), keep the unacked suffix spooled, and re-ship it on
  reconnect (``sink_recovered``). The sink's ``sid`` dedup plus the
  readers' merge dedup (``merge_source_events``) give exactly-once per
  event id across the fallback seam — chaos invariant 12
  (``python -m maggy_tpu.chaos --sink``) kills the sink mid-soak and
  asserts zero lost events, zero duplicates, zero experiment failures.
- **Clock alignment** — ``ClockOffsetEstimator`` turns the agents'
  AJOIN/ALEASE exchanges into an RTT-bounded clock-offset estimate
  (Cristian's algorithm with a min-RTT filter, so re-estimation
  converges monotonically); the fleet journals it per agent as
  ``clock_offset`` events, and ``telemetry trace --unified`` uses the
  offsets to merge fleet + sink + local journals into ONE Perfetto
  trace with cross-process flow arrows.
"""

from __future__ import annotations

import os
import socket
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from maggy_tpu.telemetry.journal import (JournalEvents, TelemetryJournal,
                                         read_events)

#: Directory (under the fleet home) the sink demuxes per-source journals
#: into — the fleet's unified journal dir.
SINK_DIR_NAME = "journal"

#: Default shipper flush cadence. Short: the sink is on the same
#: control-plane network as the heartbeats, and small batches keep the
#: ingest lag (and the loss window on a hard kill) bounded.
SHIP_INTERVAL_S = 0.25

#: Events per JSINK frame. Batches beyond this split across frames —
#: well under MAX_FRAME even for log-heavy events.
SHIP_BATCH_EVENTS = 400

#: Cadence at which a shipper re-sends the per-source metric counter
#: snapshot for fleet-side federation (every batch would be wasteful for
#: an idle source).
COUNTER_SHIP_INTERVAL_S = 2.0

#: A clock-offset estimate older than this is re-anchored even if its
#: RTT is worse than the best seen — clocks drift, and a minutes-old
#: tight bound is a lie.
OFFSET_MAX_AGE_S = 60.0

#: How often an agent reports its current offset estimate to the fleet
#: (piggybacked on its ALEASE poll; also reported immediately whenever
#: the estimate improves).
OFFSET_REPORT_INTERVAL_S = 5.0


def sanitize_source(source: str) -> str:
    """Filename-safe source id (one journal file per source)."""
    return "".join(ch if ch.isalnum() or ch in "-_." else "_"
                   for ch in str(source)) or "unknown"


# ------------------------------------------------------------ clock offset


class ClockOffsetEstimator:
    """RTT-based clock-offset estimate between this process and a server
    (Cristian's algorithm): for one request/reply exchange timed locally
    as ``t_send``/``t_recv`` around a reply carrying the server's
    ``server_t``, the server clock read maps to local time
    ``(t_send + t_recv) / 2`` with error at most ``rtt / 2`` — so
    ``offset_s = (t_send + t_recv) / 2 - server_t`` is the local clock's
    lead over the server's, bounded by ``bound_s = rtt / 2``.

    A min-RTT filter makes re-estimation converge monotonically: a new
    sample replaces the estimate only when its RTT (and therefore its
    error bound) is no worse than the current one, unless the estimate
    aged past ``max_age_s`` (clock drift makes an old tight bound
    worthless, so staleness re-anchors unconditionally). Not
    thread-safe: one estimator per polling loop.
    """

    def __init__(self, max_age_s: float = OFFSET_MAX_AGE_S):
        self.max_age_s = float(max_age_s)
        self.offset_s: Optional[float] = None
        self.rtt_s: Optional[float] = None
        self.bound_s: Optional[float] = None
        self.samples = 0
        self._estimate_t: Optional[float] = None

    def sample(self, t_send: float, server_t: Optional[float],
               t_recv: float) -> bool:
        """Feed one exchange; returns True when the estimate updated.
        All timestamps are caller-supplied (testable with fake clocks):
        ``t_send``/``t_recv`` on the LOCAL clock, ``server_t`` on the
        server's."""
        if server_t is None:
            return False
        rtt = t_recv - t_send
        if rtt < 0:
            return False
        self.samples += 1
        stale = (self._estimate_t is not None
                 and t_recv - self._estimate_t > self.max_age_s)
        if self.bound_s is not None and rtt / 2.0 > self.bound_s \
                and not stale:
            return False
        self.offset_s = (t_send + t_recv) / 2.0 - float(server_t)
        self.rtt_s = rtt
        self.bound_s = rtt / 2.0
        self._estimate_t = t_recv
        return True


# ----------------------------------------------------------- wire client


class SinkBinding:
    """Where a shipper dials: the fleet's shared listener address plus
    the sink tenant's secret (distinct from every experiment's and from
    the fleet-agent secret — a journal shipper cannot lease agents)."""

    def __init__(self, addr: Tuple[str, int], secret: str):
        self.addr = (str(addr[0]), int(addr[1]))
        self.secret = secret

    def key(self) -> Tuple[Tuple[str, int], str]:
        return (self.addr, self.secret)


class _SinkChannel:
    """One persistent authenticated connection to the sink tenant, with
    a single reconnect retry per call (the shipper's own cycle provides
    the outer retry loop)."""

    def __init__(self, addr: Tuple[str, int], secret: str,
                 timeout: float = 5.0):
        self.addr = tuple(addr)
        self.secret = secret.encode() if isinstance(secret, str) else secret
        self.timeout = timeout
        self._sock: Optional[socket.socket] = None

    def call(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        from maggy_tpu.core.rpc import MessageSocket

        for attempt in (0, 1):
            try:
                if self._sock is None:
                    sock = socket.create_connection(self.addr,
                                                    timeout=self.timeout)
                    sock.settimeout(self.timeout)
                    self._sock = sock
                MessageSocket.send_msg(self._sock, msg, self.secret)
                return MessageSocket.recv_msg(self._sock, self.secret)
            except (ConnectionError, socket.timeout, OSError):
                self.close()
                if attempt:
                    raise
        raise ConnectionError("unreachable")

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None


# --------------------------------------------------------- client journal


class SinkJournal:
    """Drop-in journal for the ``Telemetry`` facade that ships its
    events to the fleet's journal sink instead of running a private
    flusher thread. ``record()`` stamps each event with a monotonic
    ``sid`` (the exactly-once event id) and buffers it; the process-wide
    ``SinkShipper`` this journal attaches to drains the unshipped suffix
    on its cadence.

    Degradation contract: when shipping fails (sink dead, sink tenant
    backpressured and shedding frames), the journal records ONE
    ``sink_degraded`` event, persists everything not yet locally durable
    to its ordinary local journal file (``local_path`` — the same
    ``telemetry.jsonl`` a sink-less run would write), and keeps
    retrying; the first successful ship records ``sink_recovered`` and
    re-ships the whole unacked suffix. The sink dedupes by ``sid``, and
    readers merging sink segments with a surviving local journal dedupe
    the same way (``merge_source_events``) — each event id lands exactly
    once in the unified view no matter where the seam fell.
    """

    def __init__(self, env, local_path: str, binding: SinkBinding,
                 source: str,
                 metrics_fn: Optional[Callable[[], Dict[str, Any]]] = None,
                 shipper: Optional["SinkShipper"] = None):
        self.env = env
        self.local_path = local_path
        self.source = sanitize_source(source)
        self.metrics_fn = metrics_fn
        self._lock = threading.Lock()
        self._events: List[Dict[str, Any]] = []  # guarded-by: _lock
        self._sid = 0  # guarded-by: _lock
        #: Leading events acked by the sink (durable fleet-side).
        self._acked = 0  # guarded-by: _lock
        #: Leading events persisted to the local fallback file.
        self._local_flushed = 0  # guarded-by: _lock
        self._closed = False  # guarded-by: _lock
        self._closing = False  # guarded-by: _lock
        self.degraded = False  # unguarded-ok: diagnostic flag written only by the shipper thread's ship_cycle, read by monitors/tests
        self.torn_lines = 0
        self._local_append_ok: Optional[bool] = None  # shipper-thread only
        self._last_counter_ship = 0.0  # shipper-thread only
        if shipper is not None:
            self.shipper = shipper
            shipper.attach(self)
        else:
            # Lookup + attach are ONE atomic step under the registry
            # lock: attaching after get_shipper returned would race a
            # concurrent last-detach stopping the same shipper.
            self.shipper = get_shipper(binding, journal=self)

    # ------------------------------------------------------------- hot path

    def record(self, event: Dict[str, Any]) -> None:
        """Buffer one event, stamped with its per-source event id."""
        with self._lock:
            if self._closed:
                return
            self._sid += 1
            event["sid"] = self._sid
            self._events.append(event)

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    def max_sid(self) -> int:
        with self._lock:
            return self._sid

    # ------------------------------------------------------------- shipping

    def ship_cycle(self, channel: "_SinkChannel",
                   counters: Optional[Dict[str, Any]] = None) -> None:
        """One shipper pass: ship the unacked suffix in bounded batches;
        on failure enter (or stay in) degraded mode and persist the
        not-yet-local suffix to the local journal file. Serialized by
        the shipper's ship lock; ``counters`` is pre-computed by the
        caller OUTSIDE that lock (a metrics snapshot takes the registry
        locks, which the canonical lock order puts before the
        shipper's)."""
        import json

        while True:
            with self._lock:
                start = self._acked
                batch = list(self._events[start:start + SHIP_BATCH_EVENTS])
            # An empty batch still ships while degraded: the probe is
            # what detects recovery for a source that went quiet.
            if not batch and counters is None and not self.degraded:
                return
            try:
                # Wire-safe copy: journal events may hold values only the
                # file writer's default=str serializer accepts; the frame
                # codec (msgpack) must see plain JSON types.
                wire = json.loads(json.dumps(batch, default=str))
                # client_t: this source's wall clock at ship time — the
                # sink derives a SKEW-FREE ingest lag from it (event age
                # measured entirely on the client clock), so remote
                # agents with offset clocks don't poison the lag stats.
                resp = channel.call({"type": "JSINK",
                                     "source": self.source,
                                     "events": wire,
                                     "counters": counters,
                                     "client_t": time.time()})
                if resp.get("type") == "ERR":
                    raise ConnectionError(resp.get("error"))
            except (ConnectionError, socket.timeout, OSError, ValueError,
                    TypeError):
                self._enter_degraded()
                return
            with self._lock:
                # Advance by POSITION, not by the acked sid: after a
                # resume restore the local buffer may start mid-sid-run,
                # and a sid-based cursor could overshoot past events
                # never shipped. The sink acked at least our batch's top
                # sid (its dedup absorbs overlap), so the whole shipped
                # prefix is durable fleet-side.
                self._acked = max(self._acked, start + len(batch))
            counters = None  # shipped at most once per cycle
            if self.degraded:
                self.degraded = False
                self.record({"t": time.time(), "ev": "sink_recovered",
                             "source": self.source})
            if len(batch) < SHIP_BATCH_EVENTS:
                return

    def counters_payload(self) -> Optional[Dict[str, Any]]:
        now = time.monotonic()
        if self.metrics_fn is None \
                or now - self._last_counter_ship < COUNTER_SHIP_INTERVAL_S:
            return None
        self._last_counter_ship = now
        try:
            snap = self.metrics_fn() or {}
        except Exception:  # noqa: BLE001 - metrics must never break shipping
            return None
        return {"counters": snap.get("counters") or {},
                "gauges": snap.get("gauges") or {}}

    def _enter_degraded(self) -> None:
        if not self.degraded:
            self.degraded = True
            self.record({"t": time.time(), "ev": "sink_degraded",
                         "source": self.source})
        self._flush_local()

    def _flush_local(self) -> None:
        """Persist events[_local_flushed:] to the local journal file —
        the degraded-mode durability path. First write is a full atomic
        rewrite (truncates any stale file), later writes append.
        Shipper-thread only (plus the final close())."""
        with self._lock:
            start = self._local_flushed
            total = len(self._events)
            snapshot = list(self._events[start:total])
        if not snapshot:
            return
        import json

        payload = "".join(json.dumps(e, default=str) + "\n"
                          for e in snapshot)
        try:
            if start == 0 or self._local_append_ok is False:
                with self._lock:
                    full = list(self._events[:total])
                payload = "".join(json.dumps(e, default=str) + "\n"
                                  for e in full)
                self.env.dump(payload, self.local_path)
            else:
                try:
                    with self.env.open_file(self.local_path, "a") as f:
                        f.write(payload)
                    self._local_append_ok = True
                except Exception:  # noqa: BLE001 - backend without append
                    self._local_append_ok = False
                    with self._lock:
                        full = list(self._events[:total])
                    payload = "".join(json.dumps(e, default=str) + "\n"
                                      for e in full)
                    self.env.dump(payload, self.local_path)
            with self._lock:
                self._local_flushed = max(self._local_flushed, total)
        except Exception:  # noqa: BLE001 - telemetry must never fail a run
            pass

    # ------------------------------------------------------------ lifecycle

    def load_existing(self) -> int:
        """Resume support: a sink-routed journal's history lives fleet-
        side; only a local fallback file (a previous degraded window) is
        restorable here. Restored events keep their original sids and
        are NOT re-shipped (the sink may already hold them)."""
        try:
            existing = read_events(self.local_path, env=self.env)
        except Exception:  # noqa: BLE001 - no local file = nothing to restore
            return 0
        with self._lock:
            self.torn_lines += getattr(existing, "torn_lines", 0)
            self._events = list(existing) + self._events
            restored = len(existing)
            self._acked += restored
            self._local_flushed += restored
            self._sid = max(self._sid,
                            max((e.get("sid") or 0 for e in existing),
                                default=0))
        return restored

    def flush(self) -> None:
        """Synchronous best-effort drain (finalize paths): ask the
        shipper for an immediate cycle on the caller's thread."""
        self.shipper.flush_now(self)

    def close(self) -> None:
        with self._lock:
            if self._closed or self._closing:
                return
            self._closing = True
        # Drain while still OPEN: if this final ship is the one that
        # recovers a degraded journal, its sink_recovered event must be
        # recordable — closing first would silently drop it (and leave
        # the source flagged DEGRADED in the sink forever).
        self.shipper.flush_now(self)
        with self._lock:
            self._closed = True
        # Second pass ships anything the first one recorded (e.g. the
        # recovery event); then make any tail the sink never took
        # locally durable.
        self.shipper.flush_now(self)
        with self._lock:
            unshipped = self._acked < len(self._events)
        if unshipped:
            self._flush_local()
        self.shipper.detach(self)


class SinkShipper:
    """Process-wide batching shipper: ONE daemon thread drains every
    attached ``SinkJournal`` toward one sink binding — 500 churn tenants
    share one thread and one socket, which is the whole point. Keyed by
    binding in a module registry (``get_shipper``); the thread and the
    connection close when the last journal detaches."""

    def __init__(self, binding: SinkBinding,
                 interval_s: float = SHIP_INTERVAL_S):
        self.binding = binding
        self.interval_s = float(interval_s)
        self._channel = _SinkChannel(binding.addr, binding.secret)
        self._lock = threading.Lock()
        self._journals: List[SinkJournal] = []  # guarded-by: _lock
        # Serializes ship cycles: the flusher thread and a flush_now
        # caller must not interleave batches of one journal.
        self._ship_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="telemetry-sink-ship")
        self._thread.start()

    def attach(self, journal: SinkJournal) -> None:
        with self._lock:
            if journal not in self._journals:
                self._journals.append(journal)

    def detach(self, journal: SinkJournal) -> None:
        """Drop one journal; the LAST detach also retires the shipper
        from the registry and stops it. Registry membership and the
        empty-check are decided under the module registry lock so a
        concurrent ``get_shipper`` can never attach to a shipper that
        is already being stopped."""
        stop = False
        with _SHIPPER_LOCK:
            with self._lock:
                self._journals = [j for j in self._journals
                                  if j is not journal]
                remaining = len(self._journals)
            if remaining == 0:
                if _SHIPPERS.get(self.binding.key()) is self:
                    del _SHIPPERS[self.binding.key()]
                stop = True
        if stop:
            self.stop()

    def flush_now(self, journal: Optional[SinkJournal] = None) -> None:
        targets = [journal] if journal is not None else None
        if targets is None:
            with self._lock:
                targets = list(self._journals)
        self._ship_all(targets)

    def _ship_all(self, journals: List[SinkJournal]) -> None:
        for j in journals:
            try:
                # Metrics snapshot BEFORE the ship lock: the registry
                # locks sit earlier in the canonical acquisition order.
                counters = j.counters_payload()
                with self._ship_lock:
                    j.ship_cycle(self._channel, counters=counters)
            except Exception:  # noqa: BLE001 - one journal must not kill the shipper
                pass

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            with self._lock:
                journals = list(self._journals)
            self._ship_all(journals)

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)
        self._channel.close()


_SHIPPER_LOCK = threading.Lock()
_SHIPPERS: Dict[Tuple[Tuple[str, int], str], SinkShipper] = {}


def get_shipper(binding: SinkBinding,
                journal: Optional[SinkJournal] = None) -> SinkShipper:
    """The process-wide shipper for ``binding`` (started on first use).
    Pass ``journal`` to attach it atomically with the lookup — the only
    race-free way to join a refcounted shipper (a bare lookup could
    return a shipper whose last journal is concurrently detaching,
    which stops it)."""
    with _SHIPPER_LOCK:
        shipper = _SHIPPERS.get(binding.key())
        if shipper is None:
            shipper = SinkShipper(binding)
            _SHIPPERS[binding.key()] = shipper
        if journal is not None:
            shipper.attach(journal)
        return shipper


# ------------------------------------------------------------- fleet side


class JournalSink:
    """The fleet-side journal sink service: demux JSINK batches into
    per-source journal files under ``journal_dir`` (PR 9 rotation, one
    shared flusher thread for ALL sources), dedupe re-shipped events by
    ``sid``, journal a ``jsink`` ingest record per batch into the fleet
    journal (offline-replayable ingest lag), and hold each source's last
    shipped counter snapshot for /metrics federation."""

    def __init__(self, env, journal_dir: str, telemetry=None,
                 max_mb: Optional[float] = None,
                 flush_interval_s: float = 0.5):
        self.env = env
        self.journal_dir = journal_dir.rstrip("/")
        self.telemetry = telemetry
        self.max_mb = max_mb
        self._lock = threading.Lock()
        self._writers: Dict[str, TelemetryJournal] = {}  # guarded-by: _lock
        self._last_sid: Dict[str, int] = {}  # guarded-by: _lock
        self._stats: Dict[str, Dict[str, Any]] = {}  # guarded-by: _lock
        self._federated: Dict[str, Dict[str, Any]] = {}  # guarded-by: _lock
        self._stopped = False  # guarded-by: _lock
        try:
            env.mkdir(self.journal_dir)
        except Exception:  # noqa: BLE001 - writers mkdir through env.dump anyway
            pass
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._flusher, daemon=True,
                                        name="telemetry-sink-flush")
        self._thread.start()

    # -------------------------------------------------------------- ingest

    def ingest(self, source, events, counters=None,
               client_t=None) -> Dict[str, Any]:
        """One JSINK batch. Returns the ack carrying the highest ``sid``
        this sink now holds for the source; the sink's sid dedup absorbs
        re-shipped (lost-ack) batches without duplication. ``client_t``
        is the source's wall clock at ship time: event age is measured
        against it — entirely on the CLIENT clock — so a remote agent's
        clock skew never poisons the lag stats."""
        if not isinstance(source, str) or not source:
            return {"type": "ERR", "error": "JSINK without a source id"}
        source = sanitize_source(source)
        now = time.time()
        events = events if isinstance(events, list) else []
        with self._lock:
            if self._stopped:
                return {"type": "ERR", "error": "journal sink is stopped"}
            writer = self._writers.get(source)
            if writer is None:
                writer = TelemetryJournal(
                    self.env,
                    "{}/{}.jsonl".format(self.journal_dir, source),
                    max_mb=self.max_mb, start_flusher=False)
                self._writers[source] = writer
            last = self._last_sid.get(source, 0)
            stats = self._stats.setdefault(source, {
                "ingested": 0, "dup": 0, "batches": 0, "degraded": False,
                "last_lag_s": None, "last_ingest_t": None})
        fresh: List[Dict[str, Any]] = []
        top = last
        for ev in events:
            if not isinstance(ev, dict):
                continue
            sid = ev.get("sid")
            if isinstance(sid, int):
                if sid <= last:
                    continue
                top = max(top, sid)
            fresh.append(ev)
        for ev in fresh:
            writer.record(ev)
        dup = len(events) - len(fresh)
        lag_ms = None
        event_ts = [ev["t"] for ev in fresh
                    if isinstance(ev.get("t"), (int, float))]
        if event_ts:
            # Skew-free when the shipper stamped its clock: the newest
            # event's age AT SHIP TIME, both ends on the source clock.
            # Fallback (no stamp) compares across clocks — fine for the
            # in-process case, wrong by the skew for remote agents.
            ref = float(client_t) if isinstance(client_t, (int, float)) \
                else now
            lag_ms = max(0.0, (ref - max(event_ts)) * 1e3)
        degraded = None
        for ev in fresh:
            if ev.get("ev") == "sink_degraded":
                degraded = True
            elif ev.get("ev") == "sink_recovered":
                degraded = False
        with self._lock:
            self._last_sid[source] = top
            stats["batches"] += 1
            stats["ingested"] += len(fresh)
            stats["dup"] += dup
            stats["last_ingest_t"] = now
            if lag_ms is not None:
                stats["last_lag_s"] = lag_ms / 1e3
            if degraded is not None:
                stats["degraded"] = degraded
            if isinstance(counters, dict):
                self._federated[source] = {
                    "counters": dict(counters.get("counters") or {}),
                    "gauges": dict(counters.get("gauges") or {})}
        telem = self.telemetry
        if telem is not None:
            telem.metrics.counter("sink.batches").inc()
            telem.metrics.counter("sink.events").inc(len(fresh))
            if dup:
                telem.metrics.counter("sink.dup_drops").inc(dup)
            if lag_ms is not None:
                telem.metrics.histogram("sink.ingest_lag_ms").observe(
                    lag_ms)
            if events:
                # Journaled per non-empty batch — INCLUDING batches the
                # sid dedup fully absorbed (n=0, dup>0): the re-ship
                # window's dedup activity must be replayable, or offline
                # dup counts stay blind to the seam. Empty keepalive
                # probes alone skip.
                telem.event("jsink", source=source, n=len(fresh),
                            dup=dup, sid=top,
                            lag_ms=round(lag_ms, 3)
                            if lag_ms is not None else None)
        return {"type": "OK", "acked": top}

    # ------------------------------------------------------------ querying

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        """Per-source lag view for status.json / ``monitor --fleet``:
        backlog (events buffered fleet-side but not yet flushed to the
        segment files), last-event age, last-ingest age, degraded flag
        (as reported by the source's own journal across the seam)."""
        now = time.time()
        with self._lock:
            out: Dict[str, Dict[str, Any]] = {}
            for source, stats in self._stats.items():
                writer = self._writers.get(source)
                backlog = 0
                if writer is not None:
                    with writer._lock:
                        backlog = len(writer._events) - writer._flushed
                ingest_age = (now - stats["last_ingest_t"]) \
                    if stats["last_ingest_t"] else None
                # Event age = time since last ingest (fleet clock) plus
                # how old the newest event already was AT ingest
                # (client clock) — no cross-clock subtraction, so a
                # skewed remote agent reads true lag, not its offset.
                event_age = None
                if ingest_age is not None:
                    event_age = ingest_age + (stats["last_lag_s"] or 0.0)
                out[source] = {
                    "ingested": stats["ingested"],
                    "batches": stats["batches"],
                    "dup": stats["dup"],
                    "backlog": backlog,
                    "last_sid": self._last_sid.get(source, 0),
                    "degraded": stats["degraded"],
                    "last_event_age_s": round(event_age, 2)
                    if event_age is not None else None,
                    "last_ingest_age_s": round(ingest_age, 2)
                    if ingest_age is not None else None,
                }
            return out

    def federated_snapshots(self) -> List[Tuple[Dict[str, str],
                                                Dict[str, Any]]]:
        """``[(labels, registry-snapshot), ...]`` per source, in the
        shape ``obs.render_prometheus`` consumes — plugged into the
        fleet's obs registration so one scrape of the fleet host exposes
        every agent's and tenant's shipped counters."""
        with self._lock:
            return [({"experiment": source, "via": "jsink"},
                     {"counters": dict(snap.get("counters") or {}),
                      "gauges": dict(snap.get("gauges") or {}),
                      "histograms": {}})
                    for source, snap in sorted(self._federated.items())]

    def source_path(self, source: str) -> str:
        return "{}/{}.jsonl".format(self.journal_dir,
                                    sanitize_source(source))

    # ----------------------------------------------------------- lifecycle

    def _flusher(self) -> None:
        while not self._stop.wait(0.5):
            with self._lock:
                writers = list(self._writers.values())
            for writer in writers:
                writer.flush()

    def stop(self) -> None:
        with self._lock:
            if self._stopped:
                return
            self._stopped = True
            writers = list(self._writers.values())
        self._stop.set()
        self._thread.join(timeout=5)
        for writer in writers:
            writer.close()


# ----------------------------------------------------------- offline read


def sink_sources(journal_dir: str) -> Dict[str, str]:
    """Discover the per-source journals in a sink dir: ``{source:
    path}``. Rotation segments (``<name>.jsonl.000001``) belong to their
    base file and are not separate sources."""
    out: Dict[str, str] = {}
    if not os.path.isdir(journal_dir):
        return out
    for name in sorted(os.listdir(journal_dir)):
        if name.endswith(".jsonl"):
            out[name[:-len(".jsonl")]] = os.path.join(journal_dir, name)
    return out


def read_sink_dir(journal_dir: str) -> Dict[str, JournalEvents]:
    """Read every source's (possibly rotated) journal in a sink dir.
    Torn lines — including a torn tail in a segment the sink is still
    appending — are counted per source, never raised."""
    out: Dict[str, JournalEvents] = {}
    for source, path in sink_sources(journal_dir).items():
        try:
            out[source] = read_events(path)
        except Exception:  # noqa: BLE001 - a half-written source must not block the rest
            empty = JournalEvents()
            empty.torn_lines = 0
            out[source] = empty
    return out


def merge_source_events(*streams: Optional[List[Dict[str, Any]]]
                        ) -> JournalEvents:
    """Merge one source's event streams (sink segments, surviving local
    journal) into a single exactly-once stream: events deduped by their
    ``sid`` event id (first stream wins), events without a sid kept
    verbatim, result ordered by timestamp. ``torn_lines`` sums across
    the inputs."""
    merged = JournalEvents()
    torn = 0
    seen: set = set()
    for stream in streams:
        if not stream:
            continue
        torn += getattr(stream, "torn_lines", 0)
        for ev in stream:
            sid = ev.get("sid") if isinstance(ev, dict) else None
            if isinstance(sid, int):
                if sid in seen:
                    continue
                seen.add(sid)
            merged.append(ev)
    merged.sort(key=lambda e: (e.get("t") or 0.0, e.get("sid") or 0))
    merged.torn_lines = torn
    return merged


def check_exactly_once(merged: List[Dict[str, Any]],
                       expected_max_sid: Optional[int] = None
                       ) -> List[str]:
    """Invariant-12 core: over one source's MERGED stream, every event
    id 1..max must appear exactly once — no gap (a lost event the
    re-ship should have recovered) and no duplicate (a dedup failure
    across the fallback seam). ``expected_max_sid`` additionally pins
    the tail: the source is known to have emitted that many events."""
    violations: List[str] = []
    sids = [ev.get("sid") for ev in merged
            if isinstance(ev, dict) and isinstance(ev.get("sid"), int)]
    counts: Dict[int, int] = {}
    for sid in sids:
        counts[sid] = counts.get(sid, 0) + 1
    dups = sorted(s for s, c in counts.items() if c > 1)
    if dups:
        violations.append(
            "duplicate event id(s) across the fallback seam: "
            "{}".format(dups[:10]))
    top = expected_max_sid if expected_max_sid is not None \
        else (max(counts) if counts else 0)
    missing = sorted(s for s in range(1, top + 1) if s not in counts)
    if missing:
        violations.append(
            "lost event id(s) — never re-shipped and absent from the "
            "local journal: {} of {} (sample {})".format(
                len(missing), top, missing[:10]))
    return violations


__all__ = [
    "SINK_DIR_NAME", "ClockOffsetEstimator", "SinkBinding", "SinkJournal",
    "SinkShipper", "JournalSink", "get_shipper",
    "sink_sources", "read_sink_dir", "merge_source_events",
    "check_exactly_once", "sanitize_source",
    "OFFSET_REPORT_INTERVAL_S",
]
