"""Trial-span tracing: per-trial phase timestamps and derived scheduling
metrics.

A span is minted when the driver creates a trial and its id travels inside
the existing RPC payloads (TRIAL info, METRIC, FINAL, the STOP reply), so
every control-plane hop about a trial can be attributed to one span without
a new wire protocol. Phases:

    queued -> assigned -> running -> first_metric
                                  -> stop_flagged -> finalized

``derive()`` is the single source of truth for the numbers the paper's
scheduling claim rests on — hand-off gap, early-stop reaction latency — and
is a PURE function over journal events: the same event list always yields
the same numbers, whether computed live by the driver, over the TELEM RPC,
or replayed offline from a journal file (bench.py does exactly that).
"""

from __future__ import annotations

import secrets as pysecrets
import threading
import time
from typing import Any, Dict, List, Optional

from maggy_tpu.telemetry.vocab import SPAN_PHASES

#: Trial phases in nominal order (a requeued trial may revisit phases; the
#: journal records every occurrence, derivation picks the appropriate one).
#: ``requeued`` marks a trial re-entering the schedule after runner loss /
#: blacklist — the explicit edge recovery latency derives from (the span's
#: first-occurrence timestamps alone cannot carry it).
#: ``profile_skipped`` is an annotation, not a lifecycle edge: the runner
#: reported the trial ran untraced (profiler lock contended).
#: ``suggested`` marks the controller materializing the trial (possibly
#: well before ``queued`` — the prefetch pipeline runs suggest() ahead of
#: dispatch); ``prefetch_hit`` marks a hand-off served inline on the FINAL
#: reply (journaled on the dispatched trial), ``prefetch_miss`` a FINAL
#: whose freed runner had to fall back to GET polling (journaled on the
#: finalized trial). hit/(hit+miss) is the pipeline's hit rate.
#: ``preempt_requested`` -> ``preempted`` -> ``resumed`` are the
#: checkpoint-assisted preemption edges (fleet scheduling / chaos
#: preempt_trial): requested when the driver arms the preempt flag,
#: preempted when the runner's ack lands (carrying the checkpoint step),
#: resumed when the trial is re-dispatched with a ``resume_step``.
#: ``compiled`` is an annotation carrying the runner-measured ttfm
#: breakdown (warm flag + init_ms/trace_ms/compile_ms/first_step_ms/
#: ttfm_ms — see telemetry/runnerstats.py): warm trials reuse the runner's
#: resident program (train/warm.py), cold trials paid the XLA compile.
#: One home: telemetry/vocab.py — the shared emitter/consumer vocabulary
#: the journalvocab checker (maggy_tpu.analysis) verifies both sides
#: against. Re-exported here for compatibility.
PHASES = SPAN_PHASES

#: Gaps at or above this bound are scheduling (a runner idling on purpose at
#: a rung barrier), not hand-off overhead — excluded from the gap stats.
#: Matches the historical bench.py cap so numbers stay comparable.
HANDOFF_CAP_S = 2.0


class TrialSpan:
    """One trial's phase timeline. ``phases`` keeps the FIRST time each
    phase was observed; the journal keeps every occurrence."""

    __slots__ = ("span_id", "trial_id", "phases", "partition")

    def __init__(self, span_id: str, trial_id: str):
        self.span_id = span_id
        self.trial_id = trial_id
        self.phases: Dict[str, float] = {}
        self.partition: Optional[int] = None

    def to_dict(self) -> Dict[str, Any]:
        return {"span": self.span_id, "trial": self.trial_id,
                "partition": self.partition,
                "phases": {k: round(v, 6) for k, v in self.phases.items()}}


class SpanTracker:
    """Thread-safe span registry keyed by trial id."""

    def __init__(self):
        self._lock = threading.Lock()
        self._spans: Dict[str, TrialSpan] = {}  # guarded-by: _lock

    def mint(self, trial_id: str) -> str:
        """Create (or return) the span for ``trial_id``."""
        with self._lock:
            span = self._spans.get(trial_id)
            if span is None:
                span = TrialSpan(pysecrets.token_hex(6), trial_id)
                self._spans[trial_id] = span
            return span.span_id

    def span_id(self, trial_id: str) -> Optional[str]:
        with self._lock:
            span = self._spans.get(trial_id)
            return span.span_id if span else None

    def partition_of(self, trial_id: str) -> Optional[int]:
        """The trial's LAST observed partition (the fork-affinity hint:
        where the parent's warm slot and checkpoint live), or None."""
        with self._lock:
            span = self._spans.get(trial_id)
            return span.partition if span else None

    def mark(self, trial_id: str, phase: str, t: Optional[float] = None,
             partition: Optional[int] = None) -> tuple:
        """Record ``phase`` on the trial's span (minting it if the caller
        skipped mint — robustness for resumed/requeued trials). Returns
        ``(span_id, first)`` where ``first`` says whether this was the
        phase's first occurrence on the span. Only the first occurrence
        lands in the span's timeline; the caller decides what to journal
        (every occurrence by default, first-only for phases a heartbeat
        loop would otherwise repeat)."""
        t = time.time() if t is None else t
        with self._lock:
            span = self._spans.get(trial_id)
            if span is None:
                span = TrialSpan(pysecrets.token_hex(6), trial_id)
                self._spans[trial_id] = span
            first = phase not in span.phases
            span.phases.setdefault(phase, t)
            if partition is not None:
                span.partition = int(partition)
            return span.span_id, first

    def restore(self, trial_id: str, span_id: Optional[str], phase: str,
                t: Optional[float], partition: Optional[int] = None) -> None:
        """Rebuild one journaled phase occurrence into the tracker
        (crash-only recovery / resume): the span keeps its ORIGINAL
        journaled id — a recovered trial's later phases must land on the
        same span the pre-crash events named, or the journal would carry
        two spans for one trial — and first-occurrence timestamps are
        preserved (setdefault, like mark). ``once=True`` emit dedup then
        works across incarnations for free: a phase the dead incarnation
        already journaled is not first on the restored span."""
        if t is None:
            return
        with self._lock:
            span = self._spans.get(trial_id)
            if span is None:
                span = TrialSpan(span_id or pysecrets.token_hex(6), trial_id)
                self._spans[trial_id] = span
            span.phases.setdefault(phase, t)
            if partition is not None:
                span.partition = int(partition)

    def all(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [s.to_dict() for s in self._spans.values()]

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)


def _dist_stats(values_ms: List[float]) -> Dict[str, Any]:
    """median/p95/n over a list of millisecond values — the exact shape
    bench.py's historical ``handoff_gaps`` emitted, so BENCH_*.json stays
    comparable across rounds."""
    if not values_ms:
        return {}
    ordered = sorted(values_ms)
    return {"median_ms": round(ordered[len(ordered) // 2], 1),
            "p95_ms": round(ordered[int(len(ordered) * 0.95)], 1),
            "n": len(ordered)}


def derive(events: List[Dict[str, Any]],
           handoff_cap_s: float = HANDOFF_CAP_S) -> Dict[str, Any]:
    """Derived scheduling metrics from journal events (pure function).

    - ``handoff``: per-partition gap from one trial's ``finalized`` to the
      SAME runner's next trial ``running`` — the control plane's per-trial
      overhead. Gaps >= ``handoff_cap_s`` (rung-barrier idling) and negative
      gaps (requeue overlap) are excluded.
    - ``early_stop_reaction``: ``stop_flagged`` (driver armed the flag) to
      that trial's ``finalized`` (runner confirmed the stop) — how fast an
      early-stop frees its runner.
    - ``requeue_recovery``: each ``requeued`` occurrence to the SAME
      trial's next ``assigned`` — how fast a lost trial re-enters a
      runner (the recovery-latency edge chaos soaks assert on).
    - ``suggest``: the hand-off pipeline's health — prefetch hit/miss
      counts + hit rate (``prefetch_hit``/``prefetch_miss`` phase events)
      and controller suggest() latency (``ev: "suggest"`` events with an
      ``ms`` field, recorded by the driver's suggester thread and inline
      fallback). Empty when the experiment ran without prefetch.
    - ``compile``: the compile-once hot path's health — warm-slot hit
      counts/rate from ``compiled`` phase events, ttfm distributions split
      by cold/warm, the attributed phase distributions (init/trace/
      compile/first_step), and the persistent XLA compilation cache's
      cumulative hit/miss counts summed over runners (from the
      ``runner_stats`` events' counter fields). Empty for pre-warm
      journals.
    - ``fork``: checkpoint-forking genealogy — forked vs from-scratch
      promotion counts, the parent steps the forks did NOT re-train
      (``steps_saved``), fork-load (checkpoint staging) latency p50/p95,
      downgrades (``fork_source_lost``) and ``ckpt_gc`` retirements.
      Empty for non-forking journals.
    - ``goodput``: the chip-time ledger (telemetry/goodput.py) — every
      held runner-second classified into the closed GOODPUT_BUCKETS
      taxonomy (train vs init/trace/compile/ckpt/fork_stage/rework/
      handoff/queue_wait/idle/unaccounted), per-partition and per-trial,
      gang-aware. Empty for journals with no runner activity.
    - ``trials``: lifecycle counts.
    """
    # Lazy import: goodput.py imports HANDOFF_CAP_S from this module at
    # top level, so the cycle is broken here, not there.
    from maggy_tpu.telemetry.goodput import compute_goodput
    by_partition: Dict[int, List[tuple]] = {}
    stop_flagged: Dict[str, float] = {}
    finalized_at: Dict[str, float] = {}
    requeued_at: Dict[str, List[float]] = {}
    assigned_at: Dict[str, List[float]] = {}
    finalized = errors = lost = requeues = 0
    # Distinct trials, not 'queued' events: a resumed experiment's
    # continuous journal re-queues in-flight trials, and double-counting
    # them would overstate the schedule.
    created: set = set()
    early: set = set()
    hits = misses = 0
    suggest_ms: List[float] = []
    preempted_at: Dict[str, List[float]] = {}
    resumed_at: Dict[str, List[float]] = {}
    preempt_resumed = 0
    compiled_recs: Dict[str, Dict[str, Any]] = {}
    cache_cum: Dict[Any, Dict[str, int]] = {}
    cache_banked: Dict[Any, Dict[str, int]] = {}
    forked: Dict[str, Dict[str, Any]] = {}
    parented: set = set()
    fork_downgrades = 0
    ckpt_gcs = 0
    for ev in events:
        if ev.get("ev") == "suggest":
            if ev.get("ms") is not None:
                suggest_ms.append(float(ev["ms"]))
            continue
        if ev.get("ev") == "runner_stats":
            # Cumulative per-runner counters: monotone within ONE runner
            # process, but a replaced runner (chaos kill, pool respawn)
            # restarts at zero — a value going backwards marks the new
            # attempt, so bank the dead attempt's total instead of letting
            # the overwrite erase it from the sums.
            cum = cache_cum.setdefault(ev.get("partition"), {})
            bank = cache_banked.setdefault(ev.get("partition"), {})
            for key in ("xla_cache_hits", "xla_cache_misses",
                        "warm_hits", "warm_misses"):
                if ev.get(key) is not None:
                    v = int(ev[key])
                    if v < cum.get(key, 0):
                        bank[key] = bank.get(key, 0) + cum[key]
                    cum[key] = v
            continue
        if ev.get("ev") == "ckpt_gc":
            ckpt_gcs += 1
            continue
        if ev.get("ev") != "trial":
            continue
        phase, t, trial = ev.get("phase"), ev.get("t"), ev.get("trial")
        if t is None or trial is None:
            continue
        if phase == "queued":
            created.add(trial)
            if (ev.get("info") or {}).get("parent") is not None:
                # Fork-eligible: a parent-carrying schedule entry (ASHA
                # promotion, PBT segment, BO near-duplicate). Whether it
                # actually forked is decided by its forked_from edge.
                parented.add(trial)
        elif phase == "running":
            pid = ev.get("partition")
            if pid is not None:
                by_partition.setdefault(int(pid), []).append(("run", t, trial))
        elif phase == "assigned":
            assigned_at.setdefault(trial, []).append(t)
        elif phase == "stop_flagged":
            stop_flagged.setdefault(trial, t)
        elif phase == "prefetch_hit":
            hits += 1
        elif phase == "prefetch_miss":
            misses += 1
        elif phase == "compiled":
            compiled_recs.setdefault(trial, ev)
        elif phase == "forked_from":
            forked.setdefault(trial, ev)
        elif phase == "preempted":
            preempted_at.setdefault(trial, []).append(t)
        elif phase == "resumed":
            preempt_resumed += 1
            resumed_at.setdefault(trial, []).append(t)
        elif phase == "lost":
            lost += 1
        elif phase == "requeued":
            requeues += 1
            requeued_at.setdefault(trial, []).append(t)
            if ev.get("reason") == "fork_source_lost":
                fork_downgrades += 1
        elif phase == "finalized":
            finalized += 1
            if ev.get("error"):
                errors += 1
            if ev.get("early_stop"):
                early.add(trial)
            finalized_at[trial] = t
            pid = ev.get("partition")
            if pid is not None:
                by_partition.setdefault(int(pid), []).append(("fin", t, trial))
    gaps: List[float] = []
    for seq in by_partition.values():
        seq.sort(key=lambda e: e[1])
        last_fin = None
        for kind, t, _trial in seq:
            if kind == "fin":
                last_fin = t
            elif last_fin is not None:  # "run" after a finalize
                gap = t - last_fin
                if 0 <= gap < handoff_cap_s:
                    gaps.append(gap * 1e3)
                last_fin = None
    reactions = [(finalized_at[tid] - t0) * 1e3
                 for tid, t0 in stop_flagged.items()
                 if tid in finalized_at and finalized_at[tid] >= t0]
    recoveries: List[float] = []
    for tid, times in requeued_at.items():
        marks = sorted(assigned_at.get(tid, []))
        for t0 in times:
            nxt = next((t for t in marks if t >= t0), None)
            if nxt is not None:
                recoveries.append((nxt - t0) * 1e3)
    suggest: Dict[str, Any] = {}
    if hits or misses or suggest_ms:
        suggest = {"prefetch_hits": hits, "prefetch_misses": misses,
                   "hit_rate": round(hits / (hits + misses), 3)
                   if (hits + misses) else None,
                   "latency": _dist_stats(suggest_ms)}
    # Preemption -> resume latency: each preempted occurrence to the SAME
    # trial's next resumed (checkpoint-assisted) re-dispatch.
    preempt: Dict[str, Any] = {}
    if preempted_at:
        resume_lat = []
        for tid, times in preempted_at.items():
            marks = sorted(resumed_at.get(tid, []))
            for t0 in times:
                nxt = next((t for t in marks if t >= t0), None)
                if nxt is not None:
                    resume_lat.append((nxt - t0) * 1e3)
        preempt = {"n": sum(len(v) for v in preempted_at.values()),
                   "resumed": preempt_resumed,
                   "resume_latency": _dist_stats(resume_lat)}
    # Compile-once hot path: warm hit rate + ttfm split cold/warm + the
    # attributed phase distributions + persistent-cache counters.
    compile_block: Dict[str, Any] = {}
    if compiled_recs or any(cache_cum.values()):
        def _counter_total(key):
            return (sum(c.get(key, 0) for c in cache_cum.values())
                    + sum(b.get(key, 0) for b in cache_banked.values()))

        warm_recs = [r for r in compiled_recs.values() if r.get("warm")]
        cold_recs = [r for r in compiled_recs.values() if not r.get("warm")]
        hits_n, misses_n = len(warm_recs), len(cold_recs)
        if not compiled_recs:
            # No per-trial compiled records survived (runner died before
            # its flush) but the heartbeat-shipped cumulative counters
            # did — report THOSE instead of a contradictory zero.
            hits_n = _counter_total("warm_hits")
            misses_n = _counter_total("warm_misses")

        def ms_dist(recs, key):
            return _dist_stats([float(r[key]) for r in recs
                                if r.get(key) is not None])

        all_recs = list(compiled_recs.values())
        compile_block = {
            "warm_hits": hits_n, "warm_misses": misses_n,
            "warm_hit_rate": round(hits_n / (hits_n + misses_n), 3)
            if (hits_n + misses_n) else None,
            "ttfm_cold": ms_dist(cold_recs, "ttfm_ms"),
            "ttfm_warm": ms_dist(warm_recs, "ttfm_ms"),
            "init_ms": ms_dist(all_recs, "init_ms"),
            "trace_ms": ms_dist(all_recs, "trace_ms"),
            "compile_ms": ms_dist(all_recs, "compile_ms"),
            "first_step_ms": ms_dist(all_recs, "first_step_ms"),
        }
        cache_hits = _counter_total("xla_cache_hits")
        cache_misses = _counter_total("xla_cache_misses")
        if cache_hits or cache_misses:
            compile_block["cache"] = {
                "hits": cache_hits, "misses": cache_misses,
                "hit_rate": round(cache_hits / (cache_hits + cache_misses),
                                  3)}
    # Checkpoint-forking search: genealogy + the compute the forks saved.
    # forked = trials dispatched with a forked_from edge; from_scratch =
    # parent-carrying schedule entries (promotions/exploits) that ran
    # without one (fork off, parent never checkpointed, or downgraded);
    # steps_saved = parent steps NOT re-trained (the fork points summed);
    # fork_load_ms = the runner-measured checkpoint staging cost (from
    # the compiled records).
    fork_block: Dict[str, Any] = {}
    if forked or parented or ckpt_gcs:
        load_ms = [float(r["fork_load_ms"])
                   for r in compiled_recs.values()
                   if r.get("fork_load_ms") is not None]
        fork_block = {
            "forked": len(forked),
            "from_scratch": len(parented - set(forked)),
            # A fork at step S skips re-training steps 0..S: S+1 saved.
            "steps_saved": sum(int(e["step"]) + 1
                               for e in forked.values()
                               if e.get("step") is not None),
            "fork_load_ms": _dist_stats(load_ms),
            "downgrades": fork_downgrades,
            "ckpt_gc": ckpt_gcs,
        }
    return {
        "trials": {"created": len(created), "finalized": finalized,
                   "early_stopped": len(early), "errors": errors,
                   "lost": lost, "requeued": requeues},
        "handoff": _dist_stats(gaps),
        "early_stop_reaction": _dist_stats(reactions),
        "requeue_recovery": _dist_stats(recoveries),
        "suggest": suggest,
        "preempt": preempt,
        "compile": compile_block,
        "fork": fork_block,
        "goodput": compute_goodput(events, handoff_cap_s=handoff_cap_s),
    }
