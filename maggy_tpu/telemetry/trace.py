"""Timeline export: telemetry journal -> Chrome-trace-event / Perfetto JSON.

``python -m maggy_tpu.telemetry trace <exp_dir>`` converts any telemetry
journal into the JSON object format chrome://tracing and https://ui.perfetto.dev
load natively — so the paper's scheduling claim is literally *visible*:
one track per partition, each trial a slice, and the hand-off gap between
one trial's ``finalized`` and the same runner's next ``running`` an actual
visible gap between slices.

Mapping:

- **tracks**: one trace "process" per partition (``pid = partition + 1``,
  named via process_name metadata) plus a ``driver`` track (``pid = 0``)
  for events with no partition attribution (queued, stop_flagged,
  experiment lifecycle).
- **trial slices**: per run attempt (a requeued trial re-runs as a new
  slice on its new partition), an outer ``X`` slice from ``assigned`` to
  the attempt's terminal event, with nested phase sub-slices:
  ``dispatch`` (assigned → running), ``startup`` (running → first_metric;
  the compile stall made visible), ``train`` (first_metric → finalized).
- **instant events**: STOP flags (``stop_flagged`` / ``stop_sent``),
  ``requeued`` / ``lost`` edges, chaos injections (``chaos:<kind>``), and
  health findings (``health:<check>``).
- **counters**: runner-stats memory/RTT samples become ``C`` counter
  events per partition (``rss_mb``, ``hb_rtt_ms``), so a leaking trial is
  a visibly climbing line under its track.
- **gang lanes**: an assembled gang (``gang_assembled`` →
  ``gang_released``) renders one identical slice on every member
  partition's ``gang`` lane, so an N-chip gang is a grouped band across N
  contiguous partition tracks; placer decisions (``pack`` events —
  reserve/stall/release) are instant markers on the driver track.
- **vmap lanes**: a vectorized block's K lane trials (``config.
  vmap_lanes``; lane-stamped ``assigned``/``running``/``finalized``
  edges) each render on their own ``lane <i>`` sub-track under the
  shared partition, so the block is a stack of K parallel trial slices
  and a masked lane's early FINAL is a visibly shorter slice — the
  ``lane_idle`` tail the goodput ledger charges is the empty space to
  the block's right edge.

The exporter is pure (events in, dict out) and the journal is the only
input — any soak/bench artifact can be rendered after the fact.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

#: pid of the driver track; partition p maps to pid p + 1.
DRIVER_PID = 0

#: Phase pairs rendered as nested sub-slices inside a trial slice.
_SUB_SLICES = (
    ("dispatch", "assigned", "running"),
    ("startup", "running", "first_metric"),
    ("train", "first_metric", "finalized"),
)

#: Trial phases rendered as instant markers rather than slice edges.
#: ``suggested`` lands on the driver track (no partition yet): the visible
#: distance to the same trial's ``running`` IS the prefetch lead time;
#: ``prefetch_hit``/``prefetch_miss`` mark each hand-off's path on the
#: partition track.
_INSTANT_PHASES = ("suggested", "queued", "stop_flagged", "stop_sent",
                   "requeued", "lost", "profile_skipped", "prefetch_hit",
                   "prefetch_miss", "preempt_requested", "preempted",
                   "resumed", "gang_assembled", "gang_released",
                   "forked_from")

#: tid of the per-partition gang lane: a gang trial's busy interval is
#: rendered as one slice on EVERY member partition's gang lane, so the
#: assembled block is visible as a grouped band across the contiguous
#: partition tracks (the trial's own slice stays on the leader's tid 0).
GANG_TID = 1

#: tid base of the per-partition vmap lane sub-tracks: a vectorized
#: block's lane ``i`` trial renders on tid ``LANE_TID_BASE + i`` under
#: its partition's process, so the K lanes stack as parallel sub-tracks
#: (scalar trials stay on tid 0; gang lane is tid 1).
LANE_TID_BASE = 100

#: ttfm-breakdown fields of a ``compiled`` event, rendered (in runtime
#: order) as sequential sub-slices inside the attempt's ``startup`` window
#: — the compile stall decomposed: sharded init, jaxpr trace, XLA compile,
#: then the residual first steps' execution.
_COMPILE_SLICES = (("init", "init_ms"), ("trace", "trace_ms"),
                   ("compile", "compile_ms"), ("first_step",
                                               "first_step_ms"))


def _pid(partition: Optional[int]) -> int:
    return DRIVER_PID if partition is None else int(partition) + 1


def build_trace(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Pure journal-events -> Chrome-trace dict (``{"traceEvents": [...]}``,
    timestamps in microseconds relative to the first event)."""
    times = [e["t"] for e in events if isinstance(e.get("t"), (int, float))]
    t0 = min(times) if times else 0.0

    def us(t: float) -> int:
        return int(round((t - t0) * 1e6))

    out: List[Dict[str, Any]] = []
    partitions = set()
    by_trial: Dict[str, List[Dict[str, Any]]] = {}
    for ev in events:
        kind = ev.get("ev")
        t = ev.get("t")
        if not isinstance(t, (int, float)):
            continue
        pid = ev.get("partition")
        if pid is not None:
            partitions.add(int(pid))
        if kind == "trial" and ev.get("trial") is not None:
            by_trial.setdefault(ev["trial"], []).append(ev)
        elif kind == "chaos":
            out.append({"name": "chaos:{}".format(ev.get("kind")),
                        "cat": "chaos", "ph": "i", "s": "t",
                        "ts": us(t), "pid": _pid(pid), "tid": 0,
                        "args": {k: v for k, v in ev.items()
                                 if k not in ("ev", "t")}})
        elif kind == "health":
            out.append({"name": "health:{}".format(ev.get("check")),
                        "cat": "health", "ph": "i", "s": "t",
                        "ts": us(t), "pid": _pid(pid), "tid": 0,
                        "args": {k: v for k, v in ev.items()
                                 if k not in ("ev", "t", "stacks")}})
        elif kind == "pack":
            # Placer decisions (reserve/stall/release) on the driver
            # track: a fragmentation stall is a visible marker exactly
            # where the timeline shows scattered free chips.
            out.append({"name": "pack:{}".format(ev.get("op")),
                        "cat": "pack", "ph": "i", "s": "p",
                        "ts": us(t), "pid": DRIVER_PID, "tid": 0,
                        "args": {k: v for k, v in ev.items()
                                 if k not in ("ev", "t")}})
        elif kind == "runner_stats" and pid is not None:
            for counter in ("rss_mb", "hb_rtt_ms"):
                if ev.get(counter) is not None:
                    out.append({"name": counter, "cat": "runner",
                                "ph": "C", "ts": us(t), "pid": _pid(pid),
                                "args": {counter: ev[counter]}})
        elif kind in ("experiment", "runner", "worker", "chaos_armed",
                      "chaos_summary"):
            out.append({"name": "{}:{}".format(kind, ev.get("phase", "")),
                        "cat": "lifecycle", "ph": "i", "s": "p",
                        "ts": us(t), "pid": _pid(pid), "tid": 0,
                        "args": {k: v for k, v in ev.items()
                                 if k not in ("ev", "t")}})

    lane_parts: Dict[int, set] = {}
    for trial_id, evs in by_trial.items():
        evs.sort(key=lambda e: e["t"])
        out.extend(_trial_slices(trial_id, evs, us, lane_parts))
        for ev in evs:
            if ev.get("phase") in _INSTANT_PHASES:
                out.append({"name": "{}:{}".format(ev["phase"],
                                                   trial_id[:8]),
                            "cat": "trial", "ph": "i", "s": "t",
                            "ts": us(ev["t"]),
                            "pid": _pid(ev.get("partition")),
                            "tid": LANE_TID_BASE + int(ev["lane"])
                            if ev.get("lane") is not None else 0,
                            "args": {k: v for k, v in ev.items()
                                     if k not in ("ev", "t")}})

    # Fork genealogy flow arrows (checkpoint-forking search): one
    # Perfetto flow per forked_from edge, from the PARENT's finalized
    # point (the end of its trial slice — where the forked checkpoint
    # was last written) to the CHILD's running edge on its own
    # partition track. Lineage is literally visible: promotion chains
    # render as arrows climbing the rung ladder across runner tracks.
    fork_flows = 0
    fin_point: Dict[str, tuple] = {}
    for trial_id, evs in by_trial.items():
        fin = next((e for e in evs if e.get("phase") == "finalized"), None)
        if fin is not None:
            fin_point[trial_id] = (us(fin["t"]), _pid(fin.get("partition")))
    for trial_id, evs in by_trial.items():
        fork = next((e for e in evs if e.get("phase") == "forked_from"),
                    None)
        if fork is None:
            continue
        src = fin_point.get(fork.get("parent"))
        if src is None:
            continue  # parent finalized outside this journal window
        dst = next((e for e in evs if e.get("phase") == "running"), fork)
        fork_flows += 1
        fid = "fork-{}".format(fork_flows)
        out.append({"name": "fork-flow", "cat": "flow", "ph": "s",
                    "id": fid, "ts": src[0], "pid": src[1], "tid": 0})
        out.append({"name": "fork-flow", "cat": "flow", "ph": "f",
                    "bp": "e", "id": fid, "ts": us(dst["t"]),
                    "pid": _pid(dst.get("partition")), "tid": 0})

    # Gang lanes: each assembled gang renders one slice per MEMBER
    # partition (gang lane, tid GANG_TID) spanning gang_assembled ->
    # gang_released, so an N-chip gang is a grouped band across N
    # contiguous partition tracks — packing (and fragmentation) is
    # literally visible. A journal ending mid-gang closes the band at
    # the last event.
    last_us = max((us(e["t"]) for e in events
                   if isinstance(e.get("t"), (int, float))), default=0)
    gang_parts = set()
    for trial_id, evs in by_trial.items():
        open_gang = None
        for ev in evs:
            phase = ev.get("phase")
            if phase == "gang_assembled":
                open_gang = ev
            elif phase == "gang_released" and open_gang is not None:
                out.extend(_gang_band(trial_id, open_gang, us(ev["t"]),
                                      us, gang_parts))
                open_gang = None
        if open_gang is not None:
            out.extend(_gang_band(trial_id, open_gang, last_us, us,
                                  gang_parts))
    # Idle-held members may never emit an event of their own — their
    # tracks exist because a gang band lands on them.
    partitions |= gang_parts

    # Per-partition goodput-fraction counter tracks: the chip-time
    # ledger's cumulative train/held fraction sampled at each attempt
    # end (telemetry/goodput.py) — utilization drift is a visible line
    # under each partition's track, next to its rss/RTT counters.
    from maggy_tpu.telemetry.goodput import compute_goodput

    gp = compute_goodput(events)
    for p, pts in (gp.get("partition_samples") or {}).items():
        for t, frac in pts:
            out.append({"name": "goodput_fraction", "cat": "goodput",
                        "ph": "C", "ts": us(t), "pid": _pid(int(p)),
                        "args": {"goodput_fraction": frac}})

    # Track naming metadata: driver + one process per partition, sorted so
    # Perfetto lists partition 0..N in order.
    meta = [{"name": "process_name", "ph": "M", "pid": DRIVER_PID, "tid": 0,
             "args": {"name": "driver"}},
            {"name": "process_sort_index", "ph": "M", "pid": DRIVER_PID,
             "tid": 0, "args": {"sort_index": -1}}]
    for p in sorted(partitions):
        meta.append({"name": "process_name", "ph": "M", "pid": _pid(p),
                     "tid": 0, "args": {"name": "partition {}".format(p)}})
        meta.append({"name": "process_sort_index", "ph": "M", "pid": _pid(p),
                     "tid": 0, "args": {"sort_index": p}})
        if p in gang_parts:
            meta.append({"name": "thread_name", "ph": "M", "pid": _pid(p),
                         "tid": GANG_TID, "args": {"name": "gang"}})
            meta.append({"name": "thread_sort_index", "ph": "M",
                         "pid": _pid(p), "tid": GANG_TID,
                         "args": {"sort_index": GANG_TID}})
        for lane in sorted(lane_parts.get(p, ())):
            meta.append({"name": "thread_name", "ph": "M", "pid": _pid(p),
                         "tid": LANE_TID_BASE + lane,
                         "args": {"name": "lane {}".format(lane)}})
            meta.append({"name": "thread_sort_index", "ph": "M",
                         "pid": _pid(p), "tid": LANE_TID_BASE + lane,
                         "args": {"sort_index": LANE_TID_BASE + lane}})
    out.sort(key=lambda e: e.get("ts", 0))
    return {"traceEvents": meta + out, "displayTimeUnit": "ms",
            "otherData": {"source": "maggy_tpu.telemetry",
                          "t0_unix_s": t0,
                          "partitions": sorted(partitions),
                          "trials": len(by_trial),
                          "fork_flows": fork_flows}}


def _gang_band(trial_id: str, assembled: Dict[str, Any], end_us: int,
               us, gang_parts: set) -> List[dict]:
    """One gang's grouped band: an identical slice on every member
    partition's gang lane, from the assembled edge to ``end_us``."""
    out: List[dict] = []
    start = us(assembled["t"])
    members = assembled.get("members") or []
    name = "gang {} x{} ({})".format(
        trial_id[:8], len(members) or "?",
        assembled.get("strategy", "?"))
    args = {"trial": trial_id, "members": list(members),
            "chips": assembled.get("chips"),
            "leader": assembled.get("partition"),
            "strategy": assembled.get("strategy")}
    for m in members:
        gang_parts.add(int(m))
        out.append({"name": name, "cat": "gang", "ph": "X", "ts": start,
                    "dur": max(1, end_us - start), "pid": _pid(int(m)),
                    "tid": GANG_TID, "args": args})
    return out


def _trial_slices(trial_id: str, evs: List[Dict[str, Any]], us,
                  lane_parts: Optional[Dict[int, set]] = None) -> List[dict]:
    """Slices for one trial: one outer slice (+ phase sub-slices) per run
    attempt, split on ``assigned`` occurrences so a requeued trial renders
    as separate slices on each partition it visited. A vectorized block
    lane attempt (lane-stamped edges) lands on its partition's ``lane <i>``
    sub-track (tid ``LANE_TID_BASE + i``) so the block's K trials stack;
    ``lane_parts`` (partition -> lane indices) collects the sub-tracks the
    caller must name."""
    out: List[dict] = []
    attempts: List[List[Dict[str, Any]]] = []
    for ev in evs:
        if ev.get("phase") == "assigned" or not attempts:
            attempts.append([])
        attempts[-1].append(ev)
    for attempt in attempts:
        marks: Dict[str, float] = {}
        partition = None
        terminal = None
        lane = None
        for ev in attempt:
            phase = ev.get("phase")
            if phase not in marks:
                marks[phase] = ev["t"]
            if ev.get("partition") is not None:
                partition = int(ev["partition"])
            if ev.get("lane") is not None:
                lane = int(ev["lane"])
            if phase in ("finalized", "lost") and terminal is None:
                terminal = ev["t"]
        start = marks.get("assigned")
        if start is None or partition is None:
            continue
        end = terminal if terminal is not None else attempt[-1]["t"]
        if end < start:
            continue
        tid = 0
        if lane is not None:
            tid = LANE_TID_BASE + lane
            if lane_parts is not None:
                lane_parts.setdefault(partition, set()).add(lane)
        args = {"trial": trial_id}
        final = next((e for e in attempt if e.get("phase") == "finalized"),
                     None)
        if final is not None:
            args.update({k: final[k] for k in ("early_stop", "error", "span",
                                               "lane", "block")
                         if final.get(k) is not None})
        out.append({"name": "trial {}".format(trial_id[:8]), "cat": "trial",
                    "ph": "X", "ts": us(start),
                    "dur": max(1, us(end) - us(start)),
                    "pid": _pid(partition), "tid": tid, "args": args})
        for name, p_from, p_to in _SUB_SLICES:
            a, b = marks.get(p_from), marks.get(p_to)
            if a is None or b is None or b < a:
                continue
            out.append({"name": name, "cat": "phase", "ph": "X",
                        "ts": us(a), "dur": max(1, us(b) - us(a)),
                        "pid": _pid(partition), "tid": tid,
                        "args": {"trial": trial_id}})
        # Runner-attributed ttfm breakdown: the compiled event carries
        # DURATIONS (runner clock), so the sub-slices are laid out
        # sequentially from the attempt's running edge — driver/runner
        # clock skew shifts the anchor, never the widths.
        compiled = next((e for e in attempt
                         if e.get("phase") == "compiled"), None)
        anchor = marks.get("running")
        if compiled is not None and anchor is not None:
            cursor = us(anchor)
            warm_tag = "warm" if compiled.get("warm") else "cold"
            for name, key in _COMPILE_SLICES:
                ms = compiled.get(key)
                if not ms or ms <= 0:
                    continue
                dur = max(1, int(round(ms * 1e3)))
                out.append({"name": "{} ({})".format(name, warm_tag),
                            "cat": "compile", "ph": "X", "ts": cursor,
                            "dur": dur, "pid": _pid(partition), "tid": tid,
                            "args": {"trial": trial_id, key: ms,
                                     "warm": bool(compiled.get("warm"))}})
                cursor += dur
    return out


def build_fleet_trace(fleet_events: List[Dict[str, Any]],
                      experiments: Dict[str, List[Dict[str, Any]]]
                      ) -> Dict[str, Any]:
    """Fleet timeline: one trace process per FLEET RUNNER, with one
    thread lane per experiment inside it — so multiplexing is literally
    visible: runner 0's track shows experiment A's trial slices on A's
    lane giving way to B's after a preemption marker.

    ``fleet_events`` is the fleet journal (lease/preempt/lifecycle);
    ``experiments`` maps experiment name -> that experiment's own
    telemetry journal events. Experiment-journal partitions are
    per-experiment slot ids, so each trial slice is placed on the fleet
    runner whose lease of (experiment, slot) covers the slice's time —
    slices with no covering lease (driver-side edges) land on the driver
    track."""
    all_events = list(fleet_events)
    for evs in experiments.values():
        all_events.extend(evs)
    times = [e["t"] for e in all_events
             if isinstance(e.get("t"), (int, float))]
    t0 = min(times) if times else 0.0

    def us(t: float) -> int:
        return int(round((t - t0) * 1e6))

    exp_names = sorted(experiments)
    exp_tid = {name: i + 1 for i, name in enumerate(exp_names)}

    # Lease intervals per (exp, slot pid): [(start_us, end_us, runner)].
    leases: Dict[tuple, List[tuple]] = {}
    open_leases: Dict[tuple, tuple] = {}
    out: List[Dict[str, Any]] = []
    runners = set()
    max_us = max((us(t) for t in times), default=0)
    for ev in fleet_events:
        t = ev.get("t")
        if not isinstance(t, (int, float)):
            continue
        kind = ev.get("ev")
        if kind == "lease":
            key = (ev.get("exp"), ev.get("pid"))
            runner = ev.get("runner")
            if runner is not None:
                runners.add(int(runner))
            if ev.get("phase") == "start":
                open_leases[key] = (us(t), runner)
            elif ev.get("phase") == "end":
                started = open_leases.pop(key, None)
                if started is not None:
                    leases.setdefault(key, []).append(
                        (started[0], us(t), started[1]))
        elif kind == "preempt":
            out.append({"name": "preempt:{}".format(ev.get("exp")),
                        "cat": "fleet", "ph": "i", "s": "g", "ts": us(t),
                        "pid": DRIVER_PID, "tid": 0,
                        "args": {k: v for k, v in ev.items()
                                 if k not in ("ev", "t")}})
        elif kind in ("fleet", "fleet_submit", "fleet_admit",
                      "fleet_experiment"):
            out.append({"name": "{}:{}".format(
                            kind, ev.get("exp", ev.get("phase", ""))),
                        "cat": "fleet", "ph": "i", "s": "p", "ts": us(t),
                        "pid": DRIVER_PID, "tid": 0,
                        "args": {k: v for k, v in ev.items()
                                 if k not in ("ev", "t")}})
    for key, (start, runner) in open_leases.items():  # journal ended mid-lease
        leases.setdefault(key, []).append((start, max_us, runner))

    def runner_at(exp: str, slot: int, ts: int):
        for start, end, runner in leases.get((exp, slot), []):
            if start <= ts <= end and runner is not None:
                return int(runner)
        return None

    # Lease slices on each runner track, in the owning experiment's lane.
    for (exp, slot), intervals in leases.items():
        tid = exp_tid.get(exp, 0)
        for start, end, runner in intervals:
            if runner is None:
                continue
            out.append({"name": "lease {}".format(exp), "cat": "lease",
                        "ph": "X", "ts": start,
                        "dur": max(1, end - start),
                        "pid": int(runner) + 1, "tid": tid,
                        "args": {"exp": exp, "slot": slot}})

    # Trial slices from each experiment's journal, remapped from its slot
    # ids onto the fleet runner serving that slot at the slice's time.
    for name, evs in experiments.items():
        tid = exp_tid[name]
        by_trial: Dict[str, List[Dict[str, Any]]] = {}
        for ev in evs:
            if ev.get("ev") == "trial" and ev.get("trial") is not None \
                    and isinstance(ev.get("t"), (int, float)):
                by_trial.setdefault(ev["trial"], []).append(ev)
        for trial_id, tevs in by_trial.items():
            tevs.sort(key=lambda e: e["t"])
            for s in _trial_slices(trial_id, tevs, us):
                slot = s["pid"] - 1  # _pid() inverse
                runner = runner_at(name, slot, s["ts"]) \
                    if slot >= 0 else None
                s["pid"] = DRIVER_PID if runner is None else runner + 1
                s["tid"] = tid
                s.setdefault("args", {})["exp"] = name
                out.append(s)

    meta = [{"name": "process_name", "ph": "M", "pid": DRIVER_PID, "tid": 0,
             "args": {"name": "fleet"}},
            {"name": "process_sort_index", "ph": "M", "pid": DRIVER_PID,
             "tid": 0, "args": {"sort_index": -1}}]
    for r in sorted(runners):
        meta.append({"name": "process_name", "ph": "M", "pid": r + 1,
                     "tid": 0, "args": {"name": "runner {}".format(r)}})
        meta.append({"name": "process_sort_index", "ph": "M", "pid": r + 1,
                     "tid": 0, "args": {"sort_index": r}})
        for name in exp_names:
            meta.append({"name": "thread_name", "ph": "M", "pid": r + 1,
                         "tid": exp_tid[name],
                         "args": {"name": "exp {}".format(name)}})
            meta.append({"name": "thread_sort_index", "ph": "M",
                         "pid": r + 1, "tid": exp_tid[name],
                         "args": {"sort_index": exp_tid[name]}})
    out.sort(key=lambda e: e.get("ts", 0))
    return {"traceEvents": meta + out, "displayTimeUnit": "ms",
            "otherData": {"source": "maggy_tpu.telemetry(fleet)",
                          "t0_unix_s": t0,
                          "runners": sorted(runners),
                          "experiments": exp_names}}


#: tid of the per-agent execution lane inside an agent's process group
#: (their trial slices render on the per-experiment lanes, like thread
#: runners; this lane carries the agent's OWN journal: lease..done exec
#: slices, clock_offset / sink degradation instants).
AGENT_LANE_TID = 999


def build_unified_trace(fleet_events: List[Dict[str, Any]],
                        experiments: Dict[str, List[Dict[str, Any]]],
                        agent_journals: Optional[Dict[str, List[Dict[str,
                                                                     Any]]]]
                        = None,
                        offsets: Optional[Dict[str, float]] = None
                        ) -> Dict[str, Any]:
    """ONE Perfetto trace for the whole fleet: the fleet timeline
    (``build_fleet_trace`` — driver track, one process per runner with a
    lane per experiment) EXTENDED with the cross-process telemetry the
    journal sink fans in:

    - runner process groups held by REMOTE AGENTS are renamed
      ``agent <id> @host`` (from the fleet journal's ``agent`` join
      events), so each agent process is its own group;
    - each agent's OWN journal (sink segment or surviving local
      ``agent.jsonl``) renders on the agent's execution lane, with every
      timestamp corrected onto the FLEET clock by the agent's journaled
      ``clock_offset`` (``offsets`` overrides per agent; an agent event
      at agent-clock ``t`` happened at fleet-clock ``t - offset_s``);
    - FLOW ARROWS follow each remotely-leased trial across the process
      boundary: ABIND dispatch (driver track) -> the agent-side
      execution slice -> the trial's FINAL — the Perfetto ``s``/``t``/
      ``f`` flow triple, one per delivered lease.

    Pure like every builder here: journals in, trace dict out.
    """
    agent_journals = agent_journals or {}
    # Agent registry + journaled clock offsets from the fleet journal.
    runner_agent: Dict[int, str] = {}
    agent_runner: Dict[str, int] = {}
    agent_host: Dict[str, str] = {}
    derived_offsets: Dict[str, float] = {}
    for ev in fleet_events:
        kind = ev.get("ev")
        if kind == "agent" and ev.get("phase") == "join" \
                and ev.get("agent") is not None \
                and ev.get("runner") is not None:
            aid = str(ev["agent"])
            runner_agent[int(ev["runner"])] = aid
            agent_runner[aid] = int(ev["runner"])
            agent_host[aid] = str(ev.get("host") or "?")
        elif kind == "clock_offset" and ev.get("agent") \
                and ev.get("offset_s") is not None:
            derived_offsets[str(ev["agent"])] = float(ev["offset_s"])
    offs = dict(derived_offsets)
    offs.update(offsets or {})

    base = build_fleet_trace(fleet_events, experiments)
    out: List[Dict[str, Any]] = base["traceEvents"]
    t0 = base["otherData"]["t0_unix_s"]

    def us(t: float) -> int:
        return int(round((t - t0) * 1e6))

    # Rename agent-held runner process groups (latest join wins — slot
    # reuse after an agent loss keeps the newest identity).
    for ev in out:
        if ev.get("ph") == "M" and ev.get("name") == "process_name" \
                and ev["pid"] - 1 in runner_agent:
            aid = runner_agent[ev["pid"] - 1]
            ev["args"] = {"name": "agent {} @{}".format(
                aid, agent_host.get(aid, "?"))}

    exp_names = sorted(experiments)
    exp_tid = {name: i + 1 for i, name in enumerate(exp_names)}

    # Agent-side lanes: exec slices (lease..done) + instants, clocks
    # corrected onto the fleet time base. exec_index[(aid, exp, pid)] is
    # the ordered list of corrected exec windows, consumed in order by
    # the flow matcher below.
    exec_index: Dict[tuple, List[tuple]] = {}
    for aid, a_events in sorted(agent_journals.items()):
        runner = agent_runner.get(aid)
        if runner is None:
            continue
        pid = runner + 1
        off = offs.get(aid, 0.0)
        open_lease: Optional[Dict[str, Any]] = None
        open_t: Optional[float] = None
        last_t: Optional[float] = None
        out.append({"name": "thread_name", "ph": "M", "pid": pid,
                    "tid": AGENT_LANE_TID,
                    "args": {"name": "agent {}".format(aid)}})
        out.append({"name": "thread_sort_index", "ph": "M", "pid": pid,
                    "tid": AGENT_LANE_TID,
                    "args": {"sort_index": AGENT_LANE_TID}})

        def _close(end_t: float) -> None:
            nonlocal open_lease, open_t
            if open_lease is None or open_t is None:
                return
            key = (aid, open_lease.get("exp"), open_lease.get("pid"))
            exec_index.setdefault(key, []).append((open_t, end_t))
            out.append({"name": "exec {}".format(open_lease.get("exp")),
                        "cat": "agent", "ph": "X", "ts": us(open_t),
                        "dur": max(1, us(end_t) - us(open_t)),
                        "pid": pid, "tid": AGENT_LANE_TID,
                        "args": {"agent": aid,
                                 "exp": open_lease.get("exp"),
                                 "slot": open_lease.get("pid"),
                                 "offset_s": off}})
            open_lease, open_t = None, None

        for ev in sorted((e for e in a_events
                          if isinstance(e.get("t"), (int, float))),
                         key=lambda e: e["t"]):
            t = ev["t"] - off  # agent clock -> fleet clock
            last_t = t
            kind = ev.get("ev")
            if kind == "agent" and ev.get("phase") == "lease":
                _close(t)
                open_lease, open_t = ev, t
            elif kind == "agent" and ev.get("phase") == "done":
                _close(t)
            elif kind in ("clock_offset", "sink_degraded",
                          "sink_recovered", "obs_started"):
                out.append({"name": kind, "cat": "agent", "ph": "i",
                            "s": "t", "ts": us(t), "pid": pid,
                            "tid": AGENT_LANE_TID,
                            "args": {k: v for k, v in ev.items()
                                     if k not in ("ev", "t")}})
        if open_lease is not None and last_t is not None:
            _close(last_t)  # journal ended mid-lease

    # Flow arrows: ABIND dispatch (fleet journal 'agent' lease event,
    # driver track) -> agent-side exec slice -> the trial's FINAL on the
    # runner's experiment lane. Leases match exec windows in delivery
    # order per (agent, exp, slot).
    finals: Dict[tuple, List[float]] = {}
    for name, evs in experiments.items():
        for ev in evs:
            if ev.get("ev") == "trial" and ev.get("phase") == "finalized" \
                    and ev.get("partition") is not None \
                    and isinstance(ev.get("t"), (int, float)):
                finals.setdefault((name, int(ev["partition"])),
                                  []).append(ev["t"])
    for fs in finals.values():
        fs.sort()
    exec_cursor: Dict[tuple, int] = {}
    flows = 0
    for ev in fleet_events:
        if ev.get("ev") != "agent" or ev.get("phase") != "lease" \
                or not isinstance(ev.get("t"), (int, float)):
            continue
        aid = str(ev.get("agent"))
        key = (aid, ev.get("exp"), ev.get("pid"))
        windows = exec_index.get(key) or []
        i = exec_cursor.get(key, 0)
        if i >= len(windows):
            continue
        exec_cursor[key] = i + 1
        exec_start, exec_end = windows[i]
        flows += 1
        fid = "abind-{}".format(flows)
        abind_t = ev["t"]
        pid = agent_runner[aid] + 1
        # Anchor slice on the driver track for the flow start.
        out.append({"name": "abind {}".format(ev.get("exp")),
                    "cat": "fleet", "ph": "X", "ts": us(abind_t),
                    "dur": 1000, "pid": DRIVER_PID, "tid": 0,
                    "args": {"agent": aid, "exp": ev.get("exp"),
                             "slot": ev.get("pid")}})
        out.append({"name": "trial-flow", "cat": "flow", "ph": "s",
                    "id": fid, "ts": us(abind_t), "pid": DRIVER_PID,
                    "tid": 0})
        out.append({"name": "trial-flow", "cat": "flow", "ph": "t",
                    "id": fid, "ts": us(exec_start) + 1, "pid": pid,
                    "tid": AGENT_LANE_TID})
        # The FINAL inside (or just after) the exec window, consumed
        # in order so each lease binds its own trial's FINAL.
        fin_list = finals.get((ev.get("exp"), ev.get("pid"))) or []
        fin = next((t for t in fin_list if exec_start <= t), None)
        if fin is not None:
            fin_list.remove(fin)
            out.append({"name": "trial-flow", "cat": "flow", "ph": "f",
                        "bp": "e", "id": fid, "ts": us(fin), "pid": pid,
                        "tid": exp_tid.get(ev.get("exp"), 0)})

    out.sort(key=lambda e: e.get("ts", 0))
    base["otherData"].update({
        "source": "maggy_tpu.telemetry(unified)",
        "agents": sorted(agent_runner),
        "clock_offsets": offs,
        "flows": flows,
    })
    return base


def validate_trace(trace: Dict[str, Any]) -> int:
    """Sanity-check a trace dict is loadable Chrome-trace JSON: a
    ``traceEvents`` list whose entries carry the mandatory keys. Returns
    the event count; raises ValueError otherwise. bench.py runs this on
    the emitted file before recording its path as an artifact."""
    events = trace.get("traceEvents") if isinstance(trace, dict) else None
    if not isinstance(events, list) or not events:
        raise ValueError("not a Chrome trace: missing/empty traceEvents")
    if all(ev.get("ph") == "M" for ev in events if isinstance(ev, dict)):
        raise ValueError("trace carries only metadata — the journal had "
                         "no renderable events")
    for ev in events:
        if not isinstance(ev, dict) or "ph" not in ev or "pid" not in ev:
            raise ValueError("malformed trace event: {!r}".format(ev))
        if ev["ph"] in ("X", "i", "C") and "ts" not in ev:
            raise ValueError("trace event without ts: {!r}".format(ev))
    json.dumps(trace)  # must be pure-JSON serializable
    return len(events)


def write_trace(events: List[Dict[str, Any]], out_path: str,
                env=None) -> int:
    """Build, validate, and write the trace. Returns the trace-event
    count."""
    trace = build_trace(events)
    n = validate_trace(trace)
    payload = json.dumps(trace)
    if env is not None:
        env.dump(payload, out_path)
    else:
        with open(out_path, "w") as f:
            f.write(payload)
    return n
