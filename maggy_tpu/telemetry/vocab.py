"""The journal vocabulary: every string the telemetry journal speaks.

One home for the stringly-typed contract between EMITTERS (``Telemetry.
trial_event`` / ``Telemetry.event`` call sites across the package) and
CONSUMERS (``spans.derive`` / ``replay_journal``, ``trace.py``,
``monitor``, ``chaos/harness.py`` invariants, ``fleet.replay_fleet_
journal``). An emitter typo used to vanish silently from replay,
Perfetto, and invariant checking all at once; the ``journalvocab``
checker (``python -m maggy_tpu.analysis``) now statically verifies

- every literal phase/kind/reason EMITTED appears here,
- every entry here is emitted somewhere (no orphan vocabulary), and
- every literal a CONSUMER matches against appears here (a consumer typo
  matches nothing — the worst kind of false green).

Extend the vocabulary here FIRST, then emit/consume. Entries are plain
frozensets so the checker (pure AST, no imports) can read them
literally: keep every entry a literal string in this file.
"""

from __future__ import annotations

#: Trial-span lifecycle + annotation phases (``ev: "trial"`` events).
#: Nominal order; see telemetry/spans.py for the semantics of each.
SPAN_PHASES = (
    "suggested", "queued", "assigned", "running", "first_metric",
    "stop_flagged", "stop_sent", "finalized", "lost", "requeued",
    "profile_skipped", "prefetch_hit", "prefetch_miss",
    "preempt_requested", "preempted", "resumed", "compiled",
    # Gang scheduling (maggy_tpu.gang): the trial's contiguous chip
    # block became fully held and the leader was dispatched / the
    # block's chips returned to the pool (fields: members, chips; the
    # pair brackets the trial's N-chip busy interval in replay_pack).
    "gang_assembled", "gang_released",
    # Checkpoint-forking search (docs/user.md "Forking search"): this
    # trial was dispatched to RESUME from another trial's checkpoint —
    # an ASHA promotion continuing its rung parent, a PBT exploit
    # copying the winner, a BO near-duplicate warm start. Fields:
    # parent (the source trial id), step (the checkpoint step forked
    # from), partition. The genealogy edge trace.py renders as a
    # parent→child Perfetto flow arrow and derive()'s fork block counts
    # steps_saved from.
    "forked_from",
    # Runner-measured checkpoint I/O totals for one trial, shipped once
    # at trial end through the heartbeat stats channel (mirrors
    # "compiled"). Fields: save_ms, restore_ms, saves, restores,
    # partition. The goodput ledger's ckpt_save / ckpt_restore badput
    # buckets fold from this record.
    "ckpt_saved",
)

#: Top-level journal event kinds (the ``ev`` field).
EVENT_KINDS = frozenset({
    "trial",                  # span phase occurrence (phase in SPAN_PHASES)
    "suggest",                # controller suggest() latency sample
    "runner_stats",           # heartbeat-piggybacked runner stats delta
    "runner",                 # trial-runner lifecycle (phase: RUNNER_PHASES)
    "worker",                 # dist-worker lifecycle (phase: WORKER_PHASES)
    "experiment",             # experiment lifecycle (phase: EXPERIMENT_PHASES)
    "prefetch_invalidated",   # schedule-stale prefetches dropped
    "chaos",                  # one fault injection
    "chaos_armed",            # chaos engine armed for the experiment
    "chaos_summary",          # end-of-experiment injection tally
    "health",                 # health engine finding / lifecycle
    "fleet",                  # fleet lifecycle (phase: FLEET_PHASES)
    "fleet_submit",           # experiment submitted to the fleet
    "fleet_admit",            # experiment admitted past the queue
    "fleet_experiment",       # per-experiment fleet lifecycle
    "lease",                  # runner lease start/end (phase: LEASE_PHASES)
    "preempt",                # fleet preemption decision
    "pack",                   # gang placer decision (op: init/reserve/
                              #   stall/release — maggy_tpu.gang)
    "obs_started",            # observability server bound (host, port) —
                              #   journaled so tools can discover an
                              #   ephemeral (port 0) bind
    "profile_captured",       # device profile + thread dump artifact
                              #   written (path, reason: manual|auto,
                              #   check, partition — telemetry.profiling)
    "shed",                   # load shed: an admission refused at the
                              #   fleet's max_queued bound (scope=
                              #   "admission", fleet journal) or a frame+
                              #   connection dropped at a tenant's full
                              #   dispatch queue (scope="rpc", tenant
                              #   journal) — rpc.SharedServer /
                              #   fleet.FleetScheduler
    "agent",                  # remote fleet-agent lifecycle (phase:
                              #   AGENT_PHASES — fleet journal lane per
                              #   agent; maggy_tpu.fleet.agent)
    "jsink",                  # journal-sink ingest record: one JSINK
                              #   batch demuxed into a per-source
                              #   segment (source, n, dup, sid, lag_ms —
                              #   fleet journal; telemetry/sink.py)
    "sink_degraded",          # a source's shipper lost the sink and
                              #   fell back to its local journal
                              #   (telemetry/sink.py SinkJournal)
    "sink_recovered",         # the shipper reconnected; the spooled
                              #   suffix re-ships (sid-deduped)
    "clock_offset",           # RTT-bounded clock-offset estimate for
                              #   one agent vs the fleet host (offset_s,
                              #   rtt_s — Cristian's algorithm over the
                              #   AJOIN/ALEASE exchange; journaled
                              #   fleet-side per agent and agent-side)
    "driver_epoch",           # driver incarnation boundary: a (re)started
                              #   driver journals the epoch it claimed via
                              #   util.claim_driver_epoch — the seam
                              #   crash-only recovery and invariant 13
                              #   split a multi-incarnation journal on
    "ckpt_gc",                # checkpoint garbage collection: a parent
                              #   rung's checkpoint dir retired once no
                              #   live or schedulable child can still
                              #   fork from it (trial, parent of no one
                              #   pending — fields: trial, why; bounds
                              #   disk growth of forking sweeps)
})

#: ``reason=`` on a trial ``requeued`` phase: why it re-entered the
#: schedule.
REQUEUE_REASONS = frozenset({
    "blacklist",        # executor died and re-registered (BLACK path)
    "heartbeat_loss",   # runner went silent holding the trial (LOST path)
    "dead_partition",   # fresh suggestion rerouted off a dead runner
    "preempted",        # graceful scheduler preemption (resume-capable)
    "gang_member_lost",  # a gang member died: whole lease revoked, the
                         # trial reassembles a fresh gang (exactly once)
    "fork_source_lost",  # a forked trial's staged checkpoint AND its
                         # parent's vanished before re-dispatch (disk
                         # loss / raced GC): the fork is downgraded to a
                         # from-scratch run — journaled so genealogy
                         # shows the downgrade instead of a silent
                         # restart-at-0
    "vmap_block_lost",   # a vectorized block's runner died (LOST/BLACK)
                         # or its leader was preempted: every live lane
                         # requeues exactly once as an individual scalar
                         # trial (chaos invariant 16 — no phantom FINALs,
                         # no lane lost to the block seam)
})

#: ``reason=`` on a ``profile_captured`` event: what triggered the
#: capture — an operator /profilez request or the health engine's
#: first-flag auto-capture hook (telemetry/profiling.py).
PROFILE_REASONS = frozenset({"manual", "auto"})

#: ``phase=`` per non-trial event kind.
#: ``recovered`` = crash-only recovery rebuilt the control plane from
#: the journal (trial store + reservations + controller state); fields
#: carry the reconstruction counts (inflight, adopted_partitions, ...).
EXPERIMENT_PHASES = frozenset({"start", "resumed", "recovered",
                               "finalized", "end"})
#: ``adopted`` = a pre-crash runner's first message re-bound it to the
#: restarted driver (JOIN resume path / heartbeat / retried FINAL).
RUNNER_PHASES = frozenset({"registered", "adopted"})
WORKER_PHASES = frozenset({"registered", "finalized"})
FLEET_PHASES = frozenset({"start", "stop"})
#: fleet_experiment mirrors the scheduler entry states.
FLEET_EXPERIMENT_PHASES = frozenset({"start", "done", "failed"})
LEASE_PHASES = frozenset({"start", "end"})
#: ``reason=`` on a lease ``end``. ``agent_lost`` = the remote agent
#: serving the lease went silent past the liveness bound mid-lease (the
#: fleet revoked it; the experiment's own slot-reclaim liveness requeues
#: the trial exactly once).
LEASE_END_REASONS = frozenset({"released", "error", "agent_lost"})
#: ``phase=`` on an ``agent`` event: one remote agent's lifecycle in the
#: fleet journal — join (AJOIN admitted), lease (ABIND delivered), done
#: (ADONE received, lease closed), lost (silent past the liveness
#: bound), leave (orderly exit / fleet shutdown).
AGENT_PHASES = frozenset({"join", "lease", "done", "lost", "leave"})

#: Chaos fault kinds — the ``kind=`` field of ``ev: "chaos"`` injection
#: records (mirrors chaos/plan.py KINDS; the chaos plan validates kinds
#: at build time, this copy lets replay/trace/invariant consumers be
#: checked without importing the chaos engine).
CHAOS_KINDS = frozenset({
    "kill_runner", "stall_runner", "fake_preemption", "preempt_trial",
    "kill_gang_member",
    "drop_msg", "delay_msg", "sever_conn", "env_write_fail",
    # Fleet scale soak (fleet/soak.py run_slow_tenant_soak): one tenant's
    # handlers artificially delayed — the head-of-line-isolation fault.
    # Injected by the soak harness (not a plan.py fault kind): it wraps
    # ONE experiment's handle_message, which per-verb plan targeting
    # cannot express (partition ids overlap across tenants).
    "slow_tenant",
    # Agent soak (fleet/soak.py run_agent_soak): a remote agent process
    # SIGKILLed mid-lease — invariant 11 (lease revoked, trial requeued
    # exactly once). Harness-injected like slow_tenant: the chaos plan's
    # pool-level kill cannot reach an agent in another OS process.
    "kill_agent",
    # Sink soak (fleet/soak.py run_sink_soak): the fleet's journal-sink
    # tenant detached mid-soak — invariant 12 (shippers degrade to local
    # journals, re-ship on reconnect, zero lost / zero duplicate events
    # per event id, zero experiment failures). Harness-injected: the
    # sink is fleet infrastructure, not an experiment-plan target.
    "kill_sink",
    # Driver soak (chaos/driver_soak.py run_driver_soak): the DRIVER
    # process SIGKILLed mid-sweep and restarted with resume — invariant
    # 13 (journal replay rebuilds the control plane; no trial lost, no
    # duplicate FINAL, completed trials never re-run, the sweep
    # completes on survivors). Harness-injected: the fault kills the
    # process that owns the chaos engine, so no in-process plan can
    # record it — the soak appends the record to the quiesced journal.
    "kill_driver",
    # Fork soak (chaos/harness.py run_fork_soak, `--fork`): the runner
    # a forked trial was just dispatched to is killed (plan kind, fired
    # on_phase=forked_from) — invariant 14: exactly-once requeue
    # resuming from the SAME fork point, genealogy intact.
    "kill_fork",
})

#: The goodput ledger's closed chip-time taxonomy (telemetry/goodput.py):
#: every held runner-second folds into exactly one bucket. ``train`` is
#: goodput; everything else is badput; ``unaccounted`` is the explicit
#: residual the bench gate bounds (never silently absorbed into another
#: bucket). Order is the canonical reporting order.
GOODPUT_BUCKETS = (
    "train",          # inside train_fn, productive (first-run) steps
    "init",           # sharded state init (compiled record init_ms)
    "trace",          # jaxpr trace (compiled record trace_ms)
    "compile",        # XLA compile (compiled record compile_ms)
    "ckpt_save",      # checkpoint writes (ckpt_saved record save_ms)
    "ckpt_restore",   # checkpoint reads (ckpt_saved record restore_ms)
    "fork_stage",     # parent-checkpoint staging (fork_load_ms)
    "rework",         # re-trained work: dead attempts + from-scratch
                      #   promotions re-running the parent prefix
    "handoff",        # FINAL -> next running gap (< HANDOFF_CAP_S)
    "queue_wait",     # runner registered -> first trial running
    "idle",           # reserved but trial-less (rung barriers, drain)
    "lane_idle",      # vectorized blocks (config.vmap_lanes): a masked
                      #   (early-stopped) lane's share of block chip-time
                      #   after its own FINAL while surviving lanes kept
                      #   training — the price of lockstep execution
    "unaccounted",    # residual the accounting could not attribute
)

#: Health-engine event fields (``ev: "health"``).
HEALTH_STATUSES = frozenset({"raised", "cleared", "started", "error"})
HEALTH_CHECKS = frozenset({"engine", "straggler", "hb_rtt", "hang"})

#: Everything a consumer may match a ``phase`` field against — the union
#: the journalvocab checker verifies consumer literals into.
ALL_PHASES = (frozenset(SPAN_PHASES) | EXPERIMENT_PHASES | RUNNER_PHASES
              | WORKER_PHASES | FLEET_PHASES | FLEET_EXPERIMENT_PHASES
              | LEASE_PHASES | AGENT_PHASES)
ALL_REASONS = REQUEUE_REASONS | LEASE_END_REASONS | PROFILE_REASONS

__all__ = [
    "SPAN_PHASES", "EVENT_KINDS", "REQUEUE_REASONS", "PROFILE_REASONS",
    "GOODPUT_BUCKETS",
    "EXPERIMENT_PHASES", "RUNNER_PHASES", "WORKER_PHASES",
    "FLEET_PHASES", "FLEET_EXPERIMENT_PHASES", "LEASE_PHASES",
    "LEASE_END_REASONS", "AGENT_PHASES", "CHAOS_KINDS",
    "HEALTH_STATUSES", "HEALTH_CHECKS", "ALL_PHASES", "ALL_REASONS",
]
