"""Per-trial TensorBoard integration.

Parity: reference `maggy/tensorboard.py` — module-global logdir registered
per trial (:25-44), HParams-plugin experiment config for the searchspace
(:75-87) and per-trial hparams (:90-93). Implemented over
`torch.utils.tensorboard` (bundled; avoids importing full TF) with a JSON
fallback, plus `jax.profiler` trace capture as the idiomatic TPU addition
(SURVEY.md §5.1).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

_logdir: Optional[str] = None
_writer = None


def _make_writer(logdir: str):
    try:
        from torch.utils.tensorboard import SummaryWriter

        return SummaryWriter(log_dir=logdir)
    except Exception:  # noqa: BLE001 - TB optional; JSON fallback below
        return None


def _register(trial_logdir: str) -> None:
    """Called by the trial executor when a trial starts."""
    global _logdir, _writer
    _close()
    os.makedirs(trial_logdir, exist_ok=True)
    _logdir = trial_logdir
    _writer = _make_writer(trial_logdir)


def _close() -> None:
    global _writer, _logdir
    if _writer is not None:
        try:
            _writer.close()
        except Exception:  # noqa: BLE001
            pass
    _writer = None
    _logdir = None


def logdir() -> str:
    """The current trial's TensorBoard logdir (reference `tensorboard.py:33`)."""
    if _logdir is None:
        raise RuntimeError("No trial logdir registered; are you inside a trial?")
    return _logdir


def add_scalar(tag: str, value: float, step: int = 0) -> None:
    if _writer is not None:
        _writer.add_scalar(tag, value, step)
    elif _logdir is not None:
        with open(os.path.join(_logdir, "scalars.jsonl"), "a") as f:
            f.write(json.dumps({"tag": tag, "value": float(value), "step": step}) + "\n")


def write_hparams(hparams: Dict[str, Any], metrics: Optional[Dict[str, float]] = None) -> None:
    """Per-trial hparams record (reference `tensorboard.py:90-93`)."""
    if _logdir is None:
        return
    if _writer is not None:
        clean = {k: v if isinstance(v, (int, float, str, bool)) else str(v)
                 for k, v in hparams.items()}
        _writer.add_hparams(clean, metrics or {}, run_name=".")
    else:
        with open(os.path.join(_logdir, "hparams.json"), "w") as f:
            json.dump(hparams, f, default=str)


def start_trace(trace_dir: Optional[str] = None) -> None:
    """Capture a jax.profiler trace into the trial logdir (viewable in
    TensorBoard's profile plugin)."""
    import jax

    jax.profiler.start_trace(trace_dir or logdir())


def stop_trace() -> None:
    import jax

    jax.profiler.stop_trace()
