"""Per-trial TensorBoard integration — torch-free.

Parity: reference `maggy/tensorboard.py` — module-global logdir registered
per trial (:25-44), HParams-plugin experiment config for the searchspace
(:75-87) and per-trial hparams (:90-93). The reference writes real TF event
files through `tf.summary`; a JAX framework must not pull in torch (or a
full TF session) for that, so this module writes event files directly with
the `tensorboard` package's own `EventFileWriter` + HParams-plugin protos:

- `add_scalar` -> a `Summary.Value(simple_value=...)` event per call;
- `write_hparams` -> the HParams plugin's `session_start_info` record (the
  dashboard groups each trial dir as one session);
- `_close` -> `session_end_info` (STATUS_SUCCESS) + flush;
- `write_experiment_config` -> the experiment-level `hparams_config` record
  mapping the Searchspace to HParam domains (dashboard column setup).

Falls back to JSON artifacts when the `tensorboard` package is absent.
`jax.profiler` trace capture is the idiomatic TPU addition (SURVEY.md §5.1);
traces land in the trial logdir and open in TB's profile plugin.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Optional

_logdir: Optional[str] = None
_writer = None


def _clean_hparams(hparams: Dict[str, Any]) -> Dict[str, Any]:
    return {k: v if isinstance(v, (int, float, str, bool)) else str(v)
            for k, v in hparams.items()}


class _EventWriter:
    """Thin wrapper over tensorboard's EventFileWriter with the HParams
    plugin records. Proto note: when tensorflow is installed the hparams
    helpers return TF-flavored protos while EventFileWriter wants
    tensorboard.compat protos — they are wire-identical, so we re-parse."""

    def __init__(self, logdir: str):
        from tensorboard.summary.writer.event_file_writer import EventFileWriter

        self._writer = EventFileWriter(logdir)

    def _event(self, **kwargs):
        from tensorboard.compat.proto.event_pb2 import Event

        return Event(wall_time=time.time(), **kwargs)

    def _compat(self, summary):
        from tensorboard.compat.proto.summary_pb2 import Summary

        if isinstance(summary, Summary):
            return summary
        return Summary.FromString(summary.SerializeToString())

    def add_scalar(self, tag: str, value: float, step: int) -> None:
        from tensorboard.compat.proto.summary_pb2 import Summary

        summary = Summary(value=[Summary.Value(tag=tag,
                                               simple_value=float(value))])
        self._writer.add_event(self._event(step=int(step), summary=summary))

    def write_hparams(self, hparams: Dict[str, Any],
                      metrics: Optional[Dict[str, float]]) -> None:
        from tensorboard.plugins.hparams import summary as hp_summary

        start = hp_summary.session_start_pb(_clean_hparams(hparams))
        self._writer.add_event(self._event(summary=self._compat(start)))
        for tag, value in (metrics or {}).items():
            self.add_scalar(tag, value, 0)

    def write_experiment(self, summary_pb) -> None:
        self._writer.add_event(self._event(summary=self._compat(summary_pb)))

    def close(self) -> None:
        from tensorboard.plugins.hparams import summary as hp_summary

        try:
            end = hp_summary.session_end_pb("STATUS_SUCCESS")
            self._writer.add_event(self._event(summary=self._compat(end)))
        except Exception:  # noqa: BLE001 - close must always flush
            pass
        self._writer.flush()
        self._writer.close()


def _make_writer(logdir: str):
    try:
        return _EventWriter(logdir)
    except Exception:  # noqa: BLE001 - tensorboard optional; JSON fallback
        return None


def _register(trial_logdir: str) -> None:
    """Called by the trial executor when a trial starts."""
    global _logdir, _writer
    _close()
    os.makedirs(trial_logdir, exist_ok=True)
    _logdir = trial_logdir
    _writer = _make_writer(trial_logdir)


def _close() -> None:
    global _writer, _logdir
    if _writer is not None:
        try:
            _writer.close()
        except Exception:  # noqa: BLE001
            pass
    _writer = None
    _logdir = None


def logdir() -> str:
    """The current trial's TensorBoard logdir (reference `tensorboard.py:33`)."""
    if _logdir is None:
        raise RuntimeError("No trial logdir registered; are you inside a trial?")
    return _logdir


def add_scalar(tag: str, value: float, step: int = 0) -> None:
    if _writer is not None:
        _writer.add_scalar(tag, value, step)
    elif _logdir is not None:
        with open(os.path.join(_logdir, "scalars.jsonl"), "a") as f:
            f.write(json.dumps({"tag": tag, "value": float(value), "step": step}) + "\n")


def write_hparams(hparams: Dict[str, Any], metrics: Optional[Dict[str, float]] = None) -> None:
    """Per-trial hparams record (reference `tensorboard.py:90-93`)."""
    if _logdir is None:
        return
    if _writer is not None:
        _writer.write_hparams(hparams, metrics)
    else:
        with open(os.path.join(_logdir, "hparams.json"), "w") as f:
            json.dump(hparams, f, default=str)


def _experiment_pb(searchspace):
    """Searchspace -> HParams-plugin experiment config proto (the dashboard
    column setup; reference `tensorboard.py:75-87`)."""
    from tensorboard.plugins.hparams import api as hp
    from tensorboard.plugins.hparams import summary_v2 as hp_v2

    hparams = []
    for name, spec in searchspace.to_dict().items():
        hp_type, region = spec["type"], spec["values"]
        if hp_type == "DOUBLE":
            dom = hp.RealInterval(float(region[0]), float(region[1]))
        elif hp_type == "INTEGER":
            dom = hp.IntInterval(int(region[0]), int(region[1]))
        else:  # DISCRETE / CATEGORICAL
            dom = hp.Discrete(list(region))
        hparams.append(hp.HParam(name, dom))
    return hp_v2.hparams_config_pb(
        hparams=hparams, metrics=[hp.Metric("metric")])


def write_experiment_config(exp_dir: str, searchspace) -> None:
    """Experiment-level HParams dashboard config, written once at startup
    into ``exp_dir/tensorboard`` (TB treats each trial dir as a session
    under this root)."""
    if searchspace is None:
        return
    try:
        pb = _experiment_pb(searchspace)
        w = _EventWriter(os.path.join(exp_dir, "tensorboard"))
        w.write_experiment(pb)
        w._writer.flush()
        w._writer.close()
    except Exception:  # noqa: BLE001 - TB must never block an experiment
        pass


def start_trace(trace_dir: Optional[str] = None) -> None:
    """Capture a jax.profiler trace into the trial logdir (viewable in
    TensorBoard's profile plugin)."""
    import jax

    jax.profiler.start_trace(trace_dir or logdir())


def stop_trace() -> None:
    import jax

    jax.profiler.stop_trace()
