"""Per-trial TensorBoard integration — torch-free.

Parity: reference `maggy/tensorboard.py` — module-global logdir registered
per trial (:25-44), HParams-plugin experiment config for the searchspace
(:75-87) and per-trial hparams (:90-93). The reference writes real TF event
files through `tf.summary`; a JAX framework must not pull in torch (or a
full TF session) for that, so this module writes event files directly with
the `tensorboard` package's own `EventFileWriter` + HParams-plugin protos:

- `add_scalar` -> a `Summary.Value(simple_value=...)` event per call;
- `write_hparams` -> the HParams plugin's `session_start_info` record (the
  dashboard groups each trial dir as one session);
- `_close` -> `session_end_info` (STATUS_SUCCESS) + flush;
- `write_experiment_config` -> the experiment-level `hparams_config` record
  mapping the Searchspace to HParam domains (dashboard column setup).

The HParams records are assembled directly from the plugin's proto modules
(`api_pb2`/`plugin_data_pb2`/`metadata`) rather than through
`tensorboard.plugins.hparams.{api,summary}`: those helper modules import
full TensorFlow (~5 s), which would land on the experiment-startup critical
path the first time a searchspace config or trial hparams record is
written. The proto modules load in ~0.2 s with no TF.

Falls back to JSON artifacts when the `tensorboard` package is absent.
`jax.profiler` trace capture is the idiomatic TPU addition (SURVEY.md §5.1);
traces land in the trial logdir and open in TB's profile plugin.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, Optional

# Per-THREAD registry: trial runners are threads sharing this module (the
# reference's executors are separate processes, `trial_executor.py:122`, so
# its module-global logdir is per-trial for free — here a module global
# would let concurrent trials close/steal each other's writers).
_state = threading.local()


def _get(name: str):
    return getattr(_state, name, None)


def _clean_hparams(hparams: Dict[str, Any]) -> Dict[str, Any]:
    return {k: v if isinstance(v, (int, float, str, bool)) else str(v)
            for k, v in hparams.items()}


def _hp_record(tag: str, plugin_data):
    """Summary carrying one HParamsPluginData record (compat-flavored, so
    it feeds EventFileWriter without re-parsing)."""
    from tensorboard.compat.proto.summary_pb2 import Summary
    from tensorboard.plugins.hparams import metadata as hp_meta

    s = Summary()
    v = s.value.add(tag=tag, metadata=hp_meta.create_summary_metadata(plugin_data))
    v.tensor.CopyFrom(hp_meta.NULL_TENSOR)
    return s


def _session_start_summary(hparams: Dict[str, Any]):
    from tensorboard.plugins.hparams import metadata as hp_meta
    from tensorboard.plugins.hparams import plugin_data_pb2

    info = plugin_data_pb2.SessionStartInfo(start_time_secs=time.time())
    for name, val in hparams.items():
        if isinstance(val, bool):  # before int: bool is an int subtype
            info.hparams[name].bool_value = val
        elif isinstance(val, (int, float)):
            info.hparams[name].number_value = val
        else:
            info.hparams[name].string_value = str(val)
    return _hp_record(
        hp_meta.SESSION_START_INFO_TAG,
        plugin_data_pb2.HParamsPluginData(session_start_info=info))


def _session_end_summary():
    from tensorboard.plugins.hparams import api_pb2
    from tensorboard.plugins.hparams import metadata as hp_meta
    from tensorboard.plugins.hparams import plugin_data_pb2

    info = plugin_data_pb2.SessionEndInfo(
        status=api_pb2.STATUS_SUCCESS, end_time_secs=time.time())
    return _hp_record(
        hp_meta.SESSION_END_INFO_TAG,
        plugin_data_pb2.HParamsPluginData(session_end_info=info))


def _force_tb_stub() -> None:
    """Point tensorboard.compat's lazy `tf` at the bundled stub unless real
    TensorFlow is already loaded. EventFileWriter only needs `tf.io.gfile`;
    without this, its first use triggers `import tensorflow` (~5 s) on the
    experiment-startup critical path. Installing the `tensorboard.compat.notf`
    marker module is the package's documented way to force the stub."""
    import sys
    import types

    if "tensorflow" not in sys.modules:
        sys.modules.setdefault(
            "tensorboard.compat.notf", types.ModuleType("tensorboard.compat.notf"))


class _EventWriter:
    """Thin wrapper over tensorboard's EventFileWriter with the HParams
    plugin records (built proto-level — see module docstring)."""

    def __init__(self, logdir: str):
        _force_tb_stub()
        from tensorboard.summary.writer.event_file_writer import EventFileWriter

        # Remote logdirs (gs://...) are STAGED locally and uploaded through
        # the experiment env's filesystem at close. EventFileWriter's own
        # remote support resolves gs:// via a fresh gcsfs client — not the
        # env's (possibly injected/authenticated) fs — and its writer
        # thread BLOCKS the experiment forever when that client can't
        # reach the bucket. Trade-off: no live remote tail; event files
        # land whole at trial/experiment end.
        self._remote_dir = None
        if _is_remote(logdir):
            import tempfile

            self._remote_dir = logdir
            logdir = tempfile.mkdtemp(prefix="maggy_tb_staging_")
        self._staging_dir = logdir
        self._writer = EventFileWriter(logdir)

    def _event(self, **kwargs):
        from tensorboard.compat.proto.event_pb2 import Event

        return Event(wall_time=time.time(), **kwargs)

    def add_scalar(self, tag: str, value: float, step: int) -> None:
        from tensorboard.compat.proto.summary_pb2 import Summary

        summary = Summary(value=[Summary.Value(tag=tag,
                                               simple_value=float(value))])
        self._writer.add_event(self._event(step=int(step), summary=summary))

    def write_hparams(self, hparams: Dict[str, Any],
                      metrics: Optional[Dict[str, float]]) -> None:
        start = _session_start_summary(_clean_hparams(hparams))
        self._writer.add_event(self._event(summary=start))
        for tag, value in (metrics or {}).items():
            self.add_scalar(tag, value, 0)

    def write_experiment(self, summary_pb) -> None:
        self._writer.add_event(self._event(summary=summary_pb))

    def finish(self) -> None:
        """Flush + close the event file and upload a staged remote logdir
        — the teardown every one-shot writer (experiment config, telemetry
        scalars) needs, without the per-trial session_end record."""
        self._writer.flush()
        self._writer.close()
        if self._remote_dir is not None:
            _upload_tree(self._staging_dir, self._remote_dir)

    def close(self) -> None:
        try:
            self._writer.add_event(self._event(summary=_session_end_summary()))
        except Exception:  # noqa: BLE001 - close must always flush
            pass
        self.finish()


def _is_remote(path: str) -> bool:
    return "://" in path


def _upload_tree(local_dir: str, remote_dir: str) -> None:
    """Copy a staged logdir to its remote home via the experiment env's
    filesystem (best-effort: TB artifacts must never fail a trial)."""
    import shutil

    from maggy_tpu.core.environment import EnvSing

    try:
        env = EnvSing.get_instance()
        for root, _, files in os.walk(local_dir):
            rel = os.path.relpath(root, local_dir)
            for fname in files:
                remote = "/".join(p for p in (
                    remote_dir, "" if rel == "." else rel, fname) if p)
                with open(os.path.join(root, fname), "rb") as src, \
                        env.open_file(remote, "wb") as dst:
                    # Chunked: profiler traces run to GBs; slurping would
                    # spike runner RSS at trial close.
                    shutil.copyfileobj(src, dst)
    except Exception:  # noqa: BLE001
        pass
    finally:
        # The staging dir exists only to be uploaded; one leaks per trial
        # (or per trace) otherwise — /tmp is tmpfs on TPU VMs.
        shutil.rmtree(local_dir, ignore_errors=True)


def _make_writer(logdir: str):
    try:
        return _EventWriter(logdir)
    except Exception:  # noqa: BLE001 - tensorboard optional; JSON fallback
        return None


def _register(trial_logdir: str) -> None:
    """Called by the trial executor (in the runner's thread) when a trial
    starts; closes this thread's previous trial writer."""
    _close()
    if not _is_remote(trial_logdir):
        os.makedirs(trial_logdir, exist_ok=True)
    _state.logdir = trial_logdir
    _state.writer = _make_writer(trial_logdir)


def _close() -> None:
    writer = _get("writer")
    if writer is not None:
        try:
            writer.close()
        except Exception:  # noqa: BLE001
            pass
    _state.writer = None
    _state.logdir = None


def logdir() -> str:
    """The current trial's TensorBoard logdir (reference `tensorboard.py:33`)."""
    current = _get("logdir")
    if current is None:
        raise RuntimeError("No trial logdir registered; are you inside a trial?")
    return current


def add_scalar(tag: str, value: float, step: int = 0) -> None:
    writer, current = _get("writer"), _get("logdir")
    if writer is not None:
        writer.add_scalar(tag, value, step)
    elif current is not None and not _is_remote(current):
        with open(os.path.join(current, "scalars.jsonl"), "a") as f:
            f.write(json.dumps({"tag": tag, "value": float(value), "step": step}) + "\n")


def write_hparams(hparams: Dict[str, Any], metrics: Optional[Dict[str, float]] = None) -> None:
    """Per-trial hparams record (reference `tensorboard.py:90-93`)."""
    writer, current = _get("writer"), _get("logdir")
    if current is None:
        return
    if writer is not None:
        writer.write_hparams(hparams, metrics)
    elif not _is_remote(current):
        with open(os.path.join(current, "hparams.json"), "w") as f:
            json.dump(hparams, f, default=str)


def _experiment_pb(searchspace):
    """Searchspace -> HParams-plugin experiment config proto (the dashboard
    column setup; reference `tensorboard.py:75-87`). Built proto-level: the
    `hparams.api` helper module imports full TensorFlow."""
    from google.protobuf import struct_pb2
    from tensorboard.plugins.hparams import api_pb2
    from tensorboard.plugins.hparams import metadata as hp_meta
    from tensorboard.plugins.hparams import plugin_data_pb2

    infos = []
    for name, spec in searchspace.to_dict().items():
        hp_type, region = spec["type"], spec["values"]
        from maggy_tpu.searchspace import Searchspace

        if hp_type in Searchspace.CONTINUOUS_TYPES:
            infos.append(api_pb2.HParamInfo(
                name=name, type=api_pb2.DATA_TYPE_FLOAT64,
                domain_interval=api_pb2.Interval(
                    min_value=float(region[0]), max_value=float(region[1]))))
        else:  # DISCRETE / CATEGORICAL
            numeric = all(isinstance(v, (int, float)) and not isinstance(v, bool)
                          for v in region)
            domain = struct_pb2.ListValue()
            for v in region:
                if numeric:
                    domain.values.add().number_value = float(v)
                else:
                    domain.values.add().string_value = str(v)
            infos.append(api_pb2.HParamInfo(
                name=name,
                type=(api_pb2.DATA_TYPE_FLOAT64 if numeric
                      else api_pb2.DATA_TYPE_STRING),
                domain_discrete=domain))
    experiment = api_pb2.Experiment(
        time_created_secs=time.time(), hparam_infos=infos,
        metric_infos=[api_pb2.MetricInfo(
            name=api_pb2.MetricName(tag="metric"))])
    return _hp_record(
        hp_meta.EXPERIMENT_TAG,
        plugin_data_pb2.HParamsPluginData(experiment=experiment))


def write_experiment_config(exp_dir: str, searchspace) -> None:
    """Experiment-level HParams dashboard config, written once at startup
    into ``exp_dir/tensorboard`` (TB treats each trial dir as a session
    under this root)."""
    if searchspace is None:
        return
    try:
        pb = _experiment_pb(searchspace)
        w = _EventWriter("/".join((exp_dir, "tensorboard"))
                         if _is_remote(exp_dir)
                         else os.path.join(exp_dir, "tensorboard"))
        w.write_experiment(pb)
        w.finish()
    except Exception:  # noqa: BLE001 - TB must never block an experiment
        pass


def write_telemetry_scalars(exp_dir: str, snapshot: Dict[str, Any]) -> None:
    """Mirror a telemetry snapshot's derived scheduling numbers into the
    experiment-level TensorBoard dir (next to the hparams config), so the
    dashboard shows hand-off gap / early-stop reaction alongside the sweep.
    Best-effort like every TB artifact; JSON fallback when the tensorboard
    package is absent."""
    spans = (snapshot or {}).get("spans") or {}
    scalars: Dict[str, float] = {}
    for group in ("handoff", "early_stop_reaction", "requeue_recovery"):
        stats = spans.get(group) or {}
        for key in ("median_ms", "p95_ms", "n"):
            if stats.get(key) is not None:
                scalars["telemetry/{}_{}".format(group, key)] = float(stats[key])
    for key, val in (spans.get("trials") or {}).items():
        scalars["telemetry/trials_{}".format(key)] = float(val)
    if not scalars:
        return
    logdir = ("/".join((exp_dir, "tensorboard")) if _is_remote(exp_dir)
              else os.path.join(exp_dir, "tensorboard"))
    try:
        w = _EventWriter(logdir)
    except Exception:  # noqa: BLE001 - tensorboard optional; JSON fallback
        if not _is_remote(logdir):
            os.makedirs(logdir, exist_ok=True)
            with open(os.path.join(logdir, "telemetry_scalars.json"), "w") as f:
                json.dump(scalars, f, indent=2)
        return
    try:
        for tag, value in sorted(scalars.items()):
            w.add_scalar(tag, value, 0)
        w.finish()
    except Exception:  # noqa: BLE001 - TB must never block an experiment
        pass


def start_trace(trace_dir: Optional[str] = None) -> None:
    """Capture a jax.profiler trace into the trial logdir (viewable in
    TensorBoard's profile plugin). Remote logdirs are staged locally and
    uploaded at stop_trace (same rationale as _EventWriter)."""
    import jax

    target = trace_dir or logdir()
    if _is_remote(target):
        import tempfile

        _state.trace_staging = (tempfile.mkdtemp(prefix="maggy_trace_"), target)
        target = _state.trace_staging[0]
    else:
        _state.trace_staging = None
    jax.profiler.start_trace(target)


def stop_trace() -> None:
    import jax

    jax.profiler.stop_trace()
    staging = _get("trace_staging")
    if staging is not None:
        _upload_tree(staging[0], staging[1])
        _state.trace_staging = None
