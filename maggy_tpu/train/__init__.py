from maggy_tpu.train.trainer import (
    cross_entropy_loss,
    init_train_state,
    make_train_step,
    next_token_loss,
    swept_transform,
    Trainer,
)
from maggy_tpu.train.data import ShardedBatchIterator
from maggy_tpu.train.registry import DatasetRegistry
from maggy_tpu.train.warm import clear_warm, warm_cache

__all__ = ["cross_entropy_loss", "init_train_state", "make_train_step",
           "next_token_loss", "swept_transform", "Trainer",
           "ShardedBatchIterator", "DatasetRegistry", "clear_warm",
           "warm_cache"]
