from maggy_tpu.train.trainer import (
    build_step_fn,
    cross_entropy_loss,
    init_train_state,
    make_train_step,
    next_token_loss,
    swept_transform,
    Trainer,
)
from maggy_tpu.train.data import ShardedBatchIterator
from maggy_tpu.train.registry import DatasetRegistry
from maggy_tpu.train.vmap import VmapTrainer
from maggy_tpu.train.warm import clear_warm, warm_cache

__all__ = ["build_step_fn", "cross_entropy_loss", "init_train_state",
           "make_train_step", "next_token_loss", "swept_transform",
           "Trainer", "VmapTrainer", "ShardedBatchIterator",
           "DatasetRegistry", "clear_warm", "warm_cache"]
