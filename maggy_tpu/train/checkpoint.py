"""Per-trial checkpoint/resume via orbax.

Parity gap being closed (SURVEY.md §5.4): the reference has NO model-state
checkpointing — a promoted ASHA trial re-runs from scratch (noted at
`hyperband.py:325-326` as a wanted optimization). Here each trial dir can
hold an orbax checkpoint; a promoted trial restores its parent's state and
continues training at the bigger budget, which is a direct trials/hour win.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Optional

# orbax/tensorstore checkpoint I/O is not thread-safe within one process
# (async finalization renames race); thread-pooled trial runners share a
# process, so serialize all checkpoint ops. Trials spend ~all their time
# training, not checkpointing, so contention is negligible.
_CKPT_LOCK = threading.Lock()


class TrialCheckpointer:
    def __init__(self, trial_dir: str, max_to_keep: int = 1):
        import orbax.checkpoint as ocp

        self.path = os.path.abspath(os.path.join(trial_dir, "checkpoints"))
        with _CKPT_LOCK:
            self.manager = ocp.CheckpointManager(
                self.path,
                options=ocp.CheckpointManagerOptions(
                    max_to_keep=max_to_keep, enable_async_checkpointing=False),
            )

    def save(self, step: int, state: Any) -> None:
        """Synchronous save (async checkpointing is disabled above, so the
        write has fully landed when this returns)."""
        import orbax.checkpoint as ocp

        with _CKPT_LOCK:
            self.manager.save(step, args=ocp.args.StandardSave(state))

    def latest_step(self) -> Optional[int]:
        with _CKPT_LOCK:
            return self.manager.latest_step()

    def restore(self, abstract_state: Any, step: Optional[int] = None) -> Any:
        import orbax.checkpoint as ocp

        with _CKPT_LOCK:
            step = step if step is not None else self.manager.latest_step()
            if step is None:
                return None
            return self.manager.restore(
                step, args=ocp.args.StandardRestore(abstract_state))

    def close(self) -> None:
        with _CKPT_LOCK:
            self.manager.close()


def latest_checkpoint_step(trial_dir: str) -> Optional[int]:
    """Newest checkpointed step under ``trial_dir`` or None — by listing
    the CheckpointManager's per-step directory layout directly, so the
    preemption ack path (which only needs the NUMBER) never pays the
    orbax import or touches checkpoint I/O."""
    path = os.path.join(trial_dir, "checkpoints")
    if not os.path.isdir(path):
        return None
    steps = [int(name) for name in os.listdir(path) if name.isdigit()]
    return max(steps) if steps else None


def restore_parent_state(exp_dir: str, parent_trial_id: str,
                         abstract_state: Any) -> Optional[Any]:
    """Warm-start a promoted trial from its parent's checkpoint (the ASHA
    promotion carries `info_dict["parent"]`)."""
    parent_dir = os.path.join(exp_dir, parent_trial_id)
    if not os.path.isdir(os.path.join(parent_dir, "checkpoints")):
        return None
    ckpt = TrialCheckpointer(parent_dir)
    try:
        return ckpt.restore(abstract_state)
    finally:
        ckpt.close()


# ------------------------------------------------- cross-trial forking

def latest_checkpoint_step_env(env, trial_dir: str) -> Optional[int]:
    """``latest_checkpoint_step`` through the environment abstraction, so
    the DRIVER can resolve a parent's ack'd checkpoint step at fork-stamp
    time on local fs AND GCS (the local helper above stays the runner's
    import-free fast path)."""
    path = "{}/checkpoints".format(trial_dir)
    if not env.isdir(path):
        return None
    steps = [int(name) for name in env.ls(path) if name.isdigit()]
    return max(steps) if steps else None


def _copy_tree_env(env, src: str, dst: str) -> int:
    """Recursive env-abstracted copy (returns files copied). Used by the
    fork staging below for envs with no local filesystem (GCS).
    Byte-exact by construction: checkpoint artifacts are opaque data, so
    every file round-trips as bytes — no text-mode encoding detour."""
    copied = 0
    env.mkdir(dst)
    for name in env.ls(src):
        s, d = "{}/{}".format(src, name), "{}/{}".format(dst, name)
        if env.isdir(s):
            copied += _copy_tree_env(env, s, d)
        else:
            with env.open_file(s, "rb") as f:
                data = f.read()
            with env.open_file(d, "wb") as out:
                out.write(data)
            copied += 1
    return copied


def fork_checkpoint(env, exp_dir: str, parent_trial_id: str,
                    child_trial_dir: str,
                    step: Optional[int] = None) -> Optional[int]:
    """Stage the parent trial's checkpoint into the child's trial dir so
    the child RESUMES instead of re-training — the cross-trial
    generalization of PR 5's same-trial resume (``ctx.resume_step``). The
    copy makes the child self-contained: its own ``restore_checkpoint``
    works unchanged, a requeued fork re-stages idempotently, and the
    parent's dir stays intact for siblings (a PBT winner may donate to
    several exploiting members).

    ``step``: the specific checkpoint step to stage (None = the parent's
    latest). Returns the staged step, or None when the parent has no
    usable checkpoint (the caller falls back to a from-scratch run).
    Idempotent AND crash-safe: a child that already holds a COMPLETE
    copy of the step (a re-dispatched requeue, or a raced double-stage)
    returns it without copying, while a copy torn by a mid-staging death
    (the kill-mid-fork chaos scenario) is detected and re-copied — the
    local path publishes atomically (tmp dir + os.replace), the env
    path writes a ``.fork_complete.<step>`` marker LAST (next to the
    step dir, never inside it — orbax must not see foreign files — and
    non-digit, so ``latest_checkpoint_step`` never counts it)."""
    target = step
    parent_dir = "{}/{}".format(exp_dir, parent_trial_id)
    if target is None:
        target = latest_checkpoint_step_env(env, parent_dir)
        if target is None:
            return None
    local = getattr(env, "FAST_LOCAL_WRITES", False)
    child_step_dir = "{}/checkpoints/{}".format(child_trial_dir, target)
    marker = "{}/checkpoints/.fork_complete.{}".format(child_trial_dir,
                                                       target)
    if env.isdir(child_step_dir) and (local or env.exists(marker)):
        # Already staged (local publishes are atomic; remote copies are
        # complete iff the marker landed) — or the child checkpointed
        # this step itself on a local fs, which is just as restorable.
        return int(target)
    src = "{}/checkpoints/{}".format(parent_dir, target)
    if not env.isdir(src):
        return None
    if local and os.path.isdir(src):
        # Local fs fast path: one shutil tree copy, no per-file env hops.
        import shutil

        os.makedirs(os.path.dirname(child_step_dir), exist_ok=True)
        tmp = child_step_dir + ".fork_tmp"
        if os.path.isdir(tmp):
            shutil.rmtree(tmp)
        shutil.copytree(src, tmp)
        # Atomic publish: a crash mid-copy leaves only the tmp dir, which
        # the next staging attempt replaces — latest_checkpoint_step
        # never sees a half-copied step (its name is not a digit).
        os.replace(tmp, child_step_dir)
    else:
        # Re-copy overwrites a torn partial byte-for-byte; the marker
        # write is the publish point.
        _copy_tree_env(env, src, child_step_dir)
        env.dump("{}", marker)
    return int(target)
