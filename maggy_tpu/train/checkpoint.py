"""Per-trial checkpoint/resume via orbax.

Parity gap being closed (SURVEY.md §5.4): the reference has NO model-state
checkpointing — a promoted ASHA trial re-runs from scratch (noted at
`hyperband.py:325-326` as a wanted optimization). Here each trial dir can
hold an orbax checkpoint; a promoted trial restores its parent's state and
continues training at the bigger budget, which is a direct trials/hour win.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Optional

# orbax/tensorstore checkpoint I/O is not thread-safe within one process
# (async finalization renames race); thread-pooled trial runners share a
# process, so serialize all checkpoint ops. Trials spend ~all their time
# training, not checkpointing, so contention is negligible.
_CKPT_LOCK = threading.Lock()


class TrialCheckpointer:
    def __init__(self, trial_dir: str, max_to_keep: int = 1):
        import orbax.checkpoint as ocp

        self.path = os.path.abspath(os.path.join(trial_dir, "checkpoints"))
        with _CKPT_LOCK:
            self.manager = ocp.CheckpointManager(
                self.path,
                options=ocp.CheckpointManagerOptions(
                    max_to_keep=max_to_keep, enable_async_checkpointing=False),
            )

    def save(self, step: int, state: Any) -> None:
        """Synchronous save (async checkpointing is disabled above, so the
        write has fully landed when this returns)."""
        import orbax.checkpoint as ocp

        with _CKPT_LOCK:
            self.manager.save(step, args=ocp.args.StandardSave(state))

    def latest_step(self) -> Optional[int]:
        with _CKPT_LOCK:
            return self.manager.latest_step()

    def restore(self, abstract_state: Any, step: Optional[int] = None) -> Any:
        import orbax.checkpoint as ocp

        with _CKPT_LOCK:
            step = step if step is not None else self.manager.latest_step()
            if step is None:
                return None
            return self.manager.restore(
                step, args=ocp.args.StandardRestore(abstract_state))

    def close(self) -> None:
        with _CKPT_LOCK:
            self.manager.close()


def latest_checkpoint_step(trial_dir: str) -> Optional[int]:
    """Newest checkpointed step under ``trial_dir`` or None — by listing
    the CheckpointManager's per-step directory layout directly, so the
    preemption ack path (which only needs the NUMBER) never pays the
    orbax import or touches checkpoint I/O."""
    path = os.path.join(trial_dir, "checkpoints")
    if not os.path.isdir(path):
        return None
    steps = [int(name) for name in os.listdir(path) if name.isdigit()]
    return max(steps) if steps else None


def restore_parent_state(exp_dir: str, parent_trial_id: str,
                         abstract_state: Any) -> Optional[Any]:
    """Warm-start a promoted trial from its parent's checkpoint (the ASHA
    promotion carries `info_dict["parent"]`)."""
    parent_dir = os.path.join(exp_dir, parent_trial_id)
    if not os.path.isdir(os.path.join(parent_dir, "checkpoints")):
        return None
    ckpt = TrialCheckpointer(parent_dir)
    try:
        return ckpt.restore(abstract_state)
    finally:
        ckpt.close()
