"""Input pipeline: transparent per-rank sharding + device placement.

Parity: reference `maggy/core/patching.py` (`MaggyDataLoader`) — in-memory
datasets get a DistributedSampler (:50-68) and path datasets are sharded by
``cur_shard=RANK, shard_count=WORLD_SIZE`` (:70-81), with automatic device
movement (:89-107). TPU-native version: numpy-array datasets sharded by the
same (current_shard, shard_count) contract, batched, and `jax.device_put`
onto the mesh's batch sharding — no global monkey-patching of a DataLoader
class (the reference patches `torch.utils.data.DataLoader` on import,
`dist_executor.py:36-37`, which we deliberately avoid).
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, Optional

import numpy as np


class ShardedBatchIterator:
    """Iterate minibatches of a dict-of-arrays dataset, restricted to this
    process's shard, optionally placed onto a mesh."""

    def __init__(
        self,
        data: Dict[str, np.ndarray],
        batch_size: int,
        shard_count: int = 1,
        current_shard: int = 0,
        shuffle: bool = True,
        seed: int = 0,
        drop_remainder: bool = True,
        mesh=None,
        epochs: Optional[int] = 1,
    ):
        if not data:
            raise ValueError("Empty dataset.")
        sizes = {k: len(v) for k, v in data.items()}
        if len(set(sizes.values())) != 1:
            raise ValueError("All arrays must share the leading dim: {}".format(sizes))
        if not (0 <= current_shard < shard_count):
            raise ValueError("current_shard must be in [0, shard_count)")
        self.data = data
        self.n = next(iter(sizes.values()))
        self.batch_size = batch_size
        self.shard_count = shard_count
        self.current_shard = current_shard
        self.shuffle = shuffle
        self.seed = seed
        self.drop_remainder = drop_remainder
        self.mesh = mesh
        self.epochs = epochs

    def _shard_indices(self, epoch: int) -> np.ndarray:
        idx = np.arange(self.n)
        if self.shuffle:
            # Same permutation on every shard (seeded by epoch), disjoint
            # slices per shard — the DistributedSampler contract.
            rng = np.random.default_rng(self.seed + epoch)
            idx = rng.permutation(idx)
        return idx[self.current_shard::self.shard_count]

    def __iter__(self) -> Iterator[Dict[str, Any]]:
        epoch = 0
        while self.epochs is None or epoch < self.epochs:
            idx = self._shard_indices(epoch)
            stop = len(idx) - self.batch_size + 1 if self.drop_remainder \
                else len(idx)
            for start in range(0, max(stop, 0), self.batch_size):
                sel = idx[start:start + self.batch_size]
                batch = {k: v[sel] for k, v in self.data.items()}
                if self.mesh is not None:
                    batch = self._place(batch)
                yield batch
            epoch += 1

    def _place(self, batch):
        import jax

        from maggy_tpu.parallel.sharding import batch_sharding

        # shape= lets the seq-axis rule skip tensors whose dim 1 isn't a
        # sequence dim (e.g. [B, features] labels on a seq-parallel mesh).
        return {k: jax.device_put(v, batch_sharding(self.mesh, shape=v.shape))
                for k, v in batch.items()}

    def __len__(self) -> int:
        # Exact size of THIS shard's slice idx[current_shard::shard_count]
        # (early shards get the ceil share).
        per_shard = (self.n - self.current_shard + self.shard_count - 1) \
            // self.shard_count
        full = per_shard // self.batch_size
        if not self.drop_remainder and per_shard % self.batch_size:
            full += 1
        return full * (self.epochs or 1)
