"""Input pipeline: transparent per-rank sharding + device placement.

Parity: reference `maggy/core/patching.py` (`MaggyDataLoader`) — in-memory
datasets get a DistributedSampler (:50-68) and path datasets are sharded by
``cur_shard=RANK, shard_count=WORLD_SIZE`` (:70-81), with automatic device
movement (:89-107). TPU-native version: numpy-array datasets sharded by the
same (current_shard, shard_count) contract, batched, and `jax.device_put`
onto the mesh's batch sharding — no global monkey-patching of a DataLoader
class (the reference patches `torch.utils.data.DataLoader` on import,
`dist_executor.py:36-37`, which we deliberately avoid).
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, Optional

import numpy as np


class ShardedBatchIterator:
    """Iterate minibatches of a dict-of-arrays dataset, restricted to this
    process's shard, optionally placed onto a mesh."""

    def __init__(
        self,
        data: Dict[str, np.ndarray],
        batch_size: int,
        shard_count: int = 1,
        current_shard: int = 0,
        shuffle: bool = True,
        seed: int = 0,
        drop_remainder: bool = True,
        mesh=None,
        epochs: Optional[int] = 1,
        prefetch: int = 0,
    ):
        if not data:
            raise ValueError("Empty dataset.")
        sizes = {k: len(v) for k, v in data.items()}
        if len(set(sizes.values())) != 1:
            raise ValueError("All arrays must share the leading dim: {}".format(sizes))
        if not (0 <= current_shard < shard_count):
            raise ValueError("current_shard must be in [0, shard_count)")
        self.data = data
        self.n = next(iter(sizes.values()))
        self.batch_size = batch_size
        self.shard_count = shard_count
        self.current_shard = current_shard
        self.shuffle = shuffle
        self.seed = seed
        self.drop_remainder = drop_remainder
        self.mesh = mesh
        self.epochs = epochs
        self.prefetch = prefetch

    def _shard_indices(self, epoch: int) -> np.ndarray:
        idx = np.arange(self.n)
        if self.shuffle:
            # Same permutation on every shard (seeded by epoch), disjoint
            # slices per shard — the DistributedSampler contract.
            rng = np.random.default_rng(self.seed + epoch)
            idx = rng.permutation(idx)
        return idx[self.current_shard::self.shard_count]

    def __iter__(self) -> Iterator[Dict[str, Any]]:
        it = self._generate()
        # Gather + host->device copy run in a producer thread, `prefetch`
        # batches ahead, so input staging overlaps the (async-dispatched)
        # device compute of earlier steps.
        return prefetch_iterator(it, self.prefetch) if self.prefetch > 0 else it

    def _generate(self) -> Iterator[Dict[str, Any]]:
        epoch = 0
        while self.epochs is None or epoch < self.epochs:
            idx = self._shard_indices(epoch)
            stop = len(idx) - self.batch_size + 1 if self.drop_remainder \
                else len(idx)
            for start in range(0, max(stop, 0), self.batch_size):
                sel = idx[start:start + self.batch_size]
                batch = {k: v[sel] for k, v in self.data.items()}
                if self.mesh is not None:
                    batch = self._place(batch)
                yield batch
            epoch += 1

    def _place(self, batch):
        import jax

        from maggy_tpu.parallel.sharding import cached_batch_sharding

        # shape= lets the seq-axis rule skip tensors whose dim 1 isn't a
        # sequence dim (e.g. [B, features] labels on a seq-parallel mesh);
        # the sharding is memoized by (mesh, shape) so the steady-state
        # loop skips the per-leaf spec re-derivation.
        return {k: jax.device_put(v, cached_batch_sharding(self.mesh, v.shape))
                for k, v in batch.items()}

    @classmethod
    def from_path(
        cls,
        path: str,
        batch_size: int,
        columns: Optional[list] = None,
        shard_by: str = "row",
        shard_count: int = 1,
        current_shard: int = 0,
        **kwargs,
    ) -> "ShardedBatchIterator":
        """Build an iterator from an on-disk dataset: a ``.parquet`` file, a
        directory of ``.parquet`` files, or a ``.npz`` archive.

        Parity: the reference's path-dataset mode shards petastorm/parquet
        readers with ``cur_shard=RANK, shard_count=WORLD_SIZE`` (reference
        `patching.py:69-81`). ``shard_by="row"`` reproduces those semantics
        exactly (disjoint row slices of a shared permutation);
        ``shard_by="file"`` assigns whole parquet files round-robin to
        shards before loading, so each host only reads its own files —
        the right choice when the dataset is large and file-partitioned.
        """
        if shard_by not in ("row", "file"):
            raise ValueError("shard_by must be 'row' or 'file'")
        if shard_by == "file":
            data = load_path_dataset(path, columns=columns,
                                     file_shard=(current_shard, shard_count))
            # Rows within this shard's files all belong to this shard.
            return cls(data, batch_size, shard_count=1, current_shard=0,
                       **kwargs)
        data = load_path_dataset(path, columns=columns)
        return cls(data, batch_size, shard_count=shard_count,
                   current_shard=current_shard, **kwargs)

    def __len__(self) -> int:
        # Exact size of THIS shard's slice idx[current_shard::shard_count]
        # (early shards get the ceil share).
        per_shard = (self.n - self.current_shard + self.shard_count - 1) \
            // self.shard_count
        full = per_shard // self.batch_size
        if not self.drop_remainder and per_shard % self.batch_size:
            full += 1
        return full * (self.epochs or 1)


def prefetch_iterator(iterator, size: int = 2):
    """Run ``iterator`` in a daemon producer thread, keeping up to ``size``
    items staged. Producer exceptions re-raise at the consumer."""
    import queue
    import threading

    if size < 1:
        raise ValueError("prefetch size must be >= 1")
    q: "queue.Queue" = queue.Queue(maxsize=size)
    end = object()
    errors: list = []
    stop = threading.Event()

    def producer():
        try:
            for item in iterator:
                # Bounded put that watches for consumer abandonment: an
                # early-stopped trial (EarlyStopException mid-epoch) drops
                # the generator, and the producer must not stay blocked on
                # a full queue forever.
                while not stop.is_set():
                    try:
                        q.put(item, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if stop.is_set():
                    return
        except BaseException as e:  # noqa: BLE001 - re-raised at consumer
            errors.append(e)
        finally:
            # The sentinel must actually arrive (a full queue would swallow
            # put_nowait and leave the consumer blocked forever after it
            # drains); same bounded stop-watching put as for items.
            while not stop.is_set():
                try:
                    q.put(end, timeout=0.1)
                    break
                except queue.Full:
                    continue

    threading.Thread(target=producer, daemon=True,
                     name="batch-prefetch").start()
    try:
        while True:
            item = q.get()
            if item is end:
                if errors:
                    raise errors[0]
                return
            yield item
    finally:
        stop.set()


def drop_feature(data: Dict[str, Any], feature: Optional[str]) -> Dict[str, Any]:
    """Dict-of-arrays minus one column — the LOCO dataset ablation step
    (the reference drops the ablated feature from the training-dataset
    schema itself, `loco.py:41-80`). Returns a new dict whose values ALIAS
    the input arrays (shallow); `feature_dropping_generator` adds the
    per-trial copies. An unknown feature raises: silently "dropping"
    nothing would corrupt the study's comparison."""
    if feature is None:
        return dict(data)
    if feature not in data:
        raise KeyError(
            "Ablated feature {!r} is not a column of the dataset "
            "(have: {}).".format(feature, sorted(data)))
    return {k: v for k, v in data.items() if k != feature}


def feature_dropping_generator(source):
    """Build a LOCO ``dataset_generator``: ``gen(ablated_feature=None)``
    returns the training data as a dict of arrays minus the ablated
    feature. ``source`` is a dict of arrays or a path `load_path_dataset`
    understands (.npz / .parquet / .tfrecord / dirs); paths are loaded once per
    process and cached across the study's trials. Each call returns FRESH
    array copies — trials routinely normalize in place, and aliased arrays
    would leak one trial's mutations into every other (concurrent
    in-process runners share this generator)."""
    cache = {}

    def generator(ablated_feature: Optional[str] = None):
        if isinstance(source, str):
            if "data" not in cache:
                cache["data"] = load_path_dataset(source)
            data = cache["data"]
        else:
            data = source
        return {k: np.array(v, copy=True)
                for k, v in drop_feature(data, ablated_feature).items()}

    return generator


def load_path_dataset(path, columns=None, file_shard=None,
                      registry_root=None):
    """Load an on-disk dataset into a dict of numpy arrays.

    Supported formats: a ``.npz`` archive, a single ``.parquet`` file, a
    directory of ``.parquet`` files, a ``.tfrecord``/``.tfrecords`` file,
    or a directory of them (the reference's feature-store format,
    `loco.py:41-80`), plus ``registry://name[@version]`` URIs resolved
    through the dataset registry (train/registry.py — the featurestore-
    equivalent indirection); ``registry_root`` (or
    $MAGGY_TPU_REGISTRY_ROOT) addresses a registry outside the default
    ``<base dir>/datasets`` root. ``file_shard=(current, count)`` restricts
    a parquet/tfrecord directory to files ``[current::count]`` (file-level
    sharding; single files and npz archives reject it — there is nothing to
    split without reading everything anyway).
    """
    import os

    from maggy_tpu.train import registry as _reg
    from maggy_tpu.train import tfrecord as _tfr

    if _reg.is_registry_uri(path):
        path = _reg.resolve_path(path, root=registry_root)

    if _tfr.is_tfrecord_path(path):
        if os.path.isdir(path):
            files = sorted(
                os.path.join(path, f) for f in os.listdir(path)
                if f.endswith((".tfrecord", ".tfrecords")))
            if file_shard is not None:
                current, count = file_shard
                if count > len(files):
                    raise ValueError(
                        "{} shards but only {} tfrecord files; use "
                        "shard_by='row'".format(count, len(files)))
                files = files[current::count]
        else:
            if file_shard is not None and file_shard[1] > 1:
                raise ValueError(
                    "file-level sharding needs a tfrecord directory")
            files = [path]
        return _tfr.load_tfrecord_dataset(files, columns=columns)

    if path.endswith(".npz"):
        if file_shard is not None and file_shard[1] > 1:
            raise ValueError("file-level sharding needs a parquet directory")
        with np.load(path) as archive:
            keys = columns or list(archive.keys())
            return {k: archive[k] for k in keys}

    if os.path.isdir(path):
        files = sorted(
            os.path.join(path, f) for f in os.listdir(path)
            if f.endswith(".parquet"))
        if not files:
            raise ValueError("No .parquet files under {}".format(path))
        if file_shard is not None:
            current, count = file_shard
            if count > len(files):
                raise ValueError(
                    "{} shards but only {} parquet files; use shard_by='row'"
                    .format(count, len(files)))
            files = files[current::count]
    elif path.endswith(".parquet"):
        if file_shard is not None and file_shard[1] > 1:
            raise ValueError("file-level sharding needs a parquet directory")
        files = [path]
    else:
        raise ValueError(
            "Unsupported dataset path {!r} (.npz, .parquet, or a directory "
            "of .parquet files)".format(path))

    import pyarrow.parquet as pq

    tables = [pq.read_table(f, columns=columns) for f in files]
    table = tables[0] if len(tables) == 1 else _concat_tables(tables)
    out = {}
    for name in table.column_names:
        col = table.column(name).to_numpy(zero_copy_only=False)
        out[name] = np.asarray(col)
    return out


def _concat_tables(tables):
    import pyarrow as pa

    return pa.concat_tables(tables)
