"""LoRA training utilities: freeze the base model, train only adapters.

The flagship sweep (BASELINE.md config 5, "Llama-3-8B LoRA hyperparameter
sweep") trains ONLY the low-rank adapter matrices injected by
`models.llama.LoRADense` (`lora_a` / `lora_b` leaves of the params tree).
`optax.masked` gives exactly that: masked-out (frozen) parameters get no
optimizer state at all, so at 8B scale the Adam moments shrink from
~64 GB (2 x fp32 x 8B) to megabytes — the difference between a sweep that
fits a v4-32 slice and one that does not.

The reference has no model/optimizer code (SURVEY.md §5.7); this module is
part of the TPU-native training surface around the sweep framework.
"""

from __future__ import annotations

from typing import Any

import jax
import optax


def _is_lora_path(path) -> bool:
    for entry in path:
        key = getattr(entry, "key", None)
        if key in ("lora_a", "lora_b"):
            return True
    return False


def lora_mask(params) -> Any:
    """Boolean pytree: True on `lora_a`/`lora_b` leaves, False elsewhere.

    Works on concrete params, `jax.eval_shape` outputs, and the full
    variables dict (mask follows structure).
    """
    return jax.tree_util.tree_map_with_path(
        lambda path, _: _is_lora_path(path), params)


def lora_adapter_count(params) -> int:
    """Number of trainable (adapter) parameters in ``params``."""
    total = 0
    for path, leaf in jax.tree_util.tree_leaves_with_path(params):
        if _is_lora_path(path) and hasattr(leaf, "shape"):
            size = 1
            for d in leaf.shape:
                size *= int(d)
            total += size
    return total


def only_lora(tx: optax.GradientTransformation) -> optax.GradientTransformation:
    """Wrap ``tx`` so it updates ONLY LoRA adapter leaves.

    Frozen (base-model) leaves receive zero updates and allocate no
    optimizer state (`optax.masked` stores a placeholder for them).
    Use with any optax optimizer::

        tx = only_lora(optax.adamw(lr))
    """
    return optax.masked(tx, lora_mask)
