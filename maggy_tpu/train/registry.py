"""Named, versioned dataset registry — the featurestore-equivalent surface.

The reference resolves datasets and their schemas through the Hopsworks
feature store (accessor surface on
`/root/reference/maggy/core/environment/abstractenvironment.py`; LOCO reads
dataset schemas from it in `/root/reference/maggy/ablation/ablator/loco.py:41-80`).
This is the platform-free equivalent: JSON manifests stored through the
active environment's fs ops, so the same registry works on a local disk and
on GCS (`core.environment.GCSEnv`) without code changes.

A manifest records ``{name, version, path, format, schema, description,
created}``. Consumers address datasets as ``registry://name`` (latest) or
``registry://name@<version>`` anywhere a dataset path is accepted
(`ShardedBatchIterator.from_path`, `AblationStudy(train_set=...)`,
`train.data.load_path_dataset`).
"""

from __future__ import annotations

import datetime
import json
import os
from typing import Any, Dict, List, Optional

REGISTRY_SCHEME = "registry://"

#: Overrides the default registry root (``<env base dir>/datasets``) for
#: every consumer that does not pass an explicit root — the data loaders'
#: ``registry://`` resolution and LOCO's registry probe included. Makes
#: custom-root registries URI-addressable without threading a root through
#: each call site.
REGISTRY_ROOT_ENV_VAR = "MAGGY_TPU_REGISTRY_ROOT"


def _env():
    from maggy_tpu.core.environment import EnvSing

    return EnvSing.get_instance()


class DatasetRegistry:
    """Register and resolve named dataset versions.

    ``root`` defaults to ``<environment base dir>/datasets``. All IO goes
    through the environment (atomic dumps, GCS transparency).
    """

    def __init__(self, env=None, root: Optional[str] = None):
        self.env = env or _env()
        self.root = (root or os.environ.get(REGISTRY_ROOT_ENV_VAR)
                     or self.env.experiment_base_dir() + "/datasets")

    # ------------------------------------------------------------- manifest
    def _dir(self, name: str) -> str:
        if not name or "/" in name or "@" in name:
            raise ValueError("Dataset names must be non-empty and contain "
                             "no '/' or '@': {!r}".format(name))
        return "{}/{}".format(self.root, name)

    def _manifest_path(self, name: str, version: int) -> str:
        return "{}/v{}.json".format(self._dir(name), int(version))

    def register(
        self,
        name: str,
        path: str,
        version: Optional[int] = None,
        schema: Optional[Dict[str, str]] = None,
        description: str = "",
    ) -> int:
        """Record a dataset version; returns the version number.

        ``version=None`` auto-increments past the latest. ``schema=None``
        infers column names/dtypes from the data (loads the source once —
        fine for sweep-sized sets; pass an explicit schema for huge ones).
        Re-registering an existing (name, version) raises: versions are
        immutable, append a new one instead.
        """
        if version is None:
            existing = self.versions(name)
            version = (existing[-1] + 1) if existing else 1
        mpath = self._manifest_path(name, version)
        if self.env.exists(mpath):
            raise ValueError(
                "{}@{} already registered; versions are immutable — "
                "register a new version instead.".format(name, version))
        if schema is None:
            schema = infer_schema(path)
        manifest = {
            "name": name,
            "version": int(version),
            "path": path,
            "format": _format_of(path),
            "schema": schema,
            "description": description,
            "created": datetime.datetime.now(
                datetime.timezone.utc).isoformat(),
        }
        self.env.mkdir(self._dir(name))
        payload = json.dumps(manifest, indent=2)
        # Concurrent registrations of the same name can race the
        # exists()-then-dump window and pick the same auto-version;
        # exclusive_create (O_CREAT|O_EXCL locally, if_generation_match=0
        # on GCS) makes exactly ONE writer win and every loser fail loudly
        # — dump()'s atomicity alone only prevented torn files, not
        # last-writer-wins lost updates.
        if not self.env.exclusive_create(payload, mpath):
            raise ValueError(
                "{}@{} was registered concurrently by another writer; "
                "retry to get a fresh version number.".format(name, version))
        return int(version)

    # -------------------------------------------------------------- lookup
    def names(self) -> List[str]:
        if not self.env.exists(self.root):
            return []
        return sorted(n for n in self.env.ls(self.root)
                      if self.env.isdir("{}/{}".format(self.root, n)))

    def versions(self, name: str) -> List[int]:
        d = self._dir(name)
        if not self.env.exists(d):
            return []
        out = []
        for f in self.env.ls(d):
            if f.startswith("v") and f.endswith(".json"):
                try:
                    out.append(int(f[1:-5]))
                except ValueError:
                    continue
        return sorted(out)

    def get(self, name: str, version: Optional[int] = None) -> Dict[str, Any]:
        """The manifest dict for ``name`` (latest version by default)."""
        if version is None:
            vs = self.versions(name)
            if not vs:
                raise KeyError("No dataset {!r} in the registry at {} "
                               "(known: {})".format(
                                   name, self.root, self.names()))
            version = vs[-1]
        mpath = self._manifest_path(name, version)
        if not self.env.exists(mpath):
            raise KeyError("No version {} of dataset {!r} (have: {})".format(
                version, name, self.versions(name)))
        return json.loads(self.env.load(mpath))

    def path(self, name: str, version: Optional[int] = None) -> str:
        return self.get(name, version)["path"]

    def schema(self, name: str, version: Optional[int] = None) -> Dict[str, str]:
        return self.get(name, version)["schema"]

    def features(self, name: str, version: Optional[int] = None) -> List[str]:
        """Column names — what LOCO ablates over (the reference reads these
        from the feature-store schema, ref `loco.py:41-80`)."""
        return sorted(self.schema(name, version))

    # ------------------------------------------------------------------ uri
    def resolve(self, uri: str) -> Dict[str, Any]:
        """``registry://name`` or ``registry://name@<version>`` -> manifest."""
        name, version = parse_uri(uri)
        return self.get(name, version)


def parse_uri(uri: str):
    if not uri.startswith(REGISTRY_SCHEME):
        raise ValueError("Not a registry URI: {!r}".format(uri))
    ref = uri[len(REGISTRY_SCHEME):]
    if "@" in ref:
        name, _, v = ref.partition("@")
        try:
            return name, int(v)
        except ValueError:
            raise ValueError("Bad registry version in {!r} (want "
                             "registry://name@<int>)".format(uri)) from None
    return ref, None


def is_registry_uri(path: Any) -> bool:
    return isinstance(path, str) and path.startswith(REGISTRY_SCHEME)


def resolve_path(uri: str, env=None, root: Optional[str] = None) -> str:
    """Registry URI -> concrete dataset path (module-level convenience for
    the data loaders). ``root`` (or $MAGGY_TPU_REGISTRY_ROOT) addresses a
    registry living outside the default ``<base dir>/datasets`` root."""
    return DatasetRegistry(env=env, root=root).resolve(uri)["path"]


def _format_of(path: str) -> str:
    from maggy_tpu.train import tfrecord as _tfr

    if _tfr.is_tfrecord_path(path):
        return "tfrecord"
    if path.endswith(".npz"):
        return "npz"
    return "parquet"


def infer_schema(path: str) -> Dict[str, str]:
    """Column -> dtype string, read from the data itself."""
    from maggy_tpu.train.data import load_path_dataset

    data = load_path_dataset(path)
    return {k: str(v.dtype) for k, v in data.items()}
