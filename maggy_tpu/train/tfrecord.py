"""TFRecord + ``tf.train.Example`` reader/writer, dependency-free.

Parity: the reference's LOCO ablator consumes feature-store TFRecords and
drops the ablated column from the dataset schema
(reference ``maggy/ablation/ablator/loco.py:41-80``, which delegates to the
Hopsworks ``get_training_dataset`` TFRecord path). Here the format is
parsed directly — importing TensorFlow costs seconds of process startup
(the round-3 lagom latency fix removed every TF import from the hot path)
and pins a second ML runtime for what is a ~100-line container format:

- TFRecord framing: ``u64 length ‖ u32 masked-crc32c(length) ‖ payload ‖
  u32 masked-crc32c(payload)``.
- Payload: a ``tf.train.Example`` protobuf — a string-keyed map of
  ``Feature`` values, each one of bytes_list / float_list / int64_list.

The writer emits real masked-crc32c frames (TensorFlow can read files
written here — round-tripped in tests); the reader verifies them.
"""

from __future__ import annotations

import os
import struct
from typing import Any, Dict, Iterator, List, Optional

import numpy as np

# ------------------------------------------------------------------ crc32c

_CRC32C_POLY = 0x82F63B78
_CRC32C_TABLE = []
for _n in range(256):
    _c = _n
    for _ in range(8):
        _c = (_c >> 1) ^ _CRC32C_POLY if _c & 1 else _c >> 1
    _CRC32C_TABLE.append(_c)


def crc32c(data: bytes) -> int:
    # Native slice-by-8 path (~GB/s) with the table fallback (~MB/s): the
    # crc dominates TFRecord ingestion cost.
    try:
        from maggy_tpu import native as _native

        value = _native.crc32c(bytes(data))
        if value is not None:
            return value
    except Exception:  # noqa: BLE001 - fallback must always work
        pass
    crc = 0xFFFFFFFF
    for b in data:
        crc = (crc >> 8) ^ _CRC32C_TABLE[(crc ^ b) & 0xFF]
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    """TFRecord's rotated+offset crc mask (tensorflow/core/lib/hash/crc32c.h)."""
    crc = crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# ------------------------------------------------------- protobuf wire fmt

def _varint(value: int) -> bytes:
    out = bytearray()
    while True:
        bits = value & 0x7F
        value >>= 7
        if value:
            out.append(bits | 0x80)
        else:
            out.append(bits)
            return bytes(out)


def _read_varint(buf: bytes, pos: int):
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _tag(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _len_delim(field: int, payload: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(payload)) + payload


# ------------------------------------------------------------- Example enc

def _encode_feature(values) -> bytes:
    """One ``Feature``: bytes -> bytes_list(1), float -> float_list(2),
    int -> int64_list(3); lists stay lists."""
    if isinstance(values, (bytes, str, int, float, np.integer, np.floating)):
        values = [values]
    values = list(values)
    if not values:
        return _len_delim(3, b"")  # empty int64_list
    if all(isinstance(v, (bytes, str)) for v in values):
        inner = b"".join(
            _len_delim(1, v.encode() if isinstance(v, str) else bytes(v))
            for v in values)
        return _len_delim(1, inner)
    # A single float promotes the whole list: dispatching on the first
    # element alone would silently int()-truncate [1, 2.5] -> [1, 2].
    if all(isinstance(v, (int, float, np.integer, np.floating, bool,
                          np.bool_)) for v in values):
        if any(isinstance(v, (float, np.floating)) for v in values):
            inner = _tag(1, 2) + _varint(4 * len(values)) + struct.pack(
                "<{}f".format(len(values)), *[float(v) for v in values])
            return _len_delim(2, inner)
        packed = b"".join(_varint(int(v) & 0xFFFFFFFFFFFFFFFF) for v in values)
        inner = _tag(1, 2) + _varint(len(packed)) + packed
        return _len_delim(3, inner)
    raise TypeError(
        "Unsupported or mixed feature value types {}".format(
            sorted({type(v).__name__ for v in values})))


def encode_example(features: Dict[str, Any]) -> bytes:
    """``dict`` -> serialized ``tf.train.Example``."""
    entries = b""
    for name, values in features.items():
        feature = _encode_feature(values)
        entry = _len_delim(1, name.encode()) + _len_delim(2, feature)
        entries += _len_delim(1, entry)  # Features.feature map entry
    return _len_delim(1, entries)  # Example.features


def _decode_packed_or_repeated(buf: bytes, scalar_wire: int):
    """Values of a {Bytes,Float,Int64}List's field 1, handling both packed
    (one LEN record) and unpacked (repeated scalar records) encodings."""
    out: List[Any] = []
    pos = 0
    while pos < len(buf):
        key, pos = _read_varint(buf, pos)
        field, wire = key >> 3, key & 7
        if field != 1:
            pos = _skip(buf, pos, wire)
            continue
        if wire == 2 and scalar_wire == 5:  # packed floats
            ln, pos = _read_varint(buf, pos)
            out.extend(struct.unpack("<{}f".format(ln // 4),
                                     buf[pos:pos + ln]))
            pos += ln
        elif wire == 2 and scalar_wire == 0:  # packed varints
            ln, pos = _read_varint(buf, pos)
            end = pos + ln
            while pos < end:
                v, pos = _read_varint(buf, pos)
                out.append(v - (1 << 64) if v >= (1 << 63) else v)
        elif wire == 2:  # bytes element
            ln, pos = _read_varint(buf, pos)
            out.append(buf[pos:pos + ln])
            pos += ln
        elif wire == 5:  # unpacked float
            out.append(struct.unpack("<f", buf[pos:pos + 4])[0])
            pos += 4
        elif wire == 0:  # unpacked varint
            v, pos = _read_varint(buf, pos)
            out.append(v - (1 << 64) if v >= (1 << 63) else v)
        else:
            pos = _skip(buf, pos, wire)
    return out


def _skip(buf: bytes, pos: int, wire: int) -> int:
    if wire == 0:
        _, pos = _read_varint(buf, pos)
    elif wire == 1:
        pos += 8
    elif wire == 2:
        ln, pos = _read_varint(buf, pos)
        pos += ln
    elif wire == 5:
        pos += 4
    else:
        raise ValueError("Unsupported wire type {}".format(wire))
    return pos


def _submessages(buf: bytes, want_field: int):
    pos = 0
    while pos < len(buf):
        key, pos = _read_varint(buf, pos)
        field, wire = key >> 3, key & 7
        if field == want_field and wire == 2:
            ln, pos = _read_varint(buf, pos)
            yield buf[pos:pos + ln]
            pos += ln
        else:
            pos = _skip(buf, pos, wire)


def decode_example(raw: bytes) -> Dict[str, List[Any]]:
    """Serialized ``tf.train.Example`` -> ``{name: [values...]}``."""
    out: Dict[str, List[Any]] = {}
    for features in _submessages(raw, 1):  # Example.features
        for entry in _submessages(features, 1):  # map entries
            name = None
            values: List[Any] = []
            pos = 0
            while pos < len(entry):
                key, pos = _read_varint(entry, pos)
                field, wire = key >> 3, key & 7
                if field == 1 and wire == 2:  # key
                    ln, pos = _read_varint(entry, pos)
                    name = entry[pos:pos + ln].decode()
                    pos += ln
                elif field == 2 and wire == 2:  # Feature
                    ln, pos = _read_varint(entry, pos)
                    feature = entry[pos:pos + ln]
                    pos += ln
                    for kind, scalar_wire in ((1, 2), (2, 5), (3, 0)):
                        for lst in _submessages(feature, kind):
                            values = _decode_packed_or_repeated(
                                lst, scalar_wire)
                else:
                    pos = _skip(entry, pos, wire)
            if name is not None:
                out[name] = values
    return out


# ------------------------------------------------------------ file framing

def write_tfrecord(path: str, examples) -> None:
    """Write ``examples`` (dicts of feature values) as a TFRecord file."""
    with open(path, "wb") as f:
        for ex in examples:
            payload = ex if isinstance(ex, bytes) else encode_example(ex)
            header = struct.pack("<Q", len(payload))
            f.write(header)
            f.write(struct.pack("<I", _masked_crc(header)))
            f.write(payload)
            f.write(struct.pack("<I", _masked_crc(payload)))


# Whole-buffer native scanning slurps the file plus ~1x its size of index
# arrays; past this size the streaming loop (which still uses the native
# crc32c per record) wins on peak memory.
_NATIVE_SCAN_MAX_BYTES = 256 * 1024 * 1024


def iter_tfrecord(path: str, verify: bool = True) -> Iterator[bytes]:
    """Yield raw record payloads from a TFRecord file. Small files go
    through the native whole-buffer scanner (crc verified in C++); large
    files stream record-by-record with bounded memory (the per-record crc
    still dispatches to the native crc32c when built)."""
    spans = data = None
    try:
        if os.path.getsize(path) <= _NATIVE_SCAN_MAX_BYTES:
            from maggy_tpu import native as _native

            if _native.is_native():
                data = open(path, "rb").read()
                spans = _native.tfrecord_scan(data, verify=verify)
    except ValueError as e:
        raise ValueError("{} in {}".format(e, path)) from e
    except Exception:  # noqa: BLE001 - fallback must always work
        spans = data = None
    if spans is not None:
        for off, ln in spans:
            yield data[off:off + ln]
        return
    with open(path, "rb") as f:
        while True:
            header = f.read(8)
            if not header:
                return
            if len(header) < 8:
                raise ValueError("Truncated TFRecord header in {}".format(path))
            (length,) = struct.unpack("<Q", header)
            crc_bytes = f.read(4)
            if len(crc_bytes) < 4:
                raise ValueError("Truncated TFRecord length crc in {}".format(path))
            (length_crc,) = struct.unpack("<I", crc_bytes)
            if verify and length_crc != _masked_crc(header):
                raise ValueError("Corrupt TFRecord length crc in {}".format(path))
            payload = f.read(length)
            if len(payload) < length:
                raise ValueError("Truncated TFRecord payload in {}".format(path))
            crc_bytes = f.read(4)
            if len(crc_bytes) < 4:
                raise ValueError("Truncated TFRecord payload crc in {}".format(path))
            (payload_crc,) = struct.unpack("<I", crc_bytes)
            if verify and payload_crc != _masked_crc(payload):
                raise ValueError("Corrupt TFRecord payload crc in {}".format(path))
            yield payload


def load_tfrecord_dataset(paths, columns: Optional[list] = None) -> Dict[str, np.ndarray]:
    """Read TFRecord file(s) of ``tf.train.Example`` into a dict of stacked
    numpy arrays — the dict-of-arrays shape every maggy_tpu data path
    (``ShardedBatchIterator``, LOCO's ``drop_feature``) consumes.

    Scalar features stack to shape ``(N,)``; fixed-length list features to
    ``(N, k)``. Ragged features raise (pad upstream). int64 lists become
    int64 arrays, float lists float32, bytes lists object arrays of bytes.
    """
    if isinstance(paths, str):
        paths = [paths]
    rows: List[Dict[str, List[Any]]] = []
    for path in paths:
        for payload in iter_tfrecord(path):
            ex = decode_example(payload)
            if columns is not None:
                missing = set(columns) - set(ex)
                if missing:
                    raise KeyError(
                        "TFRecord example in {} lacks column(s) {}".format(
                            path, sorted(missing)))
                ex = {k: ex[k] for k in columns}
            rows.append(ex)
    if not rows:
        raise ValueError("No records in {}".format(paths))
    names = set(rows[0])
    for i, r in enumerate(rows):
        if set(r) != names:
            raise ValueError(
                "Inconsistent TFRecord schema at record {} (have {}, "
                "expected {})".format(i, sorted(r), sorted(names)))
    out: Dict[str, np.ndarray] = {}
    for name in sorted(names):
        lengths = {len(r[name]) for r in rows}
        if len(lengths) != 1:
            raise ValueError(
                "Ragged TFRecord feature {!r} (lengths {}); pad before "
                "writing".format(name, sorted(lengths)))
        (k,) = lengths
        if k == 0:
            # A feature empty in every record (legal Example encoding, and
            # write_tfrecord emits it for []): zero-width column.
            out[name] = np.zeros((len(rows), 0), dtype=np.float32)
            continue
        values = [r[name][0] if k == 1 else r[name] for r in rows]
        if values and isinstance(
                (values[0] if k == 1 else values[0][0]), bytes):
            out[name] = np.asarray(values, dtype=object)
        else:
            arr = np.asarray(values)
            if arr.dtype == np.float64:
                arr = arr.astype(np.float32)  # proto floats are f32
            out[name] = arr
    return out


def is_tfrecord_path(path: str) -> bool:
    if path.endswith((".tfrecord", ".tfrecords")):
        return True
    return os.path.isdir(path) and any(
        f.endswith((".tfrecord", ".tfrecords")) for f in os.listdir(path))
