"""Sharded training: init + jitted step over a named mesh.

This is the TPU data plane the reference delegates to torch DDP
(`dist_executor.py:102,197-223`): params are initialized straight into their
GSPMD shardings (derived from the model zoo's logical annotations), the
train step is one jit with donated state, and XLA emits the gradient
collectives over ICI — there is no wrapper class around the model.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from maggy_tpu.parallel.sharding import logical_axis_rules


def cross_entropy_loss(logits, labels):
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    onehot = jax.nn.one_hot(labels, logits.shape[-1], dtype=jnp.float32)
    return -jnp.mean(jnp.sum(onehot * logp, axis=-1))


def next_token_loss(logits, tokens):
    """Causal LM loss: predict tokens[t+1] from logits[t]."""
    return cross_entropy_loss(logits[:, :-1], tokens[:, 1:])


def _unbox_and_specs(variables, mesh, strategy):
    """Split flax's Partitioned boxes into (plain pytree, NamedShardings)."""
    import flax.linen as nn
    from jax.sharding import NamedSharding, PartitionSpec as P

    rules = dict(logical_axis_rules(strategy))

    def to_sharding(leaf):
        if isinstance(leaf, nn.Partitioned):
            spec = tuple(rules.get(n, None) if n else None for n in leaf.names)
            # Drop mesh axes that don't exist on this mesh.
            spec = tuple(s if s in mesh.axis_names else None for s in spec)
            return NamedSharding(mesh, P(*spec))
        return NamedSharding(mesh, P())

    shardings = jax.tree_util.tree_map(
        to_sharding, variables,
        is_leaf=lambda x: isinstance(x, nn.Partitioned))
    plain = jax.tree_util.tree_map(
        lambda x: x.value if isinstance(x, nn.Partitioned) else x,
        variables, is_leaf=lambda x: isinstance(x, nn.Partitioned))
    return plain, shardings


def init_train_state(
    model,
    tx,
    rng,
    example_inputs: Tuple,
    mesh,
    strategy: str = "dp",
    init_kwargs: Optional[Dict[str, Any]] = None,
    cache_key: Optional[tuple] = None,
):
    """Initialize (params, opt_state) directly INTO their shardings.

    Returns (params, opt_state, shardings) where params is the full flax
    variables dict minus boxes. ``cache_key`` shares the jitted initializer
    across trials of a sweep (same contract as Trainer's step_key); the
    shared entries live in the bounded warm cache (train/warm.py), so a
    fleet runner serving many programs no longer grows without bound.
    """
    from maggy_tpu.train import warm as _warm

    init_kwargs = init_kwargs or {}
    if cache_key is not None:
        slot, _ = _warm.warm_cache().slot(
            ("manual_init", cache_key, model, mesh, strategy))
    else:
        # Uncached: a private throwaway slot — ONE init sequence lives in
        # _init_state_via_slot, so the legacy and warm paths cannot
        # diverge (the bit-for-bit promise of warm_start=False).
        slot = _warm.WarmSlot(None)
    params, opt_state, shardings, _hit, _ikey = _init_state_via_slot(
        slot, model, tx, rng, example_inputs, mesh, strategy,
        init_kwargs, allow_buffers=False)
    return params, opt_state, shardings


def _reinit_wrapper(entry):
    """The donating re-init program: fresh VALUES from the entry's
    initializer, written into the retired trial's DONATED memory."""
    init_unboxed = entry.init_unboxed

    def reinit(r, old):
        del old  # donated: recycled memory, fresh values
        return init_unboxed(r)

    return jax.jit(reinit, out_shardings=entry.shardings,
                   donate_argnums=(1,))


def _ensure_reinit(entry):
    """The entry's donating re-init, lazily built (once, under the build
    lock) when neither the prebuild thread nor an earlier trial already
    has. A consumer arriving while the prebuild is mid-compile waits on
    the lock and gets the prebuilt executable instead of compiling its
    own."""
    fn = entry.reinit_jit
    if fn is not None:
        return fn
    with entry.reinit_lock:
        if entry.reinit_jit is None:
            entry.reinit_jit = _reinit_wrapper(entry)
        return entry.reinit_jit


def _prebuild_reinit_async(entry, rng) -> None:
    """AOT-compile the donating re-init on a background thread,
    overlapping the program family's FIRST (cold) trial — so the first
    WARM trial's init() finds the program ready instead of paying its
    one-time trace+compile (the init_ms spike). Lowering is against
    ABSTRACT inputs (ShapeDtypeStructs carrying the entry's shardings),
    so the prebuild allocates no device memory next to the live trial's
    state. Strictly an optimization: any failure — including the
    compiled executable later rejecting a call — leaves the lazy inline
    path (and its fresh-init fallback) intact.
    ``MAGGY_TPU_PREBUILD_REINIT=0`` disables it."""
    import os as _os

    if _os.environ.get("MAGGY_TPU_PREBUILD_REINIT", "1") == "0" \
            or entry.abstract is None:
        return

    def target():
        from maggy_tpu.train import warm as _warm

        try:
            rng_abs = jax.ShapeDtypeStruct(rng.shape, rng.dtype)
            old_abs = jax.tree_util.tree_map(
                lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                                  sharding=s),
                entry.abstract, entry.shardings)
            with entry.reinit_lock:
                if entry.reinit_jit is not None:
                    return
                entry.reinit_jit = _reinit_wrapper(entry).lower(
                    rng_abs, old_abs).compile()
                entry.reinit_prebuilt = True
            _warm._count("reinit_prebuilds")
        except Exception:  # noqa: BLE001 - prebuild is an optimization
            pass

    import threading as _threading

    _threading.Thread(target=target, daemon=True,
                      name="reinit-prebuild").start()


def _init_state_via_slot(slot, model, tx, rng, example_inputs, mesh,
                         strategy, init_kwargs, allow_buffers: bool = True):
    """Warm-slot init: get-or-build the per-input-shape init entry (jitted
    initializer + shardings — ``jax.eval_shape`` and the unboxing pass run
    once per program+shape, not once per trial), then initialize fresh
    state. When the slot holds the previous trial's retired buffers and
    ``allow_buffers``, the re-init DONATES them: XLA writes the fresh
    values into the retired trial's memory (no alloc churn, no transient
    double-residency on a packed HBM), and — for a matching swept-optimizer
    family — the opt_state is rebuilt the same way with only the traced
    hyperparameters rebound to this trial's values.

    Returns (params, opt_state, shardings, warm_hit, init_key). Every
    reuse path recomputes VALUES from ``rng``/``tx`` — state is never
    inherited across trials, only memory and executables are.
    """
    from maggy_tpu.train import warm as _warm

    init_kwargs = init_kwargs or {}
    ikey = (_warm.shape_key(example_inputs),
            repr(sorted(init_kwargs.items())), _warm.shape_key(rng))

    def build():
        def init_fn(r):
            variables = model.init(r, *example_inputs, **init_kwargs)
            return {k: v for k, v in variables.items() if k != "losses"}

        abstract = jax.eval_shape(init_fn, rng)
        plain_abstract, shardings = _unbox_and_specs(abstract, mesh, strategy)

        def init_unboxed(r):
            plain, _ = _unbox_and_specs(init_fn(r), mesh, strategy)
            return plain

        return _warm._InitEntry(
            jax.jit(init_unboxed, out_shardings=shardings), init_unboxed,
            shardings, abstract=plain_abstract)

    entry, hit = slot.init_entry(ikey, build)
    if not hit and allow_buffers and slot.key is not None:
        # First trial of a shared program family: compile the donating
        # re-init CONCURRENTLY with the trial (ROADMAP item 3 follow-up),
        # so the family's first WARM trial no longer pays its one-time
        # trace+compile inside init() — the init_ms spike the journal's
        # ttfm breakdown shows today.
        _prebuild_reinit_async(entry, rng)
    family = _warm.opt_family(tx)
    if allow_buffers:
        retired = entry.take_retired()
    else:
        entry.drop_retired()
        retired = None
    params = opt_state = None
    with mesh:
        if retired is not None:
            old_vars, old_opt, old_family = retired
            try:
                params = _ensure_reinit(entry)(rng, old_vars)
            except Exception:  # noqa: BLE001 - donation is an optimization
                params = None
                # A PREBUILT executable that rejects concrete calls
                # (layout/sharding mismatch vs its abstract lowering)
                # must not shadow the lazy jit path forever: evict it so
                # the next trial rebuilds inline and donation recovers.
                with entry.reinit_lock:
                    if entry.reinit_prebuilt:
                        entry.reinit_jit = None
                        entry.reinit_prebuilt = False
            if params is not None and family is not None \
                    and old_family == family:
                try:
                    if entry.opt_family != family \
                            or entry.opt_reinit_jit is None:
                        entry.opt_tx, entry.opt_family = tx, family
                        first_tx = tx

                        def opt_reinit(p, old):
                            del old  # donated
                            return first_tx.init(p)

                        entry.opt_reinit_jit = jax.jit(
                            opt_reinit, donate_argnums=(1,))
                    psub = params["params"] if "params" in params else params
                    # The cached re-init traced the family's FIRST
                    # transform, so its hyperparam constants must be
                    # rebound to THIS trial's swept values.
                    opt_state = _warm.rebind_hyperparams(
                        entry.opt_reinit_jit(psub, old_opt),
                        _warm.swept_info(tx)["hparams"])
                except Exception:  # noqa: BLE001
                    opt_state = None
        if params is None:
            params = entry.init_jit(rng)
        if opt_state is None:
            opt_state = tx.init(
                params["params"] if "params" in params else params)
    from maggy_tpu.parallel.sharding import apply_zero_sharding

    opt_state = apply_zero_sharding(
        opt_state, mesh, strategy,
        lambda x, sh: jax.device_put(x, sh) if hasattr(x, "shape") else x)
    return params, opt_state, entry.shardings, hit, ikey


def build_step_fn(
    model,
    tx,
    loss_fn: Callable,
    mesh,
    has_aux_collections: bool = False,
    train_kwargs: Optional[Dict[str, Any]] = None,
    strategy: str = "dp",
):
    """The raw (unjitted) train-step closure ``make_train_step`` jits.

    Exposed separately so the vectorized K-lane path (train/vmap.py) can
    wrap the IDENTICAL computation in ``jax.vmap`` over the stacked state
    axis — one program family, scalar and vectorized."""
    from maggy_tpu.parallel.sharding import apply_zero_sharding

    train_kwargs = train_kwargs or {}

    def step(variables, opt_state, batch):
        params = variables["params"]
        aux = {k: v for k, v in variables.items() if k != "params"}

        def compute_loss(p):
            vs = {"params": p, **aux}
            mutable = (list(aux.keys()) if has_aux_collections else []) + ["losses"]
            out, updates = model.apply(
                vs, *batch["inputs"], mutable=mutable, **train_kwargs)
            loss = loss_fn(out, batch)
            # Sowed auxiliary losses (MoE load balancing etc.) join the
            # objective; they are scalars, summed over all sow sites.
            for leaf in jax.tree_util.tree_leaves(updates.pop("losses", {})):
                loss = loss + jnp.sum(leaf)
            return loss, updates

        (loss, new_aux), grads = jax.value_and_grad(
            compute_loss, has_aux=True)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        import optax

        params = optax.apply_updates(params, updates)
        opt_state = apply_zero_sharding(
            opt_state, mesh, strategy,
            lambda x, sh: jax.lax.with_sharding_constraint(x, sh))
        return {"params": params, **new_aux} if has_aux_collections else \
            {"params": params, **aux}, opt_state, loss

    return step


def make_train_step(
    model,
    tx,
    loss_fn: Callable,
    mesh,
    donate: bool = True,
    has_aux_collections: bool = False,
    train_kwargs: Optional[Dict[str, Any]] = None,
    strategy: str = "dp",
):
    """Build the jitted SPMD train step.

    step(variables, opt_state, batch) -> (variables, opt_state, loss).
    ``loss_fn(logits_or_outputs, batch)`` computes the scalar loss; gradient
    all-reduce/reduce-scatter over the mesh comes from GSPMD. With a
    "zero" strategy part, the updated optimizer state is constrained to
    its data-axis sharding so XLA keeps the moments de-duplicated across
    replicas (shapes are static at trace time, so the constraint costs
    nothing when already satisfied).
    """
    step = build_step_fn(model, tx, loss_fn, mesh,
                         has_aux_collections=has_aux_collections,
                         train_kwargs=train_kwargs, strategy=strategy)
    jit_kwargs = {}
    if donate:
        jit_kwargs["donate_argnums"] = (0, 1)
    return jax.jit(step, **jit_kwargs)


def _has_injected_hparams(state) -> bool:
    """True if any sub-state carries injected hyperparams (swept_transform
    may sit anywhere inside an optax.chain)."""
    if hasattr(state, "hyperparams"):
        return True
    if isinstance(state, (tuple, list)):
        return any(_has_injected_hparams(s) for s in state)
    return False


def swept_transform(opt_factory: Callable, **hparams):
    """Build an optax transform whose hyperparameters are TRACED INPUTS
    (carried in opt_state) instead of baked-in constants.

    ``swept_transform(optax.adam, learning_rate=lr)`` produces identical HLO
    for every lr, so a sweep compiles its train step ONCE: the warm cache
    (train/warm.py) auto-shares the compiled step across trials whose
    optimizer FAMILY (factory + hyperparameter names) matches — no
    ``step_key`` needed — and the persistent compilation cache dedups
    across runner processes (SURVEY.md §7.3 "compile-cache churn" — the
    TPU-native answer is hparams-as-inputs, not N recompiles).
    """
    import numbers

    import optax

    tx = optax.inject_hyperparams(opt_factory)(**hparams)
    numeric = {k: v for k, v in hparams.items()
               if isinstance(v, numbers.Real) and not isinstance(v, bool)}
    statics = {k: v for k, v in hparams.items() if k not in numeric}

    def repr_stable(v):
        # A static hyperparameter joins the shared family only when its
        # repr is value-determined. A schedule/callable/array reprs by
        # object (memory address): two identical constructions would mint
        # DISTINCT families — each trial a never-matching key churning
        # genuinely-warm programs out of the bounded shared LRU. Such
        # transforms stay family-less (private warm slot: AOT split and
        # telemetry, no cross-object sharing).
        if v is None or isinstance(v, (str, bytes, bool, numbers.Number)):
            return True
        if isinstance(v, (tuple, list)):
            return all(repr_stable(x) for x in v)
        return False

    if all(repr_stable(v) for v in statics.values()):
        static = tuple(sorted((k, repr(v)) for k, v in statics.items()))
        family = ("{}.{}".format(
            getattr(opt_factory, "__module__", "?"),
            getattr(opt_factory, "__qualname__", repr(opt_factory))),
            tuple(sorted(numeric)), static)
    else:
        family = None
    try:
        # The marker rides tx.init (a plain function, so setattr works —
        # the GradientTransformation namedtuple itself rejects attributes):
        # warm.opt_family/swept_info read it to derive the value-independent
        # auto program key and the per-trial hyperparams to rebind.
        tx.init._maggy_swept = {"family": family, "hparams": numeric}
    except (AttributeError, TypeError):
        pass  # exotic init callables: loses warm family sharing only
    return tx


class Trainer:
    """Convenience loop: init + step + reporter integration.

    The per-trial training harness for HPO sweeps (models from the zoo,
    optax optimizer, metric heartbeats via the Reporter).

    **Warm path (default).** Program identity is derived automatically —
    (model config, mesh topology, strategy, loss_fn, train_kwargs, and the
    optimizer family for ``swept_transform`` transforms) — and trials whose
    identity matches reuse one warm slot (train/warm.py): the jitted+
    AOT-compiled step, the computed shardings, and the previous trial's
    retired state buffers (consumed by a donating re-init). Build the
    optimizer with ``swept_transform`` so hyperparameters ride in
    opt_state and the whole sweep compiles once; a plain transform keys by
    object identity (never shared across objects — its constants are baked
    into the program). ``warm_start=False`` (or the executor's
    ``config.warm_start=False``) restores the build-per-trial behavior
    bit-for-bit.

    ``step_key``: manual override of the automatic program key — trials
    whose (step_key, model, mesh, strategy) coincide reuse one jitted step
    regardless of optimizer identity. Include the optimizer family in the
    key if the sweep varies it (e.g. ``step_key=("mnist", "adam")``).
    """

    def __init__(self, model, tx, loss_fn, mesh, strategy: str = "dp",
                 train_kwargs: Optional[Dict[str, Any]] = None,
                 has_aux_collections: bool = False,
                 step_key: Optional[tuple] = None,
                 warm_start: Optional[bool] = None):
        from maggy_tpu.train import warm as _warm

        self.model = model
        self.tx = tx
        self.loss_fn = loss_fn
        self.mesh = mesh
        self.strategy = strategy
        self._warm_enabled = _warm.enabled() if warm_start is None \
            else bool(warm_start)
        build = functools.partial(
            make_train_step, model, tx, loss_fn, mesh,
            train_kwargs=train_kwargs,
            has_aux_collections=has_aux_collections, strategy=strategy)
        self._step_key = step_key
        self._step_shared = step_key is not None
        # Flax modules are frozen dataclasses and Mesh hashes by topology,
        # so the key pins the program identity; loss_fn keys by object
        # identity (a per-call lambda simply misses the cache — safe; a
        # module-level loss shares). Manual step_key deliberately excludes
        # tx (the user asserts hparams ride opt_state); the auto key
        # includes the optimizer family/identity so differing programs can
        # never share silently.
        tkr = repr(sorted((train_kwargs or {}).items()))
        self._slot = None
        if step_key is not None:
            key = ("manual", step_key, model, mesh, strategy,
                   has_aux_collections, loss_fn, tkr)
            self._slot, _ = _warm.warm_cache().slot(key)
        elif self._warm_enabled:
            family = _warm.opt_family(self.tx)
            if family is not None:
                key = ("auto", model, mesh, strategy, has_aux_collections,
                       loss_fn, tkr, family)
                try:
                    self._slot, _ = _warm.warm_cache().slot(key)
                except TypeError:
                    # Unhashable program component (e.g. a flax module
                    # with a list-typed field): the DEFAULT path must
                    # never reject a model that trained fine before —
                    # degrade to a private slot (no cross-trial sharing,
                    # AOT split and telemetry kept).
                    self._slot = _warm.WarmSlot(None)
            else:
                # Plain/family-less transform: no safe cross-trial
                # sharing, but a PRIVATE slot still buys the AOT
                # trace/compile split and compile telemetry without
                # churning the shared LRU.
                self._slot = _warm.WarmSlot(None)
        if self._slot is not None:
            self._step = self._slot.ensure_step(build)
        else:
            self._step = build()
        self._init_ikey = None
        self._active_step = None
        self.variables = None
        self.opt_state = None
        self.shardings = None
        _warm.register_trainer(self)

    def init(self, rng, example_inputs, init_kwargs=None):
        import time as _time

        from maggy_tpu.train import warm as _warm

        t0 = _time.perf_counter()
        self._active_step = None
        if self._slot is not None:
            allow = self._warm_enabled and not _warm.fresh_state_only()
            (self.variables, self.opt_state, self.shardings, hit,
             self._init_ikey) = _init_state_via_slot(
                self._slot, self.model, self.tx, rng, example_inputs,
                self.mesh, self.strategy, init_kwargs,
                allow_buffers=allow)
            _warm.record_warm_event(hit)
            _warm.note_compile(warm=bool(hit))
        else:
            self.variables, self.opt_state, self.shardings = init_train_state(
                self.model, self.tx, rng, example_inputs, self.mesh,
                self.strategy, init_kwargs=init_kwargs)
            _warm.note_compile(warm=False)
        _warm.note_compile(init_ms=(_time.perf_counter() - t0) * 1e3)
        if self._step_shared and not _has_injected_hparams(self.opt_state):
            import warnings

            warnings.warn(
                "Trainer(step_key=...) shares one compiled step across "
                "trials, but this tx bakes its hyperparameters into the "
                "program (use swept_transform) — all sharing trials will "
                "silently run the FIRST trial's optimizer constants.",
                stacklevel=2)
        return self

    def retire_to_warm_cache(self) -> None:
        """Hand this trainer's state buffers to its warm slot's init entry:
        the next repeat-shape trial's re-init DONATES them — fresh values
        into recycled memory. Called by the executor's trial scope at
        trial end; after it, ``variables``/``opt_state`` are None (their
        buffers now belong to the slot and will be invalidated by the
        donation)."""
        slot = self._slot
        if slot is None or self.variables is None or self._init_ikey is None:
            return
        from maggy_tpu.train import warm as _warm

        entry = slot.get_init(self._init_ikey)
        if entry is not None:
            entry.store_retired(self.variables, self.opt_state,
                                _warm.opt_family(self.tx))
            self.variables = None
            self.opt_state = None

    def place_batch(self, batch: Dict[str, Any]):
        from maggy_tpu.parallel.sharding import cached_batch_sharding

        def put(x):
            # Sharding memoized by (mesh, leaf shape): steady-state steps
            # skip the per-leaf rule re-derivation (PartitionSpec building)
            # the old per-step tree_map paid.
            sh = cached_batch_sharding(self.mesh, np.shape(x))
            return jax.device_put(jnp.asarray(x), sh)

        return jax.tree_util.tree_map(put, batch)

    def _resolve_step(self, batch):
        """Warm AOT path: per-shape compiled executables cached on the
        slot, so a repeat-shape trial skips trace AND compile and the
        split is measured (trace_ms/compile_ms telemetry). Any AOT failure
        permanently falls the slot back to the plain jit call — the warm
        path degrades, never breaks."""
        slot = self._slot
        if slot is None or not self._warm_enabled or not slot.aot_ok:
            return self._step
        from maggy_tpu.train import warm as _warm

        key = (self._init_ikey, _warm.shape_key(batch))
        fn = slot.compiled_step(key)
        if fn is None:
            import time as _time

            # One compile per (slot, shape), even when N runner threads'
            # first trials race the same program — the losers wait on the
            # winner's executable instead of compiling their own.
            with slot.aot_lock:
                fn = slot.compiled_step(key)
                if fn is None:
                    try:
                        t0 = _time.perf_counter()
                        lowered = self._step.lower(
                            self.variables, self.opt_state, batch)
                        t1 = _time.perf_counter()
                        fn = lowered.compile()
                        t2 = _time.perf_counter()
                    except Exception:  # noqa: BLE001 - AOT is an optimization
                        slot.aot_ok = False
                        return self._step
                    _warm.note_compile(trace_ms=(t1 - t0) * 1e3,
                                       compile_ms=(t2 - t1) * 1e3)
                    slot.store_compiled(key, fn)
        return fn

    def step(self, batch: Dict[str, Any]) -> float:
        with self.mesh:
            # Steady-state fast path: the batch shape is constant within
            # a trial, so reuse the last resolved executable without
            # recomputing its shape key (pure-Python per-step overhead on
            # the exact path this harness optimizes). A shape change
            # surfaces as the AOT executable's signature TypeError —
            # re-resolve once and retry; the error is re-raised when
            # re-resolution lands on the same fn (a genuine type error).
            fn = self._active_step
            if fn is None:
                fn = self._resolve_step(batch)
                self._active_step = fn
            try:
                out = fn(self.variables, self.opt_state, batch)
            except TypeError:
                refreshed = self._resolve_step(batch)
                if refreshed is fn:
                    raise
                self._active_step = refreshed
                out = refreshed(self.variables, self.opt_state, batch)
            self.variables, self.opt_state, loss = out
        return loss

    def fit(self, batches, reporter=None, report_every: int = 1,
            callbacks=()) -> float:
        """Step over ``batches``; returns the final loss.

        The per-step loss is broadcast LAZILY (an un-materialized device
        scalar): `Reporter` pulls it to host on the heartbeat thread, so
        reporting never serializes the pipelined step stream (a blocking
        ``float(loss)`` here cost ~50 ms/sync over a tunneled chip —
        BASELINE.md round-3 diagnosis). ``callbacks`` are `maggy_tpu.
        callbacks.BatchEnd`-style callables invoked as cb(logs, step) with
        the same lazy scalar in ``logs["loss"]``.
        """
        loss = None
        for i, batch in enumerate(batches):
            loss = self.step(self.place_batch(batch))
            if reporter is not None and i % report_every == 0:
                reporter.broadcast(loss, step=i)
            for cb in callbacks:
                cb({"loss": loss}, step=i)
        return float(loss) if loss is not None else float("nan")
