"""Sharded training: init + jitted step over a named mesh.

This is the TPU data plane the reference delegates to torch DDP
(`dist_executor.py:102,197-223`): params are initialized straight into their
GSPMD shardings (derived from the model zoo's logical annotations), the
train step is one jit with donated state, and XLA emits the gradient
collectives over ICI — there is no wrapper class around the model.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from maggy_tpu.parallel.sharding import batch_sharding, logical_axis_rules


def cross_entropy_loss(logits, labels):
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    onehot = jax.nn.one_hot(labels, logits.shape[-1], dtype=jnp.float32)
    return -jnp.mean(jnp.sum(onehot * logp, axis=-1))


def next_token_loss(logits, tokens):
    """Causal LM loss: predict tokens[t+1] from logits[t]."""
    return cross_entropy_loss(logits[:, :-1], tokens[:, 1:])


def _unbox_and_specs(variables, mesh, strategy):
    """Split flax's Partitioned boxes into (plain pytree, NamedShardings)."""
    import flax.linen as nn
    from jax.sharding import NamedSharding, PartitionSpec as P

    rules = dict(logical_axis_rules(strategy))

    def to_sharding(leaf):
        if isinstance(leaf, nn.Partitioned):
            spec = tuple(rules.get(n, None) if n else None for n in leaf.names)
            # Drop mesh axes that don't exist on this mesh.
            spec = tuple(s if s in mesh.axis_names else None for s in spec)
            return NamedSharding(mesh, P(*spec))
        return NamedSharding(mesh, P())

    shardings = jax.tree_util.tree_map(
        to_sharding, variables,
        is_leaf=lambda x: isinstance(x, nn.Partitioned))
    plain = jax.tree_util.tree_map(
        lambda x: x.value if isinstance(x, nn.Partitioned) else x,
        variables, is_leaf=lambda x: isinstance(x, nn.Partitioned))
    return plain, shardings


def init_train_state(
    model,
    tx,
    rng,
    example_inputs: Tuple,
    mesh,
    strategy: str = "dp",
    init_kwargs: Optional[Dict[str, Any]] = None,
    cache_key: Optional[tuple] = None,
):
    """Initialize (params, opt_state) directly INTO their shardings.

    Returns (params, opt_state, shardings) where params is the full flax
    variables dict minus boxes. ``cache_key`` shares the jitted initializer
    across trials of a sweep (same contract as Trainer's step_key).
    """
    init_kwargs = init_kwargs or {}

    def init_fn(rng):
        variables = model.init(rng, *example_inputs, **init_kwargs)
        # "losses" holds per-apply sowed scalars (e.g. MoE aux loss) — it is
        # recomputed every step, not trained state.
        return {k: v for k, v in variables.items() if k != "losses"}

    def build():
        abstract = jax.eval_shape(init_fn, rng)
        _, shardings = _unbox_and_specs(abstract, mesh, strategy)

        def init_unboxed(rng):
            variables = init_fn(rng)
            plain, _ = _unbox_and_specs(variables, mesh, strategy)
            return plain

        return jax.jit(init_unboxed, out_shardings=shardings), shardings

    if cache_key is not None:
        shapes = jax.tree_util.tree_map(jnp.shape, example_inputs)
        key = ("init", cache_key, model, mesh, strategy, repr(shapes),
               repr(sorted(init_kwargs.items())))
        with _STEP_CACHE_LOCK:
            if key not in _STEP_CACHE:
                _STEP_CACHE[key] = build()
            init_jit, shardings = _STEP_CACHE[key]
    else:
        init_jit, shardings = build()
    with mesh:
        params = init_jit(rng)
        opt_state = tx.init(params["params"] if "params" in params else params)
    from maggy_tpu.parallel.sharding import apply_zero_sharding

    opt_state = apply_zero_sharding(
        opt_state, mesh, strategy,
        lambda x, sh: jax.device_put(x, sh) if hasattr(x, "shape") else x)
    return params, opt_state, shardings


def make_train_step(
    model,
    tx,
    loss_fn: Callable,
    mesh,
    donate: bool = True,
    has_aux_collections: bool = False,
    train_kwargs: Optional[Dict[str, Any]] = None,
    strategy: str = "dp",
):
    """Build the jitted SPMD train step.

    step(variables, opt_state, batch) -> (variables, opt_state, loss).
    ``loss_fn(logits_or_outputs, batch)`` computes the scalar loss; gradient
    all-reduce/reduce-scatter over the mesh comes from GSPMD. With a
    "zero" strategy part, the updated optimizer state is constrained to
    its data-axis sharding so XLA keeps the moments de-duplicated across
    replicas (shapes are static at trace time, so the constraint costs
    nothing when already satisfied).
    """
    from maggy_tpu.parallel.sharding import apply_zero_sharding

    train_kwargs = train_kwargs or {}

    def step(variables, opt_state, batch):
        params = variables["params"]
        aux = {k: v for k, v in variables.items() if k != "params"}

        def compute_loss(p):
            vs = {"params": p, **aux}
            mutable = (list(aux.keys()) if has_aux_collections else []) + ["losses"]
            out, updates = model.apply(
                vs, *batch["inputs"], mutable=mutable, **train_kwargs)
            loss = loss_fn(out, batch)
            # Sowed auxiliary losses (MoE load balancing etc.) join the
            # objective; they are scalars, summed over all sow sites.
            for leaf in jax.tree_util.tree_leaves(updates.pop("losses", {})):
                loss = loss + jnp.sum(leaf)
            return loss, updates

        (loss, new_aux), grads = jax.value_and_grad(
            compute_loss, has_aux=True)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        import optax

        params = optax.apply_updates(params, updates)
        opt_state = apply_zero_sharding(
            opt_state, mesh, strategy,
            lambda x, sh: jax.lax.with_sharding_constraint(x, sh))
        return {"params": params, **new_aux} if has_aux_collections else \
            {"params": params, **aux}, opt_state, loss

    jit_kwargs = {}
    if donate:
        jit_kwargs["donate_argnums"] = (0, 1)
    return jax.jit(step, **jit_kwargs)


import threading as _threading

# Compiled-step sharing across trials (opt-in via Trainer(step_key=...)).
_STEP_CACHE: Dict[Any, Callable] = {}
_STEP_CACHE_LOCK = _threading.Lock()


def _has_injected_hparams(state) -> bool:
    """True if any sub-state carries injected hyperparams (swept_transform
    may sit anywhere inside an optax.chain)."""
    if hasattr(state, "hyperparams"):
        return True
    if isinstance(state, (tuple, list)):
        return any(_has_injected_hparams(s) for s in state)
    return False


def swept_transform(opt_factory: Callable, **hparams):
    """Build an optax transform whose hyperparameters are TRACED INPUTS
    (carried in opt_state) instead of baked-in constants.

    ``swept_transform(optax.adam, learning_rate=lr)`` produces identical HLO
    for every lr, so a sweep compiles its train step ONCE: combine with
    ``Trainer(step_key=...)`` for in-process sharing, and the persistent
    compilation cache dedups across runner processes (SURVEY.md §7.3
    "compile-cache churn" — the TPU-native answer is hparams-as-inputs, not
    N recompiles).
    """
    import optax

    return optax.inject_hyperparams(opt_factory)(**hparams)


class Trainer:
    """Convenience loop: init + step + reporter integration.

    The per-trial training harness for HPO sweeps (models from the zoo,
    optax optimizer, metric heartbeats via the Reporter).

    ``step_key``: opt-in compiled-step sharing for sweeps. Trials whose
    (step_key, model, mesh, strategy) coincide reuse one jitted step — pair
    it with ``swept_transform`` so the optimizer's hyperparameters live in
    opt_state rather than the program. Include the optimizer family in the
    key if the sweep varies it (e.g. ``step_key=("mnist", "adam")``).
    """

    def __init__(self, model, tx, loss_fn, mesh, strategy: str = "dp",
                 train_kwargs: Optional[Dict[str, Any]] = None,
                 has_aux_collections: bool = False,
                 step_key: Optional[tuple] = None):
        self.model = model
        self.tx = tx
        self.loss_fn = loss_fn
        self.mesh = mesh
        self.strategy = strategy
        build = functools.partial(
            make_train_step, model, tx, loss_fn, mesh,
            train_kwargs=train_kwargs,
            has_aux_collections=has_aux_collections, strategy=strategy)
        self._step_key = step_key
        self._step_shared = step_key is not None
        if step_key is not None:
            # Flax modules are frozen dataclasses and Mesh hashes by
            # topology, so the key pins the program identity; tx is
            # deliberately excluded (that's the point — see swept_transform).
            # loss_fn keys by object identity: a per-call lambda simply
            # misses the cache (safe), a module-level loss shares.
            key = (step_key, model, mesh, strategy, has_aux_collections,
                   loss_fn, repr(sorted((train_kwargs or {}).items())))
            with _STEP_CACHE_LOCK:
                if key not in _STEP_CACHE:
                    _STEP_CACHE[key] = build()
                self._step = _STEP_CACHE[key]
        else:
            self._step = build()
        self.variables = None
        self.opt_state = None
        self.shardings = None

    def init(self, rng, example_inputs, init_kwargs=None):
        self.variables, self.opt_state, self.shardings = init_train_state(
            self.model, self.tx, rng, example_inputs, self.mesh,
            self.strategy, init_kwargs=init_kwargs,
            cache_key=self._step_key)
        if self._step_shared and not _has_injected_hparams(self.opt_state):
            import warnings

            warnings.warn(
                "Trainer(step_key=...) shares one compiled step across "
                "trials, but this tx bakes its hyperparameters into the "
                "program (use swept_transform) — all sharing trials will "
                "silently run the FIRST trial's optimizer constants.",
                stacklevel=2)
        return self

    def place_batch(self, batch: Dict[str, Any]):
        def put(x):
            sh = batch_sharding(self.mesh, shape=np.shape(x))
            return jax.device_put(jnp.asarray(x), sh)

        return jax.tree_util.tree_map(put, batch)

    def step(self, batch: Dict[str, Any]) -> float:
        with self.mesh:
            self.variables, self.opt_state, loss = self._step(
                self.variables, self.opt_state, batch)
        return loss

    def fit(self, batches, reporter=None, report_every: int = 1,
            callbacks=()) -> float:
        """Step over ``batches``; returns the final loss.

        The per-step loss is broadcast LAZILY (an un-materialized device
        scalar): `Reporter` pulls it to host on the heartbeat thread, so
        reporting never serializes the pipelined step stream (a blocking
        ``float(loss)`` here cost ~50 ms/sync over a tunneled chip —
        BASELINE.md round-3 diagnosis). ``callbacks`` are `maggy_tpu.
        callbacks.BatchEnd`-style callables invoked as cb(logs, step) with
        the same lazy scalar in ``logs["loss"]``.
        """
        loss = None
        for i, batch in enumerate(batches):
            loss = self.step(self.place_batch(batch))
            if reporter is not None and i % report_every == 0:
                reporter.broadcast(loss, step=i)
            for cb in callbacks:
                cb({"loss": loss}, step=i)
        return float(loss) if loss is not None else float("nan")
