"""Vectorized micro-trials: K hyperparameter configs as ONE vmapped program.

ROADMAP item 4. Most HPO sweeps train *small* models on *big* chips, yet a
runner slot executes exactly one trial at a time — the chip idles across
the hyperparameter axis. The Podracer/Anakin architecture (PAPERS.md)
batches many learners onto one TPU as a single vmapped program; this
module is that trick wired into the warm-cache harness:

- ``VmapTrainer`` — the K-lane counterpart of ``train.Trainer``. Each lane
  is one trial's hyperparameter binding of the SAME program family
  (``swept_transform``: hyperparams are traced inputs riding in
  opt_state). Init runs the ordinary SCALAR init executable once — so a
  lane's initial state is bitwise-identical to a scalar trial's — and the
  values are stacked (or broadcast-written into the previous block's
  DONATED stacked buffers, the PR-6 donating re-init generalized across
  the lane axis). The train step is ``jax.vmap`` of the exact
  ``build_step_fn`` closure the scalar path jits, AOT-compiled ONCE per
  (program, K, batch shape) into the warm slot's vectorized entry
  (``warm._VmapEntry``) — lockstep steps, one dispatch for K trials.
- **Lane masking** — ``mask_lane(i)`` retires a lane host-side: the
  executable keeps running unchanged (no recompile, surviving lanes'
  losses bitwise untouched) while the masked lane's chip share accrues
  ``lane_idle`` badput in the goodput ledger. The freed lane is re-filled
  at the next re-init boundary: mid-block via ``refill_lane`` (fresh
  scalar-init values scatter-written into the lane's donated row), or at
  the block boundary when the next block's donating re-init overwrites
  every lane.

Bitwise caveat: per-lane parity with scalar trials holds for programs
whose ops batch exactly under ``jax.vmap`` (matmul/elementwise — e.g.
``models.MnistMLP``); batched-kernel convolutions may round differently.
The bench gate pins parity on the MLP sweep.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

from maggy_tpu.train import warm as _warm
from maggy_tpu.train.trainer import (_init_state_via_slot, build_step_fn,
                                     swept_transform)


def stack_trees(trees: Sequence[Any]):
    """Stack K congruent pytrees along a new leading lane axis."""
    import jax
    import jax.numpy as jnp

    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *trees)


def rebind_hyperparams_stacked(opt_state, lane_hparams: List[Dict[str, Any]]):
    """``warm.rebind_hyperparams`` across the lane axis: every injected-
    hyperparameter leaf (shape ``(K,)`` after stacking) is replaced by the
    per-lane values from ``lane_hparams``."""
    import jax.numpy as jnp

    def rebind(state):
        if hasattr(state, "_replace") and hasattr(state, "_fields"):
            updates = {}
            for f in state._fields:
                v = getattr(state, f)
                if f == "hyperparams" and isinstance(v, dict):
                    new = dict(v)
                    for name in new:
                        vals = [hp.get(name) for hp in lane_hparams]
                        if all(x is not None for x in vals):
                            new[name] = jnp.asarray(
                                vals, getattr(new[name], "dtype", None))
                    updates[f] = new
                elif isinstance(v, (tuple, list)):
                    updates[f] = rebind(v)
            return state._replace(**updates) if updates else state
        if isinstance(state, (tuple, list)):
            return type(state)(rebind(s) for s in state)
        return state

    return rebind(opt_state)


class VmapTrainer:
    """K-lane vectorized training harness (see module docstring).

    ``lane_hparams`` is a list of K dicts of the swept NUMERIC
    hyperparameters, one per lane (e.g. ``[{"learning_rate": 1e-3}, ...]``)
    — every lane shares the optimizer family
    ``swept_transform(opt_factory, **statics, **hp_i)``, so the program is
    identical across lanes and only the traced values differ.
    """

    def __init__(self, model, opt_factory, lane_hparams, loss_fn, mesh,
                 strategy: str = "dp",
                 train_kwargs: Optional[Dict[str, Any]] = None,
                 has_aux_collections: bool = False,
                 warm_start: Optional[bool] = None,
                 **statics: Any):
        if not lane_hparams:
            raise ValueError("need at least one lane")
        names = sorted(lane_hparams[0])
        if any(sorted(hp) != names for hp in lane_hparams):
            raise ValueError(
                "every lane must sweep the SAME hyperparameter names "
                "(one program family); got {}".format(
                    [sorted(hp) for hp in lane_hparams]))
        self.model = model
        self.opt_factory = opt_factory
        self.statics = statics
        self.lane_hparams = [dict(hp) for hp in lane_hparams]
        self.k = len(lane_hparams)
        self.loss_fn = loss_fn
        self.mesh = mesh
        self.strategy = strategy
        self.train_kwargs = train_kwargs
        self.has_aux_collections = has_aux_collections
        self._warm_enabled = _warm.enabled() if warm_start is None \
            else bool(warm_start)
        # Lane 0's transform stands in for the family everywhere a tx is
        # needed: update() reads hyperparams from opt_state, so the same
        # closure serves every lane.
        self.tx = swept_transform(opt_factory, **statics, **lane_hparams[0])
        self.family = _warm.opt_family(self.tx)
        tkr = repr(sorted((train_kwargs or {}).items()))
        self._slot = None
        if self._warm_enabled and self.family is not None:
            key = ("auto", model, mesh, strategy, has_aux_collections,
                   loss_fn, tkr, self.family)
            try:
                self._slot, _ = _warm.warm_cache().slot(key)
            except TypeError:
                self._slot = _warm.WarmSlot(None)
        else:
            self._slot = _warm.WarmSlot(None)
        self._ventry: Optional[_warm._VmapEntry] = None
        self._init_ikey = None
        self._init_entry = None
        self._rng = None
        self._vstep = None  # (batch shape key, compiled K-lane executable)
        self.variables = None  # stacked: leaves lead with the lane axis
        self.opt_state = None
        self._mask = [False] * self.k  # host-side: True = lane retired
        _warm.register_trainer(self)

    # ------------------------------------------------------------------ init

    def _scalar_init(self, rng, example_inputs, init_kwargs):
        """One run of the ordinary SCALAR init path — the exact values a
        scalar cold trial of this family starts from (never the retired
        scalar buffers: blocks donate their own stacked cells)."""
        return _init_state_via_slot(
            self._slot, self.model, self.tx, rng, example_inputs,
            self.mesh, self.strategy, init_kwargs, allow_buffers=False)

    def init(self, rng, example_inputs, init_kwargs=None):
        """Stacked K-lane init. Values come from ONE scalar init (every
        lane of a sweep starts from the same rng, so lanes differ only in
        their injected hyperparams); when the warm slot's vectorized
        entry holds the previous block's retired stacked buffers, the
        broadcast-write DONATES them — fresh values into the retired
        block's memory, lane axis included."""
        import time as _time

        import jax
        import jax.numpy as jnp

        t0 = _time.perf_counter()
        self._rng = rng
        params, opt0, shardings, hit, ikey = self._scalar_init(
            rng, example_inputs, init_kwargs)
        self._init_ikey = ikey
        self._ventry = self._slot.vmap_entry(("vmap", ikey), self.k)
        lane_opts = [_warm.rebind_hyperparams(opt0, hp)
                     for hp in self.lane_hparams]
        retired = self._ventry.take_retired() if self._warm_enabled else None
        if retired is not None and not _warm.fresh_state_only():
            old_vars, old_opt, old_family = retired
            try:
                stacked = self._broadcast_reinit(params, lane_opts,
                                                 old_vars, old_opt)
            except Exception:  # noqa: BLE001 - donation is an optimization
                stacked = None
            if stacked is not None:
                self.variables, self.opt_state = stacked
        if self.variables is None:
            self.variables = jax.tree_util.tree_map(
                lambda x: jnp.stack([x] * self.k), params)
            self.opt_state = stack_trees(lane_opts)
        self._mask = [False] * self.k
        self._vstep = None
        _warm.record_warm_event(bool(hit))
        _warm.note_compile(warm=bool(hit), vmap_lanes=self.k,
                           init_ms=(_time.perf_counter() - t0) * 1e3)
        del shardings
        return self

    def _broadcast_reinit(self, params, lane_opts, old_vars, old_opt):
        """Write fresh per-lane values into the previous block's DONATED
        stacked buffers (one jitted broadcast program per shape; XLA
        reuses the retired memory)."""
        import jax
        import jax.numpy as jnp

        fresh_opt = stack_trees(lane_opts)

        def write(fresh_v, fresh_o, old_v, old_o):
            del old_v, old_o  # donated: recycled memory, fresh values
            stacked_v = jax.tree_util.tree_map(
                lambda x: jnp.broadcast_to(x[None], (self.k,) + x.shape),
                fresh_v)
            return stacked_v, fresh_o

        fn = jax.jit(write, donate_argnums=(2, 3))
        return fn(params, fresh_opt, old_vars, old_opt)

    # ------------------------------------------------------------------ step

    def _resolve_vstep(self, batch):
        """The ONE AOT-compiled K-lane executable, cached on the warm
        slot's vectorized entry: ``jax.vmap`` of the exact scalar step
        closure over the stacked (variables, opt_state) axis with the
        batch broadcast — every block of the family reuses it."""
        import time as _time

        import jax

        bkey = _warm.shape_key(batch)
        cached = self._vstep
        if cached is not None and cached[0] == bkey:
            return cached[1]
        ventry = self._ventry
        with ventry.lock:
            stored = ventry.vstep
            if stored is not None and stored[0] == bkey:
                self._vstep = stored
                return stored[1]
        raw = build_step_fn(self.model, self.tx, self.loss_fn, self.mesh,
                            has_aux_collections=self.has_aux_collections,
                            train_kwargs=self.train_kwargs,
                            strategy=self.strategy)
        vstep = jax.jit(jax.vmap(raw, in_axes=(0, 0, None)),
                        donate_argnums=(0, 1))
        t0 = _time.perf_counter()
        try:
            lowered = vstep.lower(self.variables, self.opt_state, batch)
            t1 = _time.perf_counter()
            fn = lowered.compile()
            _warm.note_compile(trace_ms=(t1 - t0) * 1e3,
                               compile_ms=(_time.perf_counter() - t1) * 1e3)
        except Exception:  # noqa: BLE001 - AOT is an optimization
            fn = vstep
        stored = (bkey, fn)
        with ventry.lock:
            ventry.vstep = stored
        self._vstep = stored
        return fn

    def step(self, batch):
        """One lockstep step for all K lanes; returns the LAZY per-lane
        loss vector (shape ``(K,)``) — callers index lane rows without
        forcing a device sync."""
        with self.mesh:
            fn = self._resolve_vstep(batch)
            self.variables, self.opt_state, losses = fn(
                self.variables, self.opt_state, batch)
        return losses

    # ------------------------------------------------------------ lane moves

    def mask_lane(self, lane: int) -> None:
        """Retire a lane WITHOUT recompiling: the executable keeps running
        all K rows (surviving lanes' losses bitwise unchanged); the masked
        row's compute is dead until the next re-init boundary re-fills it
        (``lane_idle`` badput in the ledger)."""
        self._mask[lane] = True

    def active_lanes(self) -> List[int]:
        return [i for i in range(self.k) if not self._mask[i]]

    def refill_lane(self, lane: int, hparams: Dict[str, Any],
                    example_inputs=None, init_kwargs=None) -> None:
        """Re-fill a retired lane with a fresh trial mid-block: fresh
        values from the ordinary SCALAR init executable (bitwise-identical
        to a scalar cold trial of the same config), scatter-written into
        the lane's DONATED row of the stacked state."""
        import jax
        import jax.numpy as jnp

        tx = swept_transform(self.opt_factory, **self.statics, **hparams)
        if _warm.opt_family(tx) != self.family:
            raise ValueError(
                "refill hyperparams {} do not match the block's optimizer "
                "family".format(sorted(hparams)))
        if example_inputs is not None:
            params, opt0, _sh, _hit, _ikey = _init_state_via_slot(
                self._slot, self.model, tx, self._rng, example_inputs,
                self.mesh, self.strategy, init_kwargs, allow_buffers=False)
        else:
            params, opt0, _sh, _hit, _ikey = self._refill_from_cached(tx)

        def scatter(sv, so, fv, fo):
            new_v = jax.tree_util.tree_map(
                lambda s, f: s.at[lane].set(f), sv, fv)
            new_o = jax.tree_util.tree_map(
                lambda s, f: s.at[lane].set(jnp.asarray(f, s.dtype))
                if hasattr(s, "at") else s, so, fo)
            return new_v, new_o

        fn = jax.jit(scatter, donate_argnums=(0, 1))
        self.variables, self.opt_state = fn(
            self.variables, self.opt_state, params, opt0)
        self.lane_hparams[lane] = dict(hparams)
        self._mask[lane] = False

    def _refill_from_cached(self, tx):
        """Refill without example inputs: rebuild fresh values from the
        slot's cached init entry (the same jitted scalar initializer)."""
        entry = self._slot.get_init(self._init_ikey) \
            if self._init_ikey is not None else None
        if entry is None:
            raise ValueError("refill_lane needs example_inputs on a cold "
                             "slot (no cached init entry)")
        with self.mesh:
            params = entry.init_jit(self._rng)
            opt0 = tx.init(
                params["params"] if "params" in params else params)
        return params, opt0, entry.shardings, True, self._init_ikey

    # ------------------------------------------------------------ retirement

    def retire_to_warm_cache(self) -> None:
        """Hand the block's STACKED state buffers to the vectorized entry:
        the next block's broadcast re-init donates them (the scalar
        retired-cell contract, generalized across the lane axis)."""
        if self._ventry is None or self.variables is None:
            return
        self._ventry.store_retired(self.variables, self.opt_state,
                                   self.family)
        self.variables = None
        self.opt_state = None


__all__ = ["VmapTrainer", "stack_trees", "rebind_hyperparams_stacked"]
