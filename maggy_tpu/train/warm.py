"""Warm-state runner harness: compile-once trial hot path.

ROADMAP item 3. Every trial used to pay a fresh XLA trace+compile (and a
fresh sharded init) for a program byte-identical to the previous trial's —
a 20-40 s time-to-first-metric stall on TPU that dwarfs the ~2.6 ms
hand-off PR 4 bought. The fix is the pjit idiom ("Scalable Training of
Language Models using JAX pjit and TPUv4", PAPERS.md): program identity is
pinned by *shapes and mesh topology*, not hyperparameter values, so a
runner that keeps the compiled program resident (Podracer-style persistent
actors) only recompiles when the program actually changes.

This module is the mechanism; `train/trainer.py` is the policy:

- ``WarmCache`` — a bounded (LRU, default 4 programs) per-process registry
  of ``WarmSlot`` objects keyed by program identity. A long-lived fleet
  runner serving many experiments must not grow without bound; evicting a
  slot drops its executables and retired buffers. ``clear()`` empties it
  (exported as ``maggy_tpu.train.clear_warm``).
- ``WarmSlot`` — everything a repeat-shape trial can reuse: the jitted
  step, per-shape AOT-compiled executables, per-input-shape init entries
  (jitted initializer + computed shardings, so ``jax.eval_shape`` +
  unboxing are skipped), and the *retired state buffers* of the previous
  trial, re-consumed by a donating re-initialization (fresh VALUES, same
  memory).
- **Trial scope** — the executor wraps each trial in ``trial_scope`` so
  warm behavior follows ``config.warm_start``, compile telemetry lands in
  the trial's ``RunnerStats``, and a trial arriving with
  ``ctx.resume_step``/``restore_parent`` never consumes retired buffers
  (``fresh_state=True``): checkpoint state must be restored explicitly,
  not inherited.
- **Counters** — warm-slot hits/misses and the persistent XLA compilation
  cache's hits/misses, counted through ``jax.monitoring`` event listeners
  (the warm cache emits ``/maggy_tpu/warm_slot/{hit,miss}`` events; JAX
  itself emits ``/jax/compilation_cache/cache_{hits,misses}``). Counts are
  attributed to the current thread's trial scope (per-runner stats shipped
  on heartbeats) and mirrored in process-global counters for library use.

``MAGGY_TPU_WARM_START=0`` disables the warm default process-wide;
``MAGGY_TPU_WARM_SLOTS`` overrides the LRU bound.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import Any, Callable, Dict, Optional, Tuple

#: Default LRU bound: distinct programs kept warm per runner process.
DEFAULT_WARM_SLOTS = 4

#: Per-slot bound on AOT-compiled step executables / init entries (one per
#: distinct input-shape signature within one program family).
PER_SLOT_SHAPES = 8

#: jax.monitoring event names the warm cache emits (counted by the same
#: listener that counts JAX's persistent-compilation-cache events).
WARM_HIT_EVENT = "/maggy_tpu/warm_slot/hit"
WARM_MISS_EVENT = "/maggy_tpu/warm_slot/miss"

#: Counter keys shipped in runner stats / returned by ``counters()``.
COUNTER_KEYS = ("warm_hits", "warm_misses", "xla_cache_hits",
                "xla_cache_misses")

_local = threading.local()

_counters_lock = threading.Lock()
_counters: Dict[str, int] = {k: 0 for k in COUNTER_KEYS}

_listener_lock = threading.Lock()
_listener_installed = False


# --------------------------------------------------------------- trial scope

class _TrialScope:
    __slots__ = ("trial_id", "enabled", "stats", "fresh_state", "trainers")

    def __init__(self, trial_id, enabled, stats, fresh_state):
        self.trial_id = trial_id
        self.enabled = enabled
        self.stats = stats
        self.fresh_state = fresh_state
        self.trainers: list = []


def current_scope() -> Optional[_TrialScope]:
    return getattr(_local, "scope", None)


class trial_scope:
    """Context manager the trial executor wraps around one train_fn call.

    Arms the thread's warm behavior (``enabled`` mirrors
    ``config.warm_start``; ``fresh_state=True`` for resumed/promoted
    trials forbids retired-buffer reuse) and routes compile telemetry to
    ``stats`` (a ``RunnerStats``). On exit, every Trainer the trial built
    retires its state buffers into its warm slot so the NEXT trial's
    donating re-init can consume them."""

    def __init__(self, trial_id: Optional[str] = None, enabled: bool = True,
                 stats=None, fresh_state: bool = False):
        self._scope = _TrialScope(trial_id, enabled, stats, fresh_state)

    def __enter__(self) -> "_TrialScope":
        self._prev = getattr(_local, "scope", None)
        _local.scope = self._scope
        return self._scope

    def __exit__(self, exc_type, exc, tb) -> None:
        scope = self._scope
        _local.scope = self._prev
        if not scope.enabled:
            return
        for trainer in scope.trainers:
            try:
                trainer.retire_to_warm_cache()
            except Exception:  # noqa: BLE001 - retirement is an optimization
                pass


def enabled() -> bool:
    """Is the warm path armed for this thread? The trial scope's flag when
    inside one (``config.warm_start``), else the process default
    (``MAGGY_TPU_WARM_START`` != "0" — read at call time so process pools
    inherit it through the environment)."""
    scope = current_scope()
    if scope is not None:
        return scope.enabled
    return os.environ.get("MAGGY_TPU_WARM_START", "1") != "0"


def fresh_state_only() -> bool:
    """True when the current trial resumes a checkpoint (its own or a
    promoted parent's): the warm slot's retired buffers must not be
    consumed — reused jits are fine, inherited state is not."""
    scope = current_scope()
    return scope is not None and scope.fresh_state


def register_trainer(trainer) -> None:
    """Called by ``Trainer.__init__``: the trial scope retires this
    trainer's buffers at trial end. No-op outside a scope (library users
    may call ``trainer.retire_to_warm_cache()`` themselves)."""
    scope = current_scope()
    if scope is not None and scope.enabled:
        scope.trainers.append(trainer)


def note_compile(**fields: Any) -> None:
    """Record compile-phase telemetry for the current trial (merged into
    its RunnerStats ``compile`` record; ``*_ms`` fields accumulate)."""
    scope = current_scope()
    stats = scope.stats if scope is not None else None
    if stats is not None:
        stats.note_compile(**fields)


def note_ckpt(**fields: Any) -> None:
    """Record checkpoint I/O telemetry for the current trial (merged into
    its RunnerStats ``ckpt`` record; ``*_ms`` and ``saves``/``restores``
    accumulate). No-op outside a trial scope — library users running
    checkpointing outside an experiment pay nothing."""
    scope = current_scope()
    stats = scope.stats if scope is not None else None
    if stats is not None:
        stats.note_ckpt(**fields)


# ----------------------------------------------------------------- counters

def _count(key: str, n: int = 1) -> None:
    with _counters_lock:
        _counters[key] = _counters.get(key, 0) + n
    scope = current_scope()
    stats = scope.stats if scope is not None else None
    if stats is not None:
        stats.note_counter(key, n)


def counters() -> Dict[str, int]:
    """Process-global warm/compile-cache counter snapshot."""
    with _counters_lock:
        return dict(_counters)


def _monitoring_listener(event: str, **kwargs: Any) -> None:
    if event == WARM_HIT_EVENT:
        _count("warm_hits")
    elif event == WARM_MISS_EVENT:
        _count("warm_misses")
    elif event == "/jax/compilation_cache/cache_hits":
        _count("xla_cache_hits")
    elif event == "/jax/compilation_cache/cache_misses":
        _count("xla_cache_misses")


def install_monitoring_listener() -> bool:
    """Register the jax.monitoring event listener that turns warm-slot and
    persistent-compilation-cache events into counters. Idempotent; never
    fatal (counting is an observability feature, not a dependency)."""
    global _listener_installed
    with _listener_lock:
        if _listener_installed:
            return True
        try:
            from jax import monitoring

            monitoring.register_event_listener(_monitoring_listener)
            _listener_installed = True
            return True
        except Exception:  # noqa: BLE001 - jax absent/ancient: count nothing
            return False


def record_warm_event(hit: bool) -> None:
    """Emit the warm-slot hit/miss jax.monitoring event (counted by the
    installed listener). Falls back to direct counting if the event bus is
    unavailable."""
    if install_monitoring_listener():
        from jax import monitoring

        monitoring.record_event(WARM_HIT_EVENT if hit else WARM_MISS_EVENT)
    else:
        _count("warm_hits" if hit else "warm_misses")


# -------------------------------------------------------------- program keys

def shape_key(tree) -> str:
    """Hashable signature of a pytree's structure + leaf shapes/dtypes —
    the per-shape identity AOT executables and init entries key on."""
    import jax
    import numpy as np

    leaves, treedef = jax.tree_util.tree_flatten(tree)

    def sig(x):
        shape = getattr(x, "shape", None)
        if shape is None:
            shape = np.shape(x)
        dtype = getattr(x, "dtype", None)
        if dtype is None:
            dtype = np.asarray(x).dtype
        return (tuple(shape), str(dtype))

    return repr((treedef, [sig(x) for x in leaves]))


def swept_info(tx) -> Optional[Dict[str, Any]]:
    """The metadata ``swept_transform`` attached to a transform whose
    hyperparameters are traced inputs, or None for a plain transform."""
    return getattr(getattr(tx, "init", None), "_maggy_swept", None)


def opt_family(tx) -> Optional[tuple]:
    """Value-independent optimizer identity: transforms built by
    ``swept_transform`` from the same factory with the same hyperparameter
    NAMES (and identical repr-stable non-numeric statics) share a family —
    their opt_state structure and the compiled program are identical, only
    the traced hyperparam values differ. None for plain transforms AND for
    swept transforms with object-repr statics (schedules, callables): no
    safe cross-object sharing — constants may be baked into the program,
    and an id-bearing repr would mint a never-matching family per trial."""
    info = swept_info(tx)
    return None if info is None else info["family"]


def rebind_hyperparams(opt_state, hparams: Dict[str, Any]):
    """Return ``opt_state`` with the injected-hyperparameter leaves
    (``optax.inject_hyperparams`` state anywhere inside a chain) replaced
    by ``hparams``' values, preserving leaf dtypes. The rebind step of
    buffer-donating re-init: the cached re-init traced the FIRST trial's
    transform, so its constants must be overwritten with this trial's."""
    import jax.numpy as jnp

    def rebind(state):
        if hasattr(state, "_replace") and hasattr(state, "_fields"):
            updates = {}
            for f in state._fields:
                v = getattr(state, f)
                if f == "hyperparams" and isinstance(v, dict):
                    new = dict(v)
                    for k, hv in hparams.items():
                        if k in new:
                            new[k] = jnp.asarray(
                                hv, getattr(new[k], "dtype", None))
                    updates[f] = new
                elif isinstance(v, (tuple, list)):
                    updates[f] = rebind(v)
            return state._replace(**updates) if updates else state
        if isinstance(state, (tuple, list)):
            return type(state)(rebind(s) for s in state)
        return state

    return rebind(opt_state)


# -------------------------------------------------------------- cache/slots

class _InitEntry:
    """Per-(program, input-shape) reusable init state: the jitted
    initializer, the computed shardings (skipping eval_shape + unboxing on
    reuse), the lazily built donating re-init, and the single retired
    buffer cell the next trial consumes."""

    __slots__ = ("init_jit", "init_unboxed", "shardings", "abstract",
                 "reinit_jit", "reinit_lock", "reinit_prebuilt",
                 "opt_tx", "opt_family", "opt_reinit_jit", "retired", "lock")

    def __init__(self, init_jit, init_unboxed, shardings, abstract=None):
        self.init_jit = init_jit
        self.init_unboxed = init_unboxed
        self.shardings = shardings
        # Unboxed abstract state tree (ShapeDtypeStructs) — what the
        # background re-init prebuild lowers against, so it never touches
        # device memory.
        self.abstract = abstract
        self.reinit_jit = None
        # Serializes the donating re-init build between the concurrent
        # prebuild thread (spawned with the family's FIRST trial) and the
        # first WARM trial's inline fallback: one trace+compile, the
        # loser waits on the winner's program.
        self.reinit_lock = threading.Lock()
        self.reinit_prebuilt = False
        # First transform of the family seen on this entry: its (pure)
        # init is what the donating opt re-init traces; the per-trial
        # hyperparam values are rebound after.
        self.opt_tx = None
        self.opt_family = None
        self.opt_reinit_jit = None
        self.retired: Optional[tuple] = None  # guarded-by: lock
        self.lock = threading.Lock()

    def store_retired(self, variables, opt_state, family) -> None:
        with self.lock:
            self.retired = (variables, opt_state, family)

    def take_retired(self) -> Optional[tuple]:
        """Pop the retired buffers (at most one consumer: they are DONATED
        to the re-init, so a second taker would read deleted arrays)."""
        with self.lock:
            retired, self.retired = self.retired, None
            return retired

    def drop_retired(self) -> None:
        with self.lock:
            self.retired = None


class _VmapEntry:
    """Per-(program, K-lanes, input-shape) vectorized warm state: the ONE
    AOT-compiled K-lane vmapped step executable every block of the family
    shares, and the STACKED retired state buffers of the previous block —
    consumed by the next block's donating re-init exactly like the scalar
    ``_InitEntry.retired`` cell, generalized across the lane axis."""

    __slots__ = ("vstep", "lanes", "retired", "lock")

    def __init__(self, lanes: int):
        self.lanes = lanes
        self.vstep = None  # guarded-by: lock  # compiled K-lane executable
        self.retired: Optional[tuple] = None  # guarded-by: lock
        self.lock = threading.Lock()

    def ensure_vstep(self, build: Callable[[], Any]):
        with self.lock:
            if self.vstep is None:
                self.vstep = build()
            return self.vstep

    def store_retired(self, stacked_vars, stacked_opt, family) -> None:
        with self.lock:
            self.retired = (stacked_vars, stacked_opt, family)

    def take_retired(self) -> Optional[tuple]:
        """Pop the stacked retired buffers (single consumer: they are
        DONATED to the block re-init, a second taker would read deleted
        arrays)."""
        with self.lock:
            retired, self.retired = self.retired, None
            return retired

    def drop_retired(self) -> None:
        with self.lock:
            self.retired = None


class WarmSlot:
    """One program family's warm state. ``step_jit`` is shared by every
    trial of the family (jax.jit re-traces per input shape internally);
    ``compiled`` holds the AOT-split executables per shape so repeat
    trials skip trace AND compile; ``inits`` holds per-input-shape init
    entries; ``vmaps`` holds per-(lanes, shape) vectorized entries (the
    K-lane executables + stacked retired buffers of vectorized blocks,
    train/vmap.py)."""

    __slots__ = ("key", "lock", "step_jit", "compiled", "inits", "aot_ok",
                 "aot_lock", "vmaps")

    def __init__(self, key):
        self.key = key
        self.lock = threading.Lock()
        self.step_jit = None  # guarded-by: lock
        self.compiled: "OrderedDict[str, Any]" = OrderedDict()  # guarded-by: lock
        self.inits: "OrderedDict[Any, _InitEntry]" = OrderedDict()  # guarded-by: lock
        self.vmaps: "OrderedDict[Any, _VmapEntry]" = OrderedDict()  # guarded-by: lock
        self.aot_ok = True
        # Serializes AOT lower+compile per slot: N thread-pooled runners
        # whose first trials race the same program must produce ONE
        # compile, not N concurrent ones (the plain-jit path gets the
        # same guarantee from pjit's internal cache locking).
        self.aot_lock = threading.Lock()

    def vmap_entry(self, key, lanes: int) -> "_VmapEntry":
        """Get-or-create the vectorized entry for one (lanes, shape)
        signature; bounded by the same per-slot LRU as ``compiled``."""
        with self.lock:
            entry = self.vmaps.get(key)
            if entry is None or entry.lanes != lanes:
                entry = _VmapEntry(lanes)
                self.vmaps[key] = entry
                while len(self.vmaps) > PER_SLOT_SHAPES:
                    self.vmaps.popitem(last=False)
            else:
                self.vmaps.move_to_end(key)
            return entry

    def ensure_step(self, build: Callable[[], Any]):
        with self.lock:
            if self.step_jit is None:
                self.step_jit = build()
            return self.step_jit

    def init_entry(self, key, build: Callable[[], _InitEntry]
                   ) -> Tuple[_InitEntry, bool]:
        """Get-or-build the init entry for one input-shape signature;
        returns (entry, hit)."""
        with self.lock:
            entry = self.inits.get(key)
            if entry is not None:
                self.inits.move_to_end(key)
                return entry, True
        built = build()
        with self.lock:
            entry = self.inits.get(key)
            if entry is None:
                entry = built
                self.inits[key] = entry
                while len(self.inits) > PER_SLOT_SHAPES:
                    self.inits.popitem(last=False)
            return entry, False

    def get_init(self, key) -> Optional[_InitEntry]:
        with self.lock:
            return self.inits.get(key)

    def compiled_step(self, key: str):
        with self.lock:
            fn = self.compiled.get(key)
            if fn is not None:
                self.compiled.move_to_end(key)
            return fn

    def store_compiled(self, key: str, fn) -> None:
        with self.lock:
            self.compiled[key] = fn
            while len(self.compiled) > PER_SLOT_SHAPES:
                self.compiled.popitem(last=False)


class WarmCache:
    """Bounded LRU of warm slots keyed by program identity."""

    def __init__(self, maxsize: Optional[int] = None):
        if maxsize is None:
            maxsize = int(os.environ.get("MAGGY_TPU_WARM_SLOTS",
                                         DEFAULT_WARM_SLOTS))
        self.maxsize = max(1, maxsize)
        self._lock = threading.Lock()
        self._slots: "OrderedDict[Any, WarmSlot]" = OrderedDict()  # guarded-by: _lock

    def slot(self, key) -> Tuple[WarmSlot, bool]:
        """Get-or-create the slot for ``key``; returns (slot, existed)."""
        with self._lock:
            slot = self._slots.get(key)
            if slot is not None:
                self._slots.move_to_end(key)
                return slot, True
            slot = WarmSlot(key)
            self._slots[key] = slot
            while len(self._slots) > self.maxsize:
                self._slots.popitem(last=False)
            return slot, False

    def clear(self) -> None:
        with self._lock:
            self._slots.clear()

    def keys(self):
        with self._lock:
            return list(self._slots)

    def __len__(self) -> int:
        with self._lock:
            return len(self._slots)


_CACHE = WarmCache()


def warm_cache() -> WarmCache:
    return _CACHE


def clear_warm() -> None:
    """Drop every warm slot (compiled executables, shardings, retired
    buffers). The explicit unbounded-growth escape hatch for long-lived
    fleet runners, and the isolation reset tests/benches use between A/B
    arms. Exported as ``maggy_tpu.train.clear_warm``."""
    _CACHE.clear()
