"""Trial: the unit of schedulable work and its thread-safe state machine.

Parity: reference `maggy/trial.py` — status machine (:33-37), deterministic
md5-derived 16-char trial ids (:110-136), thread-safe early-stop flag and
step-deduplicated metric history (:83-108), json round-trip (:138-176),
ablation trials hashing only the ablated components (:62-67).
"""

from __future__ import annotations

import hashlib
import json
import threading
from typing import Any, Dict, List, Optional


def _json_default(obj):
    import numpy as np

    if isinstance(obj, np.integer):
        return int(obj)
    if isinstance(obj, np.floating):
        return float(obj)
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    raise TypeError("Object of type {} is not JSON serializable".format(type(obj)))


class Trial:
    """One evaluation of the user function at a fixed parameter point.

    Shared between the driver's worker thread and the control-plane server
    thread; all mutation is guarded by an RLock (reference `trial.py:24-31`).
    """

    PENDING = "PENDING"
    SCHEDULED = "SCHEDULED"
    RUNNING = "RUNNING"
    ERROR = "ERROR"
    FINALIZED = "FINALIZED"

    def __init__(
        self,
        params: Dict[str, Any],
        trial_type: str = "optimization",
        info_dict: Optional[Dict[str, Any]] = None,
    ):
        self.params = params
        self.trial_type = trial_type
        self.trial_id = Trial._compute_id(params, trial_type)
        self.status = Trial.PENDING  # guarded-by: lock
        self.early_stop = False  # guarded-by: lock
        # Scheduler preemption in flight: the early-stop flag carries the
        # STOP to the runner, this flag marks it as a preemption (the
        # runner acks with a preempted FINAL instead of finalizing).
        self.preempt = False  # guarded-by: lock
        self.final_metric: Optional[float] = None  # guarded-by: lock
        self.metric_history: List[float] = []  # guarded-by: lock
        self.step_history: List[int] = []  # guarded-by: lock
        self.metric_dict: Dict[int, float] = {}  # guarded-by: lock
        self.start: Optional[float] = None  # guarded-by: lock
        self.duration: Optional[float] = None  # guarded-by: lock
        # Run epoch: bumped on every reset_run_state (requeue/revocation)
        # and stamped into each dispatch, so the driver can tell a dead
        # run's in-flight FINAL from the live re-run's — even when both
        # come from the SAME partition (a revoked gang reassembling onto
        # its old leader).
        self.run_epoch = 0  # guarded-by: lock
        self.info_dict: Dict[str, Any] = info_dict or {}
        self.lock = threading.RLock()

    # -------------------------------------------------------------- identity

    @staticmethod
    def _compute_id(params: Dict[str, Any], trial_type: str) -> str:
        """16-char stable id = md5 over the canonical param json.

        Ablation trials hash only the ablated components so structurally
        identical trials dedup (reference `trial.py:62-67,110-136`). Callable
        params never occur here: ablation specs are declarative (see
        `ablation/ablator/loco.py`).
        """
        if trial_type == "ablation":
            material = {
                "ablated_feature": params.get("ablated_feature", "None"),
                "ablated_layer": params.get("ablated_layer", "None"),
                "model_key": params.get("model_key", "base"),
            }
        else:
            material = {k: v for k, v in params.items()}
        blob = json.dumps(material, sort_keys=True, default=_json_default)
        return hashlib.md5(blob.encode("utf-8")).hexdigest()[:16]

    # ----------------------------------------------------------------- state

    def set_status(self, status: str) -> None:
        with self.lock:
            self.status = status

    def get_early_stop(self) -> bool:
        with self.lock:
            return self.early_stop

    def set_early_stop(self) -> None:
        with self.lock:
            self.early_stop = True

    def get_preempt(self) -> bool:
        with self.lock:
            return self.preempt

    def set_preempt(self) -> None:
        with self.lock:
            self.preempt = True

    def reset_run_state(self) -> None:
        """Discard the state of a dead run before a re-run.

        A requeued trial restarts from step 0 on a fresh runner; stale
        metric history would otherwise collide with the new run's steps
        (dedup-by-step) and a stale early-stop flag would kill it instantly.
        Mirrors the reference wiping the trial dir on executor restart
        (`trial_executor.py:115-119`).
        """
        with self.lock:
            self.early_stop = False
            self.preempt = False
            self.final_metric = None
            self.run_epoch += 1
            self.metric_history = []
            self.step_history = []
            self.metric_dict = {}
            self.start = None
            self.status = Trial.SCHEDULED

    def append_metric(self, metric: float, step: Optional[int] = None) -> bool:
        """Record a heartbeat metric; dedup by step (reference `trial.py:93-108`).

        Returns True if the observation was new.
        """
        with self.lock:
            if metric is None:
                return False
            if step is None:
                step = self.step_history[-1] + 1 if self.step_history else 0
            if step in self.metric_dict:
                return False
            self.metric_dict[step] = float(metric)
            self.metric_history.append(float(metric))
            self.step_history.append(int(step))
            return True

    # ------------------------------------------------------------------ json

    def to_dict(self) -> Dict[str, Any]:
        with self.lock:
            return {
                "id": self.trial_id,
                "trial_type": self.trial_type,
                "params": self.params,
                "status": self.status,
                "early_stop": self.early_stop,
                "final_metric": self.final_metric,
                "metric_history": list(self.metric_history),
                "step_history": list(self.step_history),
                "start": self.start,
                "duration": self.duration,
                "info_dict": dict(self.info_dict),
            }

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), default=_json_default)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Trial":
        trial = cls(d["params"], trial_type=d.get("trial_type", "optimization"))
        trial.status = d.get("status", Trial.PENDING)
        trial.early_stop = d.get("early_stop", False)
        trial.final_metric = d.get("final_metric")
        trial.metric_history = list(d.get("metric_history", []))
        trial.step_history = list(d.get("step_history", []))
        trial.metric_dict = dict(zip(trial.step_history, trial.metric_history))
        trial.start = d.get("start")
        trial.duration = d.get("duration")
        trial.info_dict = dict(d.get("info_dict", {}))
        return trial

    @classmethod
    def from_json(cls, blob: str) -> "Trial":
        return cls.from_dict(json.loads(blob))

    def __repr__(self):
        return "Trial(id={}, status={}, params={})".format(
            # unguarded-ok: diagnostic repr — a lock here can deadlock crash logs
            self.trial_id, self.status, self.params
        )
