"""Framework utilities.

Parity: reference `maggy/util.py` — return-value validation + persistence
`handle_return_val` (:151-191), experiment registration (:264-279), numpy-safe
json (:89-99), progress bar (:71-86), summary builder (:126-148).
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Optional

import numpy as np

from maggy_tpu import constants
from maggy_tpu.exceptions import MetricTypeError, ReturnTypeError


def json_default_numpy(obj):
    if isinstance(obj, np.integer):
        return int(obj)
    if isinstance(obj, np.floating):
        return float(obj)
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    raise TypeError("Type {} not serializable".format(type(obj)))


def json_dumps_safe(obj: Any) -> str:
    return json.dumps(obj, default=json_default_numpy)


def handle_return_val(return_val: Any, trial_dir: str, optimization_key: str,
                      env=None) -> float:
    """Validate the user function's return value and persist artifacts.

    Accepts a number (the metric) or a dict containing ``optimization_key``;
    writes ``.outputs.json`` + ``.metric`` into the trial dir (reference
    `util.py:151-191`).
    """
    from maggy_tpu.core.environment import EnvSing

    env = env or EnvSing.get_instance()
    if isinstance(return_val, dict):
        if optimization_key not in return_val:
            raise ReturnTypeError(optimization_key, return_val)
        metric = return_val[optimization_key]
        outputs = return_val
    elif isinstance(return_val, constants.USER_FCT.NUMERIC_TYPES) and not isinstance(return_val, bool):
        metric = return_val
        outputs = {optimization_key: return_val}
    else:
        raise ReturnTypeError(optimization_key, return_val)
    if not isinstance(metric, constants.USER_FCT.NUMERIC_TYPES) or isinstance(metric, bool):
        raise MetricTypeError(optimization_key, metric)
    metric = float(metric)
    env.dump(json.dumps(outputs, default=json_default_numpy), trial_dir + "/.outputs.json")
    env.dump(str(metric), trial_dir + "/.metric")
    return metric


def write_hparams_config(exp_dir: str, searchspace, env=None) -> None:
    """Persist the searchspace for TensorBoard-HParams-style tooling
    (reference `tensorboard.py:75-87`)."""
    from maggy_tpu.core.environment import EnvSing

    if searchspace is None:
        return
    env = env or EnvSing.get_instance()
    env.dump(json.dumps(searchspace.to_dict(), indent=2), exp_dir + "/searchspace.json")


def build_summary(exp_dir: str, env=None) -> Dict[str, Any]:
    """Aggregate every trial dir's .hparams.json/.outputs.json into one
    summary (reference `util.py:126-148`)."""
    from maggy_tpu.core.environment import EnvSing

    env = env or EnvSing.get_instance()
    combos = []
    for entry in env.ls(exp_dir):
        tdir = os.path.join(exp_dir, entry)
        hparams_p, outputs_p = tdir + "/.hparams.json", tdir + "/.outputs.json"
        if env.isdir(tdir) and env.exists(outputs_p):
            combo = {"id": entry}
            if env.exists(hparams_p):
                combo["hparams"] = json.loads(env.load(hparams_p))
            combo["outputs"] = json.loads(env.load(outputs_p))
            combos.append(combo)
    summary = {"combinations": combos, "built_at": time.time()}
    env.dump(json.dumps(summary, indent=2, default=json_default_numpy),
             exp_dir + "/.summary.json")
    return summary


def progress_bar(done: int, total: int, width: int = 30) -> str:
    frac = 0 if total == 0 else done / total
    filled = int(width * frac)
    return "[{}{}] {}/{}".format("=" * filled, " " * (width - filled), done, total)


def next_run_id(base_dir: str, app_id: str, env=None) -> int:
    """Monotonic run id per app id under the experiment base dir, checked
    through the environment's filesystem (works for gs:// paths too)."""
    from maggy_tpu.core.environment import EnvSing

    env = env or EnvSing.get_instance()
    i = 0
    while env.exists("{}/{}_{}".format(base_dir.rstrip("/"), app_id, i)):
        i += 1
    return i
