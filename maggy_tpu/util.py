"""Framework utilities.

Parity: reference `maggy/util.py` — return-value validation + persistence
`handle_return_val` (:151-191), experiment registration (:264-279), numpy-safe
json (:89-99), progress bar (:71-86), summary builder (:126-148).
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Optional

import numpy as np

from maggy_tpu import constants
from maggy_tpu.exceptions import MetricTypeError, ReturnTypeError


def json_default_numpy(obj):
    if isinstance(obj, np.integer):
        return int(obj)
    if isinstance(obj, np.floating):
        return float(obj)
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    raise TypeError("Type {} not serializable".format(type(obj)))


def json_dumps_safe(obj: Any) -> str:
    return json.dumps(obj, default=json_default_numpy)


def handle_return_val(return_val: Any, trial_dir: str, optimization_key: str,
                      env=None) -> float:
    """Validate the user function's return value and persist artifacts.

    Accepts a number (the metric) or a dict containing ``optimization_key``;
    writes ``.outputs.json`` + ``.metric`` into the trial dir (reference
    `util.py:151-191`).
    """
    from maggy_tpu.core.environment import EnvSing

    env = env or EnvSing.get_instance()
    if isinstance(return_val, dict):
        if optimization_key not in return_val:
            raise ReturnTypeError(optimization_key, return_val)
        metric = return_val[optimization_key]
        outputs = return_val
    elif isinstance(return_val, constants.USER_FCT.NUMERIC_TYPES) and not isinstance(return_val, bool):
        metric = return_val
        outputs = {optimization_key: return_val}
    else:
        raise ReturnTypeError(optimization_key, return_val)
    if not isinstance(metric, constants.USER_FCT.NUMERIC_TYPES) or isinstance(metric, bool):
        raise MetricTypeError(optimization_key, metric)
    metric = float(metric)
    env.dump(json.dumps(outputs, default=json_default_numpy), trial_dir + "/.outputs.json")
    env.dump(str(metric), trial_dir + "/.metric")
    return metric


def write_hparams_config(exp_dir: str, searchspace, env=None) -> None:
    """Persist the searchspace for TensorBoard-HParams-style tooling
    (reference `tensorboard.py:75-87`)."""
    from maggy_tpu.core.environment import EnvSing

    if searchspace is None:
        return
    env = env or EnvSing.get_instance()
    env.dump(json.dumps(searchspace.to_dict(), indent=2), exp_dir + "/searchspace.json")
    # HParams dashboard column config (real TB event file, torch-free;
    # best-effort — write_experiment_config swallows its own failures).
    from maggy_tpu import tensorboard as tb

    tb.write_experiment_config(exp_dir, searchspace)


def build_summary(exp_dir: str, env=None) -> Dict[str, Any]:
    """Aggregate every trial dir's .hparams.json/.outputs.json into one
    summary (reference `util.py:126-148`)."""
    from maggy_tpu.core.environment import EnvSing

    env = env or EnvSing.get_instance()
    combos = []
    for entry in env.ls(exp_dir):
        tdir = os.path.join(exp_dir, entry)
        hparams_p, outputs_p = tdir + "/.hparams.json", tdir + "/.outputs.json"
        if env.isdir(tdir) and env.exists(outputs_p):
            combo = {"id": entry}
            if env.exists(hparams_p):
                combo["hparams"] = json.loads(env.load(hparams_p))
            combo["outputs"] = json.loads(env.load(outputs_p))
            combos.append(combo)
    summary = {"combinations": combos, "built_at": time.time()}
    env.dump(json.dumps(summary, indent=2, default=json_default_numpy),
             exp_dir + "/.summary.json")
    return summary


def progress_bar(done: int, total: int, width: int = 30) -> str:
    frac = 0 if total == 0 else done / total
    filled = int(width * frac)
    return "[{}{}] {}/{}".format("=" * filled, " " * (width - filled), done, total)


def next_run_id(base_dir: str, app_id: str, env=None) -> int:
    """Monotonic run id per app id under the experiment base dir, checked
    through the environment's filesystem (works for gs:// paths too).

    Scan only — racy by construction (two scanners can see the same next
    id). Starters must go through ``claim_run_id``; this stays the read
    path resume uses to FIND the most recent existing run."""
    from maggy_tpu.core.environment import EnvSing

    env = env or EnvSing.get_instance()
    i = 0
    while env.exists("{}/{}_{}".format(base_dir.rstrip("/"), app_id, i)):
        i += 1
    return i


#: Marker claimed atomically inside a run dir by the experiment that owns
#: it (see claim_run_id).
RUN_CLAIM_FILE = ".run_claim"


def find_resume_run_id(base_dir: str, app_id: str, name: str,
                       env=None) -> int:
    """The run id ``resume=True`` should re-enter: the MOST RECENT run of
    this app whose registered experiment NAME matches ``name``.

    The bare most-recent-run rule is wrong the moment one app id hosts
    more than one experiment (fleet tenants share the process app id):
    a resubmitted tenant would re-enter whichever tenant ran LAST and
    replay someone else's journal. The experiment name in each run dir's
    experiment.json is the identity that disambiguates; runs whose
    metadata is missing/torn are skipped (never adopted blind). Raises
    ``ValueError`` when no matching run exists."""
    from maggy_tpu.core.environment import EnvSing

    env = env or EnvSing.get_instance()
    base = base_dir.rstrip("/")
    last = next_run_id(base, app_id, env=env) - 1
    for i in range(last, -1, -1):
        meta_path = "{}/{}_{}/experiment.json".format(base, app_id, i)
        if not env.exists(meta_path):
            continue
        try:
            meta = json.loads(env.load(meta_path))
        except ValueError:
            continue
        if meta.get("name") == name:
            return i
    raise ValueError(
        "resume=True but no previous run of app '{}' named '{}' exists "
        "under {} ({} run dir(s) scanned)".format(app_id, name, base,
                                                  last + 1))


def claim_run_id(base_dir: str, app_id: str, env=None) -> int:
    """Atomically claim the next free run id: scan like ``next_run_id``,
    then stake the run dir with ``AbstractEnv.exclusive_create`` (hard-link
    exclusivity locally, if_generation_match=0 on GCS) so exactly ONE of N
    concurrent starters — two lagom_submit threads, two processes sharing
    a base dir — wins each id; losers move to the next. Closes the
    scan-then-create TOCTOU that could mint the same run id twice (the
    same fix PR 1 applied to DatasetRegistry.register)."""
    import threading

    from maggy_tpu.core.environment import EnvSing

    env = env or EnvSing.get_instance()
    base = base_dir.rstrip("/")
    i = next_run_id(base, app_id, env=env)
    while True:
        run_dir = "{}/{}_{}".format(base, app_id, i)
        if not env.exists(run_dir):
            marker = "{}/{}".format(run_dir, RUN_CLAIM_FILE)
            payload = json.dumps({"claimed_at": time.time(),
                                  "pid": os.getpid(),
                                  "thread": threading.get_ident()})
            if env.exclusive_create(payload, marker):
                return i
        i += 1


#: Prefix of the per-incarnation adoption markers a driver stakes inside
#: its run dir (see claim_driver_epoch).
DRIVER_EPOCH_PREFIX = ".driver_epoch."


def claim_driver_epoch(run_dir: str, env=None) -> int:
    """Atomically claim the next driver incarnation of ``run_dir``.

    Crash-only recovery lets a restarted driver re-enter an existing run
    dir (``resume=True``) — but the resume SCAN in ``next_run_id`` is
    racy by construction, so two restarting drivers can both decide to
    adopt the same run. The ``.run_claim`` marker cannot arbitrate that
    (it already exists — it belongs to the CRASHED incarnation), so
    adoption goes through its own exclusive marker: scan for the highest
    existing ``.driver_epoch.N``, then ``exclusive_create`` N+1. Exactly
    one adopter wins each epoch; the loser gets ``RunAdoptionError`` (a
    clear exit). Scope: this arbitrates CONCURRENT adopters racing for
    the same epoch — a predecessor that claimed earlier and wedged
    without exiting is instead caught by the resume port rebind (a
    still-bound pre-crash port refuses adoption; Driver.init). Fresh
    runs claim epoch 1 the same way — their run dir was staked
    exclusively by ``claim_run_id``, so the claim cannot race.

    Returns the claimed epoch (1-based)."""
    import threading

    from maggy_tpu.core.environment import EnvSing
    from maggy_tpu.exceptions import RunAdoptionError

    env = env or EnvSing.get_instance()
    run_dir = run_dir.rstrip("/")
    epoch = 1
    while env.exists("{}/{}{}".format(run_dir, DRIVER_EPOCH_PREFIX, epoch)):
        epoch += 1
    payload = json.dumps({"claimed_at": time.time(), "pid": os.getpid(),
                          "thread": threading.get_ident()})
    marker = "{}/{}{}".format(run_dir, DRIVER_EPOCH_PREFIX, epoch)
    if not env.exclusive_create(payload, marker):
        raise RunAdoptionError(
            "run dir {} was adopted by another driver (incarnation marker "
            "{} already claimed); exactly one restarted driver may adopt "
            "a run — this one must exit".format(run_dir,
                                                marker.rsplit("/", 1)[-1]))
    return epoch


def enable_compile_cache(cache_dir: Optional[str] = None) -> Optional[str]:
    """Arm JAX's persistent XLA compilation cache.

    64 concurrent trials with differing hparams compile distinct XLA
    programs (SURVEY.md §7.3 "compile-cache churn"); a shared on-disk cache
    lets runner processes — and successive trials with recurring shapes —
    reuse compiled executables instead of paying the 20-40s TPU compile
    again. Safe to call repeatedly; disabled by MAGGY_TPU_NO_COMPILE_CACHE=1.
    Returns the cache dir, or None when disabled/unavailable.
    """
    if os.environ.get("MAGGY_TPU_NO_COMPILE_CACHE") == "1":
        return None
    if cache_dir is None and os.environ.get("JAX_PLATFORMS", "") == "cpu" \
            and "MAGGY_TPU_COMPILE_CACHE_DIR" not in os.environ:
        # XLA:CPU AOT cache entries embed host ISA features and warn (or
        # SIGILL) on reuse across machines; the cache pays off on TPU where
        # compiles cost 20-40s, so default it off for CPU runs/tests.
        return None
    cache_dir = cache_dir or os.environ.get(
        "MAGGY_TPU_COMPILE_CACHE_DIR",
        os.path.join(os.path.expanduser("~"), ".cache", "maggy_tpu_xla"),
    )
    try:
        import jax

        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # Cache every program: trial workloads are small, recompiles are the
        # bottleneck (defaults skip sub-second compiles).
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        return cache_dir
    except Exception:  # noqa: BLE001 - cache is an optimization, never fatal
        return None


def apply_platform_env() -> None:
    """Make JAX_PLATFORMS authoritative even when a TPU PJRT plugin was
    registered before this process's env vars could win (sitecustomize
    imports jax at interpreter start on some images): backend choice
    freezes at first use, so force the live config before any jax call."""
    plat = os.environ.get("JAX_PLATFORMS")
    if not plat:
        return
    try:
        import jax

        jax.config.update("jax_platforms", plat)
    except Exception:  # noqa: BLE001 - never fatal; jax may be absent
        pass
