"""Packaging (parity: reference setup.py). Not needed for in-repo use."""

from setuptools import find_packages, setup

setup(
    name="maggy-tpu",
    version="0.1.0",
    description=(
        "TPU-native asynchronous hyperparameter optimization, ablation "
        "studies, and distributed training on JAX/XLA/Pallas."
    ),
    packages=find_packages(exclude=["tests", "examples"]),
    package_data={"maggy_tpu.native": ["framing.cpp"]},
    python_requires=">=3.10",
    install_requires=[
        "numpy",
        "msgpack",
        "jax",
        "flax",
        "optax",
        "scipy",
        "scikit-learn",
    ],
    extras_require={
        "checkpoint": ["orbax-checkpoint"],
        "tensorboard": ["tensorboard"],  # torch-free: proto-level writer
        "gcs": ["gcsfs"],
    },
    entry_points={
        "console_scripts": [
            "maggy-tpu-runner = maggy_tpu.runner:main",
            "maggy-tpu-monitor = maggy_tpu.monitor:main",
        ],
    },
)
