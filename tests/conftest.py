"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Multi-chip TPU hardware is unavailable in CI; sharding paths are validated on
8 virtual CPU devices via XLA host-platform device multiplexing (the
documented JAX approach for testing pjit/shard_map without accelerators).
"""

import os

# Must be set before jax is imported anywhere.
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("JAX_ENABLE_X64", "0")

import pytest  # noqa: E402


@pytest.fixture
def tmp_experiment_dir(tmp_path):
    d = tmp_path / "experiments"
    d.mkdir()
    return str(d)
