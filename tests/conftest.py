"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Multi-chip TPU hardware is unavailable in CI; sharding paths are validated on
8 virtual CPU devices via XLA host-platform device multiplexing (the
documented JAX approach for testing pjit/shard_map without accelerators).
"""

import os

# XLA_FLAGS is read lazily at CPU-client creation, so setting it here works
# even though the environment's sitecustomize imports jax at startup.
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

# jax may ALREADY be imported (sitecustomize registers the TPU plugin before
# conftest runs), so env vars alone are too late — override the live config.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running test; deselect with -m 'not slow'")
    config.addinivalue_line(
        "markers",
        "timeout(seconds): hard SIGALRM bound — the test FAILS with a "
        "TimeoutError instead of silently eating a CI budget")
    config.addinivalue_line(
        "markers",
        "chaos: fault-injection tests (maggy_tpu.chaos). The deterministic "
        "single-process smoke stays in the fast lane; the multi-process "
        "soak is additionally marked slow. Select with -m chaos.")
    config.addinivalue_line(
        "markers",
        "health: live health-engine tests (maggy_tpu.telemetry.health) — "
        "straggler/hang/RTT detection and the stall->flag chaos "
        "invariant. Select with -m health.")
    config.addinivalue_line(
        "markers",
        "perf: scheduling-performance smoke tests with generous CPU "
        "bounds (e.g. the journal-replayed hand-off gap) — fast enough "
        "for tier-1, so hand-off regressions fail in CI instead of only "
        "surfacing in bench.py. Select with -m perf.")
    config.addinivalue_line(
        "markers",
        "analysis: static concurrency/protocol conformance analysis "
        "(maggy_tpu.analysis) — the four checkers against firing/clean "
        "fixtures, the runtime lock-order witness, and the tier-1 "
        "package-must-analyze-clean gate. Select with -m analysis.")
    config.addinivalue_line(
        "markers",
        "obs: live observability plane tests (maggy_tpu.telemetry.obs) — "
        "the /metrics-/status-/healthz-/profilez HTTP surface, the "
        "Prometheus rendering, health-triggered profile capture, and the "
        "tier-1 scrape-vs-journal smoke. Select with -m obs.")
    config.addinivalue_line(
        "markers",
        "fleet: shared-fleet scheduler tests (maggy_tpu.fleet) — "
        "multiplexing concurrent experiments over one runner fleet with "
        "fair share, priorities, and checkpoint-assisted preemption. "
        "Select with -m fleet.")
    config.addinivalue_line(
        "markers",
        "agent: remote fleet-agent tests (maggy_tpu.fleet.agent) — "
        "fleet tickets, the AJOIN/ABIND/ADONE wire contract, "
        "cross-experiment re-binding, agent-death lease revocation "
        "(invariant 11), and remote-gang rendezvous wiring. The real-"
        "subprocess soak is additionally marked slow. Select with "
        "-m agent.")
    config.addinivalue_line(
        "markers",
        "scale: service-scale control-plane tests — SharedServer "
        "per-tenant dispatch pools, multi-hundred-tenant routing stress, "
        "batched heartbeats, indexed fleet admission/shedding, and the "
        "slow-tenant isolation smoke. The fast smokes run in tier-1; "
        "the big churn soaks live in bench.py --scale. Select with "
        "-m scale.")
    config.addinivalue_line(
        "markers",
        "failover: crash-only driver failover tests (core/driver/"
        "recovery.py, chaos/driver_soak.py) — journal-replay "
        "reconstruction, cross-incarnation RPC acceptance, run-dir "
        "adoption, the FINAL-path durability barrier, and invariant 13. "
        "The real-subprocess kill_driver soak is additionally marked "
        "slow. Select with -m failover.")
    config.addinivalue_line(
        "markers",
        "fork: checkpoint-forking search tests — fork/copy staging, the "
        "driver's fork stamp + genealogy + checkpoint GC, bitwise "
        "fork-parity e2e, parent-affinity scheduling, and the offline "
        "invariant-14 checker. The kill-mid-fork soak is `python -m "
        "maggy_tpu.chaos --fork`; the A/B gate is `bench.py --fork`. "
        "Select with -m fork.")
    config.addinivalue_line(
        "markers",
        "sink: fleet-wide telemetry fan-in tests (maggy_tpu.telemetry."
        "sink) — the JSINK journal sink service, client shipper "
        "degrade/re-ship exactly-once seam (invariant 12), clock-offset "
        "estimation, metrics federation, and the unified Perfetto "
        "trace. Select with -m sink.")
    config.addinivalue_line(
        "markers",
        "goodput: chip-time goodput ledger tests (maggy_tpu.telemetry."
        "goodput) — the offline journal fold (closed bucket taxonomy, "
        "exact closure, gang chip-multiplication, rotation/failover "
        "seams), clock-offset-corrected merges, rework attribution "
        "(chaos invariant 15), and the per-tenant fleet roll-up. The "
        "A/B gate is `bench.py --goodput`; the fault-free control soak "
        "is `python -m maggy_tpu.chaos --goodput`. Select with "
        "-m goodput.")
    config.addinivalue_line(
        "markers",
        "vmap: vectorized micro-trial tests (train/vmap.py, "
        "config.vmap_lanes) — K-lane VmapTrainer bitwise parity vs "
        "scalar runs, lane masking/refill, driver block assembly with "
        "scalar fallback for incompatible configs, lane-tagged journal "
        "edges, and the lane_idle goodput split. The kill-mid-block "
        "soak is `python -m maggy_tpu.chaos --vmap`; the A/B gate is "
        "`bench.py --vmap`. Select with -m vmap.")


@pytest.fixture(autouse=True)
def _hard_timeout(request):
    """Enforce @pytest.mark.timeout(N) without the pytest-timeout plugin.

    SIGALRM interrupts the main thread wherever it is blocked (joins, lock
    waits, subprocess polls), so a livelocked test surfaces as a failed
    test with a stack trace, not a hung CI job. Worker threads/processes
    the test leaked are cleaned by their own daemon/terminate paths."""
    m = request.node.get_closest_marker("timeout")
    if m is None:
        yield
        return
    import signal
    import threading

    if threading.current_thread() is not threading.main_thread():
        yield
        return
    seconds = int(m.args[0])

    def _abort(signum, frame):
        raise TimeoutError(
            "test exceeded its {}s hard timeout (marker)".format(seconds))

    old = signal.signal(signal.SIGALRM, _abort)
    signal.alarm(seconds)
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


@pytest.fixture
def tmp_experiment_dir(tmp_path):
    d = tmp_path / "experiments"
    d.mkdir()
    return str(d)
