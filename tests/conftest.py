"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Multi-chip TPU hardware is unavailable in CI; sharding paths are validated on
8 virtual CPU devices via XLA host-platform device multiplexing (the
documented JAX approach for testing pjit/shard_map without accelerators).
"""

import os

# XLA_FLAGS is read lazily at CPU-client creation, so setting it here works
# even though the environment's sitecustomize imports jax at startup.
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

# jax may ALREADY be imported (sitecustomize registers the TPU plugin before
# conftest runs), so env vars alone are too late — override the live config.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture
def tmp_experiment_dir(tmp_path):
    d = tmp_path / "experiments"
    d.mkdir()
    return str(d)
