"""Importable train function for remote-runner agent subprocesses.

The agent (`python -m maggy_tpu.runner`) imports the train function by
dotted path instead of receiving pickled closures over the wire.
"""


def train_fn(lr, units, reporter=None):
    acc = 1.0 - ((lr - 0.1) ** 2 + ((units - 32) / 64.0) ** 2)
    if reporter is not None:
        reporter.broadcast(acc, step=0)
    return {"metric": acc}


def pinned_train_fn(lr, units, reporter=None):
    """Records which chip subset this agent was pinned to (chip-pinning
    e2e: the flag must land in the env BEFORE the trial runs)."""
    import os

    import time

    marker_dir = os.environ["MAGGY_TEST_PIN_DIR"]
    pin = os.environ.get("TPU_VISIBLE_CHIPS", "unpinned")
    host = os.environ.get("MAGGY_TEST_HOST", "h?")
    with open(os.path.join(marker_dir, "{}_{}".format(host, pin.replace(",", "-"))),
              "a") as f:
        f.write("{}\n".format(os.getpid()))
    # Slow trials so the schedule spreads over ALL agents (the pin
    # assertions need every chip subset to see work).
    time.sleep(0.2)
    return {"metric": 1.0 - (lr - 0.1) ** 2}


def dist_train_fn(sharding_env, reporter=None):
    """One SPMD worker: proves the cross-process world actually formed and
    that a collective runs over it."""
    import jax
    import jax.numpy as jnp

    assert jax.process_count() == sharding_env.process_count, \
        "world did not form: {} != {}".format(
            jax.process_count(), sharding_env.process_count)
    # A real cross-process collective: global sum of one unit per device.
    from jax.experimental import multihost_utils

    total = multihost_utils.process_allgather(
        jnp.ones(()) * (sharding_env.process_index + 1)).sum()
    if reporter is not None:
        reporter.broadcast(float(total), step=0)
    return {"metric": float(jax.process_index())}
