"""Importable train function for remote-runner agent subprocesses.

The agent (`python -m maggy_tpu.runner`) imports the train function by
dotted path instead of receiving pickled closures over the wire.
"""


def train_fn(lr, units, reporter=None):
    acc = 1.0 - ((lr - 0.1) ** 2 + ((units - 32) / 64.0) ** 2)
    if reporter is not None:
        reporter.broadcast(acc, step=0)
    return {"metric": acc}
