"""Ablation subsystem tests: study spec, LOCO schedule, end-to-end lagom."""

import pytest

from maggy_tpu import AblationConfig, experiment
from maggy_tpu.ablation import AblationStudy
from maggy_tpu.ablation.ablator import LOCO
from maggy_tpu.core.environment import EnvSing
from maggy_tpu.core.environment.abstractenvironment import LocalEnv


@pytest.fixture(autouse=True)
def local_env(tmp_path):
    env = LocalEnv(base_dir=str(tmp_path / "exp"))
    EnvSing.set_instance(env)
    yield env
    EnvSing.reset()


def make_study():
    study = AblationStudy("toy", 1, "label",
                          dataset_generator=toy_dataset_generator)
    study.features.include("age", "fare")
    study.model.set_base_model_generator(toy_model_generator)
    study.model.layers.include("dense_1")
    study.model.layers.include_groups(["dense_2", "dense_3"])
    study.model.layers.include_groups(prefix="conv")
    return study


# Module-level generators: declarative specs resolve to these by reference.
FEATURES = ["age", "fare", "sex"]


def toy_dataset_generator(ablated_feature=None):
    cols = [f for f in FEATURES if f != ablated_feature]
    return {"columns": cols}


def toy_model_generator(ablated_layers=frozenset()):
    layers = ["conv_a", "conv_b", "dense_1", "dense_2", "dense_3"]
    if any(l.startswith(p) for p in ablated_layers for l in layers):
        # prefix groups arrive as 1-element frozensets
        layers = [l for l in layers
                  if not any(l.startswith(p) for p in ablated_layers)]
    return {"layers": layers}


class TestStudySpec:
    def test_feature_include_exclude(self):
        study = make_study()
        assert study.features.list_all() == ["age", "fare"]
        study.features.exclude("age")
        assert study.features.list_all() == ["fare"]

    def test_group_validation(self):
        study = AblationStudy()
        with pytest.raises(ValueError, match=">= 2"):
            study.model.layers.include_groups(["single"])

    def test_to_dict(self):
        d = make_study().to_dict()
        assert d["included_features"] == ["age", "fare"]
        assert ["conv"] in d["included_layers"]  # prefix group
        assert ["dense_2", "dense_3"] in d["included_layers"]


class TestLocoSchedule:
    def test_trial_count(self):
        loco = LOCO(make_study())
        # 1 base + 2 features + 1 layer + 2 groups (explicit + prefix)
        assert loco.get_number_of_trials() == 6
        loco.initialize()
        assert len(loco.trial_buffer) == 6

    def test_trials_declarative_and_unique(self):
        loco = LOCO(make_study())
        loco.initialize()
        trials = [loco.get_trial() for _ in range(6)]
        assert loco.get_trial() is None
        ids = {t.trial_id for t in trials}
        assert len(ids) == 6
        for t in trials:
            # Params are msgpack-serializable scalars/lists, never callables.
            for v in t.params.values():
                assert isinstance(v, (str, int, float, list))

    def test_resolver(self):
        loco = LOCO(make_study())
        loco.initialize()
        resolver = loco.make_resolver()
        feature_trial = [t for t in [loco.get_trial() for _ in range(6)]
                         if t.params["ablated_feature"] == "age"][0]
        resolved = resolver(dict(feature_trial.params))
        assert resolved["ablated_feature"] == "age"
        assert resolved["dataset_function"]()["columns"] == ["fare", "sex"]
        assert "dense_1" in resolved["model_function"]()["layers"]


class TestFeatureDropping:
    """Built-in dataset ablation (the reference drops the ablated feature
    from the dataset schema itself, `loco.py:41-80`): AblationStudy
    (train_set=...) needs no custom generator."""

    def _data(self):
        import numpy as np

        return {"age": np.arange(4.0), "fare": np.arange(4.0) * 2,
                "sex": np.zeros(4), "label": np.ones(4)}

    def test_drop_feature(self):
        from maggy_tpu.train.data import drop_feature

        data = self._data()
        out = drop_feature(data, "fare")
        assert sorted(out) == ["age", "label", "sex"]
        assert sorted(drop_feature(data, None)) == sorted(data)
        with pytest.raises(KeyError, match="cabin"):
            drop_feature(data, "cabin")

    def test_generator_from_dict_and_path(self, tmp_path):
        import numpy as np

        from maggy_tpu.train.data import feature_dropping_generator

        gen = feature_dropping_generator(self._data())
        assert "age" not in gen(ablated_feature="age")
        path = tmp_path / "ds.npz"
        np.savez(path, **self._data())
        gen = feature_dropping_generator(str(path))
        out = gen(ablated_feature="sex")
        assert sorted(out) == ["age", "fare", "label"]
        assert list(out["fare"]) == [0.0, 2.0, 4.0, 6.0]

    def test_default_generator_uses_train_set(self):
        study = AblationStudy("toy", 1, "label", train_set=self._data())
        study.features.include("age", "fare")
        study.model.set_base_model_generator(toy_model_generator)
        loco = LOCO(study)
        loco.initialize()
        resolver = loco.make_resolver()
        trial = [t for t in [loco.get_trial()
                             for _ in range(loco.get_number_of_trials())]
                 if t and t.params["ablated_feature"] == "fare"][0]
        resolved = resolver(dict(trial.params))
        data = resolved["dataset_function"]()
        assert sorted(data) == ["age", "label", "sex"]

    def test_no_source_raises(self):
        study = AblationStudy("toy", 1, "label")
        study.model.set_base_model_generator(toy_model_generator)
        from maggy_tpu.ablation.ablator.loco import default_dataset_generator

        with pytest.raises(ValueError, match="train_set"):
            default_dataset_generator(study, "age")


def ablation_train_fn(dataset_function, model_function, ablated_feature,
                      ablated_layer, reporter=None):
    data = dataset_function()
    model = model_function()
    # "accuracy" grows with features and layers kept.
    return 0.1 * len(data["columns"]) + 0.05 * len(model["layers"])


class TestAblationE2E:
    def test_full_study(self, local_env):
        config = AblationConfig(
            name="loco_e2e", ablation_study=make_study(), ablator="loco",
            direction="max", num_workers=2, hb_interval=0.05,
        )
        result = experiment.lagom(ablation_train_fn, config)
        assert result["num_trials"] == 6
        # The base trial (nothing ablated) must win under this objective.
        assert result["best_hp"]["ablated_feature"] == "None"
        assert result["best_hp"]["ablated_layer"] == "None"
        # Prefix-group trial drops both conv layers -> worst of the layer trials.
        assert result["best_val"] == pytest.approx(0.1 * 3 + 0.05 * 5)

    def test_unknown_ablator(self):
        with pytest.raises(ValueError, match="Unknown ablator"):
            from maggy_tpu.core.driver.ablation_driver import AblationDriver

            AblationDriver(AblationConfig(ablation_study=make_study(),
                                          ablator="nope"), "a", 0)
