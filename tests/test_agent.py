"""Remote fleet agents (maggy_tpu/fleet/agent.py): the cross-process
fleet.

Covers the ABIND wire contract over a real socket (AJOIN/ALEASE/ADONE),
fleet-ticket parsing, lease delivery and re-binding one agent across TWO
experiments, agent-death lease revocation + exactly-once trial requeue
(chaos invariant 11), remote-gang rendezvous wiring (driver-stamped
jax.distributed coordinates, member program delivery), the per-agent
observability surface, the CLI, and the journal/replay additions.
"""

from __future__ import annotations

import dataclasses
import glob
import json
import os
import threading
import time

import pytest

from maggy_tpu import experiment
from maggy_tpu.core.rpc import Client, MessageSocket
from maggy_tpu.fleet import (AGENT_TICKET_NAME, FLEET_JOURNAL_NAME, Fleet,
                             FleetAgent, read_fleet_ticket,
                             replay_fleet_journal)
from maggy_tpu.fleet.agent import (_AgentChannel, reserve_coord_addr,
                                   train_fn_path)
from maggy_tpu.fleet.soak import _scale_config, agent_train_fn, scale_train_fn
from maggy_tpu.telemetry import JOURNAL_NAME, read_events

pytestmark = pytest.mark.agent


def _fleet(base_dir, runners=1, max_agents=1, liveness=5.0, **kwargs):
    return Fleet(runners=runners, max_agents=max_agents,
                 home_dir=os.path.join(str(base_dir), "fleet"),
                 agent_liveness_s=liveness, **kwargs)


def _ticket(fleet, wait_s=5.0):
    return read_fleet_ticket(
        os.path.join(fleet.home_dir, AGENT_TICKET_NAME), wait_s=wait_s)


def _cfg(name, trials, base_dir, seed=1, **over):
    cfg = _scale_config(name, trials, str(base_dir), seed, telemetry=True)
    return dataclasses.replace(cfg, **over) if over else cfg


def _exp_journals(base_dir, fleet):
    for d in sorted(glob.glob(os.path.join(str(base_dir), "*"))):
        if not os.path.isdir(d) or d == fleet.home_dir:
            continue
        jp = os.path.join(d, JOURNAL_NAME)
        if os.path.exists(jp):
            yield jp


# ------------------------------------------------------------------ helpers


class TestTrainFnPath:
    def test_module_level_fn_resolves(self):
        assert train_fn_path(scale_train_fn) == \
            "maggy_tpu.fleet.soak:scale_train_fn"

    def test_lambda_and_closure_are_unnameable(self):
        assert train_fn_path(lambda x: x) is None

        def closure(x):
            return x

        assert train_fn_path(closure) is None

    def test_renamed_binding_is_unnameable(self):
        # A module attribute that does not resolve back to the object
        # would make the agent import a DIFFERENT function.
        def imposter():
            pass

        imposter.__module__ = "maggy_tpu.fleet.soak"
        imposter.__qualname__ = "scale_train_fn"
        assert train_fn_path(imposter) is None


class TestFleetTicket:
    def test_roundtrip(self, tmp_path):
        with _fleet(tmp_path) as fleet:
            ticket = _ticket(fleet)
            assert ticket["secret"]
            assert ticket["fleet"] == fleet.name
            assert ticket["max_agents"] == 1
            assert isinstance(ticket["port"], int)

    def test_missing_ticket_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            read_fleet_ticket(str(tmp_path / "nope.json"), wait_s=0.0)

    def test_partial_write_retries_then_loads(self, tmp_path):
        path = tmp_path / "t.json"
        path.write_text('{"host": "x"')  # torn write

        def fix():
            time.sleep(0.3)
            path.write_text(json.dumps(
                {"host": "h", "port": 1, "secret": "s"}))

        threading.Thread(target=fix, daemon=True).start()
        ticket = read_fleet_ticket(str(path), wait_s=5.0)
        assert ticket["host"] == "h"

    def test_reserve_coord_addr_shape(self):
        host, _, port = reserve_coord_addr().rpartition(":")
        assert host == "127.0.0.1" and int(port) > 0


# -------------------------------------------------------------- wire verbs


class TestAgentWire:
    def test_join_lease_done_roundtrip(self, tmp_path):
        """The full AJOIN -> ALEASE(OK) -> ABIND -> ADONE contract over
        a real socket, raw frames (no FleetAgent sugar). runners=0: the
        fake agent must be the one leased, not a thread runner."""
        with _fleet(tmp_path, runners=0, max_agents=2) as fleet:
            t = _ticket(fleet)
            ch = _AgentChannel((t["host"], t["port"]), t["secret"])
            j = ch.call({"type": "AJOIN", "host": "h1", "chips": 2,
                         "process_index": 3, "coord_addr": "127.0.0.1:9",
                         "os_pid": os.getpid(), "agent": None})
            assert j["type"] == "AJOIN" and j["agent"]
            assert j["poll_s"] > 0 and j["liveness_s"] > 0
            # Idle fleet: nothing to lease.
            assert ch.call({"type": "ALEASE",
                            "agent": j["agent"]})["type"] == "OK"
            # Capacity declaration landed in the registry.
            snap = fleet.status()["agents"]
            assert snap[0]["chips"] == 2 and snap[0]["process_index"] == 3
            # Submit work -> the poll returns an ABIND with the target
            # experiment's secret + executor config + dotted train fn.
            h = experiment.lagom_submit(
                scale_train_fn, _cfg("wire", 1, tmp_path), fleet=fleet,
                block=False, name="wire")
            lease = None
            deadline = time.monotonic() + 30
            while lease is None and time.monotonic() < deadline:
                r = ch.call({"type": "ALEASE", "agent": j["agent"]})
                if r["type"] == "ABIND":
                    lease = r
                else:
                    time.sleep(0.05)
            assert lease is not None
            assert lease["exp"] == "wire"
            assert lease["train_fn"] == \
                "maggy_tpu.fleet.soak:scale_train_fn"
            assert "warm_start" in lease
            assert lease["secret"] and lease["secret"] != t["secret"]
            # A retried ALEASE re-serves the SAME lease (lost reply).
            again = ch.call({"type": "ALEASE", "agent": j["agent"]})
            assert again["type"] == "ABIND"
            assert again["partition_id"] == lease["partition_id"]
            # Serve it like the executor would, then ADONE.
            cl = Client((t["host"], t["port"]), lease["partition_id"], 0,
                        lease["hb_interval"], lease["secret"])
            reporter = _FakeReporter()
            cl.register()
            cl.start_heartbeat(reporter)
            tid, params = cl.get_suggestion(timeout=20)
            assert tid is not None
            reporter.trial_id = tid  # the FINAL must name the trial
            resp = cl.finalize_metric(0.5, reporter)
            assert resp["type"] in ("OK", "GSTOP", "TRIAL")
            assert ch.call({"type": "ADONE", "agent": j["agent"],
                            "error": None})["type"] == "OK"
            assert h.result(timeout=60)["num_trials"] == 1
            cl.stop()
            ch.close()

    def test_unknown_agent_and_full_fleet_rejected(self, tmp_path):
        with _fleet(tmp_path, max_agents=1) as fleet:
            t = _ticket(fleet)
            ch = _AgentChannel((t["host"], t["port"]), t["secret"])
            assert ch.call({"type": "ALEASE",
                            "agent": "a0-dead"})["type"] == "ERR"
            j = ch.call({"type": "AJOIN", "host": "h", "chips": 1,
                         "process_index": 0, "coord_addr": None,
                         "os_pid": None, "agent": None})
            assert j["type"] == "AJOIN"
            full = ch.call({"type": "AJOIN", "host": "h2", "chips": 1,
                            "process_index": 0, "coord_addr": None,
                            "os_pid": None, "agent": None})
            assert full["type"] == "ERR" and "full" in full["error"]
            ch.close()

    def test_agent_verbs_rejected_without_plane(self, tmp_path):
        from maggy_tpu.core.rpc import FleetAgentServer

        server = FleetAgentServer(1)
        for verb in ("AJOIN", "ALEASE", "ADONE"):
            resp = server.handle_message({"type": verb, "agent": "x"})
            assert resp["type"] == "ERR"


class _FakeReporter:
    """Minimal reporter stand-in for driving a Client by hand."""

    def __init__(self):
        self.lock = threading.RLock()
        self.trial_id = None

    def get_data(self):
        return {"metric": None, "step": None, "logs": [],
                "trial_id": self.trial_id, "span": None}

    def reset(self, **kwargs):
        pass

    def log(self, *a, **k):
        pass

    def early_stop(self, **kwargs):
        pass


# -------------------------------------------------- lease + rebind e2e


class TestAgentRebind:
    def test_one_agent_two_experiments(self, tmp_path):
        """The acceptance shape, in-thread: one agent is leased to
        experiment A, released, re-bound to experiment B on the same
        fleet; both complete with thread-runner-shaped results and the
        fleet journal carries the agent's join/lease/done lanes."""
        with _fleet(tmp_path, runners=1, max_agents=1) as fleet:
            agent = FleetAgent(_ticket(fleet))
            agent.join()
            t = threading.Thread(target=agent.run,
                                 kwargs=dict(max_leases=2), daemon=True)
            t.start()
            r1 = experiment.lagom_submit(
                scale_train_fn, _cfg("reb1", 3, tmp_path, 1), fleet=fleet,
                block=False, name="reb1").result(timeout=90)
            r2 = experiment.lagom_submit(
                scale_train_fn, _cfg("reb2", 3, tmp_path, 2), fleet=fleet,
                block=False, name="reb2").result(timeout=90)
            t.join(timeout=60)
        for r in (r1, r2):
            # Journal-replayed result shape identical to thread runs.
            assert r["num_trials"] == 3
            assert r["best_val"] is not None and r["best_id"]
        assert agent.leases_served == 2
        events = read_events(
            os.path.join(fleet.home_dir, FLEET_JOURNAL_NAME))
        phases = [(e.get("phase"), e.get("exp")) for e in events
                  if e.get("ev") == "agent"]
        assert ("join", None) == (phases[0][0], None)
        leased_exps = {exp for ph, exp in phases if ph == "lease"}
        assert leased_exps == {"reb1", "reb2"}
        assert sum(1 for ph, _ in phases if ph == "done") == 2
        replay = replay_fleet_journal(
            os.path.join(fleet.home_dir, FLEET_JOURNAL_NAME))
        assert replay["agents"]["joins"] == 1
        assert replay["agents"]["leases"] == 2
        assert replay["agents"]["losses"] == 0
        assert replay["agents"]["abind_ms"]["n"] == 2

    def test_closure_train_fn_stays_on_threads(self, tmp_path):
        """An experiment whose train fn can't be named on the wire must
        complete on thread runners with the agent never leased to it."""
        captured = []

        def closure_fn(lr, units, reporter=None):
            captured.append(lr)
            return {"metric": float(lr)}

        with _fleet(tmp_path, runners=1, max_agents=1) as fleet:
            agent = FleetAgent(_ticket(fleet))
            agent.join()
            t = threading.Thread(target=agent.run, daemon=True)
            t.start()
            r = experiment.lagom_submit(
                closure_fn, _cfg("clo", 2, tmp_path), fleet=fleet,
                block=False, name="clo").result(timeout=90)
            assert r["num_trials"] == 2
            assert agent.leases_served == 0
            agent.stop()
            t.join(timeout=10)


# --------------------------------------------------- invariant 11 (death)


class TestAgentDeath:
    def test_mid_lease_death_revokes_and_requeues_once(self, tmp_path):
        """Invariant 11, unit form: a fake agent takes a lease, REGs,
        receives a trial, and vanishes. The experiment's slot-reclaim
        liveness must requeue the trial EXACTLY once, the fleet must end
        the lease with reason=agent_lost and mark the agent lost, and
        the schedule must complete on the surviving thread runner."""
        with _fleet(tmp_path, runners=1, max_agents=1,
                    liveness=2.0) as fleet:
            t = _ticket(fleet)
            ch = _AgentChannel((t["host"], t["port"]), t["secret"])
            j = ch.call({"type": "AJOIN", "host": "fake", "chips": 1,
                         "process_index": 0, "coord_addr": None,
                         "os_pid": None, "agent": None})
            h = experiment.lagom_submit(
                agent_train_fn,
                _cfg("death", 3, tmp_path, hb_loss_timeout=1.0,
                     hb_interval=0.05),
                fleet=fleet, block=False, name="death")
            lease = None
            deadline = time.monotonic() + 30
            while lease is None and time.monotonic() < deadline:
                r = ch.call({"type": "ALEASE", "agent": j["agent"]})
                if r["type"] == "ABIND":
                    lease = r
                else:
                    time.sleep(0.05)
            assert lease is not None
            cl = Client((t["host"], t["port"]), lease["partition_id"], 0,
                        lease["hb_interval"], lease["secret"])
            cl.register()
            tid, _params = cl.get_suggestion(timeout=20)
            assert tid is not None
            # Vanish mid-lease: no FINAL, no heartbeats, sockets dead.
            for s in (cl._sock, cl._hb_sock):
                s.close()
            ch.close()
            assert h.result(timeout=120)["num_trials"] == 3
        requeues = []
        for jp in _exp_journals(tmp_path, fleet):
            for ev in read_events(jp):
                if ev.get("ev") == "trial" \
                        and ev.get("phase") == "requeued" \
                        and ev.get("trial") == tid:
                    requeues.append(ev)
        assert len(requeues) == 1, requeues
        assert requeues[0].get("reason") == "heartbeat_loss"
        replay = replay_fleet_journal(
            os.path.join(fleet.home_dir, FLEET_JOURNAL_NAME))
        assert replay["agents"]["losses"] == 1
        assert replay["agents"]["lost_leases"] == 1
        assert fleet.status()["agents"][0]["state"] == "lost"

    def test_death_before_reg_frees_lease_cleanly(self, tmp_path):
        """An agent that takes an ABIND but dies before REG: the lease
        closes as agent_lost, no trial was assigned, and the experiment
        completes untouched on the thread runner."""
        with _fleet(tmp_path, runners=1, max_agents=1,
                    liveness=1.0) as fleet:
            t = _ticket(fleet)
            ch = _AgentChannel((t["host"], t["port"]), t["secret"])
            j = ch.call({"type": "AJOIN", "host": "fake", "chips": 1,
                         "process_index": 0, "coord_addr": None,
                         "os_pid": None, "agent": None})
            h = experiment.lagom_submit(
                scale_train_fn,
                _cfg("prereg", 2, tmp_path, hb_loss_timeout=1.0),
                fleet=fleet, block=False, name="prereg")
            deadline = time.monotonic() + 30
            got = None
            while got is None and time.monotonic() < deadline:
                r = ch.call({"type": "ALEASE", "agent": j["agent"]})
                got = r if r["type"] == "ABIND" else None
                time.sleep(0.05)
            ch.close()  # die silently, never REG
            assert h.result(timeout=120)["num_trials"] == 2
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                snap = fleet.status()["agents"]
                if snap and snap[0]["state"] == "lost":
                    break
                time.sleep(0.1)
        replay = replay_fleet_journal(
            os.path.join(fleet.home_dir, FLEET_JOURNAL_NAME))
        assert replay["agents"]["lost_leases"] == 1

    def test_check_invariants_kill_agent(self):
        """Invariant 11 in the offline checker: a kill_agent chaos
        event demands exactly one requeue — none is a lost lease,
        more than one kill-count is a duplicate, and a FINAL without a
        requeue is a phantom delivery from a dead agent."""
        from maggy_tpu.chaos.harness import check_invariants

        def evs(requeues, finals=1):
            out = [{"ev": "experiment", "phase": "start", "t": 0.0},
                   {"ev": "trial", "trial": "t1", "phase": "queued",
                    "t": 1.0},
                   {"ev": "chaos", "kind": "kill_agent", "trial": "t1",
                    "partition": 0, "agent": "a1", "t": 2.0}]
            for i in range(requeues):
                out.append({"ev": "trial", "trial": "t1",
                            "phase": "requeued",
                            "reason": "heartbeat_loss", "t": 3.0 + i})
            for i in range(finals):
                out.append({"ev": "trial", "trial": "t1",
                            "phase": "finalized", "t": 6.0 + i})
            out.append({"ev": "experiment", "phase": "end", "t": 9.0})
            return out

        ok = check_invariants(evs(1), stall_flag_bound_s=None)
        assert ok["ok"], ok["violations"]
        assert ok["recoveries"][0]["kind"] == "kill_agent"
        assert ok["recoveries"][0]["outcome"] == "requeued"
        missing = check_invariants(evs(0), stall_flag_bound_s=None)
        assert any("no requeue" in v for v in missing["violations"])
        double = check_invariants(evs(2), stall_flag_bound_s=None)
        assert any("duplicate requeue" in v
                   for v in double["violations"])


# ------------------------------------------------- remote-gang rendezvous


class TestRemoteGangRendezvous:
    def test_gang_context_process_ids(self):
        from maggy_tpu.gang import GangContext

        info = {"chips": [0, 1], "members": [0, 1], "leader": 0,
                "mesh": {"data": 2}, "strategy": "dp",
                "rendezvous": {"coordinator": "127.0.0.1:1234",
                               "num_processes": 2,
                               "process_ids": {"0": 0, "1": 1}},
                "partition": 1}
        ctx = GangContext(info)
        assert ctx.process_id == 1
        assert ctx.to_dict()["rendezvous"]["num_processes"] == 2
        # In-process gang: no rendezvous, ensure is a no-op.
        local = GangContext({"chips": [0], "members": [0], "leader": 0,
                             "mesh": {"data": 1}, "strategy": "dp"})
        assert local.process_id is None
        assert local.ensure_rendezvous() is False

    def test_ensure_rendezvous_initializes_once(self, monkeypatch):
        import jax

        from maggy_tpu import gang as gang_mod

        calls = []
        monkeypatch.setattr(jax.distributed, "initialize",
                            lambda **kw: calls.append(kw))
        monkeypatch.setattr(gang_mod, "_RENDEZVOUS_DONE", False)
        info = {"chips": [0, 1], "members": [0, 1], "leader": 0,
                "mesh": {"data": 2}, "strategy": "dp",
                "rendezvous": {"coordinator": "127.0.0.1:4321",
                               "num_processes": 2,
                               "process_ids": {"0": 0, "1": 1}},
                "partition": 0}
        ctx = gang_mod.GangContext(info)
        assert ctx.ensure_rendezvous() is True
        assert ctx.ensure_rendezvous() is True  # latched
        assert calls == [{"coordinator_address": "127.0.0.1:4321",
                          "num_processes": 2, "process_id": 0}]

    def test_ensure_rendezvous_without_partition_raises(self, monkeypatch):
        from maggy_tpu import gang as gang_mod

        monkeypatch.setattr(gang_mod, "_RENDEZVOUS_DONE", False)
        ctx = gang_mod.GangContext(
            {"chips": [0, 1], "members": [0, 1], "leader": 0,
             "mesh": {"data": 2}, "strategy": "dp",
             "rendezvous": {"coordinator": "c:1", "num_processes": 2,
                            "process_ids": {"0": 0, "1": 1}}})
        with pytest.raises(RuntimeError, match="process id"):
            ctx.ensure_rendezvous()

    def test_remote_gang_over_two_agents(self, tmp_path, monkeypatch):
        """Wiring e2e on a fake 2-process world: a 2-chip gang assembles
        across TWO agents; the driver stamps jax.distributed rendezvous
        coordinates (coordinator = the leader agent's advertised
        address, process ids in chip order), the MEMBER receives the
        SPMD program too (gang_role=member, runs it, never finalizes),
        and both member and leader join the rendezvous — exactly one
        ``jax.distributed.initialize`` per process (here: one, both
        agents share the test process and the latch)."""
        import jax

        from maggy_tpu import OptimizationConfig, Searchspace
        from maggy_tpu import gang as gang_mod
        from maggy_tpu.gang import GangSpec

        init_calls = []
        monkeypatch.setattr(jax.distributed, "initialize",
                            lambda **kw: init_calls.append(kw))
        monkeypatch.setattr(gang_mod, "_RENDEZVOUS_DONE", False)
        train_calls = []
        orig_fn = gang_mod.gang_train_fn

        def recording_fn(lr, budget=1, gang=None, reporter=None, ctx=None):
            train_calls.append({
                "process_id": ctx.gang.process_id if ctx and ctx.gang
                else None,
                "role": "leader" if reporter is not None else "member"})
            return orig_fn(lr, budget=budget, gang=gang,
                           reporter=reporter, ctx=ctx)

        recording_fn.__module__ = "maggy_tpu.gang"
        recording_fn.__qualname__ = "gang_train_fn"
        monkeypatch.setattr(gang_mod, "gang_train_fn", recording_fn)

        cfg = OptimizationConfig(
            name="rgang", num_trials=1, optimizer="randomsearch",
            searchspace=Searchspace(
                lr=("DOUBLE", [0.05, 0.2]),
                gang=("GANG", [GangSpec(2)])),
            direction="max", num_workers=2, hb_interval=0.05,
            hb_loss_timeout=5.0, seed=3, es_policy="none",
            experiment_dir=str(tmp_path), telemetry=True, health=False)
        with _fleet(tmp_path, runners=0, max_agents=2,
                    liveness=10.0) as fleet:
            agents = [FleetAgent(_ticket(fleet)) for _ in range(2)]
            threads = []
            for a in agents:
                a.join()
                th = threading.Thread(target=a.run, daemon=True)
                th.start()
                threads.append(th)
            r = experiment.lagom_submit(
                gang_mod.gang_train_fn, cfg, fleet=fleet, block=False,
                name="rgang").result(timeout=120)
            assert r["num_trials"] == 1
            for a in agents:
                a.stop()
        # The driver stamped the rendezvous with the leader's coord
        # address; both programs ran; initialize fired exactly once in
        # this (shared) process.
        coords = {a.coord_addr for a in agents}
        assert len(init_calls) == 1
        assert init_calls[0]["num_processes"] == 2
        assert init_calls[0]["coordinator_address"] in coords
        assert init_calls[0]["process_id"] in (0, 1)
        roles = sorted(c["role"] for c in train_calls)
        assert roles == ["leader", "member"], train_calls
        pids = {c["process_id"] for c in train_calls}
        assert pids == {0, 1}
        # Exactly one FINAL (the leader's) in the experiment journal.
        finals = []
        for jp in _exp_journals(tmp_path, fleet):
            finals.extend(e for e in read_events(jp)
                          if e.get("ev") == "trial"
                          and e.get("phase") == "finalized")
        assert len(finals) == 1

    def test_in_process_gang_has_no_rendezvous(self, tmp_path):
        """Thread-runner gangs (no host_port in any REG) must stay
        bit-for-bit on the old path: no rendezvous block stamped."""
        from maggy_tpu import OptimizationConfig, Searchspace
        from maggy_tpu import gang as gang_mod
        from maggy_tpu.gang import GangSpec

        cfg = OptimizationConfig(
            name="lgang", num_trials=1, optimizer="randomsearch",
            searchspace=Searchspace(
                lr=("DOUBLE", [0.05, 0.2]),
                gang=("GANG", [GangSpec(2)])),
            direction="max", num_workers=2, hb_interval=0.05,
            hb_loss_timeout=5.0, seed=3, es_policy="none",
            experiment_dir=str(tmp_path), telemetry=True, health=False,
            pool="thread")
        result = experiment.lagom(gang_mod.gang_train_fn, cfg)
        assert result["num_trials"] == 1
        exp_dirs = sorted(d for d in glob.glob(
            os.path.join(str(tmp_path), "*")) if os.path.isdir(d))
        trial_files = glob.glob(
            os.path.join(exp_dirs[-1], "*", "trial.json"))
        assert trial_files
        for tf in trial_files:
            with open(tf) as f:
                d = json.load(f)
            gang = (d.get("info") or {}).get("gang") or {}
            assert "rendezvous" not in gang


# ------------------------------------------------------------ scheduling


class TestAgentScheduling:
    def test_agent_slot_attach_reuse_and_targets(self, tmp_path):
        from maggy_tpu.fleet.scheduler import FleetScheduler

        sched = FleetScheduler(2, max_size=4)
        a = sched.agent_slot_attach()
        b = sched.agent_slot_attach()
        assert (a, b) == (2, 3)
        assert sched.fleet_size == 4
        assert sched.is_agent_slot(a) and not sched.is_agent_slot(1)
        sched.agent_slot_detach(a)
        assert sched.live_agent_slots() == 1
        # Reuse the vacant slot, not a new index.
        assert sched.agent_slot_attach() == a
        assert sched.fleet_size == 4

    def test_agent_slot_never_binds_agentless_entry(self, tmp_path):
        from maggy_tpu.fleet.scheduler import FleetPolicy, FleetScheduler

        sched = FleetScheduler(1, max_size=2)
        entry = sched.submit("noagent", FleetPolicy())
        entry.train_fn_path = None

        class _Drv:
            experiment_done = False
            exp_dir = None

        sched.activate(entry, _Drv(), lambda pid: None, slots=2)
        assert entry.agent_info is None
        slot = sched.agent_slot_attach()
        assert sched.next_binding(slot, timeout=0.4) is None
        # The thread runner still binds it.
        got = sched.next_binding(0, timeout=5.0)
        assert got is not None and got[0] is entry

    def test_build_agent_info_shape(self):
        from maggy_tpu.fleet.scheduler import (ExperimentEntry,
                                               FleetPolicy, FleetScheduler)

        class _Cfg:
            warm_start = False

        class _Drv:
            hb_interval = 0.5
            exp_dir = "/tmp/x"
            optimization_key = "metric"
            config = _Cfg()

            @staticmethod
            def secret_for_clients():
                return "s3cret"

        entry = ExperimentEntry("e", FleetPolicy(), 0)
        entry.train_fn_path = "m.mod:fn"
        info = FleetScheduler._build_agent_info(entry, _Drv())
        assert info == {"secret": "s3cret", "hb_interval": 0.5,
                        "exp_dir": "/tmp/x", "optimization_key": "metric",
                        "trial_type": "optimization",
                        "warm_start": False, "train_fn": "m.mod:fn",
                        "family": "m.mod:fn"}
        entry.train_fn_path = None
        assert FleetScheduler._build_agent_info(entry, _Drv()) is None


# -------------------------------------------------------- obs + monitor


class TestAgentObservability:
    def test_agent_healthz_and_status(self, tmp_path):
        import urllib.request

        from maggy_tpu.telemetry import obs as obs_mod

        with _fleet(tmp_path, runners=1, max_agents=1) as fleet:
            agent = FleetAgent(_ticket(fleet), obs_port=0,
                               home=str(tmp_path / "agent_home"))
            agent.join()
            th = threading.Thread(target=agent.run, daemon=True)
            th.start()
            deadline = time.monotonic() + 20
            server = None
            while time.monotonic() < deadline:
                server = obs_mod.active_server()
                if server is not None:
                    break
                time.sleep(0.05)
            assert server is not None, "agent obs server never started"
            host, port = server.address
            with urllib.request.urlopen(
                    "http://{}:{}/healthz".format(host, port),
                    timeout=5) as resp:
                assert resp.status == 200
            with urllib.request.urlopen(
                    "http://{}:{}/status".format(host, port),
                    timeout=5) as resp:
                body = json.loads(resp.read().decode())
            assert any("fleet-agent" in json.dumps(v)
                       for v in body.values())
            agent.stop()
            th.join(timeout=10)
        assert obs_mod.active_server() is None

    def test_render_fleet_agents_table(self):
        from maggy_tpu.monitor import render_fleet

        status = {"name": "f", "runners": 2, "active": 0,
                  "queue_depth": 0, "max_agents": 2,
                  "agents": [{"agent": "a1-ab", "runner": 2,
                              "host": "vm1", "chips": 4,
                              "process_index": 0, "state": "leased",
                              "lease": "exp1", "pid": 0, "leases": 3,
                              "last_beat_age_s": 0.1}],
                  "experiments": []}
        replay = {"agents": {"joins": 1, "leases": 3, "losses": 0,
                             "lost_leases": 0,
                             "abind_ms": {"median_ms": 5.0,
                                          "p95_ms": 9.0, "n": 3}}}
        out = render_fleet(status, replay)
        assert "agents: 1 joined / 2 slot(s)" in out
        assert "a1-ab" in out and "-> exp1" in out
        assert "abind p50 5.0 ms" in out

    def test_replay_agents_block_synthetic(self, tmp_path):
        path = tmp_path / "fleet.jsonl"
        rows = [
            {"t": 1.0, "ev": "agent", "phase": "join", "agent": "a1"},
            {"t": 2.0, "ev": "agent", "phase": "lease", "agent": "a1",
             "exp": "e", "pid": 0, "abind_ms": 12.0},
            {"t": 3.0, "ev": "lease", "phase": "start", "exp": "e",
             "runner": 1, "pid": 0},
            {"t": 4.0, "ev": "lease", "phase": "end", "exp": "e",
             "runner": 1, "pid": 0, "reason": "agent_lost"},
            {"t": 5.0, "ev": "agent", "phase": "lost", "agent": "a1"},
        ]
        path.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
        replay = replay_fleet_journal(str(path))
        agents = replay["agents"]
        assert agents["joins"] == 1
        assert agents["losses"] == 1
        assert agents["lost_leases"] == 1
        assert agents["per_agent_leases"] == {"a1": 1}
        assert agents["abind_ms"]["n"] == 1


# ------------------------------------------------------------------- CLI


class TestAgentCli:
    def test_agent_requires_ticket_or_addr(self):
        from maggy_tpu.fleet.__main__ import main

        with pytest.raises(SystemExit):
            main(["agent"])

    def test_cli_subprocess_rebinds_across_experiments(self, tmp_path):
        """THE acceptance criterion: an agent started as a separate OS
        process via ``python -m maggy_tpu.fleet agent --ticket ...`` is
        leased to one experiment, released, re-bound to a second on the
        same fleet, and both complete with journal-replayed results of
        the thread-runner shape."""
        import signal

        from maggy_tpu.fleet.soak import spawn_agent_process

        # runners=0: every trial of both experiments MUST be served by
        # the agent subprocess — nothing completes without the re-bind.
        with _fleet(tmp_path, runners=0, max_agents=1,
                    liveness=15.0) as fleet:
            proc = spawn_agent_process(
                os.path.join(fleet.home_dir, AGENT_TICKET_NAME),
                log_path=str(tmp_path / "agent.log"))
            try:
                r1 = experiment.lagom_submit(
                    scale_train_fn, _cfg("cli1", 3, tmp_path, 1),
                    fleet=fleet, block=False,
                    name="cli1").result(timeout=180)
                r2 = experiment.lagom_submit(
                    scale_train_fn, _cfg("cli2", 3, tmp_path, 2),
                    fleet=fleet, block=False,
                    name="cli2").result(timeout=180)
            finally:
                if proc.poll() is None:
                    proc.send_signal(signal.SIGTERM)
            for r in (r1, r2):
                assert r["num_trials"] == 3
                assert r["best_val"] is not None
        replay = replay_fleet_journal(
            os.path.join(fleet.home_dir, FLEET_JOURNAL_NAME))
        assert replay["agents"]["joins"] == 1
        assert replay["agents"]["leases"] == 2
        assert replay["agents"]["losses"] == 0


@pytest.mark.slow
class TestAgentSoak:
    def test_run_agent_soak(self, tmp_path):
        """Invariant 11 end to end with REAL agent processes: one is
        SIGKILLed mid-lease; the soak's own checks (exactly-once
        requeue, lease revoked as agent_lost, schedule completes) must
        all hold."""
        from maggy_tpu.fleet.soak import run_agent_soak

        report = run_agent_soak(agents=2, trials=4,
                                base_dir=str(tmp_path),
                                lock_witness=True)
        assert report["ok"], report["violations"]
        assert report["detail"]["killed"]["agent"] is not None
        assert report["detail"]["agents_replay"]["lost_leases"] == 1
        assert report["witness"] is None or \
            report["witness"]["violations"] == 0
