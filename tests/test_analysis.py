"""Tests for maggy_tpu.analysis: the four static checkers (each proven
live against a firing fixture and quiet on a clean one), the runtime
lock-order witness, the tier-1 package-must-be-clean enforcement, and
regression tests for the two real bugs the checkers surfaced in this
repo (the Reporter._async_kick rollover race and the dead FINAL
``span`` payload key)."""

from __future__ import annotations

import os
import threading
import time

import numpy as np
import pytest

from maggy_tpu.analysis import analyze_paths, run_analysis
from maggy_tpu.analysis import witness as witness_mod
from maggy_tpu.analysis.witness import Witness

pytestmark = pytest.mark.analysis


def _write(tmp_path, name, text):
    p = tmp_path / name
    p.write_text(text)
    return str(p)


def _findings(results, checker):
    return [f for f in results.get(checker, []) if not f.suppressed]


# ------------------------------------------------------------------ guards


GUARDS_BAD = '''
import threading

class Store:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = {}

    def put(self, k, v):
        with self._lock:
            self._items[k] = v

    def drop(self, k):
        with self._lock:
            self._items.pop(k, None)

    def rogue(self, k, v):
        self._items[k] = v  # write without the lock
'''

GUARDS_ANNOTATED_BAD = '''
import threading

class Flagged:
    def __init__(self):
        self._lock = threading.Lock()
        self._state = "idle"  # guarded-by: _lock

    def set_state(self, s):
        with self._lock:
            self._state = s

    def peek(self):
        return self._state  # unguarded READ of an annotated attr
'''

GUARDS_CLEAN = '''
import threading

class Store:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = {}  # guarded-by: _lock

    def put(self, k, v):
        with self._lock:
            self._items[k] = v

    def get(self, k):
        with self._lock:
            return self._items.get(k)
'''


class TestGuardsChecker:
    def test_inferred_unguarded_write_fires(self, tmp_path):
        path = _write(tmp_path, "g_bad.py", GUARDS_BAD)
        out = _findings(analyze_paths([path], checkers=("guards",)),
                        "guards")
        assert len(out) == 1
        assert "write of Store._items without holding" in out[0].message
        assert out[0].line == GUARDS_BAD.splitlines().index(
            "        self._items[k] = v  # write without the lock") + 1

    def test_annotated_unguarded_read_fires(self, tmp_path):
        path = _write(tmp_path, "g_ann.py", GUARDS_ANNOTATED_BAD)
        out = _findings(analyze_paths([path], checkers=("guards",)),
                        "guards")
        assert len(out) == 1
        assert "read of Flagged._state" in out[0].message
        assert "guarded-by annotation" in out[0].message

    def test_clean_fixture_is_quiet(self, tmp_path):
        path = _write(tmp_path, "g_clean.py", GUARDS_CLEAN)
        assert _findings(analyze_paths([path], checkers=("guards",)),
                         "guards") == []

    def test_annassign_annotation_fires(self, tmp_path):
        # Regression: a typed __init__ assignment (ast.AnnAssign, e.g.
        # ``self._state: str = "idle"``) used to be skipped by the
        # annotation indexer, silently discarding its guarded-by contract
        # — most of the package's annotated state is typed, so the
        # package gate was green without checking any of it.
        text = GUARDS_ANNOTATED_BAD.replace(
            'self._state = "idle"  # guarded-by: _lock',
            'self._state: str = "idle"  # guarded-by: _lock')
        path = _write(tmp_path, "g_typed.py", text)
        out = _findings(analyze_paths([path], checkers=("guards",)),
                        "guards")
        assert len(out) == 1
        assert "read of Flagged._state" in out[0].message
        assert "guarded-by annotation" in out[0].message

    def test_unguarded_ok_suppresses_with_reason(self, tmp_path):
        text = GUARDS_ANNOTATED_BAD.replace(
            "return self._state  # unguarded READ of an annotated attr",
            "return self._state  # unguarded-ok: racy peek is advisory")
        path = _write(tmp_path, "g_supp.py", text)
        results = analyze_paths([path], checkers=("guards",))
        assert _findings(results, "guards") == []
        supp = [f for f in results["guards"] if f.suppressed]
        assert len(supp) == 1 and supp[0].reason == "racy peek is advisory"

    def test_reasonless_suppression_is_a_finding(self, tmp_path):
        text = GUARDS_ANNOTATED_BAD.replace(
            "return self._state  # unguarded READ of an annotated attr",
            "return self._state  # unguarded-ok:")
        path = _write(tmp_path, "g_noreason.py", text)
        out = _findings(analyze_paths([path], checkers=("guards",)),
                        "guards")
        assert len(out) == 1
        assert "without a reason" in out[0].message


# ---------------------------------------------------------------- lockorder


LOCKORDER_BAD = '''
import threading

class A:
    def __init__(self, b):
        self.l1 = threading.Lock()
        self.b = b

    def forward(self):
        with self.l1:
            with self.b.l2:
                pass

class B:
    def __init__(self, a):
        self.l2 = threading.Lock()
        self.a = a

    def backward(self):
        with self.l2:
            with self.a.l1:
                pass
'''

LOCKORDER_CLEAN = '''
import threading

class A:
    def __init__(self, b):
        self.l1 = threading.Lock()
        self.b = b

    def forward(self):
        with self.l1:
            with self.b.l2:
                pass

    def also_forward(self):
        with self.l1:
            with self.b.l2:
                pass

class B:
    def __init__(self, a):
        self.l2 = threading.Lock()
        self.a = a
'''


class TestLockOrderChecker:
    def test_cycle_fires(self, tmp_path):
        path = _write(tmp_path, "lo_bad.py", LOCKORDER_BAD)
        out = _findings(analyze_paths([path], checkers=("lockorder",)),
                        "lockorder")
        assert len(out) == 1
        assert "lock-order cycle" in out[0].message
        assert "A.l1" in out[0].message and "B.l2" in out[0].message

    def test_consistent_order_is_quiet(self, tmp_path):
        path = _write(tmp_path, "lo_clean.py", LOCKORDER_CLEAN)
        assert _findings(analyze_paths([path], checkers=("lockorder",)),
                         "lockorder") == []

    def test_canonical_order_respects_edges(self, tmp_path):
        from maggy_tpu.analysis.astindex import parse_package
        from maggy_tpu.analysis.lockorder import build_graph, canonical_order

        path = _write(tmp_path, "lo_clean.py", LOCKORDER_CLEAN)
        index = parse_package(None, paths=[path])
        order = canonical_order(build_graph(index))
        assert order.index("A.l1") < order.index("B.l2")

    def test_suppressed_edge_needs_reason(self, tmp_path):
        text = LOCKORDER_BAD.replace(
            "        with self.l2:\n            with self.a.l1:",
            "        with self.l2:\n            # lock-order-ok: proven never concurrent with forward\n            with self.a.l1:")
        path = _write(tmp_path, "lo_supp.py", text)
        out = _findings(analyze_paths([path], checkers=("lockorder",)),
                        "lockorder")
        assert out == []  # suppressed with a reason: no cycle reported

    def test_call_crossing_edge_detected(self, tmp_path):
        text = '''
import threading

class C:
    def __init__(self):
        self.outer = threading.Lock()
        self.inner = threading.Lock()

    def leaf(self):
        with self.inner:
            pass

    def top(self):
        with self.outer:
            self.leaf()

    def inverted(self):
        with self.inner:
            with self.outer:
                pass
'''
        path = _write(tmp_path, "lo_call.py", text)
        out = _findings(analyze_paths([path], checkers=("lockorder",)),
                        "lockorder")
        # outer -> inner exists only THROUGH the call; inverted closes
        # the cycle.
        assert len(out) == 1 and "lock-order cycle" in out[0].message


# ------------------------------------------------------------------ rpcconf


RPCCONF_BAD = '''
class MiniServer:
    def __init__(self):
        self._handlers = {}
        self._register_handlers()

    def _register_handlers(self):
        self._handlers["PING"] = self._ping
        self._handlers["GHOST"] = self._ghost

    def _ping(self, msg):
        return {"type": "OK", "echo": msg["payload"], "extra": msg["missing"]}

    def _ghost(self, msg):
        return {"type": "OK"}

    def handle_message(self, msg):
        t0 = 0
        self.metrics.histogram("rpc.handle_ms." + msg["type"]).observe(t0)
        return self._handlers[msg["type"]](msg)


class MiniClient:
    def ping(self):
        return self._request({"type": "PING", "payload": "x",
                              "dead_key": 1})
'''

RPCCONF_CLEAN = '''
class MiniServer:
    def __init__(self):
        self._handlers = {}
        self._register_handlers()

    def _register_handlers(self):
        self._handlers["PING"] = self._ping

    def _ping(self, msg):
        return {"type": "OK", "echo": msg["payload"]}

    def handle_message(self, msg):
        t0 = 0
        self.metrics.histogram("rpc.handle_ms." + msg["type"]).observe(t0)
        return self._handlers[msg["type"]](msg)


class MiniClient:
    def ping(self):
        return self._request({"type": "PING", "payload": "x"})
'''


class TestRpcConfChecker:
    def test_bad_fixture_fires_all_three_ways(self, tmp_path):
        path = _write(tmp_path, "rpc_bad.py", RPCCONF_BAD)
        out = _findings(analyze_paths([path], checkers=("rpcconf",)),
                        "rpcconf")
        msgs = "\n".join(f.message for f in out)
        # 1. registered verb with no producer anywhere
        assert "verb GHOST is registered but has no producer" in msgs
        # 2. handler indexes a key no producer sends (KeyError on delivery)
        assert "indexes msg['missing']" in msgs
        # 3. producer sends a key no handler reads (dead vocabulary)
        assert "sends key 'dead_key'" in msgs

    def test_clean_fixture_is_quiet(self, tmp_path):
        path = _write(tmp_path, "rpc_clean.py", RPCCONF_CLEAN)
        assert _findings(analyze_paths([path], checkers=("rpcconf",)),
                         "rpcconf") == []

    def test_missing_dispatch_timing_fires(self, tmp_path):
        text = RPCCONF_CLEAN.replace(
            '        self.metrics.histogram("rpc.handle_ms." + msg["type"]).observe(t0)\n',
            "")
        path = _write(tmp_path, "rpc_untimed.py", text)
        out = _findings(analyze_paths([path], checkers=("rpcconf",)),
                        "rpcconf")
        assert len(out) == 1
        assert "no rpc.handle_ms.<verb> dispatch timing" in out[0].message

    def test_rpc_ok_suppresses(self, tmp_path):
        text = RPCCONF_BAD.replace(
            '        self._handlers["GHOST"] = self._ghost',
            '        # rpc-ok: produced by an external CLI, invisible here\n'
            '        self._handlers["GHOST"] = self._ghost')
        path = _write(tmp_path, "rpc_supp.py", text)
        out = _findings(analyze_paths([path], checkers=("rpcconf",)),
                        "rpcconf")
        assert not any("GHOST" in f.message for f in out)


# ------------------------------------------------------------- journalvocab


VOCAB_FIXTURE = '''
SPAN_PHASES = ("queued", "running")
EVENT_KINDS = frozenset({"trial"})
REQUEUE_REASONS = frozenset()
'''

EMIT_CLEAN = '''
def emit_all(t, tid):
    t.trial_event(tid, "queued")
    t.trial_event(tid, "running")
    t.event("trial", phase="queued")

def consume(ev):
    return ev.get("phase") == "running"
'''

EMIT_TYPO = '''
def emit_all(t, tid):
    t.trial_event(tid, "queued")
    t.trial_event(tid, "running")
    t.event("trial")
    t.trial_event(tid, "runing")  # emitter typo
'''

CONSUME_TYPO = '''
def emit_all(t, tid):
    t.trial_event(tid, "queued")
    t.trial_event(tid, "running")
    t.event("trial")

def consume(ev):
    return ev.get("phase") == "runningg"  # consumer typo
'''


class TestJournalVocabChecker:
    def test_emitter_typo_fires(self, tmp_path):
        paths = [_write(tmp_path, "vocab.py", VOCAB_FIXTURE),
                 _write(tmp_path, "emit.py", EMIT_TYPO)]
        out = _findings(analyze_paths(paths, checkers=("journalvocab",)),
                        "journalvocab")
        assert len(out) == 1
        assert "emitted phase 'runing' is not in the journal" \
            in out[0].message

    def test_orphan_vocab_entry_fires(self, tmp_path):
        # "running" is in the vocabulary but nothing ever emits it: a
        # consumer match that can never fire (the emitter-only direction's
        # mirror image).
        emit_one = ('def emit_all(t, tid):\n'
                    '    t.trial_event(tid, "queued")\n'
                    '    t.event("trial")\n')
        paths = [_write(tmp_path, "vocab.py", VOCAB_FIXTURE),
                 _write(tmp_path, "emit.py", emit_one)]
        out = _findings(analyze_paths(paths, checkers=("journalvocab",)),
                        "journalvocab")
        assert len(out) == 1
        assert "vocabulary entry 'running'" in out[0].message
        assert "never emitted" in out[0].message

    def test_consumer_typo_fires(self, tmp_path):
        paths = [_write(tmp_path, "vocab.py", VOCAB_FIXTURE),
                 _write(tmp_path, "code.py", CONSUME_TYPO)]
        out = _findings(analyze_paths(paths, checkers=("journalvocab",)),
                        "journalvocab")
        assert len(out) == 1
        assert "consumer matches phase 'runningg'" in out[0].message
        assert "can never fire" in out[0].message

    def test_clean_fixture_is_quiet(self, tmp_path):
        paths = [_write(tmp_path, "vocab.py", VOCAB_FIXTURE),
                 _write(tmp_path, "code.py", EMIT_CLEAN)]
        assert _findings(analyze_paths(paths, checkers=("journalvocab",)),
                         "journalvocab") == []

    def test_package_vocab_module_exists(self):
        # The real vocabulary module the checker verifies against.
        from maggy_tpu.telemetry import vocab

        assert "queued" in vocab.SPAN_PHASES
        assert "trial" in vocab.EVENT_KINDS
        assert vocab.REQUEUE_REASONS <= vocab.ALL_REASONS


# ------------------------------------------------------------------ witness


class TestWitnessUnit:
    def test_forbidden_edge_is_a_violation(self):
        w = Witness(["A.x", "B.y"])
        w.note_acquire(1, "B.y")
        w.note_acquire(2, "A.x")  # acquiring earlier-ordered while holding later
        assert len(w.violations) == 1
        v = w.violations[0]
        assert v.held == "B.y" and v.acquired == "A.x"
        with pytest.raises(AssertionError):
            w.check()

    def test_canonical_order_edge_is_clean(self):
        w = Witness(["A.x", "B.y"])
        w.note_acquire(1, "A.x")
        w.note_acquire(2, "B.y")
        assert w.violations == []
        assert ("A.x", "B.y") in w.edges
        w.check()

    def test_release_unwinds_held_set(self):
        w = Witness(["A.x", "B.y"])
        w.note_acquire(1, "B.y")
        w.note_release(1)
        w.note_acquire(2, "A.x")  # nothing held anymore: no edge at all
        assert w.violations == [] and w.edges == {}

    def test_two_instances_of_one_decl_are_unordered(self):
        w = Witness(["Trial.lock"])
        w.note_acquire(1, "Trial.lock")
        w.note_acquire(2, "Trial.lock")
        assert w.violations == [] and w.edges == {}

    def test_forbidden_edge_records_every_occurrence(self):
        # Regression: violations were only recorded the FIRST time an
        # edge was seen. With one env-armed witness shared across soaks
        # (each counting violations from its own install point), a
        # repeat offense in a later soak would slice to nothing and the
        # soak would pass despite observing the forbidden interleaving.
        w = Witness(["A.x", "B.y"])
        for _ in range(2):
            w.note_acquire(1, "B.y")
            w.note_acquire(2, "A.x")
            w.note_release(2)
            w.note_release(1)
        assert len(w.violations) == 2
        assert len(w.edges) == 1  # edge inventory stays deduped

    def test_site_named_locks_record_but_never_violate(self):
        w = Witness(["A.x"])
        w.note_acquire(1, "some/file.py:10")
        w.note_acquire(2, "A.x")
        assert ("some/file.py:10", "A.x") in w.edges
        assert w.violations == []


class TestWitnessInstall:
    def test_package_lock_wrapped_foreign_lock_passthrough(self):
        w = witness_mod.install()
        try:
            from maggy_tpu.telemetry.metrics import MetricsRegistry

            reg = MetricsRegistry()
            assert type(reg._lock).__name__ == "_WitnessLock"
            assert reg._lock._name == "MetricsRegistry._lock"
            # Allocated from THIS test file (outside the package): real.
            foreign = threading.Lock()
            assert type(foreign).__name__ != "_WitnessLock"
            # Wrapped locks still work as locks.
            reg.counter("c").inc()
            assert reg.counter("c").value == 1
        finally:
            witness_mod.uninstall()
        assert threading.Lock is witness_mod._REAL_LOCK
        assert w.violations == []

    def test_install_is_idempotent(self):
        w1 = witness_mod.install()
        try:
            assert witness_mod.install() is w1
        finally:
            witness_mod.uninstall()

    def test_condition_over_wrapped_rlock(self):
        """The fleet scheduler's wake condition wraps its RLock: wait/
        notify must work through the witness wrapper (the _release_save/
        _acquire_restore/_is_owned protocol), and the witness must not
        warn on the reentrant traffic."""
        witness_mod.install()
        try:
            from maggy_tpu.fleet.scheduler import FleetScheduler

            sched = FleetScheduler(fleet_size=1)
            assert type(sched._lock).__name__ == "_WitnessLock"
            woke = []

            def waiter():
                with sched._wake:
                    woke.append(sched._wake.wait(timeout=2.0))

            t = threading.Thread(target=waiter)
            t.start()
            time.sleep(0.1)
            with sched._wake:
                sched._wake.notify_all()
            t.join(timeout=5)
            assert woke == [True]
            # Reentrant acquisition through the wrapper is silent.
            with sched._lock:
                with sched._lock:
                    pass
            w = witness_mod.active_witness()
            assert w.violations == []
        finally:
            witness_mod.uninstall()

    def test_forbidden_runtime_edge_detected(self):
        w = witness_mod.install()
        try:
            from maggy_tpu.fleet.scheduler import FleetScheduler
            from maggy_tpu.telemetry.metrics import MetricsRegistry

            sched = FleetScheduler(fleet_size=1)
            reg = MetricsRegistry()
            a, b = sorted(
                [(w.positions["FleetScheduler._lock"], sched._lock),
                 (w.positions["MetricsRegistry._lock"], reg._lock)])
            with a[1]:
                with b[1]:  # canonical direction: clean
                    pass
            assert w.violations == []
            with b[1]:
                with a[1]:  # inverted: forbidden
                    pass
            assert len(w.violations) == 1
        finally:
            witness_mod.uninstall()


def _witness_train(lr, units, reporter=None):
    acc = 1.0 - ((lr - 0.1) ** 2 + ((units - 32) / 64.0) ** 2)
    for step in range(3):
        time.sleep(0.02)
        if reporter is not None:
            reporter.broadcast(acc * (step + 1) / 3.0, step=step)
    return {"metric": acc}


@pytest.mark.timeout(180)
class TestWitnessExperiment:
    """The tier-1 witnessed run the acceptance criteria require: a real
    experiment under the instrumented lock wrappers finishes with real
    acquisition edges recorded and ZERO forbidden ones."""

    def test_experiment_under_witness_zero_forbidden_edges(self, tmp_path):
        from maggy_tpu import OptimizationConfig, Searchspace, experiment
        from maggy_tpu.core.environment import EnvSing
        from maggy_tpu.core.environment.abstractenvironment import LocalEnv

        env = LocalEnv(base_dir=str(tmp_path / "exp"))
        EnvSing.set_instance(env)
        w = witness_mod.install()
        try:
            config = OptimizationConfig(
                name="witnessed", num_trials=4, optimizer="randomsearch",
                searchspace=Searchspace(lr=("DOUBLE", [0.0, 0.2]),
                                        units=("INTEGER", [8, 64])),
                direction="max", num_workers=2, hb_interval=0.02, seed=5,
                es_policy="none")
            result = experiment.lagom(_witness_train, config)
        finally:
            witness_mod.uninstall()
            EnvSing.reset()
        assert result["num_trials"] == 4
        snap = w.snapshot()
        assert snap["edge_count"] > 0, \
            "a real experiment must exercise nested acquisitions"
        assert snap["violations"] == []


# ----------------------------------------------------- package enforcement


@pytest.mark.timeout(180)
class TestPackageConformance:
    """The tier-1 gate: the installed package must analyze clean — every
    remaining suppression carries a written reason. A regression in any
    checker's vocabulary or a new unguarded access fails HERE, in CI,
    before any soak could ever hit the race."""

    @pytest.fixture(scope="class")
    def report(self):
        return run_analysis()

    def test_no_unsuppressed_findings(self, report):
        assert report["findings"] == [], \
            "unannotated findings:\n" + "\n".join(
                repr(f) for f in report["findings"])

    def test_every_suppression_has_a_reason(self, report):
        for f in report["suppressed"]:
            assert f.reason, "reasonless suppression: {!r}".format(f)

    def test_lock_inventory_and_order(self, report):
        # ~40 locks per the issue; the exact count moves with the code,
        # the floor pins that lock DISCOVERY keeps working.
        assert report["num_locks"] >= 30
        assert len(report["lock_order"]) >= 30
        assert len(report["lock_edges"]) >= 20
        # The canonical order is total over the discovered locks.
        assert len(report["lock_order"]) == len(set(report["lock_order"]))

    def test_cli_exits_zero(self, capsys):
        from maggy_tpu.analysis.__main__ import main

        assert main([]) == 0
        out = capsys.readouterr().out
        assert "0 finding(s)" in out


# ---------------------------------------------------------- real-bug tests


class _PendingDeviceMetric:
    """Device-array stand-in whose value is never ready, with a hook run
    inside ``is_ready`` — the exact interleaving window of the
    _async_kick rollover race."""

    shape = ()
    dtype = np.dtype("float32")

    def __init__(self, on_is_ready=None):
        self.copy_calls = 0
        self._hook = on_is_ready

    def is_ready(self):
        if self._hook is not None:
            self._hook()
        return False

    def copy_to_host_async(self):
        self.copy_calls += 1

    def __float__(self):
        return 0.5


class TestReporterAsyncKickRollover:
    """Regression for the guards-checker finding fixed in this PR: the
    heartbeat thread's async-copy kick wrote ``_async_kick`` WITHOUT the
    reporter lock. If the trial rolled over (reset()) between the
    ready-check and the kick, the write resurrected the RETIRED trial's
    device array as the NEXT trial's in-flight kick."""

    def test_rollover_mid_get_data_suppresses_kick(self):
        from maggy_tpu.core.reporter import Reporter

        rep = Reporter()
        rep.reset(trial_id="t1")
        metric = _PendingDeviceMetric(
            on_is_ready=lambda: rep.reset(trial_id="t2"))
        rep.broadcast(metric, step=0)
        data = rep.get_data()
        # The rolled-over reporter must NOT have kicked the retired
        # trial's array, nor kept it as in-flight state.
        assert metric.copy_calls == 0
        assert rep._async_kick is None
        # Nothing shippable this beat (value pending, no prior cache).
        assert data["metric"] is None and data["step"] is None

    def test_no_rollover_kicks_exactly_once(self):
        from maggy_tpu.core.reporter import Reporter

        rep = Reporter()
        rep.reset(trial_id="t1")
        metric = _PendingDeviceMetric()
        rep.broadcast(metric, step=0)
        rep.get_data()
        rep.get_data()  # second beat: kick already in flight, no re-kick
        assert metric.copy_calls == 1
        assert rep._async_kick is metric


class TestFinalPayloadConformance:
    """Regression for the rpcconf finding fixed in this PR: FINAL
    payloads carried a ``span`` key no handler or driver callback ever
    read (the driver attributes FINALs through the span tracker by trial
    id). Dead keys are exactly how the retried-FINAL race hid; the
    checker now flags them, and this pins the wire shape."""

    def _client(self, sent):
        from maggy_tpu.core import rpc

        c = object.__new__(rpc.Client)
        c.last_info = {"epoch": 3}
        c._request = lambda msg, **kw: (sent.update(msg), {"type": "OK"})[1]
        c._handle_final_reply = lambda resp: None
        return c

    def test_final_sends_no_dead_span_key(self):
        from maggy_tpu.core.reporter import Reporter

        sent = {}
        c = self._client(sent)
        rep = Reporter()
        rep.reset(trial_id="t1", span="s1")
        rep.broadcast(0.7, step=0)
        c.finalize_metric(0.7, rep)
        assert sent["type"] == "FINAL"
        assert sent["trial_id"] == "t1"
        assert sent["value"] == 0.7
        assert "span" not in sent
        # The run-epoch echo IS read (the driver's stale-FINAL guard
        # drops a dead run's FINAL by epoch mismatch) — not a dead key.
        assert sent["epoch"] == 3

    def test_error_and_preempt_finals_conform_too(self):
        from maggy_tpu.core.reporter import Reporter

        sent = {}
        c = self._client(sent)
        rep = Reporter()
        rep.reset(trial_id="t2", span="s2")
        c.finalize_error("t2", rep)
        assert sent["type"] == "FINAL" and sent["error"] is True
        assert "span" not in sent
        sent.clear()
        rep.reset(trial_id="t3", span="s3")
        c.preempt_ack("t3", rep, step=4)
        assert sent["type"] == "FINAL" and sent["preempted"] is True
        assert sent["step"] == 4
        assert "span" not in sent
