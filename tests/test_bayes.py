"""Bayesian-optimization unit tests: GP, TPE, acquisitions, async machinery.

The reference ships zero BO tests (SURVEY.md §4); these verify with seeded
RNG that (1) the full async loop runs, (2) surrogates actually steer sampling
toward the optimum on a smooth function, (3) busy-location imputation and
duplicate rejection behave as specified.
"""

import numpy as np
import pytest

from maggy_tpu.optimizers.bayes import GP, TPE
from maggy_tpu.optimizers.bayes.acquisitions import (
    GaussianProcess_EI,
    GaussianProcess_LCB,
    GaussianProcess_PI,
)
from maggy_tpu.optimizers.bayes.kde import MixedKDE
from maggy_tpu.searchspace import Searchspace
from maggy_tpu.trial import Trial


def wire(opt, sp, num_trials, direction="min"):
    opt.searchspace = sp
    opt.num_trials = num_trials
    opt.trial_store = {}
    opt.final_store = []
    opt.direction = direction
    opt._initialize()
    return opt


def drive(opt, objective, num_trials):
    """Run the optimizer loop synchronously; returns finalized trials."""
    finished = []
    last = None
    guard = 0
    while len(finished) < num_trials and guard < num_trials * 8:
        guard += 1
        t = opt.get_suggestion(last)
        if t is None:
            break
        if t == "IDLE":
            continue
        opt.trial_store[t.trial_id] = t
        t.final_metric = objective(t.params)
        t.status = Trial.FINALIZED
        opt.trial_store.pop(t.trial_id)
        opt.final_store.append(t)
        finished.append(t)
        last = t
    return finished


def quadratic(params):
    # minimum at x=0.3, y=0.7
    return (params["x"] - 0.3) ** 2 + (params["y"] - 0.7) ** 2


def space2d():
    return Searchspace(x=("DOUBLE", [0.0, 1.0]), y=("DOUBLE", [0.0, 1.0]))


class TestGP:
    def test_full_loop_beats_warmup(self):
        opt = wire(GP(seed=0, num_warmup_trials=8, random_fraction=0.1), space2d(), 40)
        finished = drive(opt, quadratic, 40)
        assert len(finished) == 40
        model_trials = [t for t in finished if t.info_dict["sample_type"] == "model"]
        assert len(model_trials) >= 5  # the surrogate was actually used
        warmup_best = min(quadratic(t.params) for t in finished[:8])
        overall_best = min(quadratic(t.params) for t in finished)
        assert overall_best <= warmup_best  # BO did not get worse
        assert overall_best < 0.01  # and actually honed in

    def test_busy_location_imputation(self):
        opt = wire(GP(seed=1, num_warmup_trials=4), space2d(), 20)
        finished = drive(opt, quadratic, 10)
        # Leave one trial in flight and refit: imputed metric recorded.
        t = opt.get_suggestion(finished[-1])
        assert isinstance(t, Trial)
        opt.trial_store[t.trial_id] = t
        opt.update_model(0)
        assert t.trial_id in opt.imputed_metrics
        # cl_min: liar equals best observed normalized metric
        y = np.asarray([tr.final_metric for tr in opt.final_store])
        assert np.isclose(opt.imputed_metrics[t.trial_id], y.min())

    def test_asy_ts_strategy(self):
        opt = wire(GP(seed=2, async_strategy="asy_ts", num_warmup_trials=6,
                      random_fraction=0.1), space2d(), 20)
        finished = drive(opt, quadratic, 20)
        assert len(finished) == 20
        assert any(t.info_dict["sample_type"] == "model" for t in finished)

    def test_direction_max(self):
        opt = wire(GP(seed=3, num_warmup_trials=8, random_fraction=0.1),
                   space2d(), 30, direction="max")
        finished = drive(opt, lambda p: -quadratic(p), 30)
        best = max(-quadratic(t.params) for t in finished)
        assert best > -0.01

    def test_invalid_args(self):
        with pytest.raises(ValueError, match="async_strategy"):
            GP(async_strategy="bogus")
        with pytest.raises(ValueError, match="impute_strategy"):
            GP(impute_strategy="bogus")
        with pytest.raises(ValueError, match="acquisition"):
            GP(acquisition="bogus")


class TestTPE:
    def test_full_loop_converges(self):
        opt = wire(TPE(seed=0, num_warmup_trials=10, random_fraction=0.1), space2d(), 50)
        finished = drive(opt, quadratic, 50)
        assert len(finished) == 50
        assert any(t.info_dict["sample_type"] == "model" for t in finished)
        assert min(quadratic(t.params) for t in finished) < 0.02

    def test_mixed_space(self):
        sp = Searchspace(x=("DOUBLE", [0.0, 1.0]), act=("CATEGORICAL", ["a", "b", "c"]))

        def obj(p):  # "b" is best
            return (p["x"] - 0.5) ** 2 + {"a": 1.0, "b": 0.0, "c": 2.0}[p["act"]]

        opt = wire(TPE(seed=1, num_warmup_trials=10, random_fraction=0.1), sp, 60)
        finished = drive(opt, obj, 60)
        model_trials = [t for t in finished if t.info_dict["sample_type"] == "model"]
        assert model_trials
        # The model should mostly propose the good category.
        frac_b = np.mean([t.params["act"] == "b" for t in model_trials[5:]])
        assert frac_b > 0.5

    def test_rejects_interim(self):
        with pytest.raises(ValueError, match="interim"):
            TPE(interim_results=True)


class TestAcquisitions:
    def make_model(self):
        from sklearn.gaussian_process import GaussianProcessRegressor
        from sklearn.gaussian_process.kernels import Matern, WhiteKernel

        # Sparse observations of a quadratic, leaving the basin unobserved.
        X = np.asarray([[0.0], [0.25], [0.75], [1.0]])
        y = (X[:, 0] - 0.5) ** 2
        gp = GaussianProcessRegressor(
            kernel=Matern(length_scale=0.3, nu=2.5) + WhiteKernel(1e-6, (1e-9, 1e-2)),
            normalize_y=True,
            optimizer=None,  # pin hyperparameters: deterministic surrogate
            random_state=0,
        ).fit(X, y)
        return gp, float(y.min())

    def test_ei_prefers_unobserved_basin(self):
        gp, y_opt = self.make_model()
        # 0.5 (predicted low, uncertain) must beat 0.875 (predicted high).
        vals = GaussianProcess_EI().evaluate(np.asarray([[0.5], [0.875]]), gp, y_opt)
        assert vals[0] < vals[1]  # more negative EI in the basin

    def test_pi_and_lcb_finite(self):
        gp, y_opt = self.make_model()
        X = np.random.default_rng(0).uniform(size=(10, 1))
        assert np.all(np.isfinite(GaussianProcess_PI().evaluate(X, gp, y_opt)))
        assert np.all(np.isfinite(GaussianProcess_LCB().evaluate(X, gp, y_opt)))


class TestKDE:
    def test_pdf_integrates_roughly(self):
        rng = np.random.default_rng(0)
        data = rng.normal(0.5, 0.1, size=(200, 1))
        kde = MixedKDE(data, ["c"])
        xs = np.linspace(-0.5, 1.5, 400)[:, None]
        mass = np.trapezoid(kde.pdf(xs), xs[:, 0])
        assert abs(mass - 1.0) < 0.05

    def test_categorical_kernel_peaks_on_mode(self):
        data = np.asarray([[0.0]] * 8 + [[1.0]] * 2)
        kde = MixedKDE(data, ["u"], n_categories=[3])
        p = kde.pdf(np.asarray([[0.0], [1.0], [2.0]]))
        assert p[0] > p[1] > p[2]

    def test_sample_around_in_bounds(self):
        rng = np.random.default_rng(1)
        data = rng.uniform(size=(20, 2))
        kde = MixedKDE(data, ["c", "c"])
        for _ in range(50):
            x = kde.sample_around(rng, int(rng.integers(0, 20)))
            assert np.all((x >= 0) & (x <= 1))


class TestDuplicateRejection:
    def test_forced_random_eventually_none(self):
        # Tiny discrete-ish space where collisions are certain: INTEGER [0,1].
        sp = Searchspace(n=("INTEGER", [0, 1]))
        opt = wire(GP(seed=0, num_warmup_trials=0, random_fraction=1.0), sp, 10)
        seen = []
        for _ in range(10):
            t = opt.get_suggestion()
            if t is None:
                break
            opt.trial_store[t.trial_id] = t
            t.final_metric = 0.0
            opt.trial_store.pop(t.trial_id)
            opt.final_store.append(t)
            seen.append(t)
        # Only 2 distinct configs exist; loop must terminate well before 10.
        assert len(seen) <= 2
