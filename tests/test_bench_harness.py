"""Wedge-proofing contract for bench.py (VERDICT r3 item 2).

The round-3 incident: an extra bench blew its compile budget, its worker
*thread* was abandoned mid-device-call, and the stale client claim wedged
the chip for every later process — including the judged bench run. The
orchestrator rewrite makes that structurally impossible:

- the orchestrator process never imports jax (cannot hold a claim);
- the headline JSON prints BEFORE any extra bench touches the device;
- each extra runs in its own subprocess KILLED on timeout (a dead process
  releases its device claim; an abandoned thread does not).

This test forces the failure mode with a fake hanging extra and asserts
the headline survives, the process exits 0, and a fresh process can still
initialize the device backend afterwards.
"""

import json
import os
import subprocess
import sys

# Heavy module (e2e / sharded-compile tests): excluded from the fast lane
# (pytest -m 'not slow').
pytestmark = __import__('pytest').mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _json_lines(text):
    out = []
    for line in text.splitlines():
        line = line.strip()
        if line.startswith("{"):
            out.append(json.loads(line))
    return out


def test_bench_survives_hanging_extra(tmp_path):
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "MAGGY_TPU_BASE_DIR": str(tmp_path),
        # Small-but-real headline: full sweep + both baselines on CPU.
        "BENCH_STEPS": "2",
        "BENCH_NUM_TRIALS": "9",  # ASHA rf=3, 3 rungs needs >= 9
        # Only the injected hanging extra runs; it must be killed at ~3s.
        "BENCH_EXTRAS": "hang",
        "BENCH_EXTRA_TIMEOUT_S": "3",
        "BENCH_DEVICE_PROBE_S": "120",
        "BENCH_HEADLINE_TIMEOUT_S": "900",
    })
    proc = subprocess.run(
        [sys.executable, "bench.py"], cwd=REPO, env=env,
        capture_output=True, text=True, timeout=900)
    assert proc.returncode == 0, proc.stderr[-3000:]
    lines = _json_lines(proc.stdout)
    assert len(lines) == 2, proc.stdout
    headline, enriched = lines

    # Headline printed before extras, and untouched by the hang.
    assert headline["value"] > 0
    assert headline["vs_baseline"] > 0
    assert "hang" not in headline["detail"]

    # Enriched line keeps the same headline numbers and records the kill.
    assert enriched["value"] == headline["value"]
    assert enriched["vs_baseline"] == headline["vs_baseline"]
    assert enriched["detail"]["hang"]["error"].startswith("timeout")

    # The device backend still initializes in a fresh process: the hang
    # was killed, not abandoned, so no stale claim survives it. Uses the
    # same CPU-honoring probe code as the orchestrator (a bare
    # `import jax` can still touch a real device via sitecustomize's
    # pre-registered TPU plugin, even with JAX_PLATFORMS=cpu).
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.pop(0)
    rc = subprocess.run(
        [sys.executable, "-c", bench._PROBE_CODE],
        env=env, timeout=120, stdout=subprocess.DEVNULL).returncode
    assert rc == 0


def test_bench_headline_timeout_emits_failure_artifact(tmp_path):
    """A hung headline child is killed and a well-formed zero-value
    artifact is still printed (rc 1, parseable JSON — never a hang)."""
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "MAGGY_TPU_BASE_DIR": str(tmp_path),
        "BENCH_STEPS": "2",
        "BENCH_NUM_TRIALS": "9",
        "BENCH_DEVICE_PROBE_S": "120",
        # Headline cannot finish warm-up in 2s -> timeout path.
        "BENCH_HEADLINE_TIMEOUT_S": "2",
        "BENCH_SKIP_EXTRAS": "1",
    })
    proc = subprocess.run(
        [sys.executable, "bench.py"], cwd=REPO, env=env,
        capture_output=True, text=True, timeout=600)
    assert proc.returncode == 1
    lines = _json_lines(proc.stdout)
    assert len(lines) == 1
    assert lines[0]["value"] == 0.0
    assert "timed out" in lines[0]["detail"]["error"]
