"""Unit tests for bench.py's analysis helpers (the judged artifact's
measurement code must itself be trustworthy)."""

import sys
import os

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench


class TestHandoffGaps:
    def _trial(self, partition, start, duration):
        return {"info_dict": {"partition": partition}, "start": start,
                "duration": duration}

    def test_gaps_are_per_partition(self):
        trials = [
            self._trial(0, 0.0, 1.0),   # p0: ends 1.0
            self._trial(0, 1.01, 1.0),  # p0: 10ms gap
            self._trial(1, 0.0, 2.0),   # p1: ends 2.0
            self._trial(1, 2.05, 1.0),  # p1: 50ms gap
        ]
        out = bench.handoff_gaps(trials)
        assert out["n"] == 2
        assert out["median_ms"] in (10.0, 50.0)

    def test_barrier_idle_excluded(self):
        trials = [
            self._trial(0, 0.0, 1.0),
            self._trial(0, 4.0, 1.0),   # 3s idle: rung barrier, not overhead
            self._trial(0, 5.002, 1.0),  # 2ms: real hand-off
        ]
        out = bench.handoff_gaps(trials)
        assert out["n"] == 1
        assert out["median_ms"] == pytest.approx(2.0, abs=0.5)

    def test_requeue_overlap_excluded(self):
        # A requeued trial can START before the falsely-lost original ended:
        # negative gaps must not pollute the overhead stat.
        trials = [
            self._trial(0, 0.0, 2.0),
            self._trial(0, 1.5, 1.0),
        ]
        assert bench.handoff_gaps(trials) == {}

    def test_missing_fields_skipped(self):
        # The two invalid rows would create spurious gaps if NOT skipped
        # (an info-less trial grouped under partition None, and a
        # start-less one under partition 0 between the two valid runs).
        trials = [
            {"info_dict": {}, "start": 0.2, "duration": 1.0},
            {"info_dict": {"partition": 0}, "start": None, "duration": 1.0},
            self._trial(0, 0.0, 1.0),
            self._trial(0, 1.02, 1.0),
        ]
        out = bench.handoff_gaps(trials)
        assert out["n"] == 1
        assert out["median_ms"] == pytest.approx(20.0, abs=0.5)


class TestChipPeak:
    def test_known_kinds_map(self, monkeypatch):
        class FakeDev:
            def __init__(self, kind):
                self.device_kind = kind

        import jax

        for kind, peak in [("TPU v5 lite", 197e12), ("TPU v4", 275e12),
                           ("TPU v5p x", 459e12)]:
            monkeypatch.setattr(jax, "devices", lambda k=kind: [FakeDev(k)])
            got_kind, got_peak = bench.chip_peak_flops()
            assert got_kind == kind and got_peak == peak

    def test_unknown_kind_conservative_default(self, monkeypatch):
        class FakeDev:
            device_kind = "TPU v99 mega"

        import jax

        monkeypatch.setattr(jax, "devices", lambda: [FakeDev()])
        kind, peak = bench.chip_peak_flops()
        assert kind == "TPU v99 mega" and peak == 197e12


class TestStageBaselines:
    """The baselines' scheduling mechanics, with train_mnist stubbed out."""

    def _record_runs(self, monkeypatch):
        import threading

        runs, active, peak = [], [0], [0]
        lock = threading.Lock()

        def fake_train(lr, batch=256, budget=1, reporter=None):
            with lock:
                active[0] += 1
                peak[0] = max(peak[0], active[0])
            try:
                import time

                time.sleep(0.02 * budget)
                with lock:
                    runs.append((lr, batch, budget))
            finally:
                with lock:
                    active[0] -= 1

        monkeypatch.setattr(bench, "train_mnist", fake_train)
        return runs, peak

    def test_packed_runs_everything_with_bounded_concurrency(self, monkeypatch):
        runs, peak = self._record_runs(monkeypatch)
        sched = [(0.1 * i, 128, 1 + (i % 3)) for i in range(10)]
        bench.run_packed_baseline(sched, workers=3)
        assert sorted(runs) == sorted(sched)
        # Actually packed: overlap happened (sleepy trials + 3 workers),
        # but never more than the worker count.
        assert 2 <= peak[0] <= 3

    def test_packed_propagates_trial_failure(self, monkeypatch):
        def boom(lr, batch=256, budget=1, reporter=None):
            raise RuntimeError("trial exploded")

        monkeypatch.setattr(bench, "train_mnist", boom)
        with pytest.raises(RuntimeError, match="exploded"):
            bench.run_packed_baseline([(0.1, 128, 1)], workers=2)

    def test_sync_sha_orders_rungs_with_barriers(self, monkeypatch):
        runs, _ = self._record_runs(monkeypatch)
        rungs = {0: [(0.1, 128, 1), (0.2, 256, 1), (0.3, 512, 1)],
                 1: [(0.1, 128, 3)],
                 2: [(0.1, 128, 9)]}
        bench.run_sync_sha_baseline(rungs, workers=2)
        budgets = [b for (_, _, b) in runs]
        # Barrier between rungs: every rung-0 run completes before the
        # rung-1 run starts, which completes before rung 2.
        assert budgets.index(3) >= 3
        assert budgets.index(9) == len(budgets) - 1
        assert len(runs) == 5


class TestProbeRetry:
    """The probe-retry loop must spend the window, remediate between
    attempts, and catch a mid-window recovery (the r3/r4 failure mode was
    ONE probe deciding a whole round)."""

    def test_recovery_mid_window_is_caught(self, monkeypatch):
        calls = {"probe": 0, "remediate": 0}

        def fake_probe(timeout_s):
            calls["probe"] += 1
            return calls["probe"] >= 3  # recovers on the third attempt

        monkeypatch.setattr(bench, "_probe_device", fake_probe)
        monkeypatch.setattr(bench, "_remediate_device",
                            lambda: calls.__setitem__(
                                "remediate", calls["remediate"] + 1))
        # Fast-failing probes trigger the anti-hammer sleep; neuter it.
        monkeypatch.setattr(bench.time, "sleep", lambda s: None)
        monkeypatch.setenv("BENCH_PROBE_ATTEMPT_S", "1")
        assert bench._probe_device_with_retry(30.0) is True
        assert calls["probe"] == 3
        assert calls["remediate"] == 2  # between attempts, not after success

    def test_budget_exhaustion_returns_false(self, monkeypatch):
        t = {"now": 0.0}
        monkeypatch.setattr(bench.time, "monotonic", lambda: t["now"])
        monkeypatch.setattr(bench.time, "sleep", lambda s: None)

        def fake_probe(timeout_s):
            t["now"] += timeout_s  # a hung probe eats its full timeout
            return False

        monkeypatch.setattr(bench, "_probe_device", fake_probe)
        monkeypatch.setattr(bench, "_remediate_device", lambda: None)
        monkeypatch.setenv("BENCH_PROBE_ATTEMPT_S", "75")
        assert bench._probe_device_with_retry(300.0) is False
        # ~300/75 attempts fit the window.
        assert 3 <= t["now"] / 75 <= 5

    def test_remediation_only_touches_stale_lockfiles(self, tmp_path,
                                                      monkeypatch):
        """A lockfile HELD by a live process must survive remediation; a
        stale one is removed."""
        import fcntl
        import glob as glob_mod

        held = tmp_path / "libtpu_lockfile_held"
        stale = tmp_path / "libtpu_lockfile_stale"
        held.write_text("")
        stale.write_text("")
        fd = os.open(str(held), os.O_RDWR)
        fcntl.flock(fd, fcntl.LOCK_EX)  # we are the live holder
        real_glob = glob_mod.glob

        def fake_glob(pattern):
            if "lockfile" in pattern and pattern.startswith("/tmp/libtpu"):
                return [str(held), str(stale)]
            if "lockfile" in pattern:
                return []
            return real_glob(pattern)

        import glob

        monkeypatch.setattr(glob, "glob", fake_glob)
        try:
            bench._remediate_device()
            assert held.exists(), "remediation deleted a HELD lockfile"
            assert not stale.exists(), "stale lockfile not removed"
        finally:
            os.close(fd)


class TestTraceArtifact:
    """bench.py must validate the emitted timeline parses as Chrome-trace
    JSON before recording its path — a BENCH artifact must never point at
    an unloadable file."""

    def _journal(self, exp_dir):
        import json as _json

        from maggy_tpu.telemetry import JOURNAL_NAME

        events = [
            {"t": 1.0, "ev": "trial", "trial": "a", "phase": "queued"},
            {"t": 1.1, "ev": "trial", "trial": "a", "phase": "assigned",
             "partition": 0},
            {"t": 1.2, "ev": "trial", "trial": "a", "phase": "running",
             "partition": 0},
            {"t": 2.0, "ev": "trial", "trial": "a", "phase": "finalized",
             "partition": 0},
        ]
        with open(os.path.join(exp_dir, JOURNAL_NAME), "w") as f:
            for ev in events:
                f.write(_json.dumps(ev) + "\n")

    def test_valid_journal_records_path(self, tmp_path):
        import json as _json

        exp_dir = str(tmp_path)
        self._journal(exp_dir)
        path = bench._export_trace_artifact(exp_dir)
        assert path == os.path.join(exp_dir, "trace.json")
        with open(path) as f:
            assert _json.load(f)["traceEvents"]

    def test_missing_journal_records_none(self, tmp_path):
        assert bench._export_trace_artifact(str(tmp_path)) is None

    def test_unwritable_or_invalid_trace_records_none(self, tmp_path,
                                                      monkeypatch):
        exp_dir = str(tmp_path)
        self._journal(exp_dir)
        # Simulate a writer that produced garbage: validation must refuse
        # to record the path.
        import maggy_tpu.telemetry.trace as trace_mod

        def bad_write(events, out, env=None):
            with open(out, "w") as f:
                f.write("NOT JSON")
            return 1

        monkeypatch.setattr(bench, "log", lambda *a, **k: None)
        real = trace_mod.write_trace
        monkeypatch.setattr(trace_mod, "write_trace", bad_write)
        try:
            assert bench._export_trace_artifact(exp_dir) is None
        finally:
            monkeypatch.setattr(trace_mod, "write_trace", real)


class TestSchedulingTelemetryCompile:
    """detail.compile rides the same journal replay as handoff/suggest —
    and pre-warm journals (or the trial.json fallback) degrade to an
    empty block instead of crashing the bench."""

    def _write_journal(self, exp_dir, events):
        import json as _json

        from maggy_tpu.telemetry import JOURNAL_NAME

        with open(os.path.join(exp_dir, JOURNAL_NAME), "w") as f:
            for ev in events:
                f.write(_json.dumps(ev) + "\n")

    def test_compile_block_replayed(self, tmp_path):
        exp_dir = str(tmp_path)
        self._write_journal(exp_dir, [
            {"t": 1.0, "ev": "trial", "trial": "a", "phase": "compiled",
             "partition": 0, "warm": False, "ttfm_ms": 4000.0,
             "compile_ms": 2000.0},
            {"t": 2.0, "ev": "trial", "trial": "b", "phase": "compiled",
             "partition": 0, "warm": True, "ttfm_ms": 30.0},
        ])
        sched = bench.scheduling_telemetry(exp_dir, [])
        assert sched["source"] == "telemetry_journal"
        assert sched["compile"]["warm_hits"] == 1
        assert sched["compile"]["ttfm_cold"]["median_ms"] == 4000.0

    def test_pre_warm_journal_empty_block(self, tmp_path):
        exp_dir = str(tmp_path)
        self._write_journal(exp_dir, [
            {"t": 1.0, "ev": "trial", "trial": "a", "phase": "queued"},
        ])
        assert bench.scheduling_telemetry(exp_dir, [])["compile"] == {}

    def test_trial_json_fallback_has_empty_block(self, tmp_path):
        sched = bench.scheduling_telemetry(str(tmp_path), [])
        assert sched["source"] == "trial_json_fallback"
        assert sched["compile"] == {}


class TestAnalysisDetail:
    """detail.analysis carries the static posture (and, for soaks, the
    witness edge count) so concurrency-discipline drift is visible in the
    bench trajectory without re-running the analyzer."""

    def test_posture_on_clean_repo(self):
        d = bench.analysis_detail()
        assert d["findings"] == 0
        assert set(d["per_checker"]) == {"guards", "lockorder", "rpcconf",
                                         "journalvocab"}
        assert d["locks"] >= 30 and d["order_edges"] >= 20
        assert "witness_edges" not in d  # no soak ran under the witness

    def test_witness_block_merged(self):
        d = bench.analysis_detail(
            {"edge_count": 17, "violations": ["lock-order violation: x"]})
        assert d["witness_edges"] == 17
        assert d["witness_violations"] == 1

    def test_analyzer_failure_is_best_effort(self, monkeypatch):
        import maggy_tpu.analysis as _an

        def boom(*a, **kw):
            raise RuntimeError("parse exploded")

        monkeypatch.setattr(_an, "run_analysis", boom)
        d = bench.analysis_detail({"edge_count": 3, "violations": []})
        assert "parse exploded" in d["error"]
        assert d["witness_edges"] == 3
