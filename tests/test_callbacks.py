"""Callback parity: BatchEnd/EpochEnd + the tf.keras shim, exercised with
TENSORFLOW PRESENT (reference `maggy/callbacks.py:20-66` is Keras-only; our
shim must actually drive a real keras fit loop, not just import)."""

import numpy as np
import pytest

from maggy_tpu.callbacks import BatchEnd, EpochEnd, keras_reporter_callbacks
from maggy_tpu.core.reporter import Reporter
from maggy_tpu.exceptions import EarlyStopException


class TestNativeCallbacks:
    def test_batch_end_reports_with_running_step(self):
        rep = Reporter()
        rep.reset(trial_id="t")
        cb = BatchEnd(rep, metric="loss")
        cb({"loss": 0.5})
        cb({"loss": 0.25})
        data = rep.get_data()
        assert data["metric"] == 0.25 and data["step"] == 1

    def test_epoch_end_uses_given_step(self):
        rep = Reporter()
        rep.reset(trial_id="t")
        cb = EpochEnd(rep, metric="acc")
        cb({"acc": 0.8}, step=3)
        assert rep.get_data() == {"metric": 0.8, "step": 3, "logs": [],
                                  "trial_id": "t", "span": None}

    def test_missing_metric_is_skipped(self):
        rep = Reporter()
        rep.reset(trial_id="t")
        BatchEnd(rep, metric="nope")({"loss": 1.0})
        assert rep.get_data()["metric"] is None


class TestKerasShim:
    @pytest.fixture
    def tf(self):
        return pytest.importorskip("tensorflow")

    @pytest.fixture
    def keras_fit(self, tf):
        rng = np.random.default_rng(0)
        X = rng.normal(size=(64, 4)).astype(np.float32)
        y = (X.sum(axis=1) > 0).astype(np.int32)

        def fit(callbacks, epochs=2):
            model = tf.keras.Sequential([
                tf.keras.layers.Dense(8, activation="relu"),
                tf.keras.layers.Dense(2),
            ])
            model.compile(
                optimizer="sgd",
                loss=tf.keras.losses.SparseCategoricalCrossentropy(
                    from_logits=True))
            model.fit(X, y, epochs=epochs, batch_size=16, verbose=0,
                      callbacks=callbacks)

        return fit

    def test_epoch_metric_streams_through_reporter(self, keras_fit):
        rep = Reporter()
        rep.reset(trial_id="t")
        cbs = keras_reporter_callbacks(rep, epoch_metric="loss")
        keras_fit(cbs, epochs=3)
        data = rep.get_data()
        assert data["metric"] is not None
        assert data["step"] == 2  # last epoch index

    def test_batch_metric_streams_through_reporter(self, keras_fit):
        rep = Reporter()
        rep.reset(trial_id="t")
        cbs = keras_reporter_callbacks(rep, batch_metric="loss",
                                       epoch_metric=None)
        keras_fit(cbs, epochs=1)
        data = rep.get_data()
        assert data["metric"] is not None
        assert data["step"] == 3  # 64 samples / batch 16 -> 4 batches

    def test_early_stop_surfaces_inside_keras_fit(self, tf, keras_fit):
        """The driver's STOP arrives between keras batches: the shim's next
        broadcast raises EarlyStopException out of model.fit, exactly like
        the reference's KerasBatchEnd (`callbacks.py:20-43`)."""
        rep = Reporter()
        rep.reset(trial_id="t")
        cbs = keras_reporter_callbacks(rep, batch_metric="loss",
                                       epoch_metric=None)

        class Arm(tf.keras.callbacks.Callback):
            def on_train_batch_end(self, batch, logs=None):
                if batch == 1:
                    rep.early_stop()

        with pytest.raises(EarlyStopException):
            keras_fit([Arm()] + cbs, epochs=2)
